"""Offered-load serving benchmark for the continuous-batching engine.

Open-loop harness: request arrivals are a seeded Poisson process (the
offered load), prompts/token budgets draw from seeded ranges, and the
engine is stepped continuously — arrivals land whenever the wall clock
passes their timestamp, exactly like traffic hitting a server that is
already busy. Closed-loop driving (submit, drain, repeat) would hide
queueing: TTFT under load IS the queue, so the clock must keep running
while the engine works.

Emits ONE JSON line:

  {"metric": "serving_tokens_per_sec", "value": ..., "unit": "tokens/s",
   "extra": {"ttft_p50_ms": ..., "ttft_p99_ms": ...,
             "per_token_p50_ms": ..., "per_token_p99_ms": ...,
             "requests_finished": ..., "requests_rejected": ...,
             "requests_expired": ..., "slot_occupancy_mean": ...,
             "prefix_hit_rate": ..., "cached_token_fraction": ...,
             "compiles_decode": 1, ...}}

`--prefix-pool N --prefix-len L` switches the prompt generator to
shared-prefix traffic (each prompt = one of N fixed L-token prefixes + a
unique suffix) — the workload the paged KV cache's radix-tree prefix
reuse is built for; `--no-prefix-cache` is the A/B baseline on the same
trace.

`python benchmarks/serve_bench.py --help` for knobs; the defaults are a
CPU-safe tiny-llama smoke. `run_offered_load` is importable — the tier-1
bench-contract test drives a miniature load through it in-process, and
bench.py's serving row reuses it for the one-line JSON contract.
"""

from __future__ import annotations

import argparse
import json
import time


def build_tiny_engine(family_name: str = "llama", num_slots: int = 4,
                      max_len: int = 128, prefill_chunk: int = 16,
                      max_queue: int = 64, seed: int = 0,
                      metrics_port: int | None = None,
                      page_size: int = 16, prefix_cache: bool = True):
    """A small engine on the named family (tiny config, fresh params).
    `metrics_port` turns on the engine's Prometheus endpoint (0 binds an
    ephemeral port, reported on `engine.metrics_server.port`);
    `prefix_cache=False` keeps the paged cache but disables cross-request
    prefix reuse (the A/B baseline for the shared-prefix workload)."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.serving import Engine, EngineConfig

    if family_name == "llama":
        from accelerate_tpu.models import llama as family

        cfg = family.LlamaConfig.tiny()
    elif family_name == "gpt2":
        from accelerate_tpu.models import gpt2 as family

        cfg = family.GPT2Config.tiny()
    else:
        raise ValueError(f"unknown family {family_name!r}")
    params = family.init_params(cfg, jax.random.key(seed))
    ec = EngineConfig(num_slots=num_slots, max_len=max_len,
                      prefill_chunk=prefill_chunk, max_queue=max_queue,
                      cache_dtype=jnp.bfloat16, seed=seed,
                      page_size=page_size, prefix_cache=prefix_cache,
                      metrics_port=metrics_port)
    return Engine(family, cfg, params, ec), cfg


def run_offered_load(
    engine,
    vocab_size: int,
    num_requests: int = 16,
    rate_hz: float = 50.0,
    prompt_len: tuple[int, int] = (4, 24),
    max_new_tokens: tuple[int, int] = (4, 16),
    temperature: float = 0.0,
    deadline_s: float | None = None,
    seed: int = 0,
    warmup_requests: int = 1,
    prefix_pool: int = 0,
    prefix_len: int = 0,
) -> dict:
    """Drive `num_requests` Poisson arrivals at `rate_hz` through the
    engine; returns the flat metrics summary plus load parameters.

    `warmup_requests` run to completion first (compile + first dispatch)
    and are excluded from the reported distributions.

    With `prefix_pool`/`prefix_len` set, prompts model shared-prefix
    traffic (system prompts, few-shot headers): each prompt is a prefix
    sampled from a pool of `prefix_pool` fixed `prefix_len`-token
    prefixes, plus a unique suffix drawn from `prompt_len`. The summary
    then carries `prefix_hit_rate` and `cached_token_fraction` from the
    engine's prefix-cache counters.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, vocab_size, (prefix_len,)).astype(np.int32)
                for _ in range(prefix_pool)] if prefix_pool and prefix_len \
        else []

    def make_prompt():
        n = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        suffix = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        if not prefixes:
            return suffix
        return np.concatenate(
            [prefixes[int(rng.integers(len(prefixes)))], suffix])

    def budget():
        return int(rng.integers(max_new_tokens[0], max_new_tokens[1] + 1))

    for _ in range(warmup_requests):
        engine.submit(make_prompt(), max_new_tokens=budget(),
                      temperature=temperature)
    engine.run_until_idle()
    engine.reset_metrics()  # drop warmup samples; programs stay compiled

    gaps = rng.exponential(1.0 / rate_hz, size=num_requests)
    start = time.perf_counter()
    arrivals = start + np.cumsum(gaps)
    submitted = 0
    while submitted < num_requests or engine.scheduler.has_work():
        now = time.perf_counter()
        while submitted < num_requests and arrivals[submitted] <= now:
            engine.submit(make_prompt(), max_new_tokens=budget(),
                          temperature=temperature, deadline_s=deadline_s)
            submitted += 1
        if not engine.step() and submitted < num_requests:
            # idle before the next arrival: sleep to it (open loop)
            time.sleep(max(0.0, arrivals[submitted] - time.perf_counter()))

    out = engine.metrics_summary()
    out.update({
        "offered_rate_hz": rate_hz,
        "num_requests": float(num_requests),
        "wall_s": round(time.perf_counter() - start, 3),
    })
    if prefixes:
        out.update({"prefix_pool": float(prefix_pool),
                    "prefix_len": float(prefix_len)})
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--family", default="llama", choices=("llama", "gpt2"))
    p.add_argument("--num-requests", type=int, default=16)
    p.add_argument("--rate-hz", type=float, default=50.0)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max-len", type=int, default=128)
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--prompt-len", type=int, nargs=2, default=(4, 24))
    p.add_argument("--max-new-tokens", type=int, nargs=2, default=(4, 16))
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--deadline-s", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prefix-pool", type=int, default=0,
                   help="shared-prefix workload: number of distinct "
                        "prefixes prompts draw from (0 = off)")
    p.add_argument("--prefix-len", type=int, default=0,
                   help="tokens per shared prefix; prompts become "
                        "prefix + unique suffix")
    p.add_argument("--page-size", type=int, default=16,
                   help="KV pool page size (prefix reuse is page-granular)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable cross-request prefix reuse (paged cache "
                        "kept) — the A/B baseline")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus /metrics while the load runs "
                        "(0 = ephemeral port, printed to stderr)")
    args = p.parse_args()

    # a shared-prefix workload must fit prefix + suffix + budget in a
    # slot; grow max_len rather than silently rejecting every request
    max_len = args.max_len
    if args.prefix_pool and args.prefix_len:
        max_len = max(max_len, args.prefix_len + args.prompt_len[1]
                      + args.max_new_tokens[1])
    engine, cfg = build_tiny_engine(
        args.family, num_slots=args.slots, max_len=max_len,
        prefill_chunk=args.prefill_chunk, seed=args.seed,
        page_size=args.page_size, prefix_cache=not args.no_prefix_cache,
        metrics_port=args.metrics_port)
    if engine.metrics_server is not None:
        import sys

        print(f"serving Prometheus metrics on "
              f":{engine.metrics_server.port}/metrics", file=sys.stderr)
    summary = run_offered_load(
        engine, cfg.vocab_size, num_requests=args.num_requests,
        rate_hz=args.rate_hz, prompt_len=tuple(args.prompt_len),
        max_new_tokens=tuple(args.max_new_tokens),
        temperature=args.temperature, deadline_s=args.deadline_s,
        seed=args.seed, prefix_pool=args.prefix_pool,
        prefix_len=args.prefix_len)
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": round(summary.get("tokens_per_sec", 0.0), 2),
        "unit": "tokens/s",
        "extra": {k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in summary.items()},
    }))


if __name__ == "__main__":
    main()
