"""Offered-load serving benchmark: engine-direct and through the HTTP door.

Open-loop harness: request arrivals are a seeded Poisson process (the
offered load), prompts/token budgets draw from seeded ranges, and the
engine is stepped continuously — arrivals land whenever the wall clock
passes their timestamp, exactly like traffic hitting a server that is
already busy. Closed-loop driving (submit, drain, repeat) would hide
queueing: TTFT under load IS the queue, so the clock must keep running
while the engine works.

Emits ONE JSON line:

  {"metric": "serving_tokens_per_sec", "value": ..., "unit": "tokens/s",
   "extra": {"ttft_p50_ms": ..., "ttft_p99_ms": ...,
             "per_token_p50_ms": ..., "per_token_p99_ms": ...,
             "requests_finished": ..., "requests_rejected": ...,
             "requests_expired": ..., "slot_occupancy_mean": ...,
             "prefix_hit_rate": ..., "cached_token_fraction": ...,
             "decode_mfu": ..., "decode_mxu_idle_fraction": ...,
             "decode_device_time_mean_ms": ..., "goodput": ...,
             "compiles_decode": 1, ...}}

The roofline fields (decode MFU / HBM-bandwidth utilization / MXU-idle
fraction, measured device-time percentiles) and `goodput` come from the
engine's cost table (ISSUE 11, telemetry/cost.py) — sampled fence-pair
device timing against the per-program FLOPs/bytes cost table, nominal
peaks off TPU. Gate a run against a previous one with
`accelerate-tpu bench-diff old.json new.json`.

`--prefix-pool N --prefix-len L` switches the prompt generator to
shared-prefix traffic (each prompt = one of N fixed L-token prefixes + a
unique suffix) — the workload the paged KV cache's radix-tree prefix
reuse is built for; `--no-prefix-cache` is the A/B baseline on the same
trace.

`--pod-roles prefill=N,decode=M` drives the same offered load through a
DISAGGREGATED pod (`serving.pod.PodEngine`): N prefill workers produce
KV pages that ship to M decode workers owning the slots; `--pod-tp K`
additionally mesh-shards every worker over K devices. The summary then
carries the pod counters (`pod_shipments`, `pod_pages_shipped`,
`pod_backpressure_stalls`) next to the usual latency percentiles.
`--pod-transport socket` is the A/B arm for the TRUE multi-host pod
(serving.pod.distributed): the same roles run as real `pod-worker` OS
processes dialing the router over TCP, so the delta against the default
`local` transport is the wire + process-boundary cost; the summary adds
the recovery counters (`pod_requests_replayed`, `pod_workers_lost`,
`pod_recovery_latency_*`).

`--tenants` switches to the MULTI-TENANT HTTP harness (`run_http_load`):
the real `accelerate_tpu.server` front door is stood up in-process on an
ephemeral port and per-tenant client fleets drive it over actual HTTP —
open-loop (Poisson or bursty arrivals at each tenant's `rate`),
closed-loop (`concurrency` workers per tenant in submit-wait-repeat),
or `--trace FILE` replay of a recorded arrival schedule. Per-tier
TTFT/per-token percentiles and SLO attainment come from the server's
OWN Prometheus /metrics route (the same series a production scrape
would read), next to client-observed TTFT and 429/shed counts::

  --tenants 'gold:priority=0,weight=4,slo=0.3,rate=10;bronze:rate=40'

`python benchmarks/serve_bench.py --help` for knobs; the defaults are a
CPU-safe tiny-llama smoke. `run_offered_load`/`run_http_load` are
importable — the tier-1 bench-contract tests drive miniature loads
through them in-process, and bench.py's serving/server rows reuse them
for the one-line JSON contract.
"""

from __future__ import annotations

import argparse
import json
import time


def build_tiny_engine(family_name: str = "llama", num_slots: int = 4,
                      max_len: int = 128, prefill_chunk: int = 16,
                      max_queue: int = 64, seed: int = 0,
                      metrics_port: int | None = None,
                      page_size: int = 16, prefix_cache: bool = True,
                      tenants=None, kv_dtype=None,
                      paged_attention="auto", speculative: bool = False,
                      draft_k: int = 4, num_pages: int | None = None,
                      host_tier_bytes: int = 0):
    """A small engine on the named family (tiny config, fresh params).
    `metrics_port` turns on the engine's Prometheus endpoint (0 binds an
    ephemeral port, reported on `engine.metrics_server.port`);
    `prefix_cache=False` keeps the paged cache but disables cross-request
    prefix reuse (the A/B baseline for the shared-prefix workload);
    `kv_dtype="int8"` quantizes the KV pool and `paged_attention`
    selects the decode attention op (True = Pallas kernel, False =
    dense-gather reference, "auto" = kernel on single-device TPU) — the
    A/B axes of the paged-attention bench. `speculative=True` turns on
    draft-model speculative decoding with a SELF-DRAFT (the same tiny
    model drafts for itself): with random-init benchmark weights only an
    identical draft agrees with the target, so the self-draft is the
    honest way to measure the MECHANISM — verify-batching efficiency,
    tokens-per-decode-step at accept rate ~1.0, compile-count flatness.
    Production deployments pass a real distilled family pair through
    `EngineConfig(speculative=(family, config, params))` instead."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.serving import Engine, EngineConfig

    if family_name == "llama":
        from accelerate_tpu.models import llama as family

        cfg = family.LlamaConfig.tiny()
    elif family_name == "gpt2":
        from accelerate_tpu.models import gpt2 as family

        cfg = family.GPT2Config.tiny()
    else:
        raise ValueError(f"unknown family {family_name!r}")
    params = family.init_params(cfg, jax.random.key(seed))
    ec = EngineConfig(num_slots=num_slots, max_len=max_len,
                      prefill_chunk=prefill_chunk, max_queue=max_queue,
                      cache_dtype=jnp.bfloat16, seed=seed,
                      page_size=page_size, prefix_cache=prefix_cache,
                      metrics_port=metrics_port, tenants=tenants,
                      kv_dtype=kv_dtype, paged_attention=paged_attention,
                      speculative=((family, cfg, params) if speculative
                                   else None),
                      draft_k=draft_k, num_pages=num_pages,
                      host_tier_bytes=host_tier_bytes)
    return Engine(family, cfg, params, ec), cfg


def parse_pod_roles(arg: str) -> tuple[int, int]:
    """'prefill=N,decode=M' -> (N, M). Order-insensitive; both required."""
    roles = {}
    for part in arg.split(","):
        name, _, val = part.strip().partition("=")
        if name not in ("prefill", "decode") or not val.isdigit():
            raise ValueError(
                f"bad --pod-roles entry {part!r} (want prefill=N,decode=M)")
        if name in roles:
            raise ValueError(
                f"--pod-roles names {name!r} twice — a typo'd duplicate "
                "would silently run the wrong worker split")
        roles[name] = int(val)
    if set(roles) != {"prefill", "decode"}:
        raise ValueError(
            f"--pod-roles needs BOTH roles, got {sorted(roles)}")
    return roles["prefill"], roles["decode"]


def build_tiny_pod_engine(family_name: str = "llama", pod_roles=(1, 1),
                          tensor_parallel: int = 1, num_slots: int = 4,
                          max_len: int = 128, prefill_chunk: int = 16,
                          max_queue: int = 64, seed: int = 0,
                          page_size: int = 16, prefix_cache: bool = True,
                          metrics_port: int | None = None, tenants=None,
                          kv_dtype=None, paged_attention="auto",
                          num_pages: int | None = None,
                          host_tier_bytes: int = 0):
    """A disaggregated pod (serving.pod.PodEngine) on the named family:
    `pod_roles=(N, M)` prefill/decode workers, optionally `tensor_parallel`
    chips per worker. Same submit/step surface as the single engine, so
    `run_offered_load` drives it unchanged. `kv_dtype="int8"` quantizes
    every worker's pool AND the page shipments between them (half the
    wire bytes)."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.serving import EngineConfig
    from accelerate_tpu.serving.pod import PodConfig, PodEngine

    if family_name == "llama":
        from accelerate_tpu.models import llama as family

        cfg = family.LlamaConfig.tiny()
    elif family_name == "gpt2":
        from accelerate_tpu.models import gpt2 as family

        cfg = family.GPT2Config.tiny()
    else:
        raise ValueError(f"unknown family {family_name!r}")
    params = family.init_params(cfg, jax.random.key(seed))
    ec = EngineConfig(num_slots=num_slots, max_len=max_len,
                      prefill_chunk=prefill_chunk, max_queue=max_queue,
                      cache_dtype=jnp.bfloat16, seed=seed,
                      page_size=page_size, prefix_cache=prefix_cache,
                      metrics_port=metrics_port, tenants=tenants,
                      kv_dtype=kv_dtype, paged_attention=paged_attention,
                      num_pages=num_pages,
                      host_tier_bytes=host_tier_bytes)
    pc = PodConfig(prefill_workers=pod_roles[0], decode_workers=pod_roles[1],
                   tensor_parallel=tensor_parallel)
    return PodEngine(family, cfg, params, ec, pc), cfg


def build_tiny_distributed_pod(family_name: str = "llama", pod_roles=(1, 1),
                               num_slots: int = 4, max_len: int = 128,
                               prefill_chunk: int = 16, max_queue: int = 64,
                               seed: int = 0, page_size: int = 16,
                               prefix_cache: bool = True, kv_dtype=None,
                               metrics_port: int | None = None,
                               worker_wait_s: float = 180.0,
                               trace: bool = False):
    """The TRUE multi-host pod: `DistributedPodRouter` in this process,
    N+M real `pod-worker` OS processes dialing its listener over TCP.
    Same submit/step surface as the single engine, so `run_offered_load`
    drives it unchanged — the A/B against `build_tiny_pod_engine` prices
    the wire + process boundary. Returns (router, cfg, procs); the
    caller owns `router.close()` and reaping the procs."""
    import os
    import sys as _sys
    import time as _time

    import accelerate_tpu
    from accelerate_tpu.commands.pod import spawn_socket_workers
    from accelerate_tpu.serving.pod.distributed import (
        ChannelListener, DistributedPodConfig, DistributedPodRouter)
    from accelerate_tpu.serving.pod.distributed.worker import (
        engine_config_from_spec)

    spec = {"family": family_name, "seed": seed, "num_slots": num_slots,
            "max_len": max_len, "prefill_chunk": prefill_chunk,
            "page_size": page_size, "max_queue": max_queue,
            "cache_dtype": "bfloat16", "kv_dtype": kv_dtype,
            "prefix_cache": prefix_cache}
    if family_name == "llama":
        from accelerate_tpu.models import llama as family

        cfg = family.LlamaConfig.tiny()
    elif family_name == "gpt2":
        from accelerate_tpu.models import gpt2 as family

        cfg = family.GPT2Config.tiny()
    else:
        raise ValueError(f"unknown family {family_name!r}")
    listener = ChannelListener("127.0.0.1", 0)
    # workers must import accelerate_tpu from this checkout even when it
    # is not pip-installed
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(accelerate_tpu.__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH", "")) if p)
    if trace:
        # distributed tracing A/B arm: workers record + export spans
        # (env flag is read at their import), router samples every
        # request so the ingest path is fully exercised
        from accelerate_tpu.telemetry.trace import configure_tracing

        env["ACCELERATE_TPU_TRACE"] = "1"
        configure_tracing(enabled=True, default_sample_rate=1.0)
    roles = (["prefill"] * pod_roles[0] + ["decode"] * pod_roles[1])
    procs = spawn_socket_workers(listener.port, spec, roles, env=env,
                                 stderr=_sys.stderr)
    router = DistributedPodRouter(
        engine_config=engine_config_from_spec(spec,
                                              metrics_port=metrics_port),
        pod_config=DistributedPodConfig(
            prefill_workers=pod_roles[0], decode_workers=pod_roles[1],
            # first-request compiles stall worker heartbeats; generous
            # timeouts keep a loaded box from counting phantom losses
            heartbeat_timeout_s=120.0, flight_timeout_s=300.0),
        listener=listener)
    deadline = _time.monotonic() + worker_wait_s
    while sum(1 for w in router.workers.values() if w.alive) < len(roles):
        router.step()
        dead = [p.returncode for p in procs if p.poll() is not None]
        if dead:
            raise RuntimeError(f"pod worker died before hello (rc={dead})")
        if _time.monotonic() > deadline:
            raise RuntimeError(
                f"only {sum(1 for w in router.workers.values() if w.alive)}"
                f"/{len(roles)} pod workers joined within {worker_wait_s}s")
        _time.sleep(0.05)
    return router, cfg, procs


def run_offered_load(
    engine,
    vocab_size: int,
    num_requests: int = 16,
    rate_hz: float = 50.0,
    prompt_len: tuple[int, int] = (4, 24),
    max_new_tokens: tuple[int, int] = (4, 16),
    temperature: float = 0.0,
    deadline_s: float | None = None,
    seed: int = 0,
    warmup_requests: int = 1,
    prefix_pool: int = 0,
    prefix_len: int = 0,
) -> dict:
    """Drive `num_requests` Poisson arrivals at `rate_hz` through the
    engine; returns the flat metrics summary plus load parameters.

    `warmup_requests` run to completion first (compile + first dispatch)
    and are excluded from the reported distributions.

    With `prefix_pool`/`prefix_len` set, prompts model shared-prefix
    traffic (system prompts, few-shot headers): each prompt is a prefix
    sampled from a pool of `prefix_pool` fixed `prefix_len`-token
    prefixes, plus a unique suffix drawn from `prompt_len`. The summary
    then carries `prefix_hit_rate` and `cached_token_fraction` from the
    engine's prefix-cache counters.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(0, vocab_size, (prefix_len,)).astype(np.int32)
                for _ in range(prefix_pool)] if prefix_pool and prefix_len \
        else []

    def make_prompt():
        n = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        suffix = rng.integers(0, vocab_size, (n,)).astype(np.int32)
        if not prefixes:
            return suffix
        return np.concatenate(
            [prefixes[int(rng.integers(len(prefixes)))], suffix])

    def budget():
        return int(rng.integers(max_new_tokens[0], max_new_tokens[1] + 1))

    for _ in range(warmup_requests):
        engine.submit(make_prompt(), max_new_tokens=budget(),
                      temperature=temperature)
    engine.run_until_idle()
    engine.reset_metrics()  # drop warmup samples; programs stay compiled

    gaps = rng.exponential(1.0 / rate_hz, size=num_requests)
    start = time.perf_counter()
    arrivals = start + np.cumsum(gaps)
    submitted = 0
    while submitted < num_requests or engine.scheduler.has_work():
        now = time.perf_counter()
        while submitted < num_requests and arrivals[submitted] <= now:
            engine.submit(make_prompt(), max_new_tokens=budget(),
                          temperature=temperature, deadline_s=deadline_s)
            submitted += 1
        if not engine.step() and submitted < num_requests:
            # idle before the next arrival: sleep to it (open loop)
            time.sleep(max(0.0, arrivals[submitted] - time.perf_counter()))

    out = engine.metrics_summary()
    out.update({
        "offered_rate_hz": rate_hz,
        "num_requests": float(num_requests),
        "wall_s": round(time.perf_counter() - start, 3),
    })
    if prefixes:
        out.update({"prefix_pool": float(prefix_pool),
                    "prefix_len": float(prefix_len)})
    return out


# ---------------------------------------------------------------------------
# multi-tenant HTTP harness
# ---------------------------------------------------------------------------


def parse_prometheus(text: str) -> dict:
    """Prometheus text exposition -> {(name, (('k','v'),...)): value}.
    Minimal on purpose (counters/gauges/summary quantiles as flat
    samples) — exactly what the attainment report needs."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        metric, _, raw = line.rpartition(" ")
        name, _, inner = metric.partition("{")
        labels = ()
        if inner:
            pairs = []
            for part in inner.rstrip("}").split(","):
                k, _, v = part.partition("=")
                pairs.append((k.strip(), v.strip().strip('"')))
            labels = tuple(sorted(pairs))
        try:
            out[(name, labels)] = float(raw)
        except ValueError:
            continue
    return out


def _prom_tenant(series: dict, name: str, tenant: str,
                 quantile: str | None = None) -> float | None:
    want = {("tenant", tenant)}
    if quantile is not None:
        want.add(("quantile", quantile))
    for (n, labels), v in series.items():
        if n == name and want <= set(labels):
            return v
    return None


def parse_tenant_load_arg(arg: str):
    """The harness grammar: TenantSpec fields + per-tenant load fields
    (`rate` arrivals/s for open loop, `concurrency` workers for closed
    loop). Returns (specs, {tenant: {"rate":…, "concurrency":…}})."""
    from accelerate_tpu.server.config import parse_tenants_arg

    return parse_tenants_arg(
        arg, extra_keys={"rate": float, "concurrency": int})


def load_trace(path: str) -> list[dict]:
    """Arrival-trace replay: JSONL of {"t": offset_s, "tenant": name,
    "prompt_len": N | "prompt": [ids], "max_new_tokens": M} sorted by t.
    Recorded once, replayed identically against any scheduler build —
    the apples-to-apples input for policy A/Bs."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return sorted(rows, key=lambda r: float(r.get("t", 0.0)))


def _arrival_offsets(mode: str, rate_hz: float, n: int, rng) -> list[float]:
    """Open-loop arrival schedule: seeded Poisson, or bursty (the same
    mean rate delivered as geometric bursts — the overload shape that
    separates an SLO-aware queue from a FIFO)."""
    if mode == "poisson":
        return list(rng.exponential(1.0 / rate_hz, size=n).cumsum())
    if mode == "burst":
        out, t, i = [], 0.0, 0
        while i < n:
            size = min(int(rng.geometric(0.25)), n - i)
            out.extend([t] * size)
            i += size
            t += size / rate_hz  # mean rate preserved
        return out
    raise ValueError(f"unknown arrival mode {mode!r} (poisson|burst)")


def run_http_load(
    engine,
    vocab_size: int,
    tenant_specs,
    tenant_load: dict,
    num_requests: int = 24,
    mode: str = "open",
    arrival: str = "poisson",
    prompt_len: tuple[int, int] = (4, 24),
    max_new_tokens: tuple[int, int] = (4, 16),
    temperature: float = 0.0,
    seed: int = 0,
    trace: list[dict] | None = None,
    model_id: str = "serve-bench",
) -> dict:
    """Stand up the real HTTP front door over `engine` (ephemeral port)
    and drive it with per-tenant client fleets; returns the flat summary
    with one `tenants.<name>.*` block per tenant, percentiles and SLO
    attainment sourced from the server's Prometheus /metrics route.

    `mode="open"`: each tenant fires `rate` arrivals/s (`arrival` =
    poisson|burst) until its share of `num_requests` is sent — queueing
    delay lands in TTFT, exactly like production. `mode="closed"`:
    `concurrency` workers per tenant in submit-wait-repeat — the
    saturation throughput view. `trace` overrides both with a recorded
    schedule."""
    import asyncio

    import numpy as np

    from accelerate_tpu.server.config import ServerConfig
    from accelerate_tpu.server.http import HttpFrontDoor
    from accelerate_tpu.server.service import InferenceService
    from accelerate_tpu.server.tokenizer import get_tokenizer

    rng = np.random.default_rng(seed)
    tenant_names = [t.name for t in tenant_specs] or ["default"]

    # compile the three programs OUTSIDE the measured window, then drop
    # the warmup samples (and the compile-poisoned step-time EMA the SLO
    # estimates would otherwise inherit)
    warm = engine.submit(np.arange(1, 5, dtype=np.int32) % vocab_size,
                         max_new_tokens=2)
    engine.run_until_idle()
    assert warm.status.value == "finished", warm.status
    engine.reset_metrics()

    def make_prompt_ids():
        n = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
        return rng.integers(0, vocab_size, (n,)).astype(int).tolist()

    def budget():
        return int(rng.integers(max_new_tokens[0], max_new_tokens[1] + 1))

    cfg = ServerConfig(port=0, model_id=model_id, tokenizer="numeric",
                       tenants=tuple(tenant_specs))
    service = InferenceService(engine, get_tokenizer("numeric", vocab_size),
                               cfg)
    door = HttpFrontDoor(service, cfg)
    # client-side books, per tenant
    obs = {t: {"sent": 0, "ok": 0, "shed_429": 0, "shed_stream": 0,
               "errors": 0, "client_ttft_s": [], "tokens": 0}
           for t in tenant_names}

    def _book(tenant: str) -> dict:
        # trace rows may name tenants outside --tenants (incl. the
        # implicit "default"); give them books instead of a KeyError
        return obs.setdefault(
            tenant, {"sent": 0, "ok": 0, "shed_429": 0, "shed_stream": 0,
                     "errors": 0, "client_ttft_s": [], "tokens": 0})

    async def one_request(port: int, tenant: str, body: dict) -> None:
        book = _book(tenant)
        book["sent"] += 1
        t0 = time.perf_counter()
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            payload = json.dumps(body).encode()
            writer.write(
                b"POST /v1/completions HTTP/1.1\r\nHost: bench\r\n"
                + f"X-Tenant: {tenant}\r\n".encode()
                + f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload)
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            status = int(head.split(b" ")[1])
            if status == 429:
                book["shed_429"] += 1
                writer.close()
                return
            if status != 200:
                book["errors"] += 1
                writer.close()
                return
            # SSE: first data frame carrying tokens = client TTFT
            first_at = None
            ntok = 0
            finish = None
            while True:
                frame = await reader.readuntil(b"\n\n")
                if frame.startswith(b"data: [DONE]"):
                    break
                row = json.loads(frame[len(b"data: "):])
                choice = row["choices"][0]
                ids = (choice.get("token_ids")
                       or choice.get("delta", {}).get("token_ids") or [])
                ntok += len(ids)
                if ids and first_at is None:
                    first_at = time.perf_counter()
                finish = choice.get("finish_reason") or finish
            if first_at is not None:
                book["client_ttft_s"].append(first_at - t0)
            book["tokens"] += ntok
            if finish == "overloaded":
                # admitted, then shed mid-wait: the stream closed with an
                # overload verdict instead of tokens
                book["shed_stream"] += 1
            else:
                book["ok"] += 1
            writer.close()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            book["errors"] += 1

    def body_for(tenant: str, prompt=None, max_toks=None) -> dict:
        return {"prompt": prompt or make_prompt_ids(),
                "max_tokens": max_toks or budget(),
                "temperature": temperature, "stream": True}

    async def open_loop(port: int) -> None:
        tasks = []
        if trace is not None:
            start = time.perf_counter()
            for row in trace:
                due = start + float(row.get("t", 0.0))
                await asyncio.sleep(max(0.0, due - time.perf_counter()))
                tenant = row.get("tenant", "default")
                prompt = row.get("prompt") or (
                    rng.integers(0, vocab_size,
                                 (int(row.get("prompt_len", 8)),))
                    .astype(int).tolist())
                tasks.append(asyncio.ensure_future(one_request(
                    port, tenant,
                    body_for(tenant, prompt, row.get("max_new_tokens")))))
        else:
            share = max(1, num_requests // max(1, len(tenant_names)))

            async def fleet(tenant: str) -> None:
                rate = tenant_load.get(tenant, {}).get("rate", 20.0)
                # zlib, not hash(): str hashing is salted per process and
                # would unseed the arrival schedule between runs
                import zlib

                offs = _arrival_offsets(
                    arrival, rate, share,
                    np.random.default_rng(
                        seed + zlib.adler32(tenant.encode()) % 10000))
                start = time.perf_counter()
                for off in offs:
                    await asyncio.sleep(
                        max(0.0, start + off - time.perf_counter()))
                    tasks.append(asyncio.ensure_future(
                        one_request(port, tenant, body_for(tenant))))

            await asyncio.gather(*(fleet(t) for t in tenant_names))
        if tasks:
            await asyncio.gather(*tasks)

    async def closed_loop(port: int) -> None:
        share = max(1, num_requests // max(1, len(tenant_names)))

        async def worker(tenant: str, n: int) -> None:
            for _ in range(n):
                await one_request(port, tenant, body_for(tenant))

        jobs = []
        for t in tenant_names:
            conc = max(1, tenant_load.get(t, {}).get("concurrency", 2))
            per = max(1, share // conc)
            jobs.extend(worker(t, per) for _ in range(conc))
        await asyncio.gather(*jobs)

    async def run() -> dict:
        await door.start()
        port = door.port
        t0 = time.perf_counter()
        if trace is not None or mode == "open":
            await open_loop(port)
        else:
            await closed_loop(port)
        # let in-flight engine work settle before the scrape
        while engine.scheduler.has_work():
            await asyncio.sleep(0.01)
        wall = time.perf_counter() - t0
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /metrics HTTP/1.1\r\nHost: bench\r\n"
                     b"Content-Length: 0\r\n\r\n")
        await writer.drain()
        raw = await reader.read()
        writer.close()
        prom = parse_prometheus(
            raw.partition(b"\r\n\r\n")[2].decode())
        await door.stop()
        return {"wall_s": wall, "prom": prom}

    res = asyncio.run(run())
    prom = res.pop("prom")
    out = engine.metrics_summary()
    out["wall_s"] = round(res["wall_s"], 3)
    out["mode"] = mode if trace is None else "trace"
    for t, book in sorted(obs.items()):
        row: dict = {
            "sent": book["sent"], "ok": book["ok"],
            "shed_429": book["shed_429"],
            "shed_stream": book["shed_stream"], "errors": book["errors"],
        }
        if book["client_ttft_s"]:
            arr = np.asarray(book["client_ttft_s"])
            row["client_ttft_p50_ms"] = float(np.percentile(arr, 50)) * 1e3
            row["client_ttft_p99_ms"] = float(np.percentile(arr, 99)) * 1e3
        # the Prometheus-sourced view: the same series a scrape reads
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            v = _prom_tenant(prom, "serving_ttft_seconds", t, str(q))
            if v is not None and v == v:
                row[f"ttft_{label}_ms"] = v * 1e3
        slo_total = _prom_tenant(prom, "serving_slo_total", t)
        slo_met = _prom_tenant(prom, "serving_slo_met_total", t)
        if slo_total:
            row["slo_total"] = slo_total
            row["slo_attainment"] = (slo_met or 0.0) / slo_total
        for name, key in (("serving_requests_finished_total", "finished"),
                          ("serving_requests_expired_total", "expired")):
            v = _prom_tenant(prom, name, t)
            if v is not None:
                row[key] = v
        for k, v in row.items():
            out[f"tenants.{t}.{k}"] = round(v, 4) if isinstance(v, float) \
                else v
    return out


def main() -> None:
    # script invocation puts benchmarks/ (not the repo root) on sys.path;
    # the lazy accelerate_tpu imports below need the root
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--family", default="llama", choices=("llama", "gpt2"))
    p.add_argument("--num-requests", type=int, default=16)
    p.add_argument("--rate-hz", type=float, default=50.0)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max-len", type=int, default=128)
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--prompt-len", type=int, nargs=2, default=(4, 24))
    p.add_argument("--max-new-tokens", type=int, nargs=2, default=(4, 16))
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--deadline-s", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prefix-pool", type=int, default=0,
                   help="shared-prefix workload: number of distinct "
                        "prefixes prompts draw from (0 = off)")
    p.add_argument("--prefix-len", type=int, default=0,
                   help="tokens per shared prefix; prompts become "
                        "prefix + unique suffix")
    p.add_argument("--page-size", type=int, default=16,
                   help="KV pool page size (prefix reuse is page-granular)")
    p.add_argument("--num-pages", type=int, default=None,
                   help="HBM page-pool size (default num_slots * "
                        "pages_per_slot). Shrink it under --prefix-pool "
                        "for the CHURN workload: a prefix pool bigger "
                        "than the HBM budget thrashes destructively "
                        "without a host tier and keeps hitting with one")
    p.add_argument("--host-tier-bytes", type=int, default=0,
                   help="host-DRAM overflow tier budget for evicted KV "
                        "pages (hierarchical KV): evictions swap out "
                        "instead of destroying, radix hits on "
                        "host-resident prefixes swap back in. 0 = off "
                        "(the A/B baseline on the same seeded trace)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable cross-request prefix reuse (paged cache "
                        "kept) — the A/B baseline")
    p.add_argument("--kv-dtype", default="bf16", choices=("bf16", "int8"),
                   help="KV pool storage: int8 stores codes + per-row "
                        "scales — half the bytes per page, 2x the pages "
                        "a fixed HBM budget holds (summary reports "
                        "kv_bytes_in_use and pages_capacity)")
    p.add_argument("--no-paged-attention", action="store_true",
                   help="force the dense-gather decode path (the Pallas "
                        "paged-attention kernel's A/B baseline; default "
                        "'auto' uses the kernel on single-device TPU)")
    p.add_argument("--speculative", action="store_true",
                   help="draft-model speculative decoding with a "
                        "self-draft (identical tiny model — accept rate "
                        "~1.0; random-init weights make any other pair "
                        "disagree, so this measures the mechanism: "
                        "tokens/decode-step, verify batching, MXU idle). "
                        "A/B against the same run without the flag.")
    p.add_argument("--draft-k", type=int, default=4,
                   help="draft tokens proposed per speculative step "
                        "(committed tokens per step range [1, draft_k])")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus /metrics while the load runs "
                        "(0 = ephemeral port, printed to stderr)")
    p.add_argument("--pod-roles", default=None, metavar="prefill=N,decode=M",
                   help="disaggregated-pod mode: drive the offered load "
                        "through serving.pod.PodEngine with N prefill and "
                        "M decode workers (KV pages ship between them)")
    p.add_argument("--pod-tp", type=int, default=1,
                   help="with --pod-roles: tensor-parallel width per "
                        "worker (mesh-sharded layer 1 under the pod)")
    p.add_argument("--pod-transport", default="local",
                   choices=("local", "socket"),
                   help="with --pod-roles: 'local' = in-process PodEngine "
                        "(default), 'socket' = real pod-worker OS "
                        "processes over TCP (serving.pod.distributed) — "
                        "the A/B prices the wire + process boundary")
    p.add_argument("--tenants", default=None,
                   help="multi-tenant HTTP harness: semicolon-separated "
                        "specs, e.g. 'gold:priority=0,weight=4,slo=0.3,"
                        "rate=10;bronze:rate=40' (rate = open-loop "
                        "arrivals/s, concurrency = closed-loop workers)")
    p.add_argument("--mode", default="open", choices=("open", "closed"),
                   help="HTTP harness loop shape (open = offered load, "
                        "closed = saturation)")
    p.add_argument("--arrival", default="poisson",
                   choices=("poisson", "burst"),
                   help="open-loop arrival process")
    p.add_argument("--trace", default=None, metavar="FILE",
                   help="replay a recorded JSONL arrival trace through "
                        "the HTTP harness instead of generating arrivals")
    p.add_argument("--pod-trace", action="store_true",
                   help="with --pod-transport socket: re-run the same "
                        "load with distributed tracing ON (100%% head "
                        "sampling, worker span export over heartbeats) "
                        "and report pod_trace_overhead_pct + span-export "
                        "lag — prices the tracing path itself")
    args = p.parse_args()

    if args.speculative and args.pod_roles:
        p.error("--speculative is not supported with --pod-roles "
                "(the pod's extract/install protocol drives the classic "
                "admit program; pod + speculation is a future arc)")
    if args.pod_transport == "socket" and not args.pod_roles:
        p.error("--pod-transport socket requires --pod-roles")
    if args.pod_transport == "socket" and args.pod_tp > 1:
        p.error("--pod-transport socket does not compose with --pod-tp "
                "(each worker process owns its whole backend)")
    if args.pod_trace and args.pod_transport != "socket":
        p.error("--pod-trace requires --pod-transport socket (the span "
                "export + clock alignment under test only exist across "
                "a real process boundary)")
    if args.tenants or args.trace:
        specs, loads = parse_tenant_load_arg(args.tenants or "")
        engine, cfg = build_tiny_engine(
            args.family, num_slots=args.slots, max_len=args.max_len,
            prefill_chunk=args.prefill_chunk, seed=args.seed,
            page_size=args.page_size,
            prefix_cache=not args.no_prefix_cache, tenants=specs,
            kv_dtype=None if args.kv_dtype == "bf16" else args.kv_dtype,
            paged_attention=False if args.no_paged_attention else "auto",
            speculative=args.speculative, draft_k=args.draft_k)
        summary = run_http_load(
            engine, cfg.vocab_size, specs, loads,
            num_requests=args.num_requests, mode=args.mode,
            arrival=args.arrival, prompt_len=tuple(args.prompt_len),
            max_new_tokens=tuple(args.max_new_tokens),
            temperature=args.temperature, seed=args.seed,
            trace=load_trace(args.trace) if args.trace else None)
        print(json.dumps({
            "metric": "serving_tokens_per_sec",
            "value": round(summary.get("tokens_per_sec", 0.0), 2),
            "unit": "tokens/s",
            "extra": {k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in summary.items()},
        }))
        return

    # a shared-prefix workload must fit prefix + suffix + budget in a
    # slot; grow max_len rather than silently rejecting every request
    max_len = args.max_len
    if args.prefix_pool and args.prefix_len:
        max_len = max(max_len, args.prefix_len + args.prompt_len[1]
                      + args.max_new_tokens[1])
    pod_procs = None
    if args.pod_roles and args.pod_transport == "socket":
        engine, cfg, pod_procs = build_tiny_distributed_pod(
            args.family, pod_roles=parse_pod_roles(args.pod_roles),
            num_slots=args.slots, max_len=max_len,
            prefill_chunk=args.prefill_chunk, seed=args.seed,
            page_size=args.page_size,
            prefix_cache=not args.no_prefix_cache,
            metrics_port=args.metrics_port,
            kv_dtype=None if args.kv_dtype == "bf16" else args.kv_dtype)
    elif args.pod_roles:
        engine, cfg = build_tiny_pod_engine(
            args.family, pod_roles=parse_pod_roles(args.pod_roles),
            tensor_parallel=args.pod_tp, num_slots=args.slots,
            max_len=max_len, prefill_chunk=args.prefill_chunk,
            seed=args.seed, page_size=args.page_size,
            prefix_cache=not args.no_prefix_cache,
            metrics_port=args.metrics_port,
            kv_dtype=None if args.kv_dtype == "bf16" else args.kv_dtype,
            paged_attention=False if args.no_paged_attention else "auto",
            num_pages=args.num_pages,
            host_tier_bytes=args.host_tier_bytes)
    else:
        engine, cfg = build_tiny_engine(
            args.family, num_slots=args.slots, max_len=max_len,
            prefill_chunk=args.prefill_chunk, seed=args.seed,
            page_size=args.page_size, prefix_cache=not args.no_prefix_cache,
            metrics_port=args.metrics_port,
            kv_dtype=None if args.kv_dtype == "bf16" else args.kv_dtype,
            paged_attention=False if args.no_paged_attention else "auto",
            speculative=args.speculative, draft_k=args.draft_k,
            num_pages=args.num_pages,
            host_tier_bytes=args.host_tier_bytes)
    if engine.metrics_server is not None:
        import sys

        print(f"serving Prometheus metrics on "
              f":{engine.metrics_server.port}/metrics", file=sys.stderr)
    try:
        summary = run_offered_load(
            engine, cfg.vocab_size, num_requests=args.num_requests,
            rate_hz=args.rate_hz, prompt_len=tuple(args.prompt_len),
            max_new_tokens=tuple(args.max_new_tokens),
            temperature=args.temperature, deadline_s=args.deadline_s,
            seed=args.seed, prefix_pool=args.prefix_pool,
            prefix_len=args.prefix_len)
    finally:
        if pod_procs is not None:
            engine.close()   # drains the workers, closes every channel
            for proc in pod_procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc in pod_procs:
                try:
                    proc.wait(timeout=15)
                except Exception:
                    proc.kill()
    if args.pod_roles:
        summary["pod_transport"] = args.pod_transport
    if args.pod_trace and pod_procs is not None:
        # second arm: identical load, tracing ON. The baseline pod is
        # already closed, so the two arms never share a port or a worker
        engine2, _, procs2 = build_tiny_distributed_pod(
            args.family, pod_roles=parse_pod_roles(args.pod_roles),
            num_slots=args.slots, max_len=max_len,
            prefill_chunk=args.prefill_chunk, seed=args.seed,
            page_size=args.page_size,
            prefix_cache=not args.no_prefix_cache,
            kv_dtype=None if args.kv_dtype == "bf16" else args.kv_dtype,
            trace=True)
        try:
            traced = run_offered_load(
                engine2, cfg.vocab_size, num_requests=args.num_requests,
                rate_hz=args.rate_hz, prompt_len=tuple(args.prompt_len),
                max_new_tokens=tuple(args.max_new_tokens),
                temperature=args.temperature, deadline_s=args.deadline_s,
                seed=args.seed, prefix_pool=args.prefix_pool,
                prefix_len=args.prefix_len)
        finally:
            engine2.close()
            for proc in procs2:
                if proc.poll() is None:
                    proc.terminate()
            for proc in procs2:
                try:
                    proc.wait(timeout=15)
                except Exception:
                    proc.kill()
            from accelerate_tpu.telemetry.trace import configure_tracing

            configure_tracing(enabled=False)
        base_tps = summary.get("tokens_per_sec", 0.0)
        traced_tps = traced.get("tokens_per_sec", 0.0)
        summary["pod_traced_tokens_per_sec"] = traced_tps
        if base_tps:
            summary["pod_trace_overhead_pct"] = \
                (1.0 - traced_tps / base_tps) * 100.0
        summary["pod_spans_ingested"] = traced.get("pod_spans_ingested", 0.0)
        if "pod_span_export_lag_s" in traced:
            summary["pod_span_export_lag_s"] = traced["pod_span_export_lag_s"]
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": round(summary.get("tokens_per_sec", 0.0), 2),
        "unit": "tokens/s",
        "extra": {k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in summary.items()},
    }))


if __name__ == "__main__":
    main()
