"""Micro-bench: attention op alone, einsum vs flash block configs.

Times fwd+bwd of the attention op on the bench shape; used to tune
flash_attention block sizes and the llama 'auto' backend threshold.
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from accelerate_tpu.models.common import dot_product_attention
from accelerate_tpu.ops.flash_attention import flash_attention

B, H, D = 8, 12, 128
SEQ = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
STEPS = 30

key = jax.random.key(0)
q = jax.random.normal(key, (B, SEQ, H, D), jnp.bfloat16)
k = jax.random.normal(jax.random.fold_in(key, 1), (B, SEQ, H, D), jnp.bfloat16)
v = jax.random.normal(jax.random.fold_in(key, 2), (B, SEQ, H, D), jnp.bfloat16)

# causal attention flops (fwd): 2 matmuls, half the S^2 positions live
flops_fwd = 2 * (2 * B * H * SEQ * SEQ * D) / 2
flops_tot = 3 * flops_fwd


def bench(name, fn):
    f = jax.jit(jax.grad(lambda q: jnp.sum(fn(q).astype(jnp.float32))))
    try:
        out = f(q)
        float(jnp.ravel(out)[0])
    except Exception as e:  # noqa: BLE001
        print(f"{name:28s}: FAILED {type(e).__name__}: {str(e)[:100]}", flush=True)
        return
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = f(q)
        float(jnp.ravel(out)[0])
        best = min(best, time.perf_counter() - t0)
    ms = best / STEPS * 1000
    tflops = flops_tot / (best / STEPS) / 1e12
    print(f"{name:28s}: {ms:7.2f} ms  {tflops:7.1f} TF/s", flush=True)


bench("einsum", lambda q: dot_product_attention(q, k, v, causal=True))
for bq, bk in [(128, 128), (256, 256), (256, 512), (512, 512), (512, 1024),
               (1024, 1024)]:
    bench(f"flash bq={bq} bk={bk}",
          lambda q, bq=bq, bk=bk: flash_attention(
              q, k, v, causal=True, block_q=bq, block_k=bk))
