"""Driver for the reference-benchmark table rows with real forensics.

Runs `big_model_inference.py` for each requested preset as a subprocess,
appending stdout JSON lines to `bench_results/<preset>.jsonl` and capturing
FULL stderr (not just the platform warning) into `bench_results/<preset>.err`
together with the exit code, phase timings, and the kill reason on timeout —
so a decode that dies leaves a diagnosis behind (VERDICT r3 weak #7/item 10).

Run: python benchmarks/run_big_model_rows.py [preset ...]
     (default: the four reference rows, ref benchmarks/README.md:29-35)

Timeouts scale with the tunnel reality: a streamed NeoX/OPT decode moves
the full stacked-layer bytes per token over the host->device link, so one
token at ~0.14 GB/s is minutes, not seconds. `--timeout` overrides.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(ROOT, "bench_results")

DEFAULT_ROWS = ["gptj-6b", "t0pp", "gpt-neox-20b", "opt-30b"]
# generous wall-clock ceilings per preset (load + compile + decode)
TIMEOUTS = {
    "gptj-6b": 3600,
    "t0pp": 5400,
    "gpt-neox-20b": 14400,
    "opt-30b": 18000,
}


def run_preset(preset: str, timeout: int | None, extra_args: list[str]) -> int:
    os.makedirs(RESULTS, exist_ok=True)
    out_path = os.path.join(RESULTS, f"{preset}.jsonl")
    err_path = os.path.join(RESULTS, f"{preset}.err")
    cmd = [sys.executable, os.path.join(ROOT, "benchmarks",
                                        "big_model_inference.py"),
           "--preset", preset, *extra_args]
    limit = timeout or TIMEOUTS.get(preset, 3600)
    t0 = time.time()
    with open(err_path, "w") as err:
        err.write(f"# cmd: {' '.join(cmd)}\n# started: {time.ctime()}\n")
        err.flush()
        try:
            proc = subprocess.run(
                cmd, stdout=subprocess.PIPE, stderr=err, text=True,
                timeout=limit,
            )
            rc, stdout = proc.returncode, proc.stdout
        except subprocess.TimeoutExpired as e:
            rc = -1
            stdout = (e.stdout or b"").decode() if isinstance(
                e.stdout, bytes) else (e.stdout or "")
            err.write(f"\n# KILLED: exceeded {limit}s wall clock\n")
        err.write(f"# finished: {time.ctime()} rc={rc} "
                  f"wall={time.time() - t0:.1f}s\n")
    lines = [ln for ln in stdout.splitlines() if ln.startswith("{")]
    with open(out_path, "a") as f:
        for ln in lines:
            f.write(ln + "\n")
    print(f"{preset}: rc={rc}, {len(lines)} row(s), "
          f"wall={time.time() - t0:.1f}s -> {out_path}")
    if rc != 0:
        tail = open(err_path).read().splitlines()[-8:]
        print("\n".join(f"  err| {ln}" for ln in tail))
    return rc


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("presets", nargs="*", default=DEFAULT_ROWS)
    ap.add_argument("--timeout", type=int, default=None)
    ap.add_argument("--new_tokens", type=int, default=None)
    args = ap.parse_args()
    extra = (["--new_tokens", str(args.new_tokens)]
             if args.new_tokens else [])
    rcs = [run_preset(p, args.timeout, extra) for p in args.presets]
    sys.exit(max((abs(rc) for rc in rcs), default=0))


if __name__ == "__main__":
    main()
