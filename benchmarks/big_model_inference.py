"""Big-model inference benchmark: checkpoint load time + per-token decode,
on the REAL reference model families.

Mirror of ref benchmarks/big_model_inference.py (the reference's ONLY
published benchmark — benchmarks/README.md:25-36):

    model         | ref hardware      | ref load | ref s/token
    GPT-J-6B      | 2x Titan RTX fp16 |   8.7 s  | 0.05
    GPT-J-6B      | cpu-offload fp32  |  57  s   | 1.04
    GPT-NeoX-20B  | cpu-offload fp16  |  ~12 s   | 14.5
    T0pp (11B)    | 2x Titan RTX fp16 |  29  s   | 0.05-0.12
    OPT-30B       | cpu-offload fp16  |  ~12 s   | 10+

Zero-egress: a synthetic safetensors checkpoint with the model's EXACT
architecture (the real GPTJConfig/GPTNeoXConfig/OPTConfig/T5Config defaults
ARE the 6B/20B/30B/11B published sizes) is written once, then timed through
the real load path (init_empty_weights -> device-map plan -> streamed
safetensors load -> dispatch) and the family's KV-cache greedy decode:
- models that fit the chip (gptj-6b, and t0pp's decoder half) decode
  on-device at HBM rate;
- models larger than device memory (gpt-neox-20b, opt-30b) use
  `streamed_generate`: weights stream host->device double-buffered per
  layer, per token — the analogue of the reference's cpu-offload rows.
  `extra.streamed_gb_per_token` reports the traffic so s/token can be
  scaled to any host link (this harness tunnels to the TPU at ~0.14 GB/s;
  a real TPU-VM host link is 2-3 orders faster).

Run: python benchmarks/big_model_inference.py --preset gptj-6b
     (presets: tiny-<family> for smoke, <family>-XXb for the real rows)
Prints one JSON line per phase.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _configs():
    from accelerate_tpu.models import gpt_neox, gptj, opt, t5

    # default config == the published size for each family
    return {
        "gptj-6b": ("gptj", gptj.GPTJConfig()),
        "gpt-neox-20b": ("gpt_neox", gpt_neox.GPTNeoXConfig()),
        "opt-30b": ("opt", opt.OPTConfig()),
        "t0pp": ("t5", t5.T5Config()),
        "tiny-gptj": ("gptj", gptj.GPTJConfig.tiny()),
        "tiny-gpt-neox": ("gpt_neox", gpt_neox.GPTNeoXConfig.tiny()),
        "tiny-opt": ("opt", opt.OPTConfig.tiny()),
        "tiny-t5": ("t5", t5.T5Config.tiny()),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="tiny-gptj",
                        choices=sorted(_configs()))
    parser.add_argument("--offload", action="store_true",
                        help="force host RAM placement + streamed decode "
                             "even if the model would fit")
    parser.add_argument("--new_tokens", type=int, default=None,
                        help="default: 32 on-chip, 3 streamed")
    parser.add_argument("--prompt_len", type=int, default=32)
    parser.add_argument("--checkpoint", default=None,
                        help="existing checkpoint dir (else synthesized)")
    args = parser.parse_args()

    import importlib

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # the hosted image pins jax_platforms to the tunnel backend at
        # import time, overriding the env var — honor the caller's CPU
        # request (same fix as tests/conftest.py and bench.py)
        from accelerate_tpu.utils.environment import force_cpu_platform

        force_cpu_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu import init_empty_weights, load_checkpoint_and_dispatch
    from accelerate_tpu.checkpointing import save_model
    from accelerate_tpu.models.common import count_params

    family, cfg = _configs()[args.preset]
    mod = importlib.import_module(f"accelerate_tpu.models.{family}")
    tiny = args.preset.startswith("tiny")
    dtype = jnp.float32 if tiny else jnp.bfloat16

    shapes = jax.eval_shape(
        lambda: mod.init_params(cfg, jax.random.key(0), dtype=dtype)
    )
    n_bytes = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(shapes)
    )
    dev_mem = getattr(jax.devices()[0], "memory_stats", lambda: None)() or {}
    hbm = dev_mem.get("bytes_limit", 16 * 2**30)
    # resident decode needs weights + caches + programs; 80% HBM is the
    # practical ceiling (same margin utils/modeling.get_balanced_memory uses)
    streamed = args.offload or n_bytes > 0.8 * hbm

    ckpt = args.checkpoint
    tmp = None
    if ckpt is None:
        tmp = tempfile.mkdtemp(dir=os.environ.get("BENCH_TMPDIR"))
        ckpt = os.path.join(tmp, "model")
        # synthesize HOST-side (numpy from eval_shape): initializing on a
        # remote/tunneled device and pulling the weights back would time the
        # tunnel, not the load path this benchmark measures. zeros: timing is
        # value-independent (decode FLOPs/bytes identical) and writing GBs of
        # zeros is instant vs sampling billions of normals
        params = jax.tree_util.tree_map(
            lambda l: np.zeros(l.shape, l.dtype), shapes
        )
        save_model(params, ckpt, max_shard_size="2GB")
        del params

    # --- timed load: abstract init -> plan -> streamed safetensors -> place
    t0 = time.perf_counter()
    shapes = init_empty_weights(mod.init_params, cfg, jax.random.key(0),
                                dtype=dtype)
    if streamed:
        # layers stay in host RAM for the streaming decode; small resident
        # modules (embeddings, norms, head) go to the device. For t5 the
        # DECODER half is placed on device at load time (it runs every
        # token; load-time placement matches the reference's accounting,
        # where `load` puts weights wherever they will execute) — only the
        # run-once encoder streams per prompt.
        stacked = "encoder" if family == "t5" else "layers"
        device_map = {
            name: ("cpu" if name == stacked else 0) for name in shapes
        }
    else:
        device_map = "auto"
    params = load_checkpoint_and_dispatch(shapes, ckpt, device_map=device_map)
    load_s = time.perf_counter() - t0
    n_params = count_params(params)
    print(json.dumps({
        "metric": "big_model_load_seconds",
        "value": round(load_s, 2),
        "unit": "s",
        "extra": {"preset": args.preset, "params": n_params,
                  "bytes": n_bytes, "streamed": streamed},
    }), flush=True)

    # --- timed decode (greedy, KV cache)
    new_tokens = args.new_tokens or (3 if streamed and not tiny else 32)
    vocab = getattr(cfg, "vocab_size")
    ids = np.random.default_rng(0).integers(
        4, vocab, (1, args.prompt_len)).astype(np.int32)

    if streamed:
        gen = lambda: mod.streamed_generate(  # noqa: E731
            cfg, params, ids, max_new_tokens=new_tokens, dtype=dtype)
    else:
        gen = lambda: mod.generate(  # noqa: E731
            cfg, params, ids, max_new_tokens=new_tokens)

    t0 = time.perf_counter()
    out = gen()
    jax.block_until_ready(out)
    first = time.perf_counter() - t0  # includes compile
    t0 = time.perf_counter()
    out = gen()
    jax.block_until_ready(out)
    decode_s = time.perf_counter() - t0
    extra = {
        "preset": args.preset, "new_tokens": new_tokens,
        "first_call_with_compile_s": round(first, 2),
        "mode": "streamed-offload" if streamed else "on-device",
    }
    if streamed:
        # per generated token, every stacked layer's weights cross the
        # host->device link once; for t5 the decoder is resident and only
        # the run-once encoder streams, PER PROMPT not per token
        if family == "t5":
            enc_bytes = sum(
                int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(shapes["encoder"])
            )
            extra["streamed_gb_per_prompt"] = round(enc_bytes / 2**30, 2)
        else:
            stacked_bytes = sum(
                int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(shapes["layers"])
            )
            extra["streamed_gb_per_token"] = round(stacked_bytes / 2**30, 2)
    print(json.dumps({
        "metric": "big_model_seconds_per_token",
        "value": round(decode_s / new_tokens, 4),
        "unit": "s/token",
        "extra": extra,
    }), flush=True)
    if tmp:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
