"""Big-model inference benchmark: checkpoint load time + per-token decode.

Mirror of ref benchmarks/big_model_inference.py (the reference's ONLY
published benchmark — GPT-J/NeoX/OPT load + generate times,
benchmarks/README.md:25-36). Zero-egress: a synthetic safetensors checkpoint
is written once, then timed through the real load path
(init_empty_weights -> device-map plan -> streamed safetensors load ->
dispatch) and the KV-cache greedy decode.

Run: python benchmarks/big_model_inference.py [--preset 1b|tiny] [--offload]
Prints one JSON line per phase.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="tiny", choices=["tiny", "1b", "6b"])
    parser.add_argument("--offload", action="store_true",
                        help="force host-offload of half the layers")
    parser.add_argument("--new_tokens", type=int, default=32)
    parser.add_argument("--checkpoint", default=None,
                        help="existing checkpoint dir (else synthesized)")
    args = parser.parse_args()

    import jax
    import numpy as np

    from accelerate_tpu import init_empty_weights, load_checkpoint_and_dispatch
    from accelerate_tpu.checkpointing import save_model
    from accelerate_tpu.models import llama
    from accelerate_tpu.models.common import count_params

    if args.preset == "6b":
        # GPT-J-6B-scale causal LM (the reference table's headline row,
        # benchmarks/README.md:29: 8.7 s load / 0.05 s/token fp16 on
        # 2x Titan RTX). bf16 checkpoint so the 6B fits one 16 GB chip.
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_hidden_layers=28, num_attention_heads=32, num_key_value_heads=32,
            max_position_embeddings=2048,
        )
    elif args.preset == "1b":
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=22, num_attention_heads=16, num_key_value_heads=16,
            max_position_embeddings=2048,
        )
    else:
        cfg = llama.LlamaConfig(
            vocab_size=2048, hidden_size=256, intermediate_size=704,
            num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=8,
            max_position_embeddings=512,
        )

    import jax.numpy as jnp

    dtype = jnp.bfloat16 if args.preset == "6b" else jnp.float32
    ckpt = args.checkpoint
    tmp = None
    if ckpt is None:
        tmp = tempfile.mkdtemp()
        ckpt = os.path.join(tmp, "model")
        # synthesize HOST-side (numpy from eval_shape): initializing on a
        # remote/tunneled device and pulling the weights back would time the
        # tunnel, not the load path this benchmark measures
        shapes = jax.eval_shape(
            lambda: llama.init_params(cfg, jax.random.key(0), dtype=dtype)
        )
        # zeros: value-independent timing (generation FLOPs/bytes identical),
        # and writing GBs of zeros is instant vs sampling billions of normals
        params = jax.tree_util.tree_map(
            lambda l: np.zeros(l.shape, l.dtype), shapes
        )
        save_model(params, ckpt, max_shard_size="512MB")
        del params

    # --- timed load: abstract init -> plan -> streamed safetensors -> place
    t0 = time.perf_counter()
    shapes = init_empty_weights(llama.init_params, cfg, jax.random.key(0))
    max_memory = None
    if args.offload:
        # leave room for only ~half the params on device; rest goes to host
        n_bytes = sum(
            int(np.prod(l.shape)) * 4 for l in jax.tree_util.tree_leaves(shapes)
        )
        max_memory = {0: n_bytes // 2, "cpu": n_bytes * 2}
    params = load_checkpoint_and_dispatch(
        shapes, ckpt, device_map="auto", max_memory=max_memory,
    )
    load_s = time.perf_counter() - t0
    n_params = count_params(params)
    print(json.dumps({
        "metric": "big_model_load_seconds",
        "value": round(load_s, 2),
        "unit": "s",
        "extra": {"params": n_params, "offload": bool(args.offload)},
    }))

    # --- timed decode (greedy, KV cache)
    ids = np.random.default_rng(0).integers(
        4, cfg.vocab_size, (1, 32)).astype(np.int32)
    t0 = time.perf_counter()
    out = llama.generate(cfg, params, ids, max_new_tokens=args.new_tokens)
    jax.block_until_ready(out)
    first = time.perf_counter() - t0  # includes compile
    t0 = time.perf_counter()
    out = llama.generate(cfg, params, ids, max_new_tokens=args.new_tokens)
    np.asarray(out)
    decode_s = time.perf_counter() - t0
    print(json.dumps({
        "metric": "big_model_seconds_per_token",
        "value": round(decode_s / args.new_tokens, 4),
        "unit": "s/token",
        "extra": {"new_tokens": args.new_tokens,
                  "first_call_with_compile_s": round(first, 2)},
    }))
    if tmp:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
