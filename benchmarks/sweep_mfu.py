"""MFU sweep for the single-chip Llama bench (bench.py's config).

Tries attention backend x remat policy x batch and prints one line per
config; used to pick bench.py's settings (VERDICT r1 item 1).
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.models.common import count_params
from accelerate_tpu.utils.constants import TPU_PEAK_FLOPS


def run(backend: str, remat: bool, policy: str, batch: int, seq: int = 2048,
        steps: int = 20) -> None:
    cfg = llama.LlamaConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=4096,
        num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=4,
        max_position_embeddings=seq, remat=remat, remat_policy=policy,
        attention_backend=backend,
    )
    acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params,
                                       tx=optax.adamw(3e-4)))
    n_params = count_params(ts.params)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (batch_arrays,) = list(loader)
    step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
    try:
        ts, m = step(ts, batch_arrays)
        jax.block_until_ready(m["loss"])
    except Exception as e:  # noqa: BLE001
        print(f"{backend:7s} remat={remat!s:5s}/{policy:4s} b={batch:3d}: "
              f"FAILED {type(e).__name__}: {str(e)[:120]}", flush=True)
        return
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, m = step(ts, batch_arrays)
        float(m["loss"])
        best = min(best, time.perf_counter() - t0)
    tok_s = batch * seq * steps / best
    attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops
    device_kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peak = next((v for k, v in TPU_PEAK_FLOPS.items() if k in device_kind), 197e12)
    mfu = flops_per_token * tok_s / peak
    print(f"{backend:7s} remat={remat!s:5s}/{policy:4s} b={batch:3d}: "
          f"{tok_s:9.1f} tok/s  mfu={mfu:.4f}", flush=True)


if __name__ == "__main__":
    configs = [
        ("einsum", True, "full", 16),   # round-1 baseline
        ("einsum", True, "dots", 16),
        ("flash", True, "full", 16),
        ("flash", True, "dots", 16),
        ("flash", False, "full", 16),
        ("flash", True, "dots", 32),
    ]
    if len(sys.argv) > 1:  # e.g. "flash,True,dots,16"
        b, r, p, bs = sys.argv[1].split(",")
        configs = [(b, r == "True", p, int(bs))]
    for c in configs:
        run(*c)
