"""fp8 vs bf16 train-step throughput on one chip (VERDICT r4 #8).

The fp8 path is correctness-tested everywhere (tests/test_quant_fp8.py);
this measures whether it is also *fast* on the present hardware. The
expectation, stated in docs/fp8.md: v5-lite has no fp8 MXU, XLA upcasts
the float8 operands, so fp8 should be AT BEST neutral vs bf16 there —
the win appears on fp8-capable parts (v5p+/trillium). Whichever way it
comes out, the measured row replaces the guess.

Run: python benchmarks/fp8_vs_bf16.py
Prints one JSON line per precision:
  {"metric": "fp8_vs_bf16_tokens_per_sec", "precision": ..., ...}
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.models.common import count_params
from accelerate_tpu.state import PartialState


def run(precision: str, steps: int = 15) -> dict:
    PartialState._reset_state()
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_hidden_layers=12, num_attention_heads=12,
            num_key_value_heads=4, max_position_embeddings=2048,
            remat=True, remat_policy="dots",
        )
        batch, seq = 8, 2048
    else:  # smoke config so the script is runnable in CI
        cfg = llama.LlamaConfig.tiny()
        batch, seq, steps = 4, 64, 3

    acc = Accelerator(mixed_precision=precision, gradient_clipping=1.0)
    params = llama.init_params(cfg, jax.random.key(0))
    fp8_state = llama.init_fp8_state(cfg) if precision == "fp8" else None
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(3e-4),
        fp8_state=fp8_state,
    ))
    n_params = count_params(ts.params)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (batch_arrays,) = list(loader)
    step = acc.train_step(lambda p, b, **kw: llama.causal_lm_loss(cfg, p, b, **kw))
    ts, m = step(ts, batch_arrays)
    jax.block_until_ready(m["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, m = step(ts, batch_arrays)
        float(m["loss"])
        best = min(best, time.perf_counter() - t0)
    tps = batch * seq * steps / best / jax.device_count()
    return {
        "metric": "fp8_vs_bf16_tokens_per_sec",
        "precision": precision,
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "extra": {
            "params": n_params, "batch": batch, "seq": seq, "steps": steps,
            "device": getattr(jax.devices()[0], "device_kind", "cpu").lower(),
        },
    }


def main() -> None:
    rows = [run("bf16"), run("fp8")]
    for r in rows:
        print(json.dumps(r))
    if rows[0]["value"] and rows[1]["value"]:
        ratio = rows[1]["value"] / rows[0]["value"]
        print(json.dumps({
            "metric": "fp8_over_bf16_speedup", "value": round(ratio, 3),
            "unit": "x",
        }))


if __name__ == "__main__":
    main()
