"""BASELINE.md row 1: `examples/nlp_example.py` steps/sec/chip.

The reference publishes no number for its nlp_example (BASELINE.md:36 —
"to be measured"); this captures ours on whatever chip is visible:
BERT-base (or --tiny) on the example's synthetic MRPC batches, the same
fused train_step the example runs, steps/sec over a timed window after a
compile warmup. Prints ONE JSON line; appended to
`bench_results/nlp_steps.jsonl` by the Makefile-style invocation in
docs/benchmarking.md.

Run: python benchmarks/nlp_steps.py [--tiny] [--batch 32] [--steps 30]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--mixed_precision", default="bf16")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from accelerate_tpu.utils.environment import force_cpu_platform

        force_cpu_platform()  # hosted image pins axon; env var alone loses
    import jax
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import bert
    from nlp_example import get_dataloaders

    acc = Accelerator(mixed_precision=args.mixed_precision,
                      gradient_clipping=1.0)
    cfg = bert.BertConfig.tiny() if args.tiny else bert.BertConfig.base()
    train_loader, _ = get_dataloaders(acc, args.batch, cfg)
    params = bert.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(2e-5)))
    step = acc.train_step(
        lambda p, b: bert.classification_loss(cfg, p, b)
    )
    batches = list(train_loader)
    ts, m = step(ts, batches[0])  # compile + warmup
    float(m["loss"])

    done = 0
    t0 = time.perf_counter()
    while done < args.steps:
        for b in batches:
            ts, m = step(ts, b)
            done += 1
            if done >= args.steps:
                break
    float(m["loss"])  # block
    dt = time.perf_counter() - t0
    n_chips = jax.device_count()
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "nlp_example_steps_per_sec_per_chip",
        "value": round(args.steps / dt / n_chips, 3),
        "unit": "steps/s/chip",
        "extra": {
            "model": "bert-tiny" if args.tiny else "bert-base",
            "batch": args.batch, "steps": args.steps,
            "wall_s": round(dt, 2), "n_chips": n_chips,
            "device": getattr(dev, "device_kind", dev.platform),
            "mixed_precision": args.mixed_precision,
        },
    }))


if __name__ == "__main__":
    main()
