"""Bench regression gate, script form (ISSUE 11).

Compares two bench rows (bench.py one-line JSON, or BENCH_r*.json
capture files wrapping the row under "parsed") with per-metric relative
tolerances and direction-aware semantics — tokens/s falling and
ttft_p99_ms rising are both regressions; configuration fields (seq,
params, wall_s, device) are never compared::

    python benchmarks/regression.py BENCH_r02.json new.json \
        --tolerance 0.05 --metric-tolerance ttft_p99_ms=0.25

Exit codes: 0 = pass, 1 = regression (a compared metric moved worse
than its tolerance, or a headline/phase row went value -> error),
2 = malformed input. This is the same gate as `accelerate-tpu
bench-diff` (accelerate_tpu/commands/bench_diff.py owns the logic); the
script form exists so the r01-r05 trajectory can be checked from a bare
checkout: `python benchmarks/regression.py BENCH_r01.json BENCH_r02.json`.
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__":
    # script invocation puts benchmarks/ (not the repo root) on sys.path
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from accelerate_tpu.commands.bench_diff import main

    sys.exit(main(sys.argv[1:]))
