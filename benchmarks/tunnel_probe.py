"""Measure the real host->device link: bandwidth and per-call latency.

Settles the load-path question from VERDICT r3 weak #4: the big-model load
moved bytes at 39-76 MB/s against a claimed ~140 MB/s tunnel. This probe
times raw `jax.device_put` at several sizes, separating per-call fixed cost
(dominates small tensors — a checkpoint has thousands) from asymptotic
bandwidth (dominates the stacked-layer megatensors). Compare
`bench_results/tunnel_probe.jsonl` with the load rate: if device_put at
256 MB reaches ~2x the observed load rate, the loader's per-tensor
round-trips are the factor; if it doesn't, the claim in
big_model_inference.py:26-28 is what needs correcting.

Run: python benchmarks/tunnel_probe.py   (prints one JSON line)
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import numpy as np

    dev = jax.devices()[0]
    sizes_mb = [1, 16, 64, 256]
    rows = {}
    for mb in sizes_mb:
        arr = np.zeros((mb * 2**20 // 4,), np.float32)
        # warm once (allocator, program setup)
        jax.block_until_ready(jax.device_put(arr, dev))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(arr, dev))
            best = min(best, time.perf_counter() - t0)
        rows[f"{mb}MB"] = {
            "seconds": round(best, 4),
            "MB_per_s": round(mb / best, 1),
        }
    # per-call fixed cost via a tiny transfer
    tiny = np.zeros((16,), np.float32)
    jax.block_until_ready(jax.device_put(tiny, dev))
    t0 = time.perf_counter()
    n = 50
    for _ in range(n):
        jax.block_until_ready(jax.device_put(tiny, dev))
    per_call_ms = (time.perf_counter() - t0) / n * 1e3
    print(json.dumps({
        "metric": "host_device_link",
        "value": rows["256MB"]["MB_per_s"],
        "unit": "MB/s@256MB",
        "extra": {"sizes": rows, "per_call_ms": round(per_call_ms, 2),
                  "device": str(dev)},
    }))


if __name__ == "__main__":
    main()
