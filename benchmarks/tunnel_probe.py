"""Measure the real host->device link: bandwidth and per-call latency.

Settles the load-path question from VERDICT r3 weak #4: the big-model load
moved bytes at 39-76 MB/s against a claimed ~140 MB/s tunnel. This probe
times raw `jax.device_put` at several sizes, separating per-call fixed cost
(dominates small tensors — a checkpoint has thousands) from asymptotic
bandwidth (dominates the stacked-layer megatensors). Compare
`bench_results/tunnel_probe.jsonl` with the load rate: if device_put at
256 MB reaches ~2x the observed load rate, the loader's per-tensor
round-trips are the factor; if it doesn't, the claim in
big_model_inference.py:26-28 is what needs correcting.

The tunnel flaps (down since r03): a transient drop no longer fails the
probe on the spot — attempts retry with exponential backoff
(`TUNNEL_PROBE_RETRIES`, default 2; `TUNNEL_PROBE_BACKOFF_S`, default 5)
and only after every attempt fails does the probe emit its error line
(still one parseable JSON line, exit 0 — same contract as bench.py).
Attempts share a PROGRESS MANIFEST (the same atomic write-then-rename
commit protocol checkpoints use, ISSUE 20): each completed size commits,
so a retry resumes at the first unmeasured size instead of re-paying the
256MB transfer that probably triggered the flap. The line reports
`extra.attempts` and `extra.resumed_sizes`.

Run: python benchmarks/tunnel_probe.py   (prints one JSON line)
"""

from __future__ import annotations

import json
import os
import tempfile
import time


def _manifest_mod():
    try:
        from accelerate_tpu.utils import manifest
    except ImportError:  # invoked from inside benchmarks/
        import sys

        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from accelerate_tpu.utils import manifest
    return manifest


def _probe(state_dir: str | None = None) -> dict:
    import jax
    import numpy as np

    dev = jax.devices()[0]
    sizes_mb = [1, 16, 64, 256]
    rows = {}
    resumed = 0
    manifest = _manifest_mod() if state_dir else None
    if manifest is not None:
        committed = manifest.read_manifest(state_dir)
        if committed:
            rows.update((committed.get("extra") or {}).get("rows") or {})
            resumed = len(rows)
    for mb in sizes_mb:
        if f"{mb}MB" in rows:
            continue  # committed by a previous attempt — don't re-pay it
        arr = np.zeros((mb * 2**20 // 4,), np.float32)
        # warm once (allocator, program setup)
        jax.block_until_ready(jax.device_put(arr, dev))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(arr, dev))
            best = min(best, time.perf_counter() - t0)
        rows[f"{mb}MB"] = {
            "seconds": round(best, 4),
            "MB_per_s": round(mb / best, 1),
        }
        if manifest is not None:
            manifest.write_manifest(state_dir, step=len(rows),
                                    extra={"rows": rows})
    # per-call fixed cost via a tiny transfer
    tiny = np.zeros((16,), np.float32)
    jax.block_until_ready(jax.device_put(tiny, dev))
    t0 = time.perf_counter()
    n = 50
    for _ in range(n):
        jax.block_until_ready(jax.device_put(tiny, dev))
    per_call_ms = (time.perf_counter() - t0) / n * 1e3
    return {
        "metric": "host_device_link",
        "value": rows["256MB"]["MB_per_s"],
        "unit": "MB/s@256MB",
        "extra": {"sizes": rows, "per_call_ms": round(per_call_ms, 2),
                  "device": str(dev), "resumed_sizes": resumed},
    }


def main() -> None:
    retries = int(os.environ.get("TUNNEL_PROBE_RETRIES", "2"))
    backoff = float(os.environ.get("TUNNEL_PROBE_BACKOFF_S", "5"))
    state_dir = (os.environ.get("TUNNEL_PROBE_STATE_DIR")
                 or tempfile.mkdtemp(prefix="tunnel_probe_"))
    last_error = None
    for attempt in range(retries + 1):
        try:
            result = _probe(state_dir)
            result["extra"]["attempts"] = attempt + 1
            print(json.dumps(result))
            return
        except Exception as e:  # a flap, not necessarily an outage
            last_error = f"{type(e).__name__}: {str(e)[:300]}"
            if attempt < retries:
                time.sleep(backoff * (2 ** attempt))
    print(json.dumps({
        "metric": "host_device_link",
        "value": None,
        "unit": "MB/s@256MB",
        "error": f"tunnel down after {retries + 1} attempts: {last_error}",
    }))


if __name__ == "__main__":
    main()
