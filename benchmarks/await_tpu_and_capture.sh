#!/bin/bash
# Round-5 capture queue (VERDICT r4 #1): poll the TPU tunnel; when it
# answers, run the queued captures in priority order — headline bench
# first, then the two missing reference big-model rows (NeoX s/token,
# OPT-30B), then the fp8-vs-bf16 row and re-captures. Safe to re-run;
# each capture appends to bench_results/. Log: bench_results/capture_loop.log
cd "$(dirname "$0")/.." || exit 1
LOG=bench_results/capture_loop.log
mkdir -p bench_results
echo "[$(date)] r5 capture loop start" >> "$LOG"
for i in $(seq 1 120); do  # up to ~20h at 10-min intervals
  if timeout 120 python -c "import jax; d=jax.devices()[0]; assert 'tpu' in (d.platform + getattr(d,'device_kind','')).lower()" 2>/dev/null; then
    echo "[$(date)] TPU is back — capturing" >> "$LOG"
    # temp + mv: a timeout/crash must not truncate the last good capture
    if timeout 1200 python bench.py > bench_results/.bench_r5.tmp 2>> "$LOG"; then
      mv bench_results/.bench_r5.tmp bench_results/bench_r5.json
      echo "[$(date)] bench.py done: $(cat bench_results/bench_r5.json)" >> "$LOG"
    fi
    # the two rows the reference table still lacks (VERDICT r4 missing #2)
    timeout 14400 python benchmarks/run_big_model_rows.py gpt-neox-20b --new_tokens 1 >> "$LOG" 2>&1
    timeout 18000 python benchmarks/run_big_model_rows.py opt-30b --new_tokens 1 >> "$LOG" 2>&1
    timeout 600 python benchmarks/tunnel_probe.py >> bench_results/tunnel_probe.jsonl 2>> "$LOG" \
      && echo "[$(date)] tunnel_probe done" >> "$LOG"
    timeout 2400 python benchmarks/fp8_vs_bf16.py >> bench_results/fp8_vs_bf16.jsonl 2>> "$LOG" \
      && echo "[$(date)] fp8_vs_bf16 done" >> "$LOG"
    timeout 900 python benchmarks/nlp_steps.py >> bench_results/nlp_steps.jsonl 2>> "$LOG" \
      && echo "[$(date)] nlp_steps done" >> "$LOG"
    timeout 3600 python benchmarks/mfu_table.py 1.5B 2B 2B-s4k >> bench_results/mfu_table_r5.txt 2>> "$LOG" \
      && echo "[$(date)] mfu_table done" >> "$LOG"
    # re-capture the r4 rows with the r5 batched loader (load-time fix)
    timeout 5400 python benchmarks/run_big_model_rows.py gptj-6b --new_tokens 8 >> "$LOG" 2>&1
    timeout 7200 python benchmarks/run_big_model_rows.py t0pp --new_tokens 8 >> "$LOG" 2>&1
    echo "[$(date)] capture queue complete" >> "$LOG"
    exit 0
  fi
  echo "[$(date)] tunnel still down (attempt $i)" >> "$LOG"
  sleep 480
done
echo "[$(date)] gave up waiting for the tunnel" >> "$LOG"
