"""Mixtral 8-expert training throughput: dense vs sparse dispatch.

The 8-expert benchmark config for the MoE dispatch work: measures a full
train step (fwd+bwd+adamw) tokens/s on the current chip.
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import mixtral


def run(moe_impl: str, batch: int = 8, seq: int = 1024, steps: int = 20) -> float:
    cfg = mixtral.MixtralConfig(
        vocab_size=32000, hidden_size=512, intermediate_size=1408,
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
        num_local_experts=8, num_experts_per_tok=2,
        max_position_embeddings=seq, moe_impl=moe_impl,
    )
    acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
    params = mixtral.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params,
                                       tx=optax.adamw(3e-4)))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (b,) = list(loader)
    step = acc.train_step(lambda p, bb: mixtral.causal_lm_loss(cfg, p, bb))
    ts, m = step(ts, b)
    float(m["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, m = step(ts, b)
        float(m["loss"])
        best = min(best, time.perf_counter() - t0)
    tok_s = batch * seq * steps / best
    print(f"moe_impl={moe_impl:7s}: {tok_s:9.1f} tok/s "
          f"({best/steps*1000:.1f} ms/step)", flush=True)
    return tok_s


if __name__ == "__main__":
    # "a2a" is the token-sharded EP dispatch; on one chip it falls back to
    # the single-device sort path, so this row mainly proves no regression —
    # the 8-way all_to_all itself is exercised by tests + the dryrun
    impls = sys.argv[1].split(",") if len(sys.argv) > 1 else ["dense", "sparse", "a2a"]
    for impl in impls:
        run(impl)
