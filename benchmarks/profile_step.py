"""Decompose the bench step: fwd-only vs fwd+bwd vs full train step MFU,
plus (--host-overhead) the per-step host-side costs the device never sees —
dispatch microseconds and input-stall time.

    python benchmarks/profile_step.py                  # MFU decomposition
    python benchmarks/profile_step.py --host-overhead  # JSON host metrics

The host-overhead mode is CPU-runnable (JAX_PLATFORMS=cpu uses a tiny
model), with one caveat: the CPU backend executes the step mostly
synchronously, so `host_dispatch_us_mean` there absorbs device compute and
is an upper bound, not the pure enqueue cost (single-digit microseconds per
leaf only shows on an async backend like TPU). The host-only proof that the
cached dispatch path works is `pin_tree_computations` (1 for a fixed state
structure) plus `input_stall_us_mean`; the JSON carries
`dispatch_includes_device_time` so tooling can tell the two regimes apart.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.models.common import count_params
from accelerate_tpu.utils.constants import TPU_PEAK_FLOPS
from accelerate_tpu.profiler import StepTimer
from accelerate_tpu.training import cast_floating

BATCH, SEQ, STEPS = 8, 2048, 20


def _on_tpu() -> bool:
    dev0 = jax.devices()[0]
    return "tpu" in (dev0.platform + getattr(dev0, "device_kind", "")).lower()


def _flagship_cfg():
    return llama.LlamaConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=4096,
        num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=4,
        max_position_embeddings=SEQ, remat=True, remat_policy="dots",
    )


def mfu_decomposition() -> None:
    cfg = _flagship_cfg()
    acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=optax.adamw(3e-4)))
    n_params = count_params(ts.params)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (BATCH, SEQ + 1)).astype(np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (batch_arrays,) = list(loader)

    device_kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peak = next((v for k, v in TPU_PEAK_FLOPS.items() if k in device_kind), 197e12)
    attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * SEQ
    fwd_flops_tok = 2 * n_params + attn_flops // 3
    tot_flops_tok = 6 * n_params + attn_flops

    def timeit(name, fn, *args, flops_per_token):
        out = fn(*args)
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(STEPS):
                out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        tok_s = BATCH * SEQ * STEPS / best
        mfu = flops_per_token * tok_s / peak
        print(f"{name:24s}: {best/STEPS*1000:8.1f} ms/step  "
              f"eq-mfu={mfu:.4f}", flush=True)
        return best / STEPS

    loss_fn = lambda p, b: llama.causal_lm_loss(cfg, p, b)  # noqa: E731

    fwd = jax.jit(lambda p, b: loss_fn(cast_floating(p, jnp.bfloat16), b))
    timeit("fwd only", fwd, ts.params, batch_arrays, flops_per_token=fwd_flops_tok)

    grad = jax.jit(jax.grad(lambda p, b: loss_fn(cast_floating(p, jnp.bfloat16), b)))
    timeit("fwd+bwd", grad, ts.params, batch_arrays, flops_per_token=tot_flops_tok)

    # train_step donates its input state, so the timing loop must keep
    # rebinding the returned state rather than restarting from a donated one
    step = acc.train_step(loss_fn)
    ts, m = step(ts, batch_arrays)
    float(m["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            ts, m = step(ts, batch_arrays)
        float(m["loss"])  # forces completion through the device tunnel
        best = min(best, time.perf_counter() - t0)
    tok_s = BATCH * SEQ * STEPS / best
    print(f"{'full train step':24s}: {best/STEPS*1000:8.1f} ms/step  "
          f"eq-mfu={tot_flops_tok * tok_s / peak:.4f}", flush=True)


def host_overhead(steps: int = 30) -> dict:
    """Measure per-step host dispatch and input-stall time through the real
    prepare()d pipeline (device prefetch + cached dispatch) and print ONE
    JSON line. The model is tiny off-TPU: these are host-side costs."""
    on_tpu = _on_tpu()
    if on_tpu:
        cfg, batch, seq = _flagship_cfg(), BATCH, SEQ
    else:
        cfg, batch, seq = llama.LlamaConfig.tiny(), 4, 64
    acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params,
                                       tx=optax.adamw(3e-4)))
    rng = np.random.default_rng(0)
    batches = [
        {"input_ids": rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)}
        for _ in range(steps)
    ]
    loader = acc.prepare(batches)
    step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))

    # AOT warmup outside the loop: the first in-loop step pays dispatch only
    it = iter(loader)
    first = next(it)
    step.warmup(ts, first)

    timer = StepTimer(warmup_steps=2)
    current = first
    while current is not None:
        with timer.dispatch():
            ts, m = step(ts, current)
        timer.tick(m["loss"])
        with timer.input_stall():
            current = next(it, None)
    summary = timer.summary()
    out = {
        "metric": "train_step_host_overhead",
        "host_dispatch_us_mean": round(timer.host_dispatch_us, 1),
        "input_stall_us_mean": round(timer.input_stall_us, 1),
        "mean_step_time_s": round(timer.mean_step_time, 6),
        # tail latency from the shared streaming histogram — a p99 far from
        # the mean means jittery steps (input stalls, recompiles, noisy
        # neighbors), which a mean-only meter hides
        "step_time_p50_s": round(summary.get("step_time_p50_s", float("nan")), 6),
        "step_time_p99_s": round(summary.get("step_time_p99_s", float("nan")), 6),
        "steps_recorded": timer.steps_recorded,
        "pin_tree_computations": step._pin_computations,
        "device": getattr(jax.devices()[0], "device_kind", "cpu").lower(),
        "n_chips": jax.device_count(),
        "on_tpu": on_tpu,
        # CPU executes the step largely synchronously inside the step()
        # call — there the dispatch reading bounds (host + device), it is
        # not the pure async enqueue cost
        "dispatch_includes_device_time": not on_tpu,
    }
    print(json.dumps(out), flush=True)
    return out


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--host-overhead", action="store_true",
        help="print per-step host dispatch + input stall metrics as JSON",
    )
    parser.add_argument("--steps", type=int, default=30,
                        help="steps for --host-overhead")
    args = parser.parse_args()
    if args.host_overhead:
        host_overhead(args.steps)
    else:
        mfu_decomposition()


if __name__ == "__main__":
    main()
