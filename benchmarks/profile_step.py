"""Decompose the bench step: fwd-only vs fwd+bwd vs full train step MFU."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.models.common import count_params
from accelerate_tpu.utils.constants import TPU_PEAK_FLOPS
from accelerate_tpu.training import cast_floating

BATCH, SEQ, STEPS = 8, 2048, 20

cfg = llama.LlamaConfig(
    vocab_size=32000, hidden_size=1536, intermediate_size=4096,
    num_hidden_layers=12, num_attention_heads=12, num_key_value_heads=4,
    max_position_embeddings=SEQ, remat=True, remat_policy="dots",
)
acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
params = llama.init_params(cfg, jax.random.key(0))
ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=optax.adamw(3e-4)))
n_params = count_params(ts.params)
rng = np.random.default_rng(0)
ids = rng.integers(0, cfg.vocab_size, (BATCH, SEQ + 1)).astype(np.int32)
loader = acc.prepare([{"input_ids": ids}])
(batch_arrays,) = list(loader)

device_kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
peak = next((v for k, v in TPU_PEAK_FLOPS.items() if k in device_kind), 197e12)
attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * SEQ
fwd_flops_tok = 2 * n_params + attn_flops // 3
tot_flops_tok = 6 * n_params + attn_flops


def timeit(name, fn, *args, flops_per_token):
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    tok_s = BATCH * SEQ * STEPS / best
    mfu = flops_per_token * tok_s / peak
    print(f"{name:24s}: {best/STEPS*1000:8.1f} ms/step  "
          f"eq-mfu={mfu:.4f}", flush=True)
    return best / STEPS


loss_fn = lambda p, b: llama.causal_lm_loss(cfg, p, b)

fwd = jax.jit(lambda p, b: loss_fn(cast_floating(p, jnp.bfloat16), b))
t_fwd = timeit("fwd only", fwd, ts.params, batch_arrays, flops_per_token=fwd_flops_tok)

grad = jax.jit(jax.grad(lambda p, b: loss_fn(cast_floating(p, jnp.bfloat16), b)))
t_bwd = timeit("fwd+bwd", grad, ts.params, batch_arrays, flops_per_token=tot_flops_tok)

# train_step donates its input state, so the timing loop must keep rebinding
# the returned state rather than restarting from a donated one
step = acc.train_step(loss_fn)
ts, m = step(ts, batch_arrays)
float(m["loss"])
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    for _ in range(STEPS):
        ts, m = step(ts, batch_arrays)
    float(m["loss"])  # forces completion through the device tunnel
    best = min(best, time.perf_counter() - t0)
tok_s = BATCH * SEQ * STEPS / best
print(f"{'full train step':24s}: {best/STEPS*1000:8.1f} ms/step  "
      f"eq-mfu={tot_flops_tok * tok_s / peak:.4f}", flush=True)
