"""MFU table: the single-chip Llama bench at increasing model scale.

BASELINE.md phrases the target as Llama-3-8B on v5p-64; one v5e chip
(16 GB) can't hold that, so this table quantifies how MFU trends as the
proxy grows toward it — larger hidden sizes make bigger MXU matmuls, so
per-chip MFU at 8B/v5p should sit at or above the largest row here.

Run: python benchmarks/mfu_table.py [name ...]
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np
import optax

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import llama
from accelerate_tpu.models.common import count_params
from accelerate_tpu.utils.constants import TPU_PEAK_FLOPS

CONFIGS = {
    # name: (hidden, ffn, layers, heads, kv_heads, batch, seq, remat_policy,
    #        moments) — the 16 GB chip fits the larger rows only by shrinking
    #        the Adam moments: 'f32' -> plain adamw, 'bf16' -> mu_dtype
    #        downcast, 'int8' -> accelerate_tpu.optimizers.adamw_8bit
    #        (~2.06 bytes/param of optimizer state instead of 8 — what lets
    #        the 1.5B/2B rows train on one chip at all)
    "400M": (1536, 4096, 12, 12, 4, 8, 2048, "dots", "f32"),
    "700M": (2048, 5504, 12, 16, 8, 4, 2048, "dots", "bf16"),
    "1B": (2048, 5504, 20, 16, 8, 4, 2048, "full", "bf16"),
    "1.5B": (2560, 6912, 20, 20, 4, 4, 2048, "full", "int8"),
    "2B": (2560, 6912, 26, 20, 4, 2, 2048, "full", "int8"),
    "2B-s4k": (2560, 6912, 26, 20, 4, 1, 4096, "full", "int8"),
}


def run(name: str, steps: int = 15) -> None:
    import jax.numpy as jnp

    from accelerate_tpu.optimizers import adamw_8bit

    h, f, L, nh, nkv, batch, seq, policy, moments = CONFIGS[name]
    cfg = llama.LlamaConfig(
        vocab_size=32000, hidden_size=h, intermediate_size=f,
        num_hidden_layers=L, num_attention_heads=nh, num_key_value_heads=nkv,
        max_position_embeddings=seq, remat=True, remat_policy=policy,
    )
    acc = Accelerator(mixed_precision="bf16", gradient_clipping=1.0)
    if moments == "int8":
        # the single-chip multi-billion recipe: bf16 weights (grads then
        # materialize bf16 straight out of autodiff) + int8 Adam moments
        # ≈ 6 bytes/param of resident state — 2B params ≈ 11.7 GB, which is
        # what fits a 16 GB chip; f32 masters + f32 grads would need ~20 GB
        params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.bfloat16)
        tx = adamw_8bit(3e-4)
    else:
        params = llama.init_params(cfg, jax.random.key(0))
        tx = optax.adamw(
            3e-4, mu_dtype=jnp.bfloat16 if moments == "bf16" else None
        )
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=tx))
    n_params = count_params(ts.params)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (b,) = list(loader)
    step = acc.train_step(lambda p, bb: llama.causal_lm_loss(cfg, p, bb))
    try:
        ts, m = step(ts, b)
        float(m["loss"])
    except Exception as e:  # noqa: BLE001
        print(f"{name:5s}: FAILED {type(e).__name__}: {str(e)[:100]}", flush=True)
        return
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, m = step(ts, b)
        float(m["loss"])
        best = min(best, time.perf_counter() - t0)
    tok_s = batch * seq * steps / best
    attn = 12 * L * h * seq
    flops_tok = 6 * n_params + attn
    device_kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peak = next((v for k, v in TPU_PEAK_FLOPS.items() if k in device_kind), 197e12)
    mfu = flops_tok * tok_s / peak
    print(f"{name:5s}: {n_params/1e6:7.1f}M params  b={batch} s={seq}  "
          f"{tok_s:9.1f} tok/s  mfu={mfu:.4f}", flush=True)


if __name__ == "__main__":
    names = sys.argv[1:] or list(CONFIGS)
    for n in names:
        run(n)
