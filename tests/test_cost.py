"""Device-cost attribution & goodput (ISSUE 11).

Three layers:

- `CostTable` contracts, model-free: registration resolution order,
  sampling cadence, roofline math against injected peaks, registry
  publication, reset/republish, JSON-safe snapshots.
- Serving-engine integration on a tiny model: non-null decode MFU /
  MXU-idle / goodput in `metrics_summary()`, the compile-count pin WITH
  sampling enabled (the acceptance bar: sampling is host-side, the
  programs must not notice), incident dumps carrying the cost table,
  and the analytic-fallback parity band vs the backend-measured FLOPs.
- `_CompiledTrainStep` integration: static cost captured once per
  (layout, batch-sig) akey riding the AOT compile, fence-sampled device
  times, and the training goodput meter in StepTimer.
"""

from __future__ import annotations

import json
import math
import time

import numpy as np
import pytest

from accelerate_tpu.telemetry.cost import (
    CostTable,
    ProgramCost,
    extract_cost_analysis,
    resolve_sample_every,
)
from accelerate_tpu.telemetry.registry import MetricsRegistry

# injected peaks: 1 TFLOP/s + 100 GB/s, non-nominal so the math is exact
PEAKS = (1e12, 100e9, False)


def make_table(sample_every=1, registry=None):
    return CostTable(registry=registry or MetricsRegistry(),
                     sample_every=sample_every, peaks=PEAKS)


class TestCostTable:
    def test_register_explicit_and_gauges(self):
        t = make_table()
        entry = t.register("decode", flops=2e9, bytes_accessed=1e9)
        assert entry.source == "explicit"
        assert entry.arith_intensity == pytest.approx(2.0)
        snap = t.registry.snapshot()
        assert snap["gauges"]['program_flops{program="decode"}'] == 2e9
        assert snap["gauges"][
            'program_arith_intensity{program="decode"}'] == pytest.approx(2.0)

    def test_register_from_cost_analysis_dict(self):
        t = make_table()
        entry = t.register("p", {"flops": 5.0, "bytes accessed": 10.0})
        assert (entry.source, entry.flops, entry.bytes_accessed) == (
            "cost_analysis", 5.0, 10.0)

    def test_register_fallback_when_backend_reports_nothing(self):
        t = make_table()
        entry = t.register("p", {"no": "flops"},
                           fallback=lambda: (7.0, 3.0))
        assert entry.source == "analytic"
        assert (entry.flops, entry.bytes_accessed) == (7.0, 3.0)

    def test_register_nothing_resolvable_returns_none(self):
        t = make_table()
        assert t.register("p") is None
        assert not t.has("p")

    def test_register_once_unless_replace(self):
        t = make_table()
        t.register("p", flops=1.0, bytes_accessed=1.0)
        t.register("p", flops=99.0, bytes_accessed=1.0)
        assert t.entries["p"].flops == 1.0  # no-op re-register
        t.register("p", flops=99.0, bytes_accessed=1.0, replace=True)
        assert t.entries["p"].flops == 99.0

    def test_extract_cost_analysis_shapes(self):
        assert extract_cost_analysis({"flops": 2.0}) == (2.0, 0.0)
        # compiled.cost_analysis() returns a list on this jax line
        assert extract_cost_analysis(
            [{"flops": 2.0, "bytes accessed": 4.0}]) == (2.0, 4.0)
        assert extract_cost_analysis([]) is None
        assert extract_cost_analysis({"flops": 0.0}) is None
        assert extract_cost_analysis("garbage") is None

        class Boom:
            def cost_analysis(self):
                raise RuntimeError("backend says no")

        assert extract_cost_analysis(Boom()) is None

    def test_sampling_cadence_skips_compile_call(self):
        t = make_table(sample_every=4)
        due = [t.sample_due("p") for _ in range(11)]
        # call 1 is trace+compile (never sampled); call 2 and every 4th
        # call after are
        assert due == [False, True, False, False, False, True,
                       False, False, False, True, False]

    def test_sampling_disabled(self):
        t = make_table(sample_every=0)
        assert not any(t.sample_due("p") for _ in range(8))

    def test_roofline_math(self):
        t = make_table()
        t.register("p", flops=1e9, bytes_accessed=5e9)
        t.record_device_time("p", 0.01)  # 1 GFLOP in 10ms = 100 GFLOP/s
        sheet = t.roofline("p")
        assert sheet["mfu"] == pytest.approx(0.1)  # vs 1 TFLOP/s peak
        assert sheet["mxu_idle_fraction"] == pytest.approx(0.9)
        # 5 GB in 10ms = 500 GB/s vs 100 GB/s peak
        assert sheet["hbm_bw_util"] == pytest.approx(5.0)
        assert sheet["device_time_samples"] == 1.0
        assert sheet["peaks_nominal"] == 0.0
        snap = t.registry.snapshot()
        assert snap["gauges"]['program_mfu{program="p"}'] == pytest.approx(0.1)
        assert snap["gauges"][
            'program_mxu_idle_fraction{program="p"}'] == pytest.approx(0.9)

    def test_maybe_sample_records_when_due(self):
        t = make_table(sample_every=1)
        t.register("p", flops=1.0, bytes_accessed=1.0)
        with t.maybe_sample("p") as sample:  # call 1: never sampled
            sample(None)
        assert t.device_time("p").count == 0
        with t.maybe_sample("p") as sample:
            time.sleep(0.002)
            sample(None)
        assert t.device_time("p").count == 1
        assert t.device_time("p").mean >= 0.002

    def test_republish_after_registry_reset(self):
        r = MetricsRegistry()
        t = make_table(registry=r)
        t.register("p", flops=3.0, bytes_accessed=1.0)
        r.reset()
        assert r.snapshot()["gauges"]['program_flops{program="p"}'] == 0.0
        t.republish()
        assert r.snapshot()["gauges"]['program_flops{program="p"}'] == 3.0

    def test_snapshot_json_safe(self):
        t = make_table()
        t.register("p", flops=1e6, bytes_accessed=2e6)
        t.sample_due("p"), t.sample_due("p")
        t.record_device_time("p", 0.001)
        snap = json.loads(json.dumps(t.snapshot()))
        assert snap["programs"]["p"]["flops"] == 1e6
        assert snap["programs"]["p"]["calls"] == 2
        assert "mfu" in snap["rooflines"]["p"]

    def test_resolve_sample_every(self, monkeypatch):
        assert resolve_sample_every(None) == 16
        assert resolve_sample_every(3) == 3
        monkeypatch.setenv("ACCELERATE_TPU_COST_SAMPLE_EVERY", "7")
        assert resolve_sample_every(None) == 7
        assert resolve_sample_every(0) == 0

    def test_program_cost_nan_intensity(self):
        assert math.isnan(ProgramCost("p", 1.0, 0.0).arith_intensity)

    def test_num_chips_scales_the_peak_denominator(self):
        # GLOBAL FLOPs over an N-chip mesh divide by N x one chip's
        # peak — a meshed decode must not read N-fold-too-high MFU
        t = CostTable(registry=MetricsRegistry(), sample_every=1,
                      peaks=PEAKS, num_chips=4)
        t.register("p", flops=1e9, bytes_accessed=5e9)
        t.record_device_time("p", 0.01)
        sheet = t.roofline("p")
        assert sheet["mfu"] == pytest.approx(0.025)  # 0.1 / 4 chips
        assert sheet["hbm_bw_util"] == pytest.approx(1.25)
        # callable resolves lazily (jax.device_count without importing
        # jax at construction)
        t2 = CostTable(registry=MetricsRegistry(), peaks=PEAKS,
                       num_chips=lambda: 2)
        assert t2.num_chips == 2


# ---------------------------------------------------------------------------
# serving-engine integration
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_engine_run():
    """One tiny-llama engine driven through a short request wave with an
    aggressive sampling cadence; shared by the read-only assertions."""
    import jax

    from accelerate_tpu.models import llama
    from accelerate_tpu.serving import Engine, EngineConfig

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    eng = Engine(llama, cfg, params,
                 EngineConfig(num_slots=4, max_len=96, prefill_chunk=16,
                              cost_sample_every=2))
    rng = np.random.default_rng(0)
    for _ in range(6):
        eng.submit(rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32),
                   max_new_tokens=6)
    eng.run_until_idle()
    yield eng
    eng.close()


class TestEngineCostAttribution:
    def test_summary_reports_roofline_and_goodput(self, tiny_engine_run):
        # the acceptance bar: decode MFU / MXU-idle / goodput non-null
        # on a CPU smoke (nominal peaks — labeled, but the numbers flow)
        s = tiny_engine_run.metrics_summary()
        for key in ("decode_mfu", "decode_mxu_idle_fraction",
                    "decode_hbm_bw_util", "decode_arith_intensity",
                    "decode_device_time_mean_ms",
                    "prefill_device_time_mean_ms", "goodput"):
            assert key in s and s[key] == s[key], key
        assert 0.0 < s["goodput"] <= 1.0
        assert 0.0 <= s["decode_mxu_idle_fraction"] <= 1.0
        assert s["decode_device_time_mean_ms"] > 0.0

    def test_compile_counts_flat_with_sampling_enabled(self,
                                                       tiny_engine_run):
        # sampling is host-side fence timing: the three programs must
        # not notice it (the pinned acceptance criterion)
        assert tiny_engine_run.compile_stats() == {
            "admit": 1, "prefill": 1, "decode": 1}
        assert tiny_engine_run.cost.device_time("decode").count > 0
        assert tiny_engine_run.cost.device_time("prefill").count > 0

    def test_static_costs_captured_per_program(self, tiny_engine_run):
        entries = tiny_engine_run.cost.entries
        assert set(entries) >= {"admit", "prefill", "decode"}
        assert entries["decode"].flops > 0
        assert entries["prefill"].flops > entries["decode"].flops

    def test_goodput_gauge_live(self, tiny_engine_run):
        snap = tiny_engine_run.registry.snapshot()
        assert 0.0 < snap["gauges"]["serving_goodput"] <= 1.0
        assert snap["gauges"]['program_flops{program="decode"}'] > 0

    def test_incident_dumps_carry_cost_table(self, tiny_engine_run):
        dumps = tiny_engine_run.incident_dumps()
        table = dumps["cost_table"]
        json.dumps(table)  # bundle files are json.dump'd
        assert set(table["programs"]) >= {"prefill", "decode"}
        assert "device_time_mean_s" in table["rooflines"]["decode"]

    def test_analytic_fallback_parity_with_measured(self, tiny_engine_run):
        # satellite: the analytic inference accounting (~2 FLOPs/param/
        # token + the attention-over-cache term) must agree with the
        # backend-reported cost table within a coarse band — catching a
        # 6ND-style formula reuse (3x over) or a dropped term (10x
        # under), not bit equality (measured ratio ~0.6-0.7 on the tiny
        # configs: analytic counts embedding params the matmuls never
        # touch)
        for prog in ("decode", "prefill"):
            measured = tiny_engine_run.cost.entries[prog]
            assert measured.source == "cost_analysis"
            flops, _ = tiny_engine_run._analytic_cost(prog)
            assert 0.25 < measured.flops / flops < 4.0, prog

    def test_reset_metrics_keeps_static_costs(self, tiny_engine_run):
        import jax

        from accelerate_tpu.models import llama
        from accelerate_tpu.serving import Engine, EngineConfig

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.key(1))
        eng = Engine(llama, cfg, params,
                     EngineConfig(num_slots=2, max_len=64,
                                  prefill_chunk=16, cost_sample_every=2))
        eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)
        eng.run_until_idle()
        assert eng.cost.device_time("decode").count > 0
        eng.reset_metrics()
        # device-time samples drop with the other windows; the static
        # program costs survive (the compiled programs didn't change)
        assert eng.cost.device_time("decode").count == 0
        snap = eng.registry.snapshot()
        assert snap["gauges"]['program_flops{program="decode"}'] > 0
        # and sampling keeps working after the reset
        eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=8)
        eng.run_until_idle()
        assert eng.cost.device_time("decode").count > 0
        assert eng.metrics_summary()["goodput"] > 0
        eng.close()

    def test_sampling_disabled_keeps_static_table(self):
        import jax

        from accelerate_tpu.models import llama
        from accelerate_tpu.serving import Engine, EngineConfig

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.key(2))
        eng = Engine(llama, cfg, params,
                     EngineConfig(num_slots=2, max_len=64,
                                  prefill_chunk=16, cost_sample_every=0))
        eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)
        eng.run_until_idle()
        assert eng.cost.entries["decode"].flops > 0
        assert eng.cost.device_time("decode").count == 0
        s = eng.metrics_summary()
        assert "decode_mfu" not in s and "goodput" not in s
        eng.close()


# ---------------------------------------------------------------------------
# train-step integration
# ---------------------------------------------------------------------------


class TestTrainStepCostAttribution:
    def test_compiled_step_registers_once_and_samples(self):
        import jax
        import optax

        from accelerate_tpu import TrainState
        from accelerate_tpu.accelerator import Accelerator
        from accelerate_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
        acc = Accelerator(cost_sample_every=2)
        params = llama.init_params(cfg, jax.random.key(0))
        ts = acc.prepare(TrainState.create(apply_fn=None, params=params,
                                           tx=optax.adamw(1e-3)))
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 17)).astype(np.int32)
        (batch,) = list(acc.prepare([{"input_ids": ids}]))
        step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
        step.warmup(ts, batch)
        assert step._aot_compiles == 1
        assert acc.cost_table.entries["train_step"].flops > 0
        for _ in range(6):
            ts, m = step(ts, batch)
        float(m["loss"])
        # the cost capture and the fence sampling added ZERO compiles
        assert step._aot_compiles == 1
        assert acc.cost_table.device_time("train_step").count > 0
        sheet = acc.cost_table.roofline("train_step")
        assert 0.0 < sheet["mfu"]
        assert 0.0 <= sheet["mxu_idle_fraction"] <= 1.0
        # a second built step must NOT share the first one's entry (an
        # eval fn overwriting the train step's FLOPs corrupts MFU)
        step2 = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
        assert step2._cost_name == "train_step_2"
        assert step._cost_name == "train_step"
        acc.end_training()

    def test_unwarmed_step_registers_lazily(self):
        # plain-jit path (no warmup call): the first due sample captures
        # the static cost from a lowering
        import jax
        import jax.numpy as jnp

        from accelerate_tpu.accelerator import _CompiledTrainStep

        table = make_table(sample_every=1)

        def step_fn(state, batch):
            p = state["p"] - 0.1 * batch.mean()
            return {"p": p}, {"loss": (p ** 2).sum()}

        step = _CompiledTrainStep(step_fn, donate=False, cost_table=table)
        state = {"p": jnp.ones((4,))}
        batch = jnp.ones((4,))
        for _ in range(3):
            state, m = step(state, batch)
        jax.block_until_ready(m["loss"])
        assert table.has("train_step")
        assert table.device_time("train_step").count > 0


class TestStepTimerGoodput:
    def test_tight_loop_goodput_near_one(self):
        from accelerate_tpu.profiler import StepTimer

        t = StepTimer(warmup_steps=0)
        t.tick()
        for _ in range(5):
            time.sleep(0.004)
            t.tick()
        assert t.goodput == pytest.approx(1.0, abs=1e-6)
        assert t.summary()["goodput"] == pytest.approx(1.0, abs=1e-6)

    def test_input_stalls_subtract_from_goodput(self):
        from accelerate_tpu.profiler import StepTimer

        t = StepTimer(warmup_steps=0)
        t.tick()
        for _ in range(4):
            with t.input_stall():
                time.sleep(0.01)  # the loader starves the device
            time.sleep(0.01)
            t.tick()
        assert 0.2 < t.goodput < 0.8  # ~half the window was stall

    def test_overhead_marker_subtracts_from_goodput(self):
        # tick intervals tile the wall clock, so between-tick work
        # (checkpoint saves) only subtracts when the loop MARKS it
        from accelerate_tpu.profiler import StepTimer

        t = StepTimer(warmup_steps=0)
        t.tick()
        for _ in range(4):
            with t.overhead():
                time.sleep(0.01)  # a "checkpoint save"
            time.sleep(0.01)
            t.tick()
        assert 0.2 < t.goodput < 0.8

    def test_goodput_nan_before_steps(self):
        from accelerate_tpu.profiler import StepTimer

        t = StepTimer(warmup_steps=0)
        assert math.isnan(t.goodput)
        t.reset()
        assert math.isnan(t.goodput)

    def test_warmup_excluded_from_window(self):
        from accelerate_tpu.profiler import StepTimer

        t = StepTimer(warmup_steps=1)
        t.tick()
        time.sleep(0.05)  # the compile tick — must not count as lost wall
        t.tick()
        for _ in range(3):
            time.sleep(0.004)
            t.tick()
        assert t.goodput == pytest.approx(1.0, abs=1e-6)


class TestInferFlopsFormula:
    def test_causal_lm_infer_flops(self):
        from accelerate_tpu.profiler import causal_lm_infer_flops

        # 2 FLOPs/param/token exactly when attention is off
        assert causal_lm_infer_flops(100, 3, attention=False) == 600.0
        # + 4*L*h*kv_len per token with the paged-attention term
        got = causal_lm_infer_flops(100, 3, num_layers=2, hidden_size=8,
                                    kv_len=10)
        assert got == 600.0 + 4.0 * 2 * 8 * 10 * 3
        # decode accounting is NOT the 6ND training formula: fwd-only is
        # a third of fwd+bwd
        from accelerate_tpu.profiler import causal_lm_train_flops

        assert causal_lm_train_flops(100, 3, attention=False) == \
            3 * causal_lm_infer_flops(100, 3, attention=False)


class TestCrossHostAggregation:
    def test_cost_gauges_and_device_time_aggregate(self):
        """Satellite: per-program cost gauges and device-time sketches
        flow through telemetry.aggregate — FLOPs gauges get a cross-host
        __sum (pod-wide FLOPs per call) and the device-time histogram
        keeps the __slowest_host_mean straggler signal."""
        from accelerate_tpu.telemetry.aggregate import aggregate_flat

        def host(flops: float, times: list[float]):
            r = MetricsRegistry()
            t = CostTable(registry=r, sample_every=1, peaks=PEAKS)
            t.register("decode", flops=flops, bytes_accessed=flops / 2)
            for s in times:
                t.record_device_time("decode", s)
            return r.snapshot(include_sketch=True)

        fast = host(1e9, [0.001, 0.001])
        slow = host(1e9, [0.010, 0.012])  # the straggler host
        flat = aggregate_flat(snapshots=[fast, slow], prefix="t/")
        assert flat['t/program_flops{program="decode"}__sum'] == 2e9
        key = 't/program_device_time_seconds{program="decode"}'
        assert flat[key + "_count"] == 4.0
        assert flat[key + "__slowest_host_mean"] == pytest.approx(
            0.011, rel=0.05)
        # non-cost gauges keep their min/mean/max shape, no __sum spam
        assert 't/program_mfu{program="decode"}__sum' not in flat
        assert 't/program_mfu{program="decode"}__max' in flat
