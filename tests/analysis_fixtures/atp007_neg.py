"""ATP007 negative: the shape argument is declared static."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(0,))
def good(n, x):
    acc = jnp.zeros(n)
    for _ in range(n):
        acc = acc + x
    return acc
