"""ATP005 positive: np.random inside traced code bakes ONE sample."""
import jax
import numpy as np


@jax.jit
def bad_dropout(x):
    mask = np.random.rand(*x.shape) > 0.5  # same mask every call
    return x * mask
