"""ATP007 positive: shape/range use of a non-static jit argument."""
import jax
import jax.numpy as jnp


@jax.jit
def bad(n, x):
    acc = jnp.zeros(n)  # n must be static_argnums to trace
    for _ in range(n):
        acc = acc + x
    return acc
