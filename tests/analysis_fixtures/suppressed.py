"""Suppression fixture: line-level and file-level `# atp:` markers.

File-level: ATP004 is accepted everywhere in this file.
"""
# atp: disable-file=ATP004
import jax


@jax.jit
def f(x):
    print(x)  # would be ATP004; suppressed file-wide
    # deliberate, measured sync; the directive must END its line
    y = x.sum().item()  # atp: disable=ATP001
    return y


@jax.jit
def g(x):
    return x.sum().item()  # NOT suppressed: must still be reported
