"""ATP008 positive: aliased-donation pytree (acceptance fixture).

The same buffer reachable through two pytree paths makes a donated call
die with "Attempt to donate the same buffer twice" — the PR 1
optimizer-state aliasing crash class."""
import jax


def make_state(w):
    state = {"params": w, "ema": w}  # both paths hit the SAME buffer
    step = jax.jit(lambda s: s, donate_argnums=(0,))
    return step(state)
