"""ATP008 negative: the aliased leaf is copied before donation."""
import jax
import jax.numpy as jnp


def make_state(w):
    state = {"params": w, "ema": jnp.array(w)}  # distinct buffers
    step = jax.jit(lambda s: s, donate_argnums=(0,))
    return step(state)
