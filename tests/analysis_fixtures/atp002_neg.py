"""ATP002 negative: casts of static values only."""
import jax


@jax.jit
def good(x, scale: float):
    n = float(len(x.shape))  # len() of a static attr: host arithmetic
    return x * scale * n
