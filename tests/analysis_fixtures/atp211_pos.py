"""ATP211 positive: terminal transitions that bypass the finalizer —
the metrics/trace undercount class (PR 6 shed_log, PR 8
_finalize_request). Four shapes: a terminal assignment with no finalize,
a conditional scheduler transition whose success arm forgets to
finalize, a shedding call never drained, and a drain loop that drops its
victims."""
class RequestStatus:
    FINISHED = "finished"
    CANCELLED = "cancelled"
    REJECTED = "rejected"
    EXPIRED = "expired"


class LeakyEngine:
    def _finalize_request(self, req):
        self.metrics.observe_request(req)

    def drop_without_finalize(self, req):
        req.status = RequestStatus.CANCELLED
        req.finished_at = self.clock()      # never reaches the finalizer

    def cancel_forgets_finalize(self, request):
        if self.scheduler.cancel(request):
            return True                     # transition done, no finalize
        return False

    def submit_never_drains(self, req):
        self.scheduler.submit(req)
        if req.done:
            self._finalize_request(req)     # the newcomer, yes...
        return req                          # ...but victims never drained

    def drain_drops_victims(self):
        for victim in self.scheduler.drain_shed():
            self.log.append(victim.request_id)   # logged, not finalized
