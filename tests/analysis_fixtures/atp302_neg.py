"""ATP302 negative: every path takes the locks in ONE global order
(books before wire), including the path through the call graph — a
consistent order can never cycle."""
import threading


class Pod:
    def __init__(self):
        self._books_lock = threading.Lock()
        self._wire_lock = threading.Lock()

    def forward(self):
        with self._books_lock:
            with self._wire_lock:        # books -> wire
                self.ship()

    def on_frame(self):
        with self._books_lock:
            self._send_locked()          # call under books...

    def _send_locked(self):
        with self._wire_lock:            # ...still books -> wire
            self.record()
