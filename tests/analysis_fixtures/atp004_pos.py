"""ATP004 positive: print of a traced value inside jitted code."""
import jax


@jax.jit
def bad(x):
    y = x * 2
    print(y)  # prints an abstract tracer, once, at trace time
    return y
