"""ATP304 negative: the textbook protocol — wait in a `while` predicate
loop under the lock, notify under the lock, and `wait_for` (which owns
its own predicate re-check) used bare."""
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self.items = []

    def take(self):
        with self._cv:
            while not self.items:
                self._cv.wait()
            return self.items.pop()

    def take_bounded(self, timeout):
        with self._cv:
            self._cv.wait_for(lambda: bool(self.items), timeout=timeout)
            return self.items.pop() if self.items else None

    def put(self, item):
        with self._cv:
            self.items.append(item)
            self._cv.notify()
