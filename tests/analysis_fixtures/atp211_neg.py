"""ATP211 negative: every terminal path routes through the finalizer,
sheds are drained into it, and scheduler-side sheds reach the shed_log
or return the handle to the finalizing caller."""
class RequestStatus:
    FINISHED = "finished"
    CANCELLED = "cancelled"
    REJECTED = "rejected"
    EXPIRED = "expired"


class CleanEngine:
    def _finalize_request(self, req):
        self.metrics.observe_request(req)

    def drop_with_finalize(self, req):
        req.status = RequestStatus.CANCELLED
        req.finished_at = self.clock()
        self._finalize_request(req)

    def cancel_finalizes(self, request):
        if self.scheduler.cancel(request):
            self._finalize_request(request)
            return True
        return False

    def submit_drains(self, req):
        self.scheduler.submit(req)
        for victim in self.scheduler.drain_shed():
            self._finalize_request(victim)
        if req.done:
            self._finalize_request(req)
        return req


class CleanScheduler:
    # no finalizer here: the scheduler's contract is to LOG the shed (or
    # return the handle) so the engine finalizes it
    def shed(self, req, now):
        req.status = RequestStatus.EXPIRED
        req.shed_code = "deadline"
        self.shed_log.append(req)

    def reject(self, request):
        request.status = RequestStatus.REJECTED
        request.shed_code = "queue_full"
        return request
