"""ATP006 negative: branching on shapes / None-ness / lax.cond."""
import jax
import jax.numpy as jnp


@jax.jit
def good(x, mask=None):
    if mask is not None:  # identity check: static
        x = x * mask
    if x.ndim == 2:  # shape attr: static under jit
        x = x[None]
    return jax.lax.cond(x.sum() > 0, lambda v: v, lambda v: -v, x)
