"""ATP002 positive: float()/bool() of a traced value in jitted code."""
import jax


@jax.jit
def bad(x):
    y = x.sum()
    if bool(y > 0):  # noqa — also an ATP006, the cast is the ATP002
        return float(y)
    return 0.0
