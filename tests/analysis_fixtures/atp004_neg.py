"""ATP004 negative: jax.debug.print and static prints are fine."""
import jax


@jax.jit
def good(x):
    y = x * 2
    jax.debug.print("y = {}", y)
    print("tracing good()")  # static string: trace-time log, harmless
    return y
