"""ATP212 negative: shed transitions carry their shed_code (before or
after the status line), and non-shed terminals need none."""
class RequestStatus:
    FINISHED = "finished"
    CANCELLED = "cancelled"
    REJECTED = "rejected"
    EXPIRED = "expired"


class CodedShed:
    def _finalize(self, req):
        self.metrics.observe_request(req)

    def worker_drop(self, user, now):
        user.status = RequestStatus.EXPIRED
        user.reject_reason = "worker dropped the request"
        user.shed_code = "worker_drop"
        user.finished_at = now
        self._finalize(user)

    def code_set_first(self, user, now):
        user.shed_code = "deadline"
        user.status = RequestStatus.EXPIRED
        self._finalize(user)

    def finished_needs_no_code(self, user, now):
        user.status = RequestStatus.FINISHED
        user.finished_at = now
        self._finalize(user)
