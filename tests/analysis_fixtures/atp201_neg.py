"""ATP201 negative: every path balances, escapes ownership, or releases
in a handler — the idioms the pass must accept."""


class CleanAdmission:
    def balanced(self, request):
        pages = self.pool.alloc(4)
        if pages is None:
            return None
        self.pool.release(pages)
        return True

    def ownership_returned(self, request, nodes):
        self.index.acquire(nodes)
        return self.build(nodes)    # ownership transfers out immediately

    def handler_releases(self, request):
        nodes = self.index.match(request.prompt)
        self.index.acquire(nodes)
        try:
            self.record(request)
        except BaseException:
            self.index.release(nodes)
            raise
        self.index.release(nodes)

    def attached_to_slot(self, slot, request):
        alloc = self.allocator.allocate(request)
        if alloc is None:
            return False
        slot.alloc = alloc                  # escape: the slot owns it now
        self.pop(request)
        return True

    def rollback_after_refused_adopt(self, engine, internal):
        alloc = engine.allocator.allocate(internal)
        if alloc is None:
            return False
        slot = engine.scheduler.adopt_running(internal, alloc)
        if slot is None:
            engine.allocator.rollback(alloc)   # consumer refused: legal
            return False
        self.install(slot)                     # the slot is put to work
        return True
