"""ATP302 positive: two methods acquire the same two locks in OPPOSITE
nested order — two threads running them concurrently deadlock. The
second pair goes through the call graph: `offer` holds the wire lock
and calls a helper that takes the books lock, while `fetch` nests them
the other way lexically."""
import threading


class Pod:
    def __init__(self):
        self._books_lock = threading.Lock()
        self._wire_lock = threading.Lock()

    def forward(self):
        with self._books_lock:
            with self._wire_lock:        # books -> wire
                self.ship()

    def on_frame(self):
        with self._wire_lock:
            with self._books_lock:       # wire -> books: the inversion
                self.record()
