"""ATP006 positive: Python control flow on a traced value."""
import jax


@jax.jit
def bad(x):
    s = x.sum()
    if s > 0:  # TracerBoolConversionError under jit
        return x
    return -x
