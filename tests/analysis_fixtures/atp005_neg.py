"""ATP005 negative: threaded jax.random key."""
import jax


@jax.jit
def good_dropout(x, key):
    mask = jax.random.bernoulli(key, 0.5, x.shape)
    return x * mask
