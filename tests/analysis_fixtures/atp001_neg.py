"""ATP001 negative: the read happens OUTSIDE the compiled function."""
import jax


@jax.jit
def good_step(x):
    return (x * x).sum()


def driver(x):
    loss = good_step(x)
    return loss.item()  # host code: fine
