"""ATP202 negative: one release per acquire per path — including the
branchy shape where each arm releases once, and a loop that re-acquires
each iteration."""


class SingleRelease:
    def one_arm_each(self, request):
        pages = self.pool.alloc(2)
        if pages is None:
            return
        if request.cancelled:
            self.pool.release(pages)
            return
        self.pool.release(pages)

    def loop_reacquires(self, requests):
        for request in requests:
            pages = self.pool.alloc(1)
            if pages is None:
                break
            self.pool.release(pages)
