"""ATP001 positive: .item() inside a jitted function (acceptance fixture)."""
import jax


@jax.jit
def bad_step(x):
    loss = (x * x).sum()
    return loss.item()  # blocks on device, breaks under trace


def also_bad(batch):
    return batch.tolist()


wrapped = jax.jit(also_bad)
