"""ATP003 negative: np work on trace-time constants is idiomatic."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def good(x):
    table = jnp.asarray(np.arange(16))  # np on host constants: fine
    return x + table.sum()
