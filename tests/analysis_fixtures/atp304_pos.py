"""ATP304 positive: both condition-variable protocol violations — a
bare `wait()` outside any `while` predicate loop (spurious wakeups and
lost notifies break it), and a `notify()` without holding the
condition's lock (RuntimeError, or a missed signal)."""
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self.items = []

    def take(self):
        with self._cv:
            if not self.items:
                self._cv.wait()          # bare wait: if, not while
            return self.items.pop()

    def put(self, item):
        self.items.append(item)
        self._cv.notify()                # lock not held
