"""ATP003 positive: np.asarray of a traced value mid-program."""
import jax
import numpy as np


@jax.jit
def bad(x):
    host = np.asarray(x)  # pulls the tracer to the host
    return host.sum()
