"""ATP201 positive: paired-resource leaks on early-return and exception
paths (acceptance fixture). Three shapes, all real bug classes:
an early return skipping the release, an exception between acquire and
release with no handler, and a void acquire (refcount) leaking at
fall-through."""


class LeakyAdmission:
    def early_return_leak(self, request):
        pages = self.pool.alloc(4)
        if pages is None:
            return None
        if request.cancelled:
            return False          # leak: pages never released/attached
        self.pool.release(pages)
        return True

    def exception_window_leak(self, request):
        nodes = self.index.match(request.prompt)
        self.index.acquire(nodes)
        self.record(request)      # may raise: refcounts leak
        self.index.release(nodes)

    def fall_through_leak(self, request):
        alloc = self.allocator.allocate(request)
        if alloc is None:
            return
        self.note(len(alloc.pages))   # len() is no-raise: not the leak
        # falls off the end holding the allocation
