"""ATP301 positive: `self.books` is written from a reader THREAD and
from an asyncio TASK (which races the thread preemptively), and the two
sites hold two DIFFERENT locks — no common lock means no exclusion.
Subscript stores count: the router-book-vs-heartbeat race is exactly
`self.books[k] = v` from two contexts."""
import asyncio
import threading


class RacyRouter:
    def start(self, loop):
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()
        loop.create_task(self._drive())

    def _pump(self):
        while not self._stop:
            with self._io_lock:
                self.books[self.next_id] = self.poll()   # lock A

    async def _drive(self):
        while True:
            with self._books_lock:
                self.books[0] = None                     # lock B != A
            await asyncio.sleep(0)

    def close(self):
        self._stop = True
        self._reader.join(timeout=5.0)
