"""ATP303 negative: the accepted idioms — `await asyncio.sleep`, timed
gets, executor offload (a callable REFERENCE, not a call), and waits
that are scheduled/bounded by asyncio rather than run inline."""
import asyncio


class Service:
    async def drive(self):
        loop = asyncio.get_running_loop()
        stop = loop.create_task(self.stop_requested.wait())
        while not stop.done():
            await asyncio.sleep(0.01)
            self._pump_once()
            await loop.run_in_executor(None, self._drain_blocking)
            await asyncio.wait_for(self.inbox_async.get(), timeout=1.0)

    def _pump_once(self):
        try:
            item = self.inbox.get(timeout=0.1)   # bounded: fine
        except Exception:
            return
        self.handle(item)

    def _drain_blocking(self):
        # only ever REFERENCED from the async side (executor offload),
        # never called from it — blocking here is the point
        self.worker.join()
