"""ATP203 positive: the acquire is conditional but the release is not —
on the no-acquire path the release underflows someone else's refcount."""


class AsymmetricProtocol:
    def conditional_acquire(self, request, cached):
        nodes = self.index.match(request.prompt)
        if cached:
            self.index.acquire(nodes)
        self.warm(request)
        self.index.release(nodes)      # no acquire on the not-cached path
