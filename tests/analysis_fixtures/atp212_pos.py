"""ATP212 positive: a shed transition (REJECTED/EXPIRED) that never sets
the machine-readable shed_code — this shed is invisible to the shed
vocabulary, clients get no structured reason, dashboards undercount."""
class RequestStatus:
    FINISHED = "finished"
    CANCELLED = "cancelled"
    REJECTED = "rejected"
    EXPIRED = "expired"


class UncodedShed:
    def _finalize(self, req):
        self.metrics.observe_request(req)

    def worker_drop(self, user, now):
        user.status = RequestStatus.EXPIRED
        user.reject_reason = "worker dropped the request"   # prose only
        user.finished_at = now
        self._finalize(user)
