"""ATP221 positive: engine state mutated BOTH from a registered thread
context (Thread target / watchdog dumps callback) and from drive-loop
methods, with no lock — a data race the event-loop confinement rule
exists to catch."""
import threading


class RacyServer:
    def start(self):
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    def _poll(self):
        while not self._stop:
            self.queue_depth = self.backlog()   # thread-side write

    def step(self):
        self.queue_depth = len(self.scheduler.queue)   # drive-side write
        return self.queue_depth
