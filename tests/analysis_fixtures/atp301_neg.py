"""ATP301 negative: the same thread-vs-task shape, but every write to
the shared attribute holds ONE common lock — the intersection of the
locksets is non-empty, so the exclusion is real."""
import asyncio
import threading


class LockedRouter:
    def start(self, loop):
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()
        loop.create_task(self._drive())

    def _pump(self):
        while not self._stop:
            with self._books_lock:
                self.books[self.next_id] = self.poll()

    async def _drive(self):
        while True:
            with self._books_lock:
                self.books[0] = None
            await asyncio.sleep(0)

    def close(self):
        self._stop = True
        self._reader.join(timeout=5.0)
