"""ATP221 negative: the accepted idioms — thread-side reads with
drive-side writes, mutations guarded by one lock on both sides, and a
read-only dumps callback handed to the watchdog."""
import threading


class ConfinedServer:
    def __init__(self):
        self._lock = threading.Lock()
        self.queue_depth = 0           # __init__ happens-before the thread
        self.watchdog = StallWatchdog(5.0, dumps=self.snapshot)
        self._thread = threading.Thread(target=self._poll, daemon=True)

    def _poll(self):
        while not self._stop:
            with self._lock:
                self.queue_depth = self.backlog()   # locked: fine

    def step(self):
        with self._lock:
            self.queue_depth = len(self.scheduler.queue)
        return self.queue_depth

    def snapshot(self):
        # read-only view from the watchdog thread: no writes, no finding
        return {"depth": self.queue_depth, "slots": list(self.slots)}
