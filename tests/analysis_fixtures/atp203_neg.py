"""ATP203 negative: release mirrors the acquire's condition, and
release-only functions (ownership passed IN from the caller) are the
other end of a cross-function protocol — not a finding."""


class SymmetricProtocol:
    def mirrored_condition(self, request, cached):
        nodes = self.index.match(request.prompt)
        if cached:
            self.index.acquire(nodes)
        count = len(request.prompt)   # no-raise work between the arms
        if cached:
            self.index.release(nodes)
        return count

    def release_only(self, slot):
        # the acquire happened at admission, in another function: the
        # caller handed us ownership, releasing it here is the protocol
        self.index.release(slot.nodes)
        self.pool.release(slot.pages)
