"""ATP305 positive: a started thread with no shutdown path — `close`
exists but never joins/stops/cancels the attribute. The daemon flag is
not an exemption: the thread still races interpreter teardown and pins
its socket."""
import threading


class Channel:
    def __init__(self, sock):
        self._sock = sock
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True)
        self._reader.start()

    def _read_loop(self):
        while not self._closed:
            self.inbox.append(self._sock.recv(4096))

    def close(self):
        self._closed = True
        self._sock.close()               # ...but the reader is never joined
