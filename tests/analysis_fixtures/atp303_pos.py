"""ATP303 positive: blocking calls on the event loop — a bare
`time.sleep` directly in an async def, and an untimed `queue.get()` in
a sync helper the async drive loop reaches through a call."""
import time


class Service:
    async def drive(self):
        while True:
            time.sleep(0.01)             # parks every task on the loop
            self._pump_once()

    def _pump_once(self):
        item = self.inbox.get()          # no timeout: blocks the loop
        self.handle(item)
