"""ATP202 positive: the same locally-acquired handle released twice on
one path — the refcount-underflow / double-free class."""


class DoubleRelease:
    def release_twice(self, request):
        nodes = self.index.match(request.prompt)
        self.index.acquire(nodes)
        self.index.release(nodes)
        self.index.release(nodes)      # underflow: already balanced

    def release_in_both_arms_then_again(self, request):
        pages = self.pool.alloc(2)
        if pages is None:
            return
        if request.cancelled:
            self.pool.release(pages)
        else:
            self.pool.release(pages)
        self.pool.release(pages)       # double on every path
