"""ATP305 negative: shutdown discipline done right — `close` reaps the
reader thread (through a same-class helper, which the closure follows)
and `stop` cancels the timer it started."""
import threading


class Channel:
    def __init__(self, sock):
        self._sock = sock
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True)
        self._reader.start()
        self._ticker = threading.Timer(5.0, self._beat)
        self._ticker.start()

    def _read_loop(self):
        while not self._closed:
            self.inbox.append(self._sock.recv(4096))

    def close(self):
        self._closed = True
        self._sock.close()
        self._reap()

    def _reap(self):
        self._reader.join(timeout=5.0)

    def stop(self):
        self._ticker.cancel()
