"""SIGKILL mid-save -> auto-resume (ISSUE 20 acceptance).

A subprocess (tests/crash_resume_script.py) commits a complete async
checkpoint at step 4, then SIGKILLs itself while step 6's background
persist is in flight. The parent asserts the on-disk outcome of the
commit protocol — step 6 torn and invisible, step 4 the newest complete
manifest — then resumes IN-PROCESS from what the dead process left
behind and verifies the continued loss trajectory is identical to an
unfaulted run.
"""

from __future__ import annotations

import importlib.util
import os
import signal
import subprocess
import sys

import pytest

from accelerate_tpu import checkpointing as ckpt

_SCRIPT = os.path.join(os.path.dirname(__file__), "crash_resume_script.py")


def _load_script_module():
    spec = importlib.util.spec_from_file_location("crash_resume_script",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_sigkill_mid_save_resumes_loss_curve_exact(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "CRASH_DIR": str(tmp_path)}
    out = subprocess.run([sys.executable, _SCRIPT], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == -signal.SIGKILL, (out.returncode, out.stderr)
    assert "ENQUEUED" in out.stdout  # died mid-persist, after the enqueue

    mod = _load_script_module()
    committed = os.path.join(str(tmp_path), f"step_{mod.COMMIT_STEP:08d}")
    torn = os.path.join(str(tmp_path), f"step_{mod.TORN_STEP:08d}")
    # the commit protocol's crash matrix: drained save complete, killed
    # save torn (bytes may exist — the manifest must not)
    assert ckpt.is_complete_checkpoint(committed)
    assert not ckpt.is_complete_checkpoint(torn)
    assert ckpt.latest_complete_checkpoint(str(tmp_path)) == \
        os.path.abspath(committed)

    # unfaulted reference trajectory, same deterministic toy loop
    ref_state = mod.make_state()
    reference = []
    for i in range(mod.NUM_STEPS):
        ref_state, metrics = mod.step_fn(ref_state, mod.batch_fn(i))
        reference.append(float(metrics["loss"]))

    # resume from the dead process's newest complete manifest
    state = mod.make_state()
    restored = ckpt.resume_latest(str(tmp_path), train_states=[state])
    assert restored is not None
    assert restored["step"] == mod.COMMIT_STEP
    assert restored["checkpoint_dir"] == os.path.abspath(committed)
    state = restored["train_states"][0]
    for i in range(mod.COMMIT_STEP, mod.NUM_STEPS):
        state, metrics = mod.step_fn(state, mod.batch_fn(i))
        assert float(metrics["loss"]) == pytest.approx(
            reference[i], abs=1e-7), i
