"""Serving-state sanitizer (ISSUE 13): the runtime half of the ATP2xx
lifecycle audit.

The suite-wide half of the acceptance lives in conftest.py — every
engine tier-1 builds runs with ACCELERATE_TPU_SANITIZE=1, so the whole
serving/speculative/pod surface is a sanitizer pass. This module proves
the sanitizer itself: deliberately corrupted engines FIRE with a
message naming the broken invariant, compile counts stay flat with the
checks on, the config/env resolution works, the pod router's joins are
covered, and a violation writes an incident bundle before propagating.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import gpt2
from accelerate_tpu.serving import (
    Engine,
    EngineConfig,
    RequestStatus,
    SanitizerViolation,
)
from accelerate_tpu.serving.sanitizer import resolve_sanitize


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (ISSUE 16 hit this the moment
    # an engine module sorted before test_launched_scripts)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _engine(cfg, params, **overrides):
    defaults = dict(num_slots=2, max_len=64, prefill_chunk=8, page_size=8,
                    cache_dtype=jnp.float32, sanitize=True)
    defaults.update(overrides)
    return Engine(gpt2, cfg, params, EngineConfig(**defaults))


def _prompt(rng, n, vocab):
    return rng.integers(0, vocab, (n,)).astype(np.int32)


def _serve_one(eng, cfg, seed=0, n=9, budget=3):
    rng = np.random.default_rng(seed)
    r = eng.submit(_prompt(rng, n, cfg.vocab_size), max_new_tokens=budget)
    eng.run_until_idle()
    assert r.status is RequestStatus.FINISHED
    return r


# ---------------------------------------------------------------------------
# config / env resolution
# ---------------------------------------------------------------------------


def test_resolve_sanitize_explicit_beats_env(monkeypatch):
    monkeypatch.setenv("ACCELERATE_TPU_SANITIZE", "1")
    assert resolve_sanitize(None) is True
    assert resolve_sanitize(False) is False
    monkeypatch.setenv("ACCELERATE_TPU_SANITIZE", "")
    assert resolve_sanitize(None) is False
    assert resolve_sanitize(True) is True


def test_sanitize_false_really_disables(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, sanitize=False)
    _serve_one(eng, cfg)
    eng._table[0, 0] = 0          # idle rows must be trash — corruption
    assert eng.step() is False    # no check, no raise


# ---------------------------------------------------------------------------
# the corrupted-pool proofs: each invariant fires with a useful message
# ---------------------------------------------------------------------------


def test_fires_on_stale_idle_table_row(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    _serve_one(eng, cfg)
    eng._table[0, 0] = 0          # a retired lane's row points at page 0
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "table"
    assert "trash" in str(ei.value)
    assert ei.value.details["slot"] == 0


def test_fires_on_free_list_duplicate(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    _serve_one(eng, cfg)
    free = eng.allocator.pool._free
    free.append(free[0])          # one page, two free-list entries
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "page-conservation"
    assert "duplicate" in str(ei.value)


def test_fires_on_refcount_corruption(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    _serve_one(eng, cfg, n=17)    # retirement caches 2 full prompt pages
    index = eng.allocator.index
    assert index.cached_pages >= 1
    node = next(iter(index.root.children.values()))
    node.refcount += 1            # phantom mapping: nobody holds this
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "refcount"
    assert ei.value.details["page"] == node.page


def test_fires_on_lost_page(gpt2_setup):
    """A page missing from free+tree+slots entirely (the classic leak
    end-state) breaks conservation."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    _serve_one(eng, cfg)
    eng.allocator.pool._free.pop()        # a page vanishes
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "page-conservation"
    assert "lost or double-counted" in str(ei.value)


def test_fires_on_scheduler_book_corruption(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=1, max_queue=4)
    rng = np.random.default_rng(3)
    r1 = eng.submit(_prompt(rng, 9, cfg.vocab_size), max_new_tokens=20)
    r2 = eng.submit(_prompt(rng, 9, cfg.vocab_size), max_new_tokens=2)
    assert r2.status is RequestStatus.QUEUED
    r2.status = RequestStatus.RUNNING     # a queued request claims RUNNING
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "scheduler-books"
    assert ei.value.details["request_id"] == r2.request_id
    # un-corrupt so the engine can drain (suite hygiene)
    r2.status = RequestStatus.QUEUED
    eng.cancel(r1)
    eng.cancel(r2)


# ---------------------------------------------------------------------------
# the two-tier (ISSUE 16) joins: host residency vs the tier's mirror
# ---------------------------------------------------------------------------


def _host_tier_engine(cfg, params, rng, serves=2):
    """An engine with host-resident radix nodes: one prompt cached, then
    churned out to the tier. Two serves are the cheapest churn that
    leaves a host-resident node; serves=3 builds a deeper host chain
    (a parent->child pair) for the suffix-property test."""
    eng = _engine(cfg, params, page_size=4, num_pages=18,
                  host_tier_bytes=1 << 28)
    for _ in range(serves):
        r = eng.submit(_prompt(rng, 33, cfg.vocab_size), max_new_tokens=2)
        eng.run_until_idle()
        assert r.status is RequestStatus.FINISHED
    assert eng.allocator.index.host_pages > 0
    return eng


def test_fires_on_host_node_without_mirror(gpt2_setup):
    """A host-resident node whose tier entry vanished is a prefix whose
    bytes are GONE — a hit would install garbage."""
    cfg, params = gpt2_setup
    eng = _host_tier_engine(cfg, params, np.random.default_rng(20))
    node = next(iter(eng._host_tier._entries))
    del eng._host_tier._entries[node]
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "page-conservation"
    assert "mirror" in str(ei.value)
    eng.close()


def test_fires_on_host_node_claiming_hbm_page(gpt2_setup):
    """A host-resident node still naming an HBM page double-owns it —
    the residency flip and the page release must be atomic."""
    cfg, params = gpt2_setup
    eng = _host_tier_engine(cfg, params, np.random.default_rng(21))
    node = next(iter(eng._host_tier._entries))
    node.page = 0
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "page-conservation"
    assert "host-resident" in str(ei.value)
    eng.close()


def test_fires_on_host_pages_counter_drift(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _host_tier_engine(cfg, params, np.random.default_rng(22))
    eng.allocator.index.host_pages += 1
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "page-conservation"
    assert "host_pages" in str(ei.value)
    eng.close()


def test_fires_on_hbm_child_under_host_parent(gpt2_setup):
    """Residency must be a suffix property along any root path —
    eviction drains leaf-first, so an HBM node under a host parent
    means the eviction order was violated."""
    cfg, params = gpt2_setup
    eng = _host_tier_engine(cfg, params, np.random.default_rng(23), serves=3)
    node = next(n for n in eng._host_tier._entries if n.children)
    child = next(iter(node.children.values()))
    assert child.residency == "host"
    # fake an HBM child: give it a page the sanitizer can see
    eng._host_tier.discard(child)
    child.residency = "hbm"
    child.page = eng.allocator.pool._free[0]
    with pytest.raises(SanitizerViolation) as ei:
        eng.step()
    assert ei.value.check == "page-conservation"
    eng.close()


# ---------------------------------------------------------------------------
# acceptance pins: host-side only, compile counts flat, PR 12 surface
# ---------------------------------------------------------------------------


def test_compile_counts_flat_with_sanitizer_on(gpt2_setup):
    """The sanitizer is host-side only: driving mixed waves (cold, hot
    prefix hit, sampled) with sanitize=True compiles each program
    exactly once — same pin as the classic guard."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, sanitize=True)
    rng = np.random.default_rng(5)
    shared = _prompt(rng, 18, cfg.vocab_size)
    for temp in (0.0, 0.9):
        reqs = [eng.submit(np.concatenate(
                    [shared, _prompt(rng, 2 + i, cfg.vocab_size)]),
                    max_new_tokens=3, temperature=temp)
                for i in range(2)]
        eng.run_until_idle()
        assert all(r.status is RequestStatus.FINISHED for r in reqs)
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}
    assert eng.metrics.prefix_hits >= 1


def test_fork_and_speculative_run_sanitized(gpt2_setup):
    """The PR 12 surface under explicit sanitize=True: a COW fork
    fan-out with a mid-flight parent cancel, and a speculative engine's
    accept/rollback paths, both complete with the checks on every
    step."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, max_len=96, sanitize=True)
    rng = np.random.default_rng(6)
    prompt = _prompt(rng, 24, cfg.vocab_size)
    parent = eng.submit(prompt, max_new_tokens=6, temperature=0.7,
                        key=np.array([1, 0], np.uint32))
    forks = [eng.fork(parent, key=np.array([1, i + 1], np.uint32))
             for i in (1, 2)]
    while len(parent.tokens) < 2:
        eng.step()
    assert eng.cancel(parent)
    eng.run_until_idle()
    assert all(f.status is RequestStatus.FINISHED for f in forks)
    assert eng.allocator.index.mapped_pages == 0

    spec = _engine(cfg, params, sanitize=True,
                   speculative=(gpt2, cfg, params), draft_k=3)
    r = spec.submit(_prompt(rng, 9, cfg.vocab_size), max_new_tokens=6)
    spec.run_until_idle()
    assert r.status is RequestStatus.FINISHED
    assert len(r.tokens) == 6


# ---------------------------------------------------------------------------
# pod router joins
# ---------------------------------------------------------------------------


def test_router_fires_on_stale_admit_snapshot(gpt2_setup):
    from accelerate_tpu.serving.pod import PodConfig, PodEngine

    cfg, params = gpt2_setup
    pod = PodEngine(gpt2, cfg, params,
                    EngineConfig(num_slots=2, max_len=64, prefill_chunk=8,
                                 cache_dtype=jnp.float32, sanitize=True))
    rng = np.random.default_rng(7)
    r = pod.submit(_prompt(rng, 9, cfg.vocab_size), max_new_tokens=3)
    pod.run_until_idle()
    assert r.status is RequestStatus.FINISHED
    # a snapshot entry whose internal is long gone: the leak class the
    # router-books join exists for
    pod._admit_pages[123456] = [0, 1]
    with pytest.raises(SanitizerViolation) as ei:
        pod.step()
    assert ei.value.check == "router-books"
    assert "snapshot" in str(ei.value)


# ---------------------------------------------------------------------------
# incident-bundle attachment
# ---------------------------------------------------------------------------


def test_violation_writes_incident_bundle(gpt2_setup, tmp_path):
    from accelerate_tpu.telemetry.watchdog import (
        list_incident_bundles,
        load_incident_bundle,
    )

    cfg, params = gpt2_setup
    eng = _engine(cfg, params, incident_dir=str(tmp_path))
    _serve_one(eng, cfg)
    eng._table[0, 0] = 0
    with pytest.raises(SanitizerViolation):
        eng.step()
    bundles = list_incident_bundles(str(tmp_path))
    assert bundles, "a sanitizer violation must leave an incident bundle"
    bundle = load_incident_bundle(bundles[-1]["path"])
    report = bundle.get("report", bundle)
    text = str(report)
    assert "table" in text and "sanitizer" in text.lower()
