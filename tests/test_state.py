"""State/mesh core tests (ref tests/test_state_checkpointing.py + test_utils)."""

import jax
import numpy as np
import pytest

from accelerate_tpu import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    DistributedType,
    GradientAccumulationPlugin,
    MeshConfig,
)


def test_partial_state_topology():
    state = PartialState()
    assert state.num_processes == 1
    assert state.process_index == 0
    assert state.device_count == 8
    assert state.is_main_process
    assert state.is_last_process
    assert state.distributed_type == DistributedType.JAX


def test_partial_state_singleton():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__


def test_default_mesh_is_data_parallel():
    state = PartialState()
    assert dict(state.mesh.shape) == {AXIS_DATA: 8}


def test_split_between_processes_single():
    state = PartialState()
    with state.split_between_processes([1, 2, 3]) as x:
        assert x == [1, 2, 3]


def test_on_main_process_decorators():
    state = PartialState()
    calls = []
    state.on_main_process(lambda: calls.append("main"))()
    state.on_last_process(lambda: calls.append("last"))()
    state.on_process(lambda: calls.append("p0"), 0)()
    assert calls == ["main", "last", "p0"]


def test_accelerator_state_mesh_config():
    state = AcceleratorState(mesh_config=MeshConfig(axes={AXIS_DATA: 2, AXIS_MODEL: 4}))
    assert dict(state.mesh.shape) == {AXIS_DATA: 2, AXIS_MODEL: 4}
    assert state.dp_size == 2
    assert state.axis_size(AXIS_MODEL) == 4


def test_accelerator_state_mixed_precision_conflict():
    AcceleratorState(mixed_precision="bf16")
    with pytest.raises(ValueError):
        AcceleratorState(mixed_precision="fp16")


def test_mesh_config_wildcard_resolution():
    cfg = MeshConfig(axes={AXIS_DATA: 2, AXIS_FSDP: -1})
    assert cfg.resolved_axes(8) == {AXIS_DATA: 2, AXIS_FSDP: 4}
    with pytest.raises(ValueError):
        MeshConfig(axes={AXIS_DATA: 3}).resolved_axes(8)
    with pytest.raises(ValueError):
        MeshConfig(axes={"bogus": 2})


def test_mesh_config_canonical_order():
    cfg = MeshConfig(axes={AXIS_MODEL: 4, AXIS_DATA: -1})
    mesh = cfg.build()
    assert mesh.axis_names == (AXIS_DATA, AXIS_MODEL)  # data outermost


def test_gradient_state_accumulation_flags():
    gs = GradientState(GradientAccumulationPlugin(num_steps=4))
    assert gs.num_steps == 4
    assert gs.sync_gradients
    gs._set_sync_gradients(False)
    assert not GradientState().sync_gradients  # singleton
    assert gs.remainder == -1  # no dataloader registered


def test_wait_for_everyone_noop_single_host():
    PartialState().wait_for_everyone()  # must not raise
