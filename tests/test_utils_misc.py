"""Tests for env parsing, versions, memory, misc utils (ref tests/test_utils.py)."""

import os

import numpy as np
import pytest

from accelerate_tpu.utils import (
    convert_bytes,
    find_executable_batch_size,
    flatten_dict,
    merge_dicts,
    parse_mesh_shape,
    patch_environment,
    release_memory,
    set_seed,
    should_reduce_batch_size,
    str_to_bool,
    unflatten_dict,
)
from accelerate_tpu.utils.versions import compare_versions


def test_str_to_bool():
    assert str_to_bool("TRUE") and str_to_bool("1") and str_to_bool("yes")
    assert not str_to_bool("0") and not str_to_bool("off") and not str_to_bool("")
    with pytest.raises(ValueError):
        str_to_bool("maybe")


def test_patch_environment():
    assert "ACC_TEST_VAR" not in os.environ
    with patch_environment(acc_test_var="7"):
        assert os.environ["ACC_TEST_VAR"] == "7"
    assert "ACC_TEST_VAR" not in os.environ


def test_parse_mesh_shape():
    assert parse_mesh_shape("data=8,model=4") == {"data": 8, "model": 4}
    assert parse_mesh_shape("8x4") == {"data": 8, "fsdp": 4}
    assert parse_mesh_shape("") == {}


def test_flatten_unflatten_roundtrip():
    tree = {"a": {"b": 1, "c": [2, 3]}, "d": 4}
    flat = flatten_dict(tree)
    assert flat["a.b"] == 1 and flat["a.c.0"] == 2
    restored = unflatten_dict(flat)
    assert restored["a"]["b"] == 1 and restored["a"]["c"]["1"] == 3


def test_merge_dicts():
    dst = {"a": {"x": 1}, "b": 2}
    merge_dicts({"a": {"y": 3}, "b": 9}, dst)
    assert dst == {"a": {"x": 1, "y": 3}, "b": 9}


def test_convert_bytes():
    assert convert_bytes(1024) == "1.0 KB"
    assert convert_bytes(3 * 1024**3) == "3.0 GB"


@pytest.fixture
def _stub_cache_clearing(monkeypatch):
    """These tests pin the halving/reraise POLICY, not the cache hygiene:
    the real `gc.collect()` + `jax.clear_caches()` between attempts cost
    ~16s against the suite's heap AND wiped every compiled program later
    tests would have reused (ISSUE 7 slow-tail satellite). Stub them; the
    policy assertions are unchanged."""
    from accelerate_tpu.utils import memory as memory_mod

    monkeypatch.setattr(memory_mod.gc, "collect", lambda: 0)
    monkeypatch.setattr(memory_mod.jax, "clear_caches", lambda: None)


def test_find_executable_batch_size_halves_on_oom(_stub_cache_clearing):
    attempts = []

    @find_executable_batch_size(starting_batch_size=16)
    def run(batch_size):
        attempts.append(batch_size)
        if batch_size > 4:
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating")
        return batch_size

    assert run() == 4
    assert attempts == [16, 8, 4]


def test_find_executable_batch_size_reraises_non_oom(_stub_cache_clearing):
    @find_executable_batch_size(starting_batch_size=8)
    def run(batch_size):
        raise ValueError("not oom")

    with pytest.raises(ValueError):
        run()


def test_find_executable_batch_size_rejects_explicit_batch(
        _stub_cache_clearing):
    @find_executable_batch_size(starting_batch_size=8)
    def run(batch_size, other):
        return batch_size

    with pytest.raises(TypeError):
        run(128, "x")


def test_should_reduce_batch_size():
    assert should_reduce_batch_size(RuntimeError("RESOURCE_EXHAUSTED: ..."))
    assert should_reduce_batch_size(MemoryError())
    assert not should_reduce_batch_size(ValueError("nope"))


def test_set_seed_deterministic():
    set_seed(1234)
    a = np.random.rand(3)
    set_seed(1234)
    b = np.random.rand(3)
    np.testing.assert_array_equal(a, b)


def test_compare_versions():
    assert compare_versions("1.2.3", "<", "1.10.0")
    assert compare_versions("jax", ">=", "0.4.0")


def test_release_memory(_stub_cache_clearing):
    """Pins the reference-dropping contract (every passed object comes
    back None). The cache-hygiene side (`gc.collect` + `jax.clear_caches`)
    is stubbed like the find_executable_batch_size tests above: against a
    late-suite heap the real calls cost ~7s and wipe every compiled
    program — the exact slow-tail class ISSUE 7's satellite fixed for the
    sibling tests (this one was the stragglers' straggler)."""
    x, y = np.ones(10), np.ones(10)
    x, y = release_memory(x, y)
    assert x is None and y is None


def test_set_virtual_host_devices_preserves_sibling_flags(monkeypatch):
    """Overlay-env substitution must start from the parent's XLA_FLAGS, not
    drop sibling flags (round-4 review find)."""
    from accelerate_tpu.utils.environment import set_virtual_host_devices

    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_dump_to=/tmp/d --xla_force_host_platform_device_count=8",
    )
    overlay = {}
    set_virtual_host_devices(2, overlay)
    assert overlay["XLA_FLAGS"] == (
        "--xla_dump_to=/tmp/d --xla_force_host_platform_device_count=2"
    )
    # direct os.environ use still substitutes in place
    set_virtual_host_devices(4)
    import os
    assert "--xla_dump_to=/tmp/d" in os.environ["XLA_FLAGS"]
    assert "device_count=4" in os.environ["XLA_FLAGS"]
