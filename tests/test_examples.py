"""Example-script smoke tests (ref tests/test_examples.py — runs every
example with tiny settings; the reference also diffs by_feature scripts
against the complete_* canon, which has no analogue here since our examples
share helpers by import instead of by copy).

Fast in-process runs with tiny args; anything needing a fresh world or >30 s
of compile is marked slow (RUN_SLOW=1).
"""

from __future__ import annotations

import importlib.util
import os
import sys
import tempfile

import pytest

EXAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def _load(relpath: str):
    path = os.path.join(EXAMPLES_DIR, relpath)
    name = relpath.removesuffix(".py").replace("/", "_")
    sys.path.insert(0, EXAMPLES_DIR)
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    finally:
        sys.path.remove(EXAMPLES_DIR)


class _Args:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def test_nlp_example():
    mod = _load("nlp_example.py")
    metrics = mod.training_function(_Args(
        mixed_precision="no", batch_size=16, num_epochs=1, lr=2e-4, seed=0,
        gradient_accumulation_steps=1, fsdp=False, tiny=True, project_dir=None,
    ))
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_cv_example():
    mod = _load("cv_example.py")
    metrics = mod.training_function(_Args(
        mixed_precision="no", batch_size=16, num_epochs=1, lr=3e-3, width=8,
        seed=0,
    ))
    assert "accuracy" in metrics


def test_gradient_accumulation_example():
    mod = _load("by_feature/gradient_accumulation.py")
    metrics = mod.training_function(_Args(
        gradient_accumulation_steps=4, batch_size=8, num_epochs=2, lr=0.05,
        seed=0,
    ))
    assert metrics["loss"] < 10


def test_early_stopping_example():
    mod = _load("by_feature/early_stopping.py")
    metrics = mod.training_function(_Args(
        loss_threshold=0.5, batch_size=8, num_epochs=10, lr=0.05, seed=0,
    ))
    assert metrics["stopped_at_step"] is not None


def test_multi_process_metrics_example():
    mod = _load("by_feature/multi_process_metrics.py")
    metrics = mod.training_function(_Args(
        batch_size=8, num_epochs=1, lr=0.05, seed=0,
    ))
    assert metrics["samples_seen"] == 100


def test_schedule_free_example():
    mod = _load("by_feature/schedule_free.py")
    metrics = mod.training_function(_Args(
        batch_size=8, num_epochs=2, lr=0.05, seed=0,
    ))
    assert metrics["eval_mse"] < 5.0


def test_checkpointing_example():
    mod = _load("by_feature/checkpointing.py")
    with tempfile.TemporaryDirectory() as tmp:
        metrics = mod.training_function(_Args(
            project_dir=tmp, batch_size=8, num_epochs=1, lr=0.05, seed=0,
        ))
    assert metrics["resumed_at_step"] == 16


def test_tracking_example():
    mod = _load("by_feature/tracking.py")
    with tempfile.TemporaryDirectory() as tmp:
        mod.training_function(_Args(
            log_with="jsonl", project_dir=tmp, batch_size=8, num_epochs=1,
            lr=0.05, seed=0,
        ))
        logged = []
        for root, _, files in os.walk(tmp):
            logged += [f for f in files if f.endswith(".jsonl")]
        assert logged, "jsonl tracker wrote nothing"


@pytest.mark.slow
def test_zero_stage_config_example():
    mod = _load("by_feature/zero_stage_config.py")
    for stage in (0, 3):
        metrics = mod.training_function(_Args(
            zero_stage=stage, offload_param_device=None,
            gradient_accumulation_steps=1, mixed_precision="no",
            batch_size=16, num_epochs=1, lr=2e-4, seed=0, tiny=True,
        ))
        assert metrics["loss"] < 10


@pytest.mark.slow
def test_gspmd_gpt_pretraining_example():
    mod = _load("by_feature/gspmd_gpt_pretraining.py")
    metrics = mod.training_function(_Args(
        tp=2, fsdp=2, dp=2, mixed_precision="no",
        activation_checkpointing=False, seq_len=64, batch_size=8,
        num_epochs=1, lr=3e-4, seed=0, tiny=True,
    ))
    assert metrics["lm_loss"] < 20


def test_low_precision_training_example():
    mod = _load("by_feature/low_precision_training.py")
    metrics = mod.training_function(_Args(
        no_fp8=False, batch_size=4, num_epochs=2, lr=5e-3, seed=0,
    ))
    assert metrics["last_loss"] < metrics["first_loss"]


def test_long_context_ring_attention_example():
    mod = _load("by_feature/long_context_ring_attention.py")
    for mode in ("ring", "ulysses"):
        metrics = mod.training_function(_Args(
            cp_mode=mode, cp_degree=2, seq_len=256, batch_size=2, steps=4,
            lr=3e-4, seed=0, mixed_precision="no", tiny=True,
        ))
        assert metrics["loss"] < metrics["first_loss"], mode
