"""Data layer tests (ref tests/test_data_loader.py, 529 LoC; same scenarios
re-expressed for the host-shard + global-array design)."""

import jax
import numpy as np
import pytest

from accelerate_tpu import GradientState, PartialState
from accelerate_tpu.data import (
    BatchSamplerShard,
    DataLoaderDispatcher,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SkipDataLoader,
    make_global_batch,
    pad_batch_to,
    prepare_data_loader,
    skip_first_batches,
)


class SimpleBatchSampler:
    def __init__(self, n, batch_size, drop_last=False):
        self.indices = list(range(n))
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for i in self.indices:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return len(self.indices) // self.batch_size
        return -(-len(self.indices) // self.batch_size)


def make_batches(n, batch_size):
    """Iterable of dict batches over arange data."""
    data = np.arange(n)
    for i in range(0, n, batch_size):
        chunk = data[i : i + batch_size]
        yield {"x": chunk.reshape(-1, 1).astype(np.float32), "y": chunk.astype(np.int32)}


# --- samplers ---------------------------------------------------------------


def test_seedable_sampler_deterministic_and_epoch_varying():
    s = SeedableRandomSampler(10, seed=3)
    first = list(s)
    assert sorted(first) == list(range(10))
    assert list(s) == first  # same epoch -> same order
    s.set_epoch(1)
    assert list(s) != first


def test_batch_sampler_shard_stride_even():
    # 8 batches over 2 shards -> 4 each, disjoint, strided
    base = SimpleBatchSampler(16, 2)
    shards = [
        list(BatchSamplerShard(base, num_processes=2, process_index=i)) for i in range(2)
    ]
    assert len(shards[0]) == len(shards[1]) == 4
    assert shards[0][0] == [0, 1] and shards[1][0] == [2, 3]
    seen = sorted(i for shard in shards for b in shard for i in b)
    assert seen == list(range(16))


def test_batch_sampler_shard_uneven_wraparound():
    # 5 batches of 2 over 2 shards: tail batch -> shard0 real, shard1 recycled
    base = SimpleBatchSampler(10, 2)
    s0 = list(BatchSamplerShard(base, num_processes=2, process_index=0))
    s1 = list(BatchSamplerShard(base, num_processes=2, process_index=1))
    assert len(s0) == len(s1) == 3
    assert all(len(b) == 2 for b in s0 + s1)
    assert s0[-1] == [8, 9]
    assert all(i < 4 for i in s1[-1])  # recycled from the initial batches


def test_batch_sampler_shard_uneven_no_even_batches():
    base = SimpleBatchSampler(10, 2)
    s0 = list(BatchSamplerShard(base, 2, 0, even_batches=False))
    s1 = list(BatchSamplerShard(base, 2, 1, even_batches=False))
    assert len(s0) == 3 and len(s1) == 2


def test_batch_sampler_shard_split_batches():
    base = SimpleBatchSampler(16, 4)
    s0 = list(BatchSamplerShard(base, 2, 0, split_batches=True))
    s1 = list(BatchSamplerShard(base, 2, 1, split_batches=True))
    assert len(s0) == len(s1) == 4
    assert s0[0] == [0, 1] and s1[0] == [2, 3]
    with pytest.raises(ValueError):
        list(BatchSamplerShard(SimpleBatchSampler(9, 3), 2, 0, split_batches=True))
    # lazy validation must not consume batches from one-shot iterators
    gen = iter(SimpleBatchSampler(16, 4))
    shard = BatchSamplerShard(gen, 2, 0, split_batches=True)
    assert list(shard)[0] == [0, 1]


def test_iterable_dataset_shard():
    shards = [
        list(IterableDatasetShard(range(10), batch_size=2, num_processes=2, process_index=i))
        for i in range(2)
    ]
    # buffers of 4: [0..3] -> p0 gets 0,1 p1 gets 2,3; [4..7] -> 4,5 / 6,7;
    # tail [8,9] padded with first-loop items [0,1]
    assert shards[0] == [0, 1, 4, 5, 8, 9]
    assert shards[1] == [2, 3, 6, 7, 0, 1]


def test_iterable_dataset_shard_drop_last():
    out = list(
        IterableDatasetShard(range(10), batch_size=2, num_processes=2, process_index=0, drop_last=True)
    )
    assert out == [0, 1, 4, 5]


# --- global assembly --------------------------------------------------------


def test_make_global_batch_shards_over_data_axis():
    batch = {"x": np.arange(16.0).reshape(16, 1)}
    out = make_global_batch(batch)
    arr = out["x"]
    assert isinstance(arr, jax.Array)
    assert arr.shape == (16, 1)
    assert len(arr.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(arr), batch["x"])


def test_make_global_batch_replicates_indivisible():
    out = make_global_batch({"x": np.ones((3, 2)), "s": np.float32(2.0)})
    assert out["x"].sharding.is_fully_replicated
    assert out["s"].sharding.is_fully_replicated


def test_pad_batch_to_wraparound():
    out = pad_batch_to({"x": np.arange(3)}, 8)
    np.testing.assert_array_equal(out["x"], [0, 1, 2, 0, 1, 2, 0, 1])


# --- loaders ----------------------------------------------------------------


def test_dataloader_shard_end_detection_and_gradient_state():
    gs = GradientState()
    loader = DataLoaderShard(list(make_batches(32, 8)))
    ends = []
    for batch in loader:
        assert isinstance(batch["x"], jax.Array)
        ends.append(gs.end_of_dataloader)
    assert ends == [False, False, False, True]
    assert not gs.in_dataloader  # unregistered after epoch


def test_dataloader_shard_uneven_final_batch_padded():
    loader = DataLoaderShard(list(make_batches(20, 8)))  # final batch of 4
    batches = list(loader)
    assert batches[-1]["x"].shape[0] == 8  # padded to divisible
    assert loader.remainder == 4


def test_dataloader_shard_epoch_advances():
    class EpochAware:
        epoch = None

        def __init__(self):
            self.batches = list(make_batches(8, 4))

        def set_epoch(self, e):
            EpochAware.epoch = e

        def __iter__(self):
            return iter(self.batches)

        def __len__(self):
            return len(self.batches)

    src = EpochAware()
    loader = DataLoaderShard(src)
    list(loader)
    assert EpochAware.epoch == 1  # advanced for next epoch


def test_dataloader_dispatcher_single_host():
    loader = DataLoaderDispatcher(list(make_batches(16, 4)))
    batches = list(loader)
    assert len(batches) == 4
    assert isinstance(batches[0]["x"], jax.Array)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["y"]) for b in batches]), np.arange(16)
    )


def test_skip_first_batches():
    loader = DataLoaderShard(list(make_batches(32, 8)))
    skipped = skip_first_batches(loader, 2)
    batches = list(skipped)
    assert len(batches) == 2
    np.testing.assert_array_equal(np.asarray(batches[0]["y"]), np.arange(16, 24))
    # original loader unaffected
    assert len(list(loader)) == 4


def test_skip_dataloader_plain():
    out = list(SkipDataLoader(list(range(5)), 3))
    assert out == [3, 4]


def test_prepare_data_loader_plain_iterable():
    loader = prepare_data_loader(list(make_batches(16, 4)))
    assert isinstance(loader, DataLoaderShard)
    assert len(list(loader)) == 4


def test_prepare_data_loader_dispatch_mode():
    loader = prepare_data_loader(list(make_batches(16, 4)), dispatch_batches=True)
    assert isinstance(loader, DataLoaderDispatcher)
    assert len(list(loader)) == 4


def test_prepare_torch_loader_resharded():
    torch = pytest.importorskip("torch")
    import torch.utils.data as tud

    class DS(tud.Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    dl = tud.DataLoader(DS(), batch_size=2, shuffle=False)
    out = prepare_data_loader(dl, num_processes=2, process_index=0, put_on_device=False)
    batches = list(out)
    assert len(batches) == 4  # 8 batches strided over 2 hosts
    np.testing.assert_array_equal(np.asarray(batches[0]["x"]), [0.0, 1.0])
    np.testing.assert_array_equal(np.asarray(batches[1]["x"]), [4.0, 5.0])


def test_sharded_batch_iterable_lockstep_shapes():
    """Uneven tail across hosts: every host yields the same number of
    batches, all padded to the full batch size (SPMD lockstep invariant)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [
        {"x": np.arange(8, dtype=np.float32)},
        {"x": np.arange(8, 16, dtype=np.float32)},
        {"x": np.arange(16, 22, dtype=np.float32)},  # short tail (6 rows)
    ]
    per_host = [
        list(ShardedBatchIterable(batches, 2, rank, even_batches=True))
        for rank in range(2)
    ]
    counts = [len(b) for b in per_host]
    assert counts == [2, 2], counts
    for host in per_host:
        for b in host:
            assert np.asarray(b["x"]).shape == (8,), b
    # host0's tail round holds the real short batch padded; host1 recycled
    real = np.asarray(per_host[0][1]["x"])
    np.testing.assert_array_equal(real[:6], np.arange(16, 22, dtype=np.float32))


def test_sharded_batch_iterable_uneven_no_even_batches():
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.full((4,), i, np.float32)} for i in range(5)]
    got = [
        [int(np.asarray(b["x"])[0]) for b in
         ShardedBatchIterable(batches, 2, rank, even_batches=False)]
        for rank in range(2)
    ]
    assert got == [[0, 2, 4], [1, 3]], got


def test_sharded_batch_iterable_short_tail_divisible_count():
    """Batch count divides P but the LAST batch is short: it must still be
    padded so hosts stay shape-lockstepped, and the duplicated rows tracked."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [
        {"x": np.arange(4, dtype=np.float32)},
        {"x": np.arange(4, 8, dtype=np.float32)},
        {"x": np.arange(8, 12, dtype=np.float32)},
        {"x": np.arange(12, 14, dtype=np.float32)},  # short (2 rows), 4 % 2 == 0
    ]
    iters = [ShardedBatchIterable(batches, 2, rank) for rank in range(2)]
    per_host = [list(it) for it in iters]
    for host in per_host:
        assert [np.asarray(b["x"]).shape for b in host] == [(4,), (4,)]
    # final round: rank0 holds batch 2 (full), rank1 batch 3 (2 real rows):
    # real rows in the gathered final round = 1*4 + 2
    assert iters[0].remainder == 6 and iters[1].remainder == 6


def test_sharded_batch_iterable_full_final_round_no_remainder():
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.full((4,), i, np.float32)} for i in range(4)]
    it = ShardedBatchIterable(batches, 2, 0)
    list(it)
    assert it.remainder == -1


def test_sharded_batch_iterable_split_mode():
    """split_batches: each host slices every batch; global batch == source
    batch size (ref data_loader split_batches semantics)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [
        {"x": np.arange(8, dtype=np.float32)},
        {"x": np.arange(8, 16, dtype=np.float32)},
        {"x": np.arange(16, 22, dtype=np.float32)},  # short tail
    ]
    iters = [
        ShardedBatchIterable(batches, 2, rank, split_batches=True)
        for rank in range(2)
    ]
    per_host = [list(it) for it in iters]
    assert [len(h) for h in per_host] == [3, 3]
    np.testing.assert_array_equal(np.asarray(per_host[0][0]["x"]), np.arange(4))
    np.testing.assert_array_equal(np.asarray(per_host[1][0]["x"]), np.arange(4, 8))
    # padded tail tracked: 6 real rows in the final global batch
    assert iters[0].remainder == 6 and iters[1].remainder == 6
    # hosts' slices of the padded tail reassemble to the real rows first
    tail = np.concatenate([np.asarray(per_host[0][2]["x"]),
                           np.asarray(per_host[1][2]["x"])])
    np.testing.assert_array_equal(tail[:6], np.arange(16, 22, dtype=np.float32))


def test_prepare_data_loader_split_batches_plain_iterable():
    """prepare_data_loader honors split_batches for plain batch lists."""
    from accelerate_tpu.data import prepare_data_loader

    batches = [{"x": np.arange(8, dtype=np.float32)}]
    loader = prepare_data_loader(
        batches, num_processes=2, process_index=1, split_batches=True,
        put_on_device=False,
    )
    (got,) = list(loader)
    np.testing.assert_array_equal(np.asarray(got["x"]), np.arange(4, 8))


def test_split_mode_no_even_batches_short_tail_raises():
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(8, dtype=np.float32)},
               {"x": np.arange(8, 13, dtype=np.float32)}]
    it = ShardedBatchIterable(batches, 2, 0, even_batches=False,
                              split_batches=True)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="short final batch"):
        list(it)


def test_split_mode_scalar_leaf_replicates():
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(8, dtype=np.float32), "w": np.float32(0.5)}]
    (got,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    np.testing.assert_array_equal(got["x"], np.arange(4, 8, dtype=np.float32))
    assert float(got["w"]) == 0.5


def test_split_mode_string_list_slices_by_row():
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32),
                "text": ["a", "b", "c", "d"]}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert got0["text"] == ["a", "b"] and got1["text"] == ["c", "d"]


def test_split_mode_tuple_batch_slices_per_field():
    """A tuple batch (inputs, labels) is pytree structure, not a row
    container: every field slices row-wise on each rank (advisor r1 finding —
    the old is_leaf matched the top-level tuple and sliced it element-wise)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [(np.arange(8, dtype=np.float32), np.arange(100, 108, dtype=np.int64))]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert isinstance(got0, tuple) and len(got0) == 2
    np.testing.assert_array_equal(got0[0], np.arange(4, dtype=np.float32))
    np.testing.assert_array_equal(got1[0], np.arange(4, 8, dtype=np.float32))
    np.testing.assert_array_equal(got0[1], np.arange(100, 104))
    np.testing.assert_array_equal(got1[1], np.arange(104, 108))


def test_split_mode_top_level_string_list_slices_by_row():
    """A batch that IS a list of strings stays a row container."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [["a", "b", "c", "d"]]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert got0 == ["a", "b"] and got1 == ["c", "d"]


def test_stride_mode_short_midstream_batch_raises():
    """Only the final batch may be short in stride mode: a short mid-stream
    batch would silently inflate `remainder` (advisor r1 finding)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32)},
               {"x": np.arange(2, dtype=np.float32)},  # short, not last
               {"x": np.arange(4, dtype=np.float32)}]
    it = ShardedBatchIterable(batches, 2, 0, even_batches=True)
    with pytest.raises(ValueError, match="only the final batch"):
        list(it)


def test_split_mode_row_container_short_tail_pads():
    """A short final row-container batch wraparound-pads like array batches
    (code-review r2 finding: pad_batch_to skipped list leaves, so nonzero
    ranks got empty shards)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [["a", "b", "c", "d"], ["e", "f"]]
    it0 = ShardedBatchIterable(batches, 2, 0, split_batches=True)
    it1 = ShardedBatchIterable(batches, 2, 1, split_batches=True)
    got0, got1 = list(it0), list(it1)
    # tail padded to 4 rows then split 2/2: real rows first, filler after,
    # remainder=2 marks how many of the reassembled rows are real
    assert got0[1] == ["e", "f"] and got1[1] == ["e", "f"]
    assert it0.remainder == 2
    assert (got0[1] + got1[1])[: it0.remainder] == ["e", "f"]


def test_stride_mode_variable_sizes_ok_without_even_batches():
    """even_batches=False never pads, so variable-size streams stay legal."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32)},
               {"x": np.arange(6, dtype=np.float32)},
               {"x": np.arange(4, dtype=np.float32)}]
    got = list(ShardedBatchIterable(batches, 2, 0, even_batches=False))
    assert [len(b["x"]) for b in got] == [4, 4]


def test_split_mode_numpy_scalar_row_list_slices():
    """A batch that is a list of numpy scalars slices per rank (code-review
    r2: conversion to 0-d arrays used to defeat row-container detection and
    replicate every row on every rank)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [[np.int64(1), np.int64(2), np.int64(3), np.int64(4)]]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert [int(x) for x in got0] == [1, 2]
    assert [int(x) for x in got1] == [3, 4]


def test_split_mode_zero_d_array_row_list_slices():
    """A list of 0-d numpy arrays is a row container too."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32),
                "y": [np.asarray(1), np.asarray(2), np.asarray(3), np.asarray(4)]}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert [int(v) for v in got0["y"]] == [1, 2]
    assert [int(v) for v in got1["y"]] == [3, 4]


def test_split_mode_oversized_batch_raises():
    """Slicing an oversized mid-stream batch would silently drop rows."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32)},
               {"x": np.arange(12, dtype=np.float32)}]
    it = ShardedBatchIterable(batches, 2, 0, split_batches=True)
    with pytest.raises(ValueError, match="may not grow"):
        list(it)


def test_split_mode_ragged_token_lists_slice_by_row():
    """Ragged tokenizer output (list of lists / list of 1-D arrays) is a row
    container: sliced by row, never along the token dimension."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32),
                "y": [[1, 2], [3, 4, 5], [6], [7, 8]]}]
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert got1["y"] == [[6], [7, 8]]

    batches = [[np.asarray([1, 2]), np.asarray([3, 4, 5]),
                np.asarray([6]), np.asarray([7, 8])]]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    assert [t.tolist() for t in got0] == [[1, 2], [3, 4, 5]]


def test_split_mode_collate_field_list_slices_per_field():
    """A list of EQUAL-length 1-D arrays is torch default_collate's
    [features, labels] field list — sliced per field, not treated as rows."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [[np.arange(8, dtype=np.float32), np.arange(100, 108)]]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    np.testing.assert_array_equal(got0[0], np.arange(4, dtype=np.float32))
    np.testing.assert_array_equal(got1[0], np.arange(4, 8, dtype=np.float32))
    np.testing.assert_array_equal(got0[1], np.arange(100, 104))
    np.testing.assert_array_equal(got1[1], np.arange(104, 108))


def test_split_mode_equal_length_ragged_rows_with_context():
    """A list of equal-length 1-D arrays with one entry per batch row IS a
    row container when the batch's row count says so (coincidentally-equal
    ragged rows must not flip to field-slicing mid-stream)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32),
                "y": [np.asarray([1, 2]), np.asarray([3, 4]),
                      np.asarray([5, 6]), np.asarray([7, 8])]}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert [t.tolist() for t in got0["y"]] == [[1, 2], [3, 4]]
    assert [t.tolist() for t in got1["y"]] == [[5, 6], [7, 8]]


def test_split_mode_square_collate_pair_stays_fields():
    """batch_rows == field_count == inner_length (the undecidable square
    case) defaults to default_collate field structure: each field slices by
    row instead of ranks receiving different fields."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [[np.arange(2, dtype=np.float32), np.arange(100, 102)]]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    np.testing.assert_array_equal(got0[0], [0.0])
    np.testing.assert_array_equal(got0[1], [100])
    np.testing.assert_array_equal(got1[0], [1.0])
    np.testing.assert_array_equal(got1[1], [101])


def test_split_mode_torch_tensor_ragged_rows():
    """Torch-tensor ragged rows behave exactly like numpy rows."""
    import torch

    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(2, dtype=np.float32),
                "y": [torch.tensor([1, 2, 3]), torch.tensor([4, 5])]},
               ]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert [list(map(int, t)) for t in got0["y"]] == [[1, 2, 3]]
    assert [list(map(int, t)) for t in got1["y"]] == [[4, 5]]


def test_split_mode_ragged_key_sorts_first():
    """Row count must come from the ragged row container even when its dict
    key sorts before the array leaves (code-review r2: find_batch_size used
    to return the first row's token count)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"ids": [np.asarray([1, 2, 3, 4]), np.asarray([5]),
                        np.asarray([6, 7]), np.asarray([8, 9, 10]),
                        np.asarray([11]), np.asarray([12, 13])],
                "x": np.arange(6, dtype=np.float32)}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert [t.tolist() for t in got0["ids"]] == [[1, 2, 3, 4], [5], [6, 7]]
    assert [t.tolist() for t in got1["ids"]] == [[8, 9, 10], [11], [12, 13]]
    np.testing.assert_array_equal(got0["x"], [0.0, 1.0, 2.0])


def test_split_mode_equal_length_tail_keeps_row_classification():
    """A short tail of equal-length token rows (token length == full batch
    size) keeps its rows classification through pad + slice (code-review r2:
    pad/slice used contradictory contexts and sliced along tokens)."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(4, dtype=np.float32),
                "y": [[1, 2], [3, 4, 5], [6], [7, 8]]},
               {"x": np.arange(2, dtype=np.float32),
                "y": [np.asarray([1, 2, 3, 4]), np.asarray([5, 6, 7, 8])]}]
    it0 = ShardedBatchIterable(batches, 2, 0, split_batches=True)
    it1 = ShardedBatchIterable(batches, 2, 1, split_batches=True)
    got0, got1 = list(it0), list(it1)
    # tail rows wraparound-padded to 4 then split 2/2 as whole rows
    assert [t.tolist() for t in got0[1]["y"]] == [[1, 2, 3, 4], [5, 6, 7, 8]]
    assert [t.tolist() for t in got1[1]["y"]] == [[1, 2, 3, 4], [5, 6, 7, 8]]
    assert it0.remainder == 2


def test_split_mode_ambiguous_list_key_order_independent():
    """An ambiguous equal-length 1-D list must not hijack the batch size
    even when its key sorts first; unambiguous array leaves win."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"a_ids": [np.asarray([1, 2]), np.asarray([3, 4]),
                          np.asarray([5, 6]), np.asarray([7, 8])],
                "z": np.arange(4, dtype=np.float32)}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    np.testing.assert_array_equal(got0["z"], [0.0, 1.0])
    np.testing.assert_array_equal(got1["z"], [2.0, 3.0])
    assert [t.tolist() for t in got0["a_ids"]] == [[1, 2], [3, 4]]
    assert [t.tolist() for t in got1["a_ids"]] == [[5, 6], [7, 8]]


def test_split_mode_empty_container_leaf_ignored():
    """An empty list leaf must not zero out the batch size."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"empty": [], "x": np.arange(4, dtype=np.float32)}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    np.testing.assert_array_equal(got0["x"], [0.0, 1.0])
    assert got0["empty"] == []


def test_split_mode_short_metadata_list_does_not_hijack_size():
    """A short metadata string list must not override array leading-dim
    evidence for the batch size."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"class_names": ["pos", "neg"],
                "x": np.zeros((8, 2), dtype=np.float32)}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert got0["x"].shape == (4, 2) and got1["x"].shape == (4, 2)


def test_stride_mode_short_array_with_metadata_list_pads():
    """Stride mode's tail padding keys off the short ARRAY rows, not a
    same-length metadata list."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": np.arange(8, dtype=np.float32),
                "names": ["a"] * 8},
               {"x": np.arange(4, dtype=np.float32),
                "names": ["a"] * 8}]
    it0 = ShardedBatchIterable(batches, 2, 0, even_batches=True)
    it1 = ShardedBatchIterable(batches, 2, 1, even_batches=True)
    got0, got1 = list(it0), list(it1)
    assert got0[0]["x"].shape[0] == 8
    assert got1[0]["x"].shape[0] == 8  # padded from 4 to 8


def test_split_mode_short_metadata_list_replicates_untouched():
    """A metadata list shorter than the batch must replicate unmodified —
    not be wraparound-extended into fake rows."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"class_names": ["pos", "neg"],
                "x": np.zeros((8, 2), dtype=np.float32)}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    assert got0["class_names"] == ["pos", "neg"]
    assert got1["class_names"] == ["pos", "neg"]


def test_dispatcher_list_leaves_pass_through_unpadded():
    """DispatcherLoader pads arrays only; list leaves replicate unchanged
    (slice_tensors never slices lists, so padding them would leak filler)."""
    from accelerate_tpu.data import DataLoaderDispatcher

    names = [f"n{i}" for i in range(10)]
    loader = DataLoaderDispatcher(
        [{"x": np.arange(10, dtype=np.float32), "names": names}])
    (batch,) = list(loader)
    assert batch["names"] == names


def test_split_mode_aux_array_replicates():
    """An auxiliary array whose leading dim is not the batch size (e.g.
    per-class weights) replicates instead of being tiled into fake rows."""
    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"a_x": np.zeros((8, 2), dtype=np.float32),
                "z_w": np.asarray([0.2, 0.3, 0.5], dtype=np.float32)}]
    (got0,) = list(ShardedBatchIterable(batches, 2, 0, split_batches=True))
    np.testing.assert_array_equal(
        got0["z_w"], np.asarray([0.2, 0.3, 0.5], dtype=np.float32)
    )
    assert got0["a_x"].shape == (4, 2)


def test_split_mode_torch_array_leaves():
    """Torch-collated batches measure and slice like numpy ones."""
    import torch

    from accelerate_tpu.data import ShardedBatchIterable

    batches = [{"x": torch.arange(8).reshape(8, 1)}]
    (got1,) = list(ShardedBatchIterable(batches, 2, 1, split_batches=True))
    np.testing.assert_array_equal(np.asarray(got1["x"]).ravel(), [4, 5, 6, 7])


def test_shard_loader_ragged_rows_not_token_padded():
    """DataLoaderShard tail padding pads ragged row LISTS by rows, never each
    row along the token dimension."""
    from accelerate_tpu.data import pad_batch_to

    batch = {"x": np.arange(10, dtype=np.float32),
             "ids": [np.asarray([1, 2, 3]), np.asarray([4, 5])] * 5}
    out = pad_batch_to(batch, 12, rows=10)
    assert len(out["ids"]) == 12
    assert out["ids"][0].tolist() == [1, 2, 3]
    assert out["x"].shape[0] == 12
    # without a known row count, containers stay untouched entirely
    out2 = pad_batch_to(batch, 12)
    assert len(out2["ids"]) == 10
    assert out2["ids"][1].tolist() == [4, 5]


def test_dispatcher_ragged_rows_slice_by_row(monkeypatch):
    """Dispatcher sharding slices ragged row lists by ROW (never along the
    token dim) and replicates aux leaves."""
    from accelerate_tpu import data as data_mod
    from accelerate_tpu.data import DataLoaderDispatcher

    ids = [np.asarray([1, 2, 3]), np.asarray([4, 5]), np.asarray([6]),
           np.asarray([7, 8, 9]), np.asarray([10]), np.asarray([11, 12])]
    batch = {"ids": ids, "x": np.arange(6, dtype=np.float32)}

    class FakeState:
        num_processes = 2
        process_index = 1
        is_main_process = True

    loader = DataLoaderDispatcher([batch], put_on_device=False)
    monkeypatch.setattr(loader, "state", FakeState())
    def fake_fetch(source):
        item = next(source, None)
        return (item, item is None)

    monkeypatch.setattr(loader, "_fetch_and_broadcast", fake_fetch)
    (got,) = list(loader)
    assert [t.tolist() for t in got["ids"]] == [[7, 8, 9], [10], [11, 12]]
    np.testing.assert_array_equal(got["x"], [3.0, 4.0, 5.0])


def test_batch_size_majority_dim_beats_key_order():
    """An aux array whose key sorts first must not hijack the batch size
    (advisor r2 finding): the majority leading dim across leaves wins."""
    from accelerate_tpu.data import _batch_size

    batch = {
        "a_weights": np.ones((3,)),          # aux, sorts first
        "x": np.ones((8, 2)),
        "y": np.ones((8,)),
    }
    assert _batch_size(batch) == 8


def test_even_batches_property_equal_counts_and_full_coverage():
    """Property pin (from an r5 400-config fuzz; 120 pinned here): with
    even_batches=True
    every rank yields the SAME number of batches and every real sample
    appears on some rank (duplication for padding allowed). With
    even_batches=False ranks may legitimately differ (join_uneven_inputs
    exists for that) — not asserted here."""
    import random

    rng = random.Random(7)
    for _ in range(120):
        n = rng.randint(1, 50)
        bs = rng.randint(1, 8)
        world = rng.choice([2, 4])
        drop_last = rng.random() < 0.5
        base = [list(range(i, min(i + bs, n))) for i in range(0, n, bs)]
        if drop_last and base and len(base[-1]) < bs:
            base = base[:-1]
        shards = [
            [list(b) for b in BatchSamplerShard(
                base, num_processes=world, process_index=rank,
                split_batches=False, even_batches=True)]
            for rank in range(world)
        ]
        counts = {len(s) for s in shards}
        assert len(counts) == 1, (n, bs, world, drop_last,
                                  [len(s) for s in shards])
        if base:
            seen = {x for s in shards for b in s for x in b}
            want = {x for b in base for x in b}
            assert want <= seen, (n, bs, world, drop_last,
                                  sorted(want - seen))


# ---------------------------------------------------------------------------
# host prefetch shutdown (ATP305 regression, ISSUE 19)
# ---------------------------------------------------------------------------


def test_prefetch_iterator_close_reaps_worker_mid_epoch():
    """ATP305 regression: an abandoned epoch (consumer breaks out of the
    loader loop) must reap the prefetch thread. Before the fix the
    worker parked forever on the full bounded queue — every early break
    leaked a thread pinning the source iterator."""
    from accelerate_tpu.data import _PrefetchIterator

    produced = []

    def source():
        for i in range(10_000):
            produced.append(i)
            yield i

    it = _PrefetchIterator(source(), prepare=lambda x: x * 2, depth=1)
    assert next(it) == 0
    assert it._thread.is_alive()
    it.close()
    assert not it._thread.is_alive(), "prefetch worker leaked past close()"
    # bounded queue really did bound the read-ahead: close() came after a
    # handful of items, not after the worker ripped through the source
    assert len(produced) < 10, produced
    it.close()                         # idempotent


def test_prefetch_iterator_close_unparks_blocked_worker():
    """The exact leak shape: queue full, worker blocked in put() when
    close() lands. The stop event must unpark it promptly."""
    import time

    from accelerate_tpu.data import _PrefetchIterator

    it = _PrefetchIterator(iter(range(100)), prepare=lambda x: x, depth=1)
    deadline = time.monotonic() + 5
    while it._queue.qsize() < 1 and time.monotonic() < deadline:
        time.sleep(0.005)              # let the worker fill the queue
    it.close()
    assert not it._thread.is_alive()


def test_dataloader_break_mid_epoch_leaves_no_prefetch_thread():
    """Loader-level: `break` inside the consumer loop runs the loader's
    finally, which closes the prefetch stage."""
    import threading

    before = {t.ident for t in threading.enumerate()}
    loader = DataLoaderShard(list(make_batches(64, 4)), put_on_device=False)
    for i, _batch in enumerate(loader):
        if i == 1:
            break
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.is_alive()]
    assert leaked == [], f"prefetch thread(s) leaked: {leaked}"
