"""Continuous-batching serving engine (accelerate_tpu.serving).

CPU contracts for the request-lifecycle layer: batched greedy decode is
token-exact vs sequential `generate()`, slots are reused after retirement,
chunked prefill interleaves with decode instead of stalling it, admission
control rejects/sheds instead of OOMing, and the engine's compiled-program
count stays flat however the request mix changes (the fixed-shape design's
whole point)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import gpt2, llama
from accelerate_tpu.models.decode import sample_token
from accelerate_tpu.serving import (
    Engine,
    EngineConfig,
    Request,
    RequestStatus,
    Scheduler,
    SlotKVCache,
    SlotState,
)


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    """Every Engine() compiles the same three tiny programs; the repo's
    persistent compilation cache turns the repeats into deserializes."""
    import os

    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (ISSUE 16 hit this the moment
    # an engine module sorted before test_launched_scripts)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _engine(cfg, params, family=gpt2, **overrides):
    defaults = dict(num_slots=3, max_len=64, prefill_chunk=8,
                    cache_dtype=jnp.float32)
    defaults.update(overrides)
    return Engine(family, cfg, params, EngineConfig(**defaults))


def _prompt(rng, n, vocab):
    return rng.integers(0, vocab, (n,)).astype(np.int32)


# ---------------------------------------------------------------------------
# the acceptance contract: staggered concurrent == sequential, one compile
# ---------------------------------------------------------------------------


def test_staggered_requests_match_sequential_generate(gpt2_setup):
    """3 requests submitted at different times (so their decode depths
    never align) produce token-identical greedy output vs 3 sequential
    `generate()` calls — through exactly ONE decode-program compilation."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    rng = np.random.default_rng(0)
    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (5, 11, 3)]

    reqs = [eng.submit(prompts[0], max_new_tokens=8)]
    for _ in range(3):  # r0 mid-prefill/decode before r1 even arrives
        eng.step()
    reqs.append(eng.submit(prompts[1], max_new_tokens=8))
    for _ in range(2):
        eng.step()
    reqs.append(eng.submit(prompts[2], max_new_tokens=8))
    eng.run_until_idle()

    for p, r in zip(prompts, reqs):
        assert r.status is RequestStatus.FINISHED
        ref = gpt2.generate(cfg, params, jnp.asarray(p)[None, :],
                            max_new_tokens=8)
        assert r.tokens == np.asarray(ref)[0, len(p):].tolist()
    assert eng.compile_stats()["decode"] == 1, eng.compile_stats()


def test_chunked_prefill_is_token_exact(gpt2_setup):
    """A prompt much longer than the chunk prefills in pieces and still
    decodes exactly like one-shot generate (writes advance by real tokens
    only; padded rows are never attended)."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, prefill_chunk=4)
    rng = np.random.default_rng(1)
    p = _prompt(rng, 19, cfg.vocab_size)
    r = eng.submit(p, max_new_tokens=6)
    eng.run_until_idle()
    ref = gpt2.generate(cfg, params, jnp.asarray(p)[None, :],
                        max_new_tokens=6)
    assert r.tokens == np.asarray(ref)[0, len(p):].tolist()


def test_gqa_family_llama_matches_sequential():
    """The engine is family-agnostic: llama's GQA cache dims ride the same
    programs."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    eng = _engine(cfg, params, family=llama, num_slots=2)
    rng = np.random.default_rng(2)
    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (6, 9)]
    reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    eng.run_until_idle()
    for p, r in zip(prompts, reqs):
        ref = llama.generate(cfg, params, jnp.asarray(p)[None, :],
                             max_new_tokens=5)
        assert r.tokens == np.asarray(ref)[0, len(p):].tolist()


# ---------------------------------------------------------------------------
# recompile guard
# ---------------------------------------------------------------------------


def test_compiled_program_count_flat_across_request_mix(gpt2_setup):
    """Waves of requests with different prompt lengths, token budgets, and
    temperatures never add a compiled program: the request mix is data,
    not shape. Extended for the paged cache (ISSUE 5): a wave of
    shared-prefix prompts (prefix-cache HITS — reused lengths and remapped
    page tables are traced data too) rides the same three programs."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2, max_len=48)
    rng = np.random.default_rng(3)
    shared = _prompt(rng, 18, cfg.vocab_size)
    waves = [(3, 4, 0.0), (13, 2, 1.0), (7, 6, 0.5), (1, 3, 0.0),
             ("shared", 3, 0.0), ("shared", 3, 1.0)]
    for wave, (plen, mnt, temp) in enumerate(waves):
        if plen == "shared":
            prompts = [np.concatenate(
                [shared, _prompt(rng, 2 + i, cfg.vocab_size)])
                for i in range(3)]
        else:
            prompts = [_prompt(rng, plen, cfg.vocab_size) for _ in range(3)]
        reqs = [eng.submit(p, max_new_tokens=mnt, temperature=temp)
                for p in prompts]
        eng.run_until_idle()
        assert all(r.status is RequestStatus.FINISHED for r in reqs)
        counts = eng.compile_stats()
        assert counts == {"admit": 1, "prefill": 1, "decode": 1}, (
            f"wave {wave} recompiled: {counts}")
    assert eng.metrics.prefix_hits >= 2  # the shared waves actually hit


# ---------------------------------------------------------------------------
# slot lifecycle
# ---------------------------------------------------------------------------


def test_slot_reuse_after_retirement(gpt2_setup):
    """More requests than slots: retired slots re-admit from the queue, and
    a reused slot's stale cache never leaks into the next request's output
    (length reset + position mask — no cache wipe)."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2)
    rng = np.random.default_rng(4)
    # equal-length prompts: slot reuse doesn't depend on length variety
    # (the staggered test covers that), and one BATCHED reference
    # generate replaces five per-length compiles (tier-1 budget)
    prompts = [_prompt(rng, 6, cfg.vocab_size) for _ in range(5)]
    reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
    assert eng.scheduler.queue_depth == 3  # only 2 slots
    eng.run_until_idle()
    refs = np.asarray(gpt2.generate(
        cfg, params, jnp.asarray(np.stack(prompts)), max_new_tokens=4))
    for i, (p, r) in enumerate(zip(prompts, reqs)):
        assert r.status is RequestStatus.FINISHED
        assert r.tokens == refs[i, len(p):].tolist()
    # all 5 ran through 2 slots
    assert eng.metrics.finished == 5


def test_prefill_decode_interleave_ordering(gpt2_setup):
    """A long prompt arriving while another request decodes must NOT stall
    it: prefill chunks and decode steps strictly alternate, so between any
    two consecutive prefill chunks there is a decode step."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, prefill_chunk=4)
    actions = []
    orig_prefill, orig_decode = eng._run_prefill_chunk, eng._run_decode
    eng._run_prefill_chunk = lambda s: (actions.append("p"), orig_prefill(s))[1]
    eng._run_decode = lambda s: (actions.append("d"), orig_decode(s))[1]

    rng = np.random.default_rng(5)
    r0 = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=16)
    for _ in range(4):  # r0 prefilled and decoding
        eng.step()
    del actions[:]
    eng.submit(_prompt(rng, 20, cfg.vocab_size), max_new_tokens=2)
    eng.run_until_idle()
    first_burst = actions[:9]  # while both kinds of work existed
    assert "p" in first_burst and "d" in first_burst
    assert "pp" not in "".join(first_burst), (
        f"prefill monopolized the engine: {actions}")
    assert r0.status is RequestStatus.FINISHED


def test_cancel_queued_and_running(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=1)
    rng = np.random.default_rng(6)
    running = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=32)
    head = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=4)
    queued = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=32)
    for _ in range(3):
        eng.step()
    assert running.status is RequestStatus.RUNNING
    # `queued` sits BEHIND `head`: removal must not compare numpy prompts
    # against other queued requests (Request compares by identity)
    assert eng.cancel(queued) and queued.status is RequestStatus.CANCELLED
    assert head.status is RequestStatus.QUEUED  # untouched by the removal
    assert eng.cancel(running) and running.status is RequestStatus.CANCELLED
    assert not eng.cancel(running)  # idempotent on terminal requests
    eng.run_until_idle()
    assert head.status is RequestStatus.FINISHED
    assert eng.scheduler.live_slots == 0


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_rejects_when_queue_full(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=1, max_queue=2)
    rng = np.random.default_rng(7)
    ok = [eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=4)
          for _ in range(3)]  # 1 would-be slot + 2 queued... all accepted
    assert all(not r.done for r in ok)
    shed = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=4)
    assert shed.status is RequestStatus.REJECTED
    assert "queue full" in shed.reject_reason
    assert shed.tokens == []
    eng.run_until_idle()  # the accepted ones still finish
    assert all(r.status is RequestStatus.FINISHED for r in ok)
    assert eng.metrics.rejected == 1


def test_submit_drains_freed_slot_before_queue_full_check(gpt2_setup):
    """A slot freed since the last step must make room BEFORE a new submit
    is judged against max_queue — the bound covers genuinely *waiting*
    requests only. Regression: submit used to capacity-check first, so a
    full queue plus a just-freed slot spuriously REJECTED."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=1, max_queue=1)
    rng = np.random.default_rng(16)
    a = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=1)
    b = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=1)
    eng.step()  # a's prefill chunk yields its only token -> slot freed
    assert a.status is RequestStatus.FINISHED
    assert eng.scheduler.queue_depth == 1  # b still holds the queue position
    c = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=1)
    assert c.status is not RequestStatus.REJECTED
    eng.run_until_idle()
    assert b.status is RequestStatus.FINISHED
    assert c.status is RequestStatus.FINISHED


def test_admission_rejects_overlong_request(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, max_len=16)
    r = eng.submit(np.arange(10, dtype=np.int32), max_new_tokens=10)
    assert r.status is RequestStatus.REJECTED
    assert "max_len" in r.reject_reason


def test_deadline_shedding_reports_expired(gpt2_setup):
    """A queued request whose deadline lapses before a slot frees is shed
    with EXPIRED — fake clock, no sleeping."""
    cfg, params = gpt2_setup
    now = [0.0]
    eng = Engine(gpt2, cfg, params,
                 EngineConfig(num_slots=1, max_len=64, prefill_chunk=8,
                              cache_dtype=jnp.float32),
                 clock=lambda: now[0])
    rng = np.random.default_rng(8)
    hog = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=32)
    patient = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=4)
    hurried = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=4,
                         deadline_s=5.0)
    for _ in range(3):
        eng.step()
        now[0] += 1.0
    assert hurried.status is RequestStatus.QUEUED
    now[0] += 10.0  # deadline lapses while still queued, behind `patient`
    eng.step()  # shedding a non-head request must not crash on numpy __eq__
    assert hurried.status is RequestStatus.EXPIRED
    assert "deadline" in hurried.reject_reason
    assert patient.status is not RequestStatus.EXPIRED
    eng.run_until_idle()
    assert hog.status is RequestStatus.FINISHED
    assert patient.status is RequestStatus.FINISHED
    assert eng.metrics.expired == 1


# ---------------------------------------------------------------------------
# streaming + sampling
# ---------------------------------------------------------------------------


def test_stream_yields_tokens_and_matches_handle(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    rng = np.random.default_rng(9)
    r = eng.submit(_prompt(rng, 5, cfg.vocab_size), max_new_tokens=7)
    streamed = list(eng.stream(r))
    assert streamed == r.tokens and len(streamed) == 7
    assert r.status is RequestStatus.FINISHED


def test_astream_interleaves_concurrent_requests(gpt2_setup):
    import asyncio

    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2)
    rng = np.random.default_rng(10)

    async def consume(req):
        return [tok async for tok in eng.astream(req)]

    async def main():
        r1 = eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=5)
        r2 = eng.submit(_prompt(rng, 6, cfg.vocab_size), max_new_tokens=5)
        return await asyncio.gather(consume(r1), consume(r2)), (r1, r2)

    (t1, t2), (r1, r2) = asyncio.run(main())
    assert t1 == r1.tokens and t2 == r2.tokens
    assert len(t1) == len(t2) == 5


def test_eos_token_finishes_early(gpt2_setup):
    """EOS is checked host-side per token; pick the greedy first token as
    the 'EOS' so the request finishes after exactly one token."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(11)
    p = _prompt(rng, 5, cfg.vocab_size)
    ref = gpt2.generate(cfg, params, jnp.asarray(p)[None, :], max_new_tokens=2)
    eos = int(np.asarray(ref)[0, len(p)])
    eng = _engine(cfg, params)
    r = eng.submit(p, max_new_tokens=16, eos_token_id=eos)
    eng.run_until_idle()
    assert r.tokens == [eos]
    assert r.status is RequestStatus.FINISHED


def test_finish_mid_prefill_never_poisons_the_prefix_cache(gpt2_setup):
    """ISSUE 13 lifecycle-audit regression: `Engine.finish` on a request
    whose prefill is still mid-flight retires it FINISHED — but only the
    pages its prefill actually completed may enter the prefix tree.
    Pre-fix, the full prompt range was inserted and a later identical
    prompt reused never-written garbage KV; pinned by token-exactness
    against a fresh engine."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(23)
    p = _prompt(rng, 30, cfg.vocab_size)
    eng = _engine(cfg, params, prefill_chunk=8, page_size=8, max_len=96)
    r1 = eng.submit(p, max_new_tokens=4)
    eng.step()                      # one chunk: 8 of 30 prompt tokens
    slot = next(s for s in eng.scheduler.slots if s.request is r1)
    assert 0 < slot.prompt_done < r1.prompt_len
    assert eng.finish(r1)           # server-side early finish
    assert r1.status is RequestStatus.FINISHED
    # the same prompt again: whatever it reuses must be REAL prefilled
    # state, so its tokens match a fresh engine's cold run exactly
    r2 = eng.submit(p, max_new_tokens=6)
    eng.run_until_idle()
    fresh = _engine(cfg, params, prefill_chunk=8, page_size=8, max_len=96)
    ref = fresh.submit(p, max_new_tokens=6)
    fresh.run_until_idle()
    assert r2.tokens == ref.tokens
    # and the reuse really was capped at the completed pages
    assert eng.allocator.tokens_reused <= 8


def test_per_slot_sampling_decorrelates_streams(gpt2_setup):
    """Two identical prompts at temperature>0 in different slots draw from
    different PRNG streams (the sample_token batched-keys satellite, wired
    through the engine's per-slot request keys)."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2)
    rng = np.random.default_rng(12)
    p = _prompt(rng, 5, cfg.vocab_size)
    a = eng.submit(p, max_new_tokens=12, temperature=1.0)
    b = eng.submit(p, max_new_tokens=12, temperature=1.0)
    eng.run_until_idle()
    assert a.tokens != b.tokens


def test_sampling_deterministic_per_key_and_schedule_independent(gpt2_setup):
    """The same request key yields the same sampled stream even when the
    engine's interleave differs (a competing request changes scheduling):
    step keys derive from (request key, position), not from step order."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(13)
    p = _prompt(rng, 5, cfg.vocab_size)
    key = jax.random.key(42)

    eng1 = _engine(cfg, params, num_slots=2)
    alone = eng1.submit(p, max_new_tokens=8, temperature=0.7, key=key)
    eng1.run_until_idle()

    eng2 = _engine(cfg, params, num_slots=2)
    crowded = eng2.submit(p, max_new_tokens=8, temperature=0.7, key=key)
    eng2.step()
    eng2.submit(_prompt(rng, 17, cfg.vocab_size), max_new_tokens=8)
    eng2.run_until_idle()

    assert alone.tokens == crowded.tokens


def test_sample_token_accepts_batched_keys():
    """models/decode.py satellite: a [B]-batch of typed keys (or [B, 2]
    raw) samples each row from its own stream, matching per-row calls."""
    logits = jax.random.normal(jax.random.key(0), (3, 1, 64))
    keys = jax.random.split(jax.random.key(1), 3)
    batched = sample_token(logits, keys, 1.0)
    assert batched.shape == (3,)
    per_row = [int(sample_token(logits[i:i + 1], keys[i], 1.0)[0])
               for i in range(3)]
    assert batched.tolist() == per_row
    raw = jax.random.key_data(keys)
    assert sample_token(logits, raw, 1.0).tolist() == per_row
    # single key still broadcasts one stream across the batch
    single = sample_token(logits, jax.random.key(1), 1.0)
    assert single.shape == (3,)
    # greedy path ignores keys entirely
    assert sample_token(logits, None, 0.0).shape == (3,)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_metrics_summary_reports_serving_stats(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    rng = np.random.default_rng(14)
    reqs = [eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=4)
            for _ in range(4)]
    eng.run_until_idle()
    s = eng.metrics_summary()
    assert s["requests_finished"] == 4
    assert s["tokens_out"] == 16
    assert s["ttft_p50_ms"] > 0 and s["ttft_p99_ms"] >= s["ttft_p50_ms"]
    assert s["per_token_p50_ms"] > 0
    assert 0 < s["slot_occupancy_mean"] <= 1
    assert s["tokens_per_sec"] > 0
    assert s["compiles_decode"] == 1
    for r in reqs:
        assert r.ttft_s is not None and r.ttft_s >= 0


def test_metrics_flow_into_tracker(gpt2_setup, tmp_path):
    """Engine metrics ride the existing tracking layer (JSONLTracker)."""
    import json

    from accelerate_tpu.tracking import JSONLTracker

    cfg, params = gpt2_setup
    tracker = JSONLTracker("serve_run", logging_dir=str(tmp_path))
    eng = Engine(gpt2, cfg, params,
                 EngineConfig(num_slots=2, max_len=64, prefill_chunk=8,
                              cache_dtype=jnp.float32),
                 tracker=tracker, log_every=2)
    rng = np.random.default_rng(15)
    for _ in range(2):
        eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=6)
    eng.run_until_idle()
    tracker.finish()
    lines = [json.loads(ln) for ln in
             (tmp_path / "serve_run" / "metrics.jsonl").read_text().splitlines()]
    logged = [ln for ln in lines if ln.get("event") == "log"]
    assert logged and any("tokens_out" in ln for ln in logged)


def test_engine_prometheus_endpoint_serves_serving_series(gpt2_setup):
    """Acceptance (ISSUE 3): an engine with the exporter enabled serves a
    Prometheus exposition containing TTFT / queue-depth / tokens-per-sec
    series. Port 0 = ephemeral, so tier-1 never collides on ports."""
    import urllib.request

    cfg, params = gpt2_setup
    eng = _engine(cfg, params, metrics_port=0)
    try:
        assert eng.metrics_server is not None
        rng = np.random.default_rng(21)
        for _ in range(3):
            eng.submit(_prompt(rng, 6, cfg.vocab_size), max_new_tokens=4)
        eng.run_until_idle()
        url = f"http://127.0.0.1:{eng.metrics_server.port}/metrics"
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        for series in ("serving_ttft_seconds", "serving_queue_depth",
                       "serving_tokens_per_sec",
                       "serving_tokens_out_total",
                       "serving_step_dispatch_seconds"):
            assert series in body, f"{series} missing from exposition"
        # counters carry the finished run's values, not just zeros
        assert "serving_requests_finished_total 3.0" in body
        assert "serving_tokens_out_total 12.0" in body
    finally:
        eng.close()


def test_engine_step_ticks_watchdog(gpt2_setup):
    """The serving loop arms the stall watchdog: every step() heartbeats,
    so a live engine never fires; the report machinery is exercised by a
    manual check after silence (fake silence via a huge negative tick)."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, watchdog_timeout_s=3600.0)
    try:
        assert eng.watchdog is not None
        rng = np.random.default_rng(22)
        eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=3)
        eng.run_until_idle()
        assert eng.watchdog.check() is None  # just ticked: silent
        eng.watchdog._last_tick -= 7200.0    # simulate 2h of silence
        report = eng.watchdog.check()
        assert report is not None and report["stall_count"] == 1
    finally:
        eng.close()


def test_engine_reset_metrics_keeps_registry_and_exporter_live(gpt2_setup):
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    rng = np.random.default_rng(23)
    eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=3)
    eng.run_until_idle()
    registry = eng.registry
    assert eng.metrics.tokens_out == 3
    eng.reset_metrics()
    assert eng.registry is registry          # same registry object
    assert eng.metrics.tokens_out == 0       # zeroed in place
    eng.submit(_prompt(rng, 4, cfg.vocab_size), max_new_tokens=2)
    eng.run_until_idle()
    assert eng.metrics.tokens_out == 2       # fresh window accumulates


# ---------------------------------------------------------------------------
# scheduler unit coverage (no model)
# ---------------------------------------------------------------------------


def _req(n=4, **kw):
    kw.setdefault("max_new_tokens", 4)
    return Request(prompt=np.zeros((n,), np.int32), **kw)


def test_scheduler_fifo_admission_and_alternation():
    now = [0.0]
    sched = Scheduler(num_slots=2, max_len=32, max_queue=8,
                      clock=lambda: now[0])
    a, b, c = _req(), _req(), _req()
    for r in (a, b, c):
        sched.submit(r)
    admitted = sched.admissions()
    assert [r.request_id for _, r in admitted] == [a.request_id, b.request_id]
    assert sched.queue_depth == 1
    # both slots prefilling: prefill then (still) prefill — no decode yet
    kind, slot = sched.next_action()
    assert kind == "prefill"
    assert not sched.note_prefill_chunk(slot, 2)   # 2 of 4 prompt tokens
    assert sched.note_prefill_chunk(slot, 2)       # prompt done -> DECODE
    assert slot.state is SlotState.DECODE
    # now one prefilling + one decoding: strict alternation
    kinds = []
    for _ in range(2):
        k, payload = sched.next_action()
        kinds.append(k)
        if k == "prefill":
            sched.note_prefill_chunk(payload, 4)
    assert sorted(kinds) == ["decode", "prefill"]


def test_prefill_is_fifo_not_slot_indexed():
    """A long prompt mid-prefill in a high-index slot must keep making
    progress while short arrivals churn through lower-index slots: prefill
    picks the earliest-admitted request, not the lowest slot (starvation
    regression — an accepted request must not see unbounded TTFT)."""
    now = [0.0]
    sched = Scheduler(num_slots=2, max_len=512, max_queue=8,
                      clock=lambda: now[0])
    early = _req(n=400, max_new_tokens=1)
    sched.submit(early)
    sched.admissions()           # early -> slot 0
    now[0] = 1.0
    late = _req(n=4, max_new_tokens=1)
    sched.submit(late)
    sched.admissions()           # late -> slot 1 (higher index, newer)
    kind, slot = sched.next_action()
    assert kind == "prefill" and slot.request is early
    # and with the order reversed (newer request in the LOWER slot) the
    # older one still wins
    sched2 = Scheduler(num_slots=2, max_len=512, max_queue=8,
                       clock=lambda: now[0])
    a, b = _req(n=400, max_new_tokens=1), _req(n=4, max_new_tokens=1)
    now[0] = 0.0
    sched2.submit(a)
    sched2.submit(b)
    sched2.admissions()          # a -> slot 0, b -> slot 1, same tick
    ((s0, _), (s1, _)) = [(s, s.request) for s in sched2.slots]
    s0.free()                    # a finishes hypothetically; slot 0 frees
    now[0] = 2.0
    c = _req(n=4, max_new_tokens=1)
    sched2.submit(c)
    sched2.admissions()          # c -> slot 0, admitted later than b
    kind, slot = sched2.next_action()
    assert kind == "prefill" and slot.request is b


def test_scheduler_cancel_and_shed_non_head_queued():
    """Removing a request from BEHIND other queued requests must not
    element-compare numpy prompts (Request is eq=False: identity only).
    Regression — the generated dataclass __eq__ raised 'truth value of an
    array is ambiguous' at any queue depth > 1."""
    now = [0.0]
    sched = Scheduler(num_slots=0, max_len=32, max_queue=8,
                      clock=lambda: now[0])
    head, mid, tail = _req(), _req(deadline_s=1.0), _req()
    for r in (head, mid, tail):
        sched.submit(r)
    assert sched.cancel(tail) and tail.status is RequestStatus.CANCELLED
    now[0] = 5.0
    shed = sched.shed_expired()
    assert shed == [mid] and mid.status is RequestStatus.EXPIRED
    assert head.status is RequestStatus.QUEUED
    assert sched.queue_depth == 1
    # equal-field requests are still distinct handles
    assert _req() != _req()


def test_scheduler_retire_frees_slot_for_queue():
    sched = Scheduler(num_slots=1, max_len=32, max_queue=8)
    first, second = _req(max_new_tokens=1), _req()
    sched.submit(first)
    sched.submit(second)
    ((slot, _),) = sched.admissions()
    sched.note_prefill_chunk(slot, 4)
    assert sched.note_token(slot, 7)   # budget 1 -> retired
    assert first.status is RequestStatus.FINISHED
    assert slot.state is SlotState.IDLE
    ((slot2, r2),) = sched.admissions()
    assert r2 is second and slot2 is slot


def test_slot_cache_shapes_and_reset():
    cache = SlotKVCache.create(num_layers=2, num_slots=3, max_len=16,
                               num_kv_heads=4, head_dim=8,
                               dtype=jnp.float32, pad_slack=4)
    assert cache.k.shape == (2, 3, 20, 4, 8)
    assert cache.rows == 20 and cache.max_len == 16
    from accelerate_tpu.serving.cache import reset_slot

    cache = cache.__class__(k=cache.k, v=cache.v,
                            lengths=cache.lengths.at[1].set(9),
                            max_len=cache.max_len, pad_slack=cache.pad_slack)
    cache = reset_slot(cache, jnp.int32(1))
    assert int(cache.lengths[1]) == 0
    # pytree round-trip (jit/donation compatibility)
    leaves, treedef = jax.tree_util.tree_flatten(cache)
    assert len(leaves) == 3
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.max_len == 16 and rebuilt.pad_slack == 4


# ---------------------------------------------------------------------------
# paged-attention kernel + int8 KV pages (ISSUE 10)
# ---------------------------------------------------------------------------


def _run_trace(eng, prompts, temps, budget=6):
    reqs = [eng.submit(p, max_new_tokens=budget, temperature=t)
            for p, t in zip(prompts, temps)]
    eng.run_until_idle()
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    return [r.tokens for r in reqs]


def test_paged_kernel_decode_token_exact_vs_dense(gpt2_setup):
    """The acceptance bar: decode with paged_attention=True (the Pallas
    kernel, interpret mode on CPU) is token-exact vs the dense-gather
    reference path on the same seeded trace — greedy AND sampled lanes —
    with compile counts still admit/prefill/decode = 1/1/1."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(7)
    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (5, 17, 3)]
    # two shared-prefix prompts ride along so the kernel path is also
    # proven on prefix-cache HITS (reused pages, non-zero start lengths)
    shared = _prompt(rng, 16, cfg.vocab_size)
    prompts += [np.concatenate([shared, _prompt(rng, n, cfg.vocab_size)])
                for n in (3, 5)]
    temps = (0.0, 0.8, 0.0, 0.0, 0.6)

    def run(eng):
        # two waves: the second shared-prefix prompt arrives after the
        # first retired, so its prompt pages are cached and it admits as
        # a prefix HIT
        out = _run_trace(eng, prompts[:4], temps[:4])
        return out + _run_trace(eng, prompts[4:], temps[4:])

    dense = run(_engine(cfg, params, page_size=8, paged_attention=False))
    eng = _engine(cfg, params, page_size=8, paged_attention=True)
    kernel = run(eng)
    assert kernel == dense
    assert eng.metrics.prefix_hits >= 1
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}
    # the path counter says the kernel actually served the steps
    ctr = eng.registry.counter("serving_decode_path_total", path="kernel")
    assert ctr.value > 0


def test_paged_kernel_gqa_and_slot_reuse_token_exact():
    """llama's GQA head groups broadcast in-kernel, and reused slots
    (more requests than slots — stale pool rows under fresh tables)
    stay exact, under strict=error so the kernel-backed decode program
    passes the full analysis audit with no findings."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(8)
    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (6, 13, 9, 4, 11)]
    temps = (0.0, 0.6, 0.0, 0.9, 0.0)
    dense = _run_trace(_engine(cfg, params, family=llama, num_slots=2,
                               page_size=8, paged_attention=False),
                       prompts, temps)
    kernel = _run_trace(_engine(cfg, params, family=llama, num_slots=2,
                                page_size=8, paged_attention=True,
                                strict="error"), prompts, temps)
    assert kernel == dense


def test_compile_flat_across_kernel_and_int8_mixes(gpt2_setup):
    """The compile-count guard extended to the new config axes: for each
    (paged_attention, kv_dtype) combination, waves of different prompt
    lengths / budgets / temperatures / prefix hits stay at exactly three
    compiled programs."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(9)
    shared = _prompt(rng, 18, cfg.vocab_size)
    # budgets/wave sizes are deliberately minimal: the guard is about
    # SHAPE variety (lengths, temps, prefix hits), and every extra
    # decode token costs real time on the interpret-mode kernel arms
    # (ISSUE 12's tier-1 budget trim: 9.3s -> measured below)
    for pa in (False, True):
        for kvd in (None, "int8"):
            eng = _engine(cfg, params, num_slots=2, max_len=48,
                          page_size=8, paged_attention=pa, kv_dtype=kvd)
            for plen, mnt, temp in ((3, 2, 0.0), (13, 1, 1.0),
                                    ("shared", 2, 0.5)):
                if plen == "shared":
                    prompts = [np.concatenate(
                        [shared, _prompt(rng, 2 + i, cfg.vocab_size)])
                        for i in range(2)]
                else:
                    prompts = [_prompt(rng, plen, cfg.vocab_size)
                               for _ in range(2)]
                reqs = [eng.submit(p, max_new_tokens=mnt, temperature=temp)
                        for p in prompts]
                eng.run_until_idle()
                assert all(r.status is RequestStatus.FINISHED for r in reqs)
                assert eng.compile_stats() == {
                    "admit": 1, "prefill": 1, "decode": 1}, (pa, kvd)


def test_int8_kv_halves_bytes_gauge(gpt2_setup):
    """kv_dtype="int8" halves the per-page code bytes for the same
    num_pages: the serving_kv_bytes_in_use gauge reports (codes +
    scales), so the ratio is (D+2)/2D — exactly 0.5 on the code bytes,
    plus the documented 2/D scale overhead."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(10)
    prompts = [_prompt(rng, 9, cfg.vocab_size)]
    seen = {}
    for kvd in (None, "int8"):
        eng = _engine(cfg, params, page_size=8, kv_dtype=kvd,
                      cache_dtype=jnp.bfloat16)
        reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
        for _ in range(3):
            eng.step()  # mid-flight: pages held, gauge live
        s = eng.metrics_summary()
        assert s["pages_in_use"] > 0
        seen[kvd] = (s["kv_bytes_in_use"], s["pages_in_use"],
                     eng.cache.page_nbytes)
        eng.run_until_idle()
    (b16, p16, pb16), (b8, p8, pb8) = seen[None], seen["int8"]
    assert p16 == p8  # same trace -> same pages
    D = cfg.head_dim
    assert pb8 / pb16 == pytest.approx((D + 2) / (2 * D))
    assert b8 / b16 == pytest.approx((D + 2) / (2 * D))
    assert b16 == p16 * pb16


def test_int8_kv_logit_error_bound_and_greedy_agreement(gpt2_setup):
    """The int8 quality gate. (1) model-level logit bound: one decode
    step over an int8-round-tripped KV history stays within a small
    logit error of the bf16 history, argmax unchanged. (2) engine-level:
    a greedy trace through the int8 engine agrees with the bf16 engine
    on (at least) the vast majority of tokens."""
    from accelerate_tpu.ops.quant import kv_dequantize_rows, kv_quantize_rows

    cfg, params = gpt2_setup
    rng = np.random.default_rng(11)
    prompt = _prompt(rng, 24, cfg.vocab_size)
    caches = gpt2.init_kv_caches(cfg, 1, 32, dtype=jnp.float32)
    logits, caches = gpt2.forward(cfg, params,
                                  jnp.asarray(prompt)[None, :],
                                  kv_caches=caches)
    ck, cv, cl = caches
    ck8 = kv_dequantize_rows(*kv_quantize_rows(ck), jnp.float32)
    cv8 = kv_dequantize_rows(*kv_quantize_rows(cv), jnp.float32)
    tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
    pos = jnp.asarray([[len(prompt)]], jnp.int32)
    l_bf, _ = gpt2.forward(cfg, params, tok, positions=pos,
                           kv_caches=(ck, cv, cl))
    l_i8, _ = gpt2.forward(cfg, params, tok, positions=pos,
                           kv_caches=(ck8, cv8, cl))
    err = float(jnp.max(jnp.abs(l_bf - l_i8)))
    assert err < 0.5, f"int8 KV logit error {err}"
    assert int(jnp.argmax(l_bf[0, 0])) == int(jnp.argmax(l_i8[0, 0]))

    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (5, 12, 8)]
    temps = (0.0, 0.0, 0.0)
    bf16 = _run_trace(_engine(cfg, params, page_size=8,
                              cache_dtype=jnp.bfloat16), prompts, temps,
                      budget=8)
    i8 = _run_trace(_engine(cfg, params, page_size=8,
                            cache_dtype=jnp.bfloat16, kv_dtype="int8"),
                    prompts, temps, budget=8)
    total = sum(len(t) for t in bf16)
    agree = sum(a == b for ta, tb in zip(bf16, i8)
                for a, b in zip(ta, tb))
    assert agree / total >= 0.9, f"greedy agreement {agree}/{total}"


def test_paged_attention_true_on_mesh_raises(gpt2_setup):
    """Explicit paged_attention=True on a meshed engine is a config
    error (the kernel is opaque to GSPMD), reported BEFORE any port or
    watchdog side effects; 'auto' quietly keeps the dense path there."""
    import jax as _jax
    from jax.sharding import Mesh

    cfg, params = gpt2_setup
    mesh = Mesh(np.array(_jax.devices()[:1]), ("model",))
    # a 1-device mesh normalizes away -> kernel fine
    eng = _engine(cfg, params, mesh=mesh, paged_attention=True)
    assert eng._use_paged_kernel
    eng.close()

    class Fake:
        size = 2

    with pytest.raises(ValueError, match="meshed engine"):
        from accelerate_tpu.serving.engine import _resolve_paged_attention

        _resolve_paged_attention(True, Fake())
    assert _resolve_paged_attention("auto", Fake()) is False
