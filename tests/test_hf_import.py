"""HF checkpoint import: converted params must reproduce transformers
logits to float tolerance (the strongest possible parity check — it pins
both the weight transform AND our model semantics to the reference
implementation)."""

from __future__ import annotations

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _hf_llama(tiny=True):
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=144,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False, attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval()
    return cfg, model


def test_llama_logit_parity():
    from accelerate_tpu.models import hf_import, llama

    hf_cfg, hf_model = _hf_llama()
    cfg = hf_import.llama_config_from_hf(hf_cfg)
    params = hf_import.llama_params_from_hf(cfg, hf_model.state_dict())

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 17)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_llama_gqa_and_longer_seq():
    from accelerate_tpu.models import hf_import, llama

    cfg_hf = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=6, num_key_value_heads=3,
        max_position_embeddings=128, rope_theta=500000.0,
        tie_word_embeddings=True, attention_dropout=0.0,
    )
    torch.manual_seed(1)
    hf_model = transformers.LlamaForCausalLM(cfg_hf).eval()
    cfg = hf_import.llama_config_from_hf(cfg_hf)
    assert cfg.tie_word_embeddings
    params = hf_import.llama_params_from_hf(cfg, hf_model.state_dict())
    ids = np.arange(0, 96, dtype=np.int32)[None, :]
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_bert_logit_parity():
    from accelerate_tpu.models import bert, hf_import

    hf_cfg = transformers.BertConfig(
        vocab_size=200, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, type_vocab_size=2, num_labels=3,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu",
    )
    torch.manual_seed(2)
    hf_model = transformers.BertForSequenceClassification(hf_cfg).eval()
    cfg = hf_import.bert_config_from_hf(hf_cfg, num_labels=3)
    params = hf_import.bert_params_from_hf(cfg, hf_model.state_dict())

    rng = np.random.default_rng(3)
    ids = rng.integers(0, 200, (2, 21)).astype(np.int32)
    tt = np.zeros_like(ids)
    tt[:, 11:] = 1
    with torch.no_grad():
        want = hf_model(
            torch.tensor(ids, dtype=torch.long),
            token_type_ids=torch.tensor(tt, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(bert.forward(cfg, params, ids, token_type_ids=tt))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_bert_attention_mask_parity():
    from accelerate_tpu.models import bert, hf_import

    hf_cfg = transformers.BertConfig(
        vocab_size=100, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2,
        max_position_embeddings=32, num_labels=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(4)
    hf_model = transformers.BertForSequenceClassification(hf_cfg).eval()
    cfg = hf_import.bert_config_from_hf(hf_cfg, num_labels=2)
    params = hf_import.bert_params_from_hf(cfg, hf_model.state_dict())
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 100, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    mask[0, 8:] = 0  # padded tail on row 0
    with torch.no_grad():
        want = hf_model(
            torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(bert.forward(cfg, params, ids, attention_mask=mask))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_mixtral_logit_parity():
    from accelerate_tpu.models import hf_import, mixtral

    hf_cfg = transformers.MixtralConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        attention_dropout=0.0, router_jitter_noise=0.0,
    )
    torch.manual_seed(6)
    hf_model = transformers.MixtralForCausalLM(hf_cfg).eval()
    cfg = hf_import.mixtral_config_from_hf(hf_cfg)
    params = hf_import.mixtral_params_from_hf(cfg, hf_model.state_dict())
    rng = np.random.default_rng(7)
    ids = rng.integers(0, 96, (2, 13)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    logits, _aux = mixtral.forward(cfg, params, ids)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=3e-4, atol=3e-4)


def test_checkpoint_dir_roundtrip(tmp_path):
    """load_hf_checkpoint reads a sharded safetensors dir written the HF way."""
    from safetensors.numpy import save_file

    from accelerate_tpu.models import hf_import, llama

    hf_cfg, hf_model = _hf_llama()
    sd = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    # split into two shards with an HF-style index
    keys = sorted(sd)
    half = len(keys) // 2
    import json

    weight_map = {}
    for i, chunk in enumerate((keys[:half], keys[half:])):
        fname = f"model-{i + 1:05d}-of-00002.safetensors"
        save_file({k: sd[k] for k in chunk}, str(tmp_path / fname))
        weight_map.update({k: fname for k in chunk})
    (tmp_path / "model.safetensors.index.json").write_text(
        json.dumps({"metadata": {}, "weight_map": weight_map})
    )
    cfg = hf_import.llama_config_from_hf(hf_cfg)
    params = hf_import.load_hf_checkpoint("llama", cfg, str(tmp_path))
    rng = np.random.default_rng(8)
    ids = rng.integers(0, 128, (1, 9)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_llama3_rope_scaling_parity():
    """Llama-3.1-style rope_scaling must reproduce HF logits — real Llama-3.1
    checkpoints ship this config and silently degrade without it."""
    from accelerate_tpu.models import hf_import, llama

    cfg_hf = transformers.LlamaConfig(
        vocab_size=96, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=6, num_key_value_heads=3,
        max_position_embeddings=256, rope_theta=500000.0,
        attention_dropout=0.0,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 64,
        },
    )
    torch.manual_seed(9)
    hf_model = transformers.LlamaForCausalLM(cfg_hf).eval()
    cfg = hf_import.llama_config_from_hf(cfg_hf)
    assert cfg.rope_scaling_dict and cfg.rope_scaling_dict["rope_type"] == "llama3"
    params = hf_import.llama_params_from_hf(cfg, hf_model.state_dict())
    ids = np.arange(0, 96, dtype=np.int32)[None, :]  # long enough to engage scaling
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_linear_rope_scaling_parity():
    from accelerate_tpu.models import hf_import, llama

    cfg_hf = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, attention_dropout=0.0,
        rope_scaling={"rope_type": "linear", "factor": 2.0},
    )
    torch.manual_seed(10)
    hf_model = transformers.LlamaForCausalLM(cfg_hf).eval()
    cfg = hf_import.llama_config_from_hf(cfg_hf)
    params = hf_import.llama_params_from_hf(cfg, hf_model.state_dict())
    rng = np.random.default_rng(11)
    ids = rng.integers(0, 64, (2, 40)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_mistral_logit_parity():
    from accelerate_tpu.models import hf_import, llama

    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=144,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=4096,
        attention_dropout=0.0,
    )
    torch.manual_seed(4)
    hf_model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("mistral", hf_cfg)
    params = hf_import.params_from_hf("mistral", cfg, hf_model.state_dict())
    ids = np.random.default_rng(5).integers(0, 128, (2, 19)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_qwen2_logit_parity():
    from accelerate_tpu.models import hf_import, llama

    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=144,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, attention_dropout=0.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(6)
    hf_model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("qwen2", hf_cfg)
    assert cfg.attention_bias  # qwen2 always carries qkv biases
    params = hf_import.params_from_hf("qwen2", cfg, hf_model.state_dict())
    assert "bias" in params["layers"]["attn"]["q_proj"]
    ids = np.random.default_rng(7).integers(0, 128, (2, 23)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_gpt2_logit_parity():
    from accelerate_tpu.models import gpt2, hf_import

    hf_cfg = transformers.GPT2Config(
        vocab_size=160, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(8)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = hf_import.config_from_hf("gpt2", hf_cfg)
    params = hf_import.params_from_hf("gpt2", cfg, hf_model.state_dict())
    ids = np.random.default_rng(9).integers(0, 160, (2, 25)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(gpt2.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("parallel_residual", [True, False])
def test_gpt_neox_logit_parity(parallel_residual):
    from accelerate_tpu.models import gpt_neox, hf_import

    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=parallel_residual,
        attention_dropout=0.0, hidden_dropout=0.0,
    )
    torch.manual_seed(10)
    hf_model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("gpt_neox", hf_cfg)
    params = hf_import.params_from_hf("gpt_neox", cfg, hf_model.state_dict())
    ids = np.random.default_rng(11).integers(0, 160, (2, 21)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(gpt_neox.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_mixtral_rope_scaling_importable():
    """Mixtral + rope_scaling imports and applies the scaling (previously
    refused outright)."""
    from accelerate_tpu.models import hf_import, mixtral

    cfg = hf_import.mixtral_config_from_hf({
        "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "num_local_experts": 4,
        "num_experts_per_tok": 2, "max_position_embeddings": 64,
        "rope_scaling": {"rope_type": "linear", "factor": 2.0},
    })
    assert cfg.rope_scaling_dict == {"rope_type": "linear", "factor": 2.0}
    params = mixtral.init_params(cfg, __import__("jax").random.key(0))
    ids = np.arange(32, dtype=np.int32)[None, :]
    out, _ = mixtral.forward(cfg, params, ids)
    # scaling must actually change the logits vs the unscaled config
    cfg0 = hf_import.mixtral_config_from_hf({
        "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "num_local_experts": 4,
        "num_experts_per_tok": 2, "max_position_embeddings": 64,
    })
    out0, _ = mixtral.forward(cfg0, params, ids)
    assert not np.allclose(np.asarray(out), np.asarray(out0), atol=1e-4)


def test_mistral_sliding_window_parity_beyond_window():
    """Sequences LONGER than sliding_window must reproduce HF logits — the
    band mask (not global attention) is what the checkpoint was trained
    with. Round-2 refused these; the window is now applied."""
    from accelerate_tpu.models import hf_import, llama

    hf_cfg = transformers.MistralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, sliding_window=8,
        attention_dropout=0.0,
    )
    torch.manual_seed(50)
    hf_model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("mistral", hf_cfg)
    assert cfg.sliding_window == 8
    params = hf_import.params_from_hf("mistral", cfg, hf_model.state_dict())
    ids = np.random.default_rng(51).integers(0, 96, (2, 33)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(llama.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_explicit_decoupled_head_dim_refused():
    from accelerate_tpu.models import hf_import

    with pytest.raises(ValueError, match="head_dim"):
        hf_import.config_from_hf("mistral", {
            "vocab_size": 64, "hidden_size": 5120, "intermediate_size": 64,
            "num_hidden_layers": 1, "num_attention_heads": 32,
            "head_dim": 128,
        })


def test_qwen2_unused_sliding_window_not_recorded():
    from accelerate_tpu.models import hf_import

    cfg = hf_import.config_from_hf("qwen2", {
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 1, "num_attention_heads": 2,
        "num_key_value_heads": 2, "max_position_embeddings": 128,
        "sliding_window": 16, "use_sliding_window": False,
    })
    assert cfg.sliding_window is None


def test_sliding_window_decode_matches_forward():
    """KV-cache decode past the window must drop out-of-band cached keys,
    matching the full windowed forward position by position."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import hf_import, llama

    cfg = hf_import.config_from_hf("mistral", {
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 2,
        "num_key_value_heads": 2, "max_position_embeddings": 128,
        "sliding_window": 6,
    })
    params = llama.init_params(cfg, jax.random.key(0))
    ids = np.random.default_rng(52).integers(0, 64, (2, 20)).astype(np.int32)
    full = llama.forward(cfg, params, ids)
    caches = llama.init_kv_caches(cfg, 2, 24, dtype=jnp.float32)
    prefix, caches = llama.forward(cfg, params, ids[:, :5], kv_caches=caches)
    np.testing.assert_allclose(np.asarray(prefix), np.asarray(full[:, :5]),
                               atol=2e-2)
    # jitted once, positions traced (15 eager op-by-op forwards were a
    # tier-1 top-30 cost)
    step = jax.jit(lambda tok, pos, c: llama.forward(
        cfg, params, tok, positions=pos, kv_caches=c))
    outs = []
    for t in range(5, 20):  # decode well past window=6
        lg, caches = step(ids[:, t : t + 1], jnp.full((2, 1), t), caches)
        outs.append(lg)
    decoded = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full[:, 5:]),
                               atol=2e-2)


def test_mistral_generate_parity_beyond_window():
    from accelerate_tpu.models import hf_import, llama

    hf_cfg = transformers.MistralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, sliding_window=8,
        attention_dropout=0.0,
    )
    torch.manual_seed(53)
    hf_model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("mistral", hf_cfg)
    params = hf_import.params_from_hf("mistral", cfg, hf_model.state_dict())
    ids = np.random.default_rng(54).integers(0, 96, (2, 12)).astype(np.int32)
    got = np.asarray(llama.generate(cfg, params, ids, max_new_tokens=10))
    _assert_greedy_match(hf_model, ids, 10, got, prompt_len=12)


def test_ring_and_ulysses_backends_run_sliding_window():
    """Windowed CP: ring (global-position banding) and ulysses (band after
    the head scatter) must match the einsum path's sliding-window logits."""
    import jax

    from accelerate_tpu.models import llama

    ids = np.random.default_rng(55).integers(0, 256, (1, 16)).astype(np.int32)
    ref_cfg = llama.LlamaConfig.tiny(sliding_window=8,
                                     attention_backend="einsum")
    params = llama.init_params(ref_cfg, jax.random.key(0))
    ref = np.asarray(llama.forward(ref_cfg, params, ids))
    for backend in ("ring", "ulysses"):
        cfg = llama.LlamaConfig.tiny(sliding_window=8,
                                     attention_backend=backend)
        got = np.asarray(llama.forward(cfg, params, ids))
        np.testing.assert_allclose(got, ref, atol=2e-4, err_msg=backend)


def test_gptj_logit_parity():
    from accelerate_tpu.models import gptj, hf_import

    hf_cfg = transformers.GPTJConfig(
        vocab_size=160, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=8, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(12)
    hf_model = transformers.GPTJForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("gptj", hf_cfg)
    params = hf_import.params_from_hf("gptj", cfg, hf_model.state_dict())
    ids = np.random.default_rng(13).integers(0, 160, (2, 19)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(gptj.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_opt_logit_parity():
    from accelerate_tpu.models import hf_import, opt

    hf_cfg = transformers.OPTConfig(
        vocab_size=160, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, dropout=0.0, attention_dropout=0.0,
        word_embed_proj_dim=64,
    )
    torch.manual_seed(14)
    hf_model = transformers.OPTForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("opt", hf_cfg)
    params = hf_import.params_from_hf("opt", cfg, hf_model.state_dict())
    ids = np.random.default_rng(15).integers(0, 160, (2, 23)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    got = np.asarray(opt.forward(cfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_opt_postln_refused():
    from accelerate_tpu.models import hf_import

    with pytest.raises(ValueError, match="post-LN"):
        hf_import.config_from_hf("opt", {
            "vocab_size": 64, "hidden_size": 32, "ffn_dim": 64,
            "num_hidden_layers": 1, "num_attention_heads": 2,
            "do_layer_norm_before": False,
        })


@pytest.mark.parametrize("gated,tied", [(False, True), (True, False)])
def test_t5_logit_parity(gated, tied):
    """t5-style (relu, tied head) and v1.1/T0-style (gated-gelu, untied)."""
    from accelerate_tpu.models import hf_import, t5

    hf_cfg = transformers.T5Config(
        vocab_size=160, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=tied, decoder_start_token_id=0,
    )
    torch.manual_seed(16)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = hf_import.config_from_hf("t5", hf_cfg)
    assert cfg.is_gated_act == gated and cfg.tie_word_embeddings == tied
    params = hf_import.params_from_hf("t5", cfg, hf_model.state_dict())
    rng = np.random.default_rng(17)
    enc_ids = rng.integers(0, 160, (2, 21)).astype(np.int32)
    dec_ids = rng.integers(0, 160, (2, 9)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(
            input_ids=torch.tensor(enc_ids, dtype=torch.long),
            decoder_input_ids=torch.tensor(dec_ids, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(t5.forward(cfg, params, enc_ids, dec_ids))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_t5_encoder_padding_mask_parity():
    from accelerate_tpu.models import hf_import, t5

    hf_cfg = transformers.T5Config(
        vocab_size=120, d_model=48, d_kv=12, d_ff=96, num_layers=2,
        num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=True,
        decoder_start_token_id=0,
    )
    torch.manual_seed(18)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = hf_import.config_from_hf("t5", hf_cfg)
    params = hf_import.params_from_hf("t5", cfg, hf_model.state_dict())
    rng = np.random.default_rng(19)
    enc_ids = rng.integers(0, 120, (2, 16)).astype(np.int32)
    mask = (np.arange(16)[None, :] < np.asarray([10, 16])[:, None])
    dec_ids = rng.integers(0, 120, (2, 7)).astype(np.int32)
    with torch.no_grad():
        want = hf_model(
            input_ids=torch.tensor(enc_ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            decoder_input_ids=torch.tensor(dec_ids, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(t5.forward(cfg, params, enc_ids, dec_ids,
                                attention_mask=mask))
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_opt_left_padding_parity():
    """HF OPT derives positions from the attention-mask cumsum; left-padded
    batches must match (code-review r2 finding)."""
    from accelerate_tpu.models import hf_import, opt

    hf_cfg = transformers.OPTConfig(
        vocab_size=120, hidden_size=48, ffn_dim=96, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, dropout=0.0, attention_dropout=0.0,
        word_embed_proj_dim=48,
    )
    torch.manual_seed(20)
    hf_model = transformers.OPTForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("opt", hf_cfg)
    params = hf_import.params_from_hf("opt", cfg, hf_model.state_dict())
    rng = np.random.default_rng(21)
    ids = rng.integers(0, 120, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int64)
    mask[0, :4] = 0  # left padding on row 0
    with torch.no_grad():
        want = hf_model(
            torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask),
        ).logits.numpy()
    got = np.asarray(opt.forward(cfg, params, ids, attention_mask=mask))
    keep = mask[:, :, None].astype(bool)
    np.testing.assert_allclose(got[keep[..., 0]], want[keep[..., 0]],
                               rtol=3e-4, atol=3e-4)


def test_t5_unknown_activation_refused():
    from accelerate_tpu.models import hf_import

    with pytest.raises(ValueError, match="feed_forward_proj"):
        hf_import.config_from_hf("t5", {
            "vocab_size": 64, "d_model": 32, "d_ff": 64, "num_layers": 1,
            "num_heads": 2, "feed_forward_proj": "gated-silu",
        })


def test_gptj_full_head_rotary_dim_none():
    from accelerate_tpu.models import hf_import

    cfg = hf_import.config_from_hf("gptj", {
        "vocab_size": 64, "n_embd": 32, "n_layer": 1, "n_head": 2,
        "n_positions": 32, "rotary_dim": None,
    })
    assert cfg.rotary_dim == 16  # full head dim


# --- greedy generate parity (the reference's benchmark operation, ref
# benchmarks/big_model_inference.py:94-108) ----------------------------------


def _assert_greedy_match(hf_model, ids, n, got, prompt_len):
    """Require token-exact greedy agreement, except where HF's own top-2
    logit gap is below float tolerance — there a 3e-4 logit wiggle
    legitimately flips argmax and the sequences fork (stop comparing that
    row). At least one full row must match end-to-end."""
    with torch.no_grad():
        out = hf_model.generate(
            torch.tensor(ids, dtype=torch.long), max_new_tokens=n,
            do_sample=False, pad_token_id=0, output_scores=True,
            return_dict_in_generate=True,
        )
    want = out.sequences.numpy()
    np.testing.assert_array_equal(got[:, :prompt_len], want[:, :prompt_len])
    full_rows = 0
    for r in range(want.shape[0]):
        forked = False
        for step, scores in enumerate(out.scores):
            col = prompt_len + step
            if col >= got.shape[1]:
                break
            if got[r, col] == want[r, col]:
                continue
            top2 = torch.topk(scores[r], 2).values
            gap = float(top2[0] - top2[1])
            assert gap < 1e-2, (
                f"row {r} diverged at step {step} with decisive HF logit "
                f"gap {gap:.4f}: got {got[r, col]}, want {want[r, col]}"
            )
            forked = True
            break
        full_rows += not forked
    assert full_rows >= 1, "every row forked on ties — suspicious"


def test_gpt2_generate_parity():
    from accelerate_tpu.models import gpt2, hf_import

    hf_cfg = transformers.GPT2Config(
        vocab_size=160, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(30)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = hf_import.config_from_hf("gpt2", hf_cfg)
    params = hf_import.params_from_hf("gpt2", cfg, hf_model.state_dict())
    ids = np.random.default_rng(31).integers(0, 160, (2, 7)).astype(np.int32)
    got = np.asarray(gpt2.generate(cfg, params, ids, max_new_tokens=8))
    _assert_greedy_match(hf_model, ids, 8, got, prompt_len=7)


def test_gptj_generate_parity():
    from accelerate_tpu.models import gptj, hf_import

    hf_cfg = transformers.GPTJConfig(
        vocab_size=160, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=8, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(32)
    hf_model = transformers.GPTJForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("gptj", hf_cfg)
    params = hf_import.params_from_hf("gptj", cfg, hf_model.state_dict())
    ids = np.random.default_rng(33).integers(0, 160, (2, 7)).astype(np.int32)
    got = np.asarray(gptj.generate(cfg, params, ids, max_new_tokens=8))
    _assert_greedy_match(hf_model, ids, 8, got, prompt_len=7)


def test_gpt_neox_generate_parity():
    from accelerate_tpu.models import gpt_neox, hf_import

    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=160, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        hidden_dropout=0.0, attention_dropout=0.0,
        use_parallel_residual=True,
    )
    torch.manual_seed(34)
    hf_model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("gpt_neox", hf_cfg)
    params = hf_import.params_from_hf("gpt_neox", cfg, hf_model.state_dict())
    ids = np.random.default_rng(35).integers(0, 160, (2, 7)).astype(np.int32)
    got = np.asarray(gpt_neox.generate(cfg, params, ids, max_new_tokens=8))
    _assert_greedy_match(hf_model, ids, 8, got, prompt_len=7)


def test_opt_generate_parity():
    from accelerate_tpu.models import hf_import, opt

    hf_cfg = transformers.OPTConfig(
        vocab_size=160, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, dropout=0.0, attention_dropout=0.0,
        word_embed_proj_dim=64,
    )
    torch.manual_seed(36)
    hf_model = transformers.OPTForCausalLM(hf_cfg).eval()
    cfg = hf_import.config_from_hf("opt", hf_cfg)
    params = hf_import.params_from_hf("opt", cfg, hf_model.state_dict())
    ids = np.random.default_rng(37).integers(2, 160, (2, 7)).astype(np.int32)
    got = np.asarray(opt.generate(cfg, params, ids, max_new_tokens=8))
    _assert_greedy_match(hf_model, ids, 8, got, prompt_len=7)


@pytest.mark.parametrize("gated,tied", [(False, True), (True, False)])
def test_t5_generate_parity(gated, tied):
    from accelerate_tpu.models import hf_import, t5

    hf_cfg = transformers.T5Config(
        vocab_size=160, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=tied, decoder_start_token_id=0,
        eos_token_id=None, pad_token_id=0,
    )
    torch.manual_seed(38)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = hf_import.config_from_hf("t5", hf_cfg)
    params = hf_import.params_from_hf("t5", cfg, hf_model.state_dict())
    enc_ids = np.random.default_rng(39).integers(0, 160, (2, 9)).astype(np.int32)
    got = np.asarray(t5.generate(cfg, params, enc_ids, max_new_tokens=8))
    # decoder output: start token + 8 generated, so prompt_len=1
    _assert_greedy_match(hf_model, enc_ids, 8, got, prompt_len=1)
