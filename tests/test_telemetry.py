"""Unified telemetry subsystem (ISSUE 3): registry/histograms, span
tracing + flight recorder, Prometheus/JSONL export, multi-host
aggregation, the stall watchdog — and the overhead + collection guards
that keep instrumentation free when observability is off."""

import json
import math
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from accelerate_tpu.telemetry import (
    MetricsRegistry,
    MetricsServer,
    StallWatchdog,
    StreamingHistogram,
    aggregate_flat,
    aggregate_snapshot,
    clear_flight_recorder,
    configure_tracing,
    drain_spans,
    export_chrome_trace,
    flatten_snapshot,
    flight_recorder,
    get_registry,
    ingest_spans,
    record_span,
    render_prometheus,
    resolve_metrics_port,
    span,
    trace_events,
    tracing_enabled,
)
from accelerate_tpu.telemetry.aggregate import merged_registry
from accelerate_tpu.telemetry.watchdog import StallError


@pytest.fixture(autouse=True)
def _tracing_off():
    """Every test starts and ends with tracing disabled and an empty
    flight recorder (module-level state must not leak across tests)."""
    configure_tracing(enabled=False)
    clear_flight_recorder()
    yield
    configure_tracing(enabled=False)
    clear_flight_recorder()


# ---------------------------------------------------------------------------
# streaming histogram (the shared quantile helper)
# ---------------------------------------------------------------------------


class TestStreamingHistogram:
    def test_quantile_parity_with_numpy_percentile(self):
        """Satellite: the shared histogram must agree with numpy.percentile
        on known data within its declared relative accuracy."""
        rng = np.random.default_rng(0)
        for data in (
            rng.lognormal(0.0, 1.5, 20_000),          # latency-shaped
            rng.uniform(0.001, 10.0, 20_000),
            np.arange(1, 5001).astype(float),
        ):
            h = StreamingHistogram(relative_accuracy=0.01)
            for v in data:
                h.record(v)
            for q in (50, 90, 99):
                exact = float(np.percentile(data, q))
                approx = h.quantile(q / 100)
                # nearest-rank + log buckets: 3x the sketch accuracy is a
                # safe deterministic bound
                assert abs(approx - exact) / exact < 0.03, (q, approx, exact)

    def test_exact_count_sum_mean_min_max(self):
        h = StreamingHistogram()
        data = [0.1, 0.2, 0.4, 0.8]
        for v in data:
            h.record(v)
        assert h.count == 4
        assert h.sum == pytest.approx(sum(data))
        assert h.mean == pytest.approx(sum(data) / 4)
        assert h.min == pytest.approx(0.1)
        assert h.max == pytest.approx(0.8)

    def test_empty_and_zero_values(self):
        h = StreamingHistogram()
        assert math.isnan(h.quantile(0.5)) and math.isnan(h.mean)
        h.record(0.0)
        h.record(0.0)
        h.record(1.0)
        assert h.quantile(0.5) == 0.0
        assert h.quantile(1.0) == pytest.approx(1.0, rel=0.02)

    def test_bounded_memory_collapses_low_buckets(self):
        h = StreamingHistogram(relative_accuracy=0.01, max_buckets=512)
        rng = np.random.default_rng(1)
        data = rng.lognormal(0.0, 1.5, 50_000)
        for v in data:
            h.record(v)
        assert len(h._buckets) <= 512
        # collapsing the LOWEST buckets keeps tail accuracy: p50/p99 sit
        # far above the collapsed bottom of the range
        for q in (50, 99):
            exact = float(np.percentile(data, q))
            assert abs(h.quantile(q / 100) - exact) / exact < 0.05

    def test_merge_equals_combined_stream(self):
        rng = np.random.default_rng(2)
        a_data, b_data = rng.lognormal(0, 1, 5000), rng.lognormal(1, 1, 5000)
        a, b, both = (StreamingHistogram() for _ in range(3))
        for v in a_data:
            a.record(v)
            both.record(v)
        for v in b_data:
            b.record(v)
            both.record(v)
        a.merge(b)
        assert a.count == both.count
        assert a.sum == pytest.approx(both.sum)
        for q in (0.5, 0.99):
            assert a.quantile(q) == pytest.approx(both.quantile(q), rel=0.03)

    def test_roundtrip_through_dict(self):
        h = StreamingHistogram()
        for v in (0.5, 1.5, 2.5):
            h.record(v)
        h2 = StreamingHistogram.from_dict(
            json.loads(json.dumps(h.to_dict())))
        assert h2.count == 3 and h2.sum == pytest.approx(4.5)
        assert h2.quantile(0.5) == pytest.approx(h.quantile(0.5))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_get_or_create_and_labels(self):
        r = MetricsRegistry()
        assert r.counter("req_total", host="0") is r.counter("req_total", host="0")
        assert r.counter("req_total", host="0") is not r.counter("req_total", host="1")
        r.counter("req_total", host="0").inc(3)
        r.gauge("depth").set(7)
        r.histogram("lat_s").record(0.25)
        snap = r.snapshot()
        assert snap["counters"]['req_total{host="0"}'] == 3.0
        assert snap["gauges"]["depth"] == 7.0
        assert snap["histograms"]["lat_s"]["count"] == 1.0

    def test_counter_rejects_negative(self):
        with pytest.raises(ValueError):
            MetricsRegistry().counter("c").inc(-1)

    def test_gauge_set_max_is_high_water(self):
        g = MetricsRegistry().gauge("hbm")
        g.set_max(10)
        g.set_max(5)
        assert g.value == 10

    def test_reset_zeroes_in_place(self):
        r = MetricsRegistry()
        c, h = r.counter("c"), r.histogram("h")
        c.inc(5)
        h.record(1.0)
        r.reset()
        # same objects (cached handles + exporter stay live), zeroed
        assert r.counter("c") is c and c.value == 0
        assert r.histogram("h") is h and h.count == 0

    def test_flatten_snapshot(self):
        r = MetricsRegistry()
        r.counter("tok").inc(2)
        r.histogram("lat").record(0.5)
        flat = flatten_snapshot(r.snapshot(), prefix="t/")
        assert flat["t/tok"] == 2.0
        assert flat["t/lat_count"] == 1.0 and "t/lat_p99" in flat

    def test_concurrent_increments_are_exact(self):
        r = MetricsRegistry()
        c = r.counter("n")

        def work():
            for _ in range(10_000):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 40_000


# ---------------------------------------------------------------------------
# span tracing + flight recorder + chrome export
# ---------------------------------------------------------------------------


class TestTracing:
    def test_disabled_span_is_shared_noop(self):
        assert not tracing_enabled()
        s1, s2 = span("a"), span("b", big="attr")
        assert s1 is s2  # the shared null span: no allocation per call
        with s1:
            pass
        assert flight_recorder() == []

    def test_nested_spans_record_ids_and_attrs(self):
        configure_tracing(enabled=True, annotate=False)
        with span("outer", phase="train"):
            with span("inner"):
                time.sleep(0.001)
        events = flight_recorder()
        assert [e["name"] for e in events] == ["inner", "outer"]
        inner, outer = events
        assert inner.get("parent_id") == outer["span_id"]
        assert inner["trace_id"] == outer["trace_id"]
        assert outer["attrs"] == {"phase": "train"}
        assert inner["dur_ns"] >= 1_000_000  # the sleep is inside it
        assert outer["dur_ns"] >= inner["dur_ns"]

    def test_sibling_roots_get_distinct_traces(self):
        configure_tracing(enabled=True, annotate=False)
        with span("a"):
            pass
        with span("b"):
            pass
        a, b = flight_recorder()
        assert a["trace_id"] != b["trace_id"]
        assert a["parent_id"] == 0 and b["parent_id"] == 0

    def test_ring_buffer_is_bounded(self):
        configure_tracing(enabled=True, ring_size=8, annotate=False)
        for i in range(50):
            with span(f"s{i}"):
                pass
        events = flight_recorder()
        assert len(events) == 8
        assert events[-1]["name"] == "s49"
        configure_tracing(enabled=False, ring_size=4096)

    def test_span_records_on_exception(self):
        configure_tracing(enabled=True, annotate=False)
        with pytest.raises(RuntimeError):
            with span("boom"):
                raise RuntimeError("x")
        (e,) = flight_recorder()
        assert e["error"] == "RuntimeError"

    def test_chrome_trace_export(self, tmp_path):
        configure_tracing(enabled=True, annotate=False)
        with span("region", k="v"):
            pass
        path = str(tmp_path / "trace.json")
        doc = export_chrome_trace(path)
        with open(path) as f:
            loaded = json.load(f)
        assert loaded == doc
        (ev,) = loaded["traceEvents"]
        assert ev["ph"] == "X" and ev["name"] == "region"
        assert ev["dur"] >= 0 and ev["args"]["k"] == "v"

    def test_annotation_forwarding_matches_jax_profiler(self):
        """Enabled spans enter jax.profiler.TraceAnnotation so host spans
        line up with XLA device traces (smoke: no device capture here)."""
        configure_tracing(enabled=True, annotate=True)
        with span("annotated-region"):
            pass
        (e,) = flight_recorder()
        assert e["name"] == "annotated-region"


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

_PROM_LINE = None  # compiled lazily


def _parse_exposition(body: str) -> dict[str, float]:
    """Minimal exposition parser: every non-comment line must be
    `name[{labels}] value`."""
    import re

    pat = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(-?[0-9.eE+naNif]+)$')
    out = {}
    for line in body.strip().splitlines():
        if line.startswith("#"):
            continue
        m = pat.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        out[m.group(1) + (m.group(2) or "")] = m.group(3)
    return out


class TestPrometheusExport:
    def test_render_types_and_values(self):
        r = MetricsRegistry()
        r.counter("tokens_total").inc(12)
        r.gauge("queue_depth").set(3)
        h = r.histogram("ttft_seconds")
        for v in (0.1, 0.2, 0.3):
            h.record(v)
        body = render_prometheus(r)
        assert "# TYPE tokens_total counter" in body
        assert "# TYPE queue_depth gauge" in body
        assert "# TYPE ttft_seconds summary" in body
        series = _parse_exposition(body)
        assert float(series["tokens_total"]) == 12.0
        assert float(series["ttft_seconds_count"]) == 3.0
        assert float(series['ttft_seconds{quantile="0.99"}']) > 0

    def test_label_escaping_and_name_sanitizing(self):
        r = MetricsRegistry()
        r.counter("weird-name.total", path='a"b\\c').inc()
        body = render_prometheus(r)
        assert "weird_name_total" in body
        assert '\\"b' in body

    def test_http_endpoint_serves_parseable_exposition(self):
        """Satellite: bind port 0 (no fixed ports), GET /metrics, parse."""
        r = MetricsRegistry()
        r.counter("up_total").inc()
        r.histogram("lat_seconds").record(0.05)
        server = MetricsServer(registry=r, port=0, host="127.0.0.1").start()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            resp = urllib.request.urlopen(url, timeout=5)
            assert resp.status == 200
            assert "text/plain" in resp.headers["Content-Type"]
            series = _parse_exposition(resp.read().decode())
            assert float(series["up_total"]) == 1.0
            assert float(series["lat_seconds_count"]) == 1.0
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/nope", timeout=5)
        finally:
            server.stop()

    def test_resolve_metrics_port(self, monkeypatch):
        monkeypatch.delenv("ACCELERATE_TPU_METRICS_PORT", raising=False)
        assert resolve_metrics_port(None) is None
        assert resolve_metrics_port(9100) == 9100
        monkeypatch.setenv("ACCELERATE_TPU_METRICS_PORT", "0")
        assert resolve_metrics_port(None) == 0
        assert resolve_metrics_port(9100) == 9100  # explicit wins

    def test_server_binds_loopback_by_default(self):
        server = MetricsServer(registry=MetricsRegistry(), port=0)
        try:
            assert server._httpd.server_address[0] == "127.0.0.1"
        finally:
            server.stop()

    def test_env_port_conflict_degrades_instead_of_crashing(self, monkeypatch):
        """Second binder of the env-configured port (e.g. an Engine next
        to an Accelerator) must warn and run without an endpoint, not
        abort construction; an explicit flag still raises."""
        from accelerate_tpu.telemetry import start_metrics_server

        first = start_metrics_server(0, registry=MetricsRegistry())
        try:
            monkeypatch.setenv("ACCELERATE_TPU_METRICS_PORT",
                               str(first.port))
            second = start_metrics_server(None, registry=MetricsRegistry())
            assert second is None
            with pytest.raises(OSError):
                start_metrics_server(first.port, registry=MetricsRegistry())
        finally:
            first.stop()

    def test_jsonl_snapshot_writer(self, tmp_path):
        from accelerate_tpu.telemetry import write_snapshot

        r = MetricsRegistry()
        r.counter("n").inc(4)
        path = str(tmp_path / "telemetry.jsonl")
        write_snapshot(path, r)
        r.counter("n").inc(1)
        write_snapshot(path, r)
        lines = [json.loads(ln) for ln in open(path)]
        assert [ln["n"] for ln in lines] == [4.0, 5.0]
        assert all("ts" in ln for ln in lines)


# ---------------------------------------------------------------------------
# multi-host aggregation
# ---------------------------------------------------------------------------


def _host_snapshot(step_times: list[float], tokens: float, hbm: float) -> dict:
    r = MetricsRegistry()
    r.counter("tokens_total").inc(tokens)
    r.gauge("hbm_peak").set(hbm)
    h = r.histogram("step_time_s")
    for v in step_times:
        h.record(v)
    return r.snapshot(include_sketch=True)


class TestAggregation:
    def test_counters_sum_gauges_reduce_hists_merge(self):
        fast = _host_snapshot([0.10] * 100, tokens=1000, hbm=5.0)
        slow = _host_snapshot([0.30] * 100, tokens=1000, hbm=9.0)
        agg = aggregate_snapshot(snapshots=[fast, slow])
        assert agg["num_hosts"] == 2
        assert agg["counters"]["tokens_total"]["sum"] == 2000.0
        g = agg["gauges"]["hbm_peak"]
        assert (g["min"], g["max"]) == (5.0, 9.0)
        assert g["mean"] == pytest.approx(7.0)
        h = agg["histograms"]["step_time_s"]
        assert h["count"] == 200.0
        # the straggler view: the merged distribution spans both hosts,
        # and slowest_host_mean pins the worst host
        assert h["slowest_host_mean"] == pytest.approx(0.30, rel=0.02)
        assert h["p99"] == pytest.approx(0.30, rel=0.03)
        assert h["mean"] == pytest.approx(0.20, rel=0.02)

    def test_single_host_passthrough_uses_gather(self):
        r = MetricsRegistry()
        r.counter("c").inc(2)
        agg = aggregate_snapshot(registry=r)  # single process: gathers [self]
        assert agg["num_hosts"] == 1
        assert agg["counters"]["c"]["sum"] == 2.0

    def test_aggregate_flat_shape(self):
        snaps = [_host_snapshot([0.1], 10, 1.0),
                 _host_snapshot([0.2], 20, 2.0)]
        flat = aggregate_flat(snapshots=snaps, prefix="t/")
        assert flat["t/num_hosts"] == 2.0
        assert flat["t/tokens_total"] == 30.0
        assert flat["t/hbm_peak__max"] == 2.0
        assert flat["t/step_time_s__slowest_host_mean"] == pytest.approx(0.2, rel=0.02)
        assert all(isinstance(v, float) for v in flat.values())


# ---------------------------------------------------------------------------
# transport-backed merge: pod heartbeat snapshots -> one registry (ISSUE 18)
# ---------------------------------------------------------------------------


class TestMergedRegistry:
    def test_same_series_two_workers_sum_under_one_origin(self):
        """Two workers exposing the same series name must SUM into a
        single labeled series, not collide or shadow each other."""
        a = MetricsRegistry()
        a.counter("pod_tokens_total", role="decode").inc(3)
        b = MetricsRegistry()
        b.counter("pod_tokens_total", role="decode").inc(4)
        b.gauge("pod_active_pages").set(9)
        reg = merged_registry([a.snapshot(include_sketch=True),
                               b.snapshot(include_sketch=True)],
                              origin="workers")
        snap = reg.snapshot()
        (key,) = snap["counters"]
        assert 'origin="workers"' in key and 'role="decode"' in key
        assert snap["counters"][key] == 7.0
        # gauges expand to the min/mean/max family, still origin-tagged
        assert any(k.startswith("pod_active_pages__max{")
                   and 'origin="workers"' in k for k in snap["gauges"])

    def test_exemplar_histograms_merge_across_origins(self):
        """Exemplar-carrying histograms from different origins merge as
        distinct series (no cross-origin collision) and still render."""
        a = MetricsRegistry()
        ha = a.histogram("pod_latency_s")
        ha.record(0.1, exemplar="trace-a")
        ha.record(0.2, exemplar="trace-a2")
        b = MetricsRegistry()
        hb = b.histogram("pod_latency_s")
        hb.record(0.4, exemplar="trace-b")
        reg = MetricsRegistry()
        merged_registry([a.snapshot(include_sketch=True)],
                        registry=reg, origin="workers")
        merged_registry([b.snapshot(include_sketch=True)],
                        registry=reg, origin="workers", stale="true")
        snap = reg.snapshot()
        hists = snap["histograms"]
        assert len(hists) == 2          # one series per label set
        assert sorted(e["count"] for e in hists.values()) == [1.0, 2.0]
        stale_key = next(k for k in hists if 'stale="true"' in k)
        assert hists[stale_key]["sum"] == pytest.approx(0.4)
        # merged output renders cleanly for the scrape endpoint
        assert "pod_latency_s" in render_prometheus(reg)

    def test_newer_schema_unknown_keys_are_ignored_not_fatal(self):
        """A snapshot from a NEWER worker build (extra sections, extra
        histogram keys, exotic sketch encoding) merges best-effort: the
        series we understand survive, the rest are skipped."""
        newer = {
            "counters": {"tokens_total": 5.0},
            "gauges": {"hbm_peak": 2.0},
            "histograms": {
                "step_time_s": {"count": 2.0, "sum": 0.4,
                                "future_stat": "x",
                                "sketch": {"v2_encoding": True}},
            },
            "spans_v2": [{"opaque": 1}],      # unknown section
        }
        older = _host_snapshot([0.1, 0.3], tokens=7, hbm=1.0)
        reg = merged_registry([newer, older], origin="workers")
        snap = reg.snapshot()
        (ckey,) = snap["counters"]
        assert snap["counters"][ckey] == 12.0
        # the foreign sketch is dropped but the host's scalar stats and
        # the older host's real sketch still produce a distribution
        (hkey,) = snap["histograms"]
        assert snap["histograms"][hkey]["count"] == 2.0

    def test_older_schema_and_garbage_sections_tolerated(self):
        """Missing sections, non-dict sections, non-numeric values, and
        histogram entries that aren't dicts must not crash the merge."""
        garbage = [
            {},                                     # empty snapshot
            {"counters": "not-a-dict"},             # wrong section type
            {"counters": {"tokens_total": "NaNish"},
             "gauges": {"hbm_peak": None},
             "histograms": {"step_time_s": 3.14}},  # entry not a dict
            {"counters": {"tokens_total": 2.0}},    # old build: no hists
        ]
        reg = merged_registry(garbage, origin="workers")
        snap = reg.snapshot()
        (ckey,) = snap["counters"]
        assert snap["counters"][ckey] == 2.0
        agg = aggregate_snapshot(snapshots=garbage)
        assert agg["num_hosts"] == 4
        assert agg["counters"][next(iter(agg["counters"]))]["sum"] == 2.0


# ---------------------------------------------------------------------------
# cross-process span export: drain -> wire -> ingest (ISSUE 18)
# ---------------------------------------------------------------------------


class TestSpanExport:
    def test_drain_cursor_monotone_newest_first_and_filtered(self):
        configure_tracing(enabled=True, annotate=False)
        record_span("local-chatter", 0.0, 0.1, trace=12345)   # int id: home
        record_span("req-a", 0.0, 0.2, trace="req-a")
        record_span("req-b", 0.3, 0.4, trace="req-b")
        events, cur = drain_spans(0)
        assert [e["name"] for e in events] == ["req-b", "req-a"]  # newest 1st
        # nothing new: cursor is stable and returns empty
        again, cur2 = drain_spans(cur)
        assert again == [] and cur2 == cur
        record_span("req-c", 0.5, 0.6, trace="req-c")
        events, cur3 = drain_spans(cur)
        assert [e["name"] for e in events] == ["req-c"] and cur3 > cur
        # the cursor space survives a ring clear: it never moves back
        clear_flight_recorder()
        empty, cur4 = drain_spans(cur3)
        assert empty == [] and cur4 == cur3

    def test_drain_keeps_link_carrying_int_trace_events(self):
        configure_tracing(enabled=True, annotate=False)
        record_span("shared-step", 0.0, 0.1, trace=99, links=[7, 8])
        events, _ = drain_spans(0)
        assert [e["name"] for e in events] == ["shared-step"]

    def test_ingest_rebases_namespaces_and_skips_malformed(self):
        configure_tracing(enabled=True, annotate=False)
        events = [
            {"name": "w-span", "trace_id": 7, "span_id": 3, "parent_id": 0,
             "start_ns": 1_000, "dur_ns": 10},
            "garbage",                       # not a dict: skipped
            {"name": "half"},                # missing start_ns: skipped
        ]
        n = ingest_spans(events, offset_s=5.0, pid=4242, worker=2)
        assert n == 1
        (ev,) = trace_events("w2:7")         # int id namespaced per worker
        assert ev["start_ns"] == 1_000 + int(5.0 * 1e9)   # rebased
        assert ev["attrs"]["worker"] == 2 and ev["pid"] == 4242
        # string (request-scoped) trace ids merge verbatim with ours
        record_span("router-side", 10.0, 10.1, trace="req-x")
        ingest_spans([{"name": "worker-side", "trace_id": "req-x",
                       "start_ns": int(9.9e9), "dur_ns": 50}],
                     offset_s=0.25, worker=1)
        names = {e["name"] for e in trace_events("req-x")}
        assert names == {"router-side", "worker-side"}

    def test_ingest_is_a_noop_when_tracing_disabled(self):
        assert ingest_spans([{"name": "x", "trace_id": "t",
                              "start_ns": 0}], offset_s=0.0) == 0


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


class TestStallWatchdog:
    def test_missed_heartbeat_fires_exactly_once_with_payload(self):
        """Satellite: fake clock — a missed heartbeat fires once with the
        stack/HBM/flight-recorder payload; ticking keeps it silent."""
        configure_tracing(enabled=True, annotate=False)
        with span("last-thing-before-hang"):
            pass
        now = [0.0]
        reports = []
        wd = StallWatchdog(10.0, clock=lambda: now[0],
                           on_stall=reports.append)
        wd.tick()
        now[0] = 9.0
        assert wd.check() is None          # within budget: silent
        now[0] = 11.0
        report = wd.check()                # fired
        assert report is not None and len(reports) == 1
        assert wd.check() is None          # exactly once per stall
        now[0] = 500.0
        assert wd.check() is None          # still the same stall
        # payload: all-thread stacks, device memory stats, recorder tail
        assert any("test_telemetry" in "".join(stack)
                   for stack in report["stacks"].values())
        assert isinstance(report["device_memory_stats"], dict)
        assert [e["name"] for e in report["flight_recorder"]] == [
            "last-thing-before-hang"]
        assert report["silence_s"] == pytest.approx(11.0)

    def test_tick_rearms_for_the_next_stall(self):
        now = [0.0]
        wd = StallWatchdog(5.0, clock=lambda: now[0], logger=_SilentLogger())
        now[0] = 6.0
        assert wd.check() is not None
        wd.tick()                          # progress: re-armed
        now[0] = 8.0
        assert wd.check() is None
        now[0] = 12.0
        assert wd.check() is not None      # second stall fires again
        assert wd.stall_count == 2

    def test_raise_on_stall(self):
        now = [0.0]
        wd = StallWatchdog(1.0, clock=lambda: now[0], raise_on_stall=True,
                           logger=_SilentLogger())
        now[0] = 2.0
        with pytest.raises(StallError):
            wd.check()

    def test_background_thread_fires_and_stays_silent_when_ticked(self):
        fired = threading.Event()
        wd = StallWatchdog(0.1, poll_interval_s=0.02,
                           on_stall=lambda r: fired.set(),
                           logger=_SilentLogger())
        with wd:
            for _ in range(5):
                wd.tick()
                time.sleep(0.02)
            assert not fired.is_set()      # heartbeats kept it silent
            assert fired.wait(timeout=5.0)  # then silence fires it

    def test_rejects_nonpositive_timeout(self):
        with pytest.raises(ValueError):
            StallWatchdog(0.0)


class _SilentLogger:
    def error(self, *a, **k):
        pass


# ---------------------------------------------------------------------------
# request-scoped trace context (ISSUE 8)
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_parse_traceparent_valid(self):
        from accelerate_tpu.telemetry import parse_traceparent

        tid, pid = "ab" * 16, "cd" * 8
        assert parse_traceparent(f"00-{tid}-{pid}-01") == (tid, pid)
        # case-insensitive per spec, normalized to lowercase
        assert parse_traceparent(f"00-{tid.upper()}-{pid}-01") == (tid, pid)

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-0011223344556677-01",
        "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",   # all-zero trace id
        "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero parent
        "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # reserved version
        "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",  # non-hex
        "00-" + "ab" * 16 + "-" + "cd" * 8,          # missing flags
    ])
    def test_parse_traceparent_malformed_is_none(self, bad):
        """Satellite contract: anything malformed -> None, so the caller
        mints a fresh id instead of erroring or propagating garbage."""
        from accelerate_tpu.telemetry import parse_traceparent

        assert parse_traceparent(bad) is None

    def test_new_trace_id_shape(self):
        from accelerate_tpu.telemetry import new_trace_id

        ids = {new_trace_id() for _ in range(32)}
        assert len(ids) == 32
        assert all(len(t) == 32 and int(t, 16) >= 0 for t in ids)

    def test_explicit_context_and_record_span_share_a_trace(self):
        """The request-tracing shape: a pre-allocated root id, live child
        spans joined via trace=/parent=, retrospective spans via
        record_span — all indexed under one trace id."""
        from accelerate_tpu.telemetry import (
            new_trace_id,
            record_span,
            trace_events,
        )
        from accelerate_tpu.telemetry.trace import next_span_id

        configure_tracing(enabled=True, annotate=False)
        tid = new_trace_id()
        root = next_span_id()
        with span("admit", trace=tid, parent=root, slot=1):
            pass
        record_span("queue_wait", 1.0, 2.0, trace=tid, parent=root)
        record_span("request", 0.5, 4.0, trace=tid, span_id=root,
                    status="finished")
        events = trace_events(tid)
        assert [e["name"] for e in events] == ["request", "queue_wait",
                                               "admit"]  # by start time
        assert all(e["trace_id"] == tid for e in events)
        children = [e for e in events if e["name"] != "request"]
        assert all(e["parent_id"] == root for e in children)
        root_ev = next(e for e in events if e["name"] == "request")
        assert root_ev["span_id"] == root
        assert root_ev["attrs"]["status"] == "finished"
        # the filtered chrome export carries exactly this trace
        doc = export_chrome_trace(trace_id=tid)
        assert len(doc["traceEvents"]) == 3

    def test_span_links(self):
        """A span serving many requests at once (one batched decode step)
        links their traces without belonging to any one of them."""
        configure_tracing(enabled=True, annotate=False)
        with span("decode_step", links=["t-a", "t-b"]):
            pass
        ev = flight_recorder()[-1]
        assert ev["links"] == ["t-a", "t-b"]
        doc = export_chrome_trace()
        assert doc["traceEvents"][-1]["args"]["links"] == ["t-a", "t-b"]

    def test_ring_eviction_prunes_trace_index(self):
        from accelerate_tpu.telemetry import record_span, trace_events

        configure_tracing(enabled=True, annotate=False, ring_size=4)
        try:
            for i in range(10):
                record_span("x", 0.0, 1.0, trace=f"t{i}")
            assert len(flight_recorder()) == 4
            assert trace_events("t0") == []          # evicted AND pruned
            assert len(trace_events("t9")) == 1
        finally:
            configure_tracing(enabled=False, ring_size=4096)

    def test_record_span_disabled_is_free(self):
        from accelerate_tpu.telemetry import record_span, trace_events

        assert record_span("x", 0.0, 1.0, trace="t") == 0
        assert flight_recorder() == [] and trace_events("t") == []

    def test_head_sampling_rates(self):
        from accelerate_tpu.telemetry import head_sample

        # disabled tracing: never sampled, whatever the rates say
        configure_tracing(enabled=False, sample_rates={"gold": 1.0})
        assert head_sample("gold") is False
        configure_tracing(enabled=True,
                          sample_rates={"gold": 1.0, "bronze": 0.0},
                          default_sample_rate=1.0)
        try:
            assert all(head_sample("gold") for _ in range(50))
            assert not any(head_sample("bronze") for _ in range(50))
            assert all(head_sample("unlisted") for _ in range(50))
            configure_tracing(default_sample_rate=0.0)
            assert not any(head_sample("unlisted") for _ in range(50))
        finally:
            configure_tracing(enabled=False, sample_rates={},
                              default_sample_rate=1.0)


# ---------------------------------------------------------------------------
# exporter: content negotiation, HEAD, exemplars (ISSUE 8 satellites)
# ---------------------------------------------------------------------------


class TestExportNegotiation:
    def _server(self):
        r = MetricsRegistry()
        r.counter("up_total").inc()
        h = r.histogram("serving_ttft_seconds")
        h.record(0.05, exemplar="ee" * 16)
        return MetricsServer(registry=r, port=0, host="127.0.0.1").start(), r

    def test_content_type_and_head_support(self):
        """Satellite: proper `text/plain; version=0.0.4` Content-Type and
        HEAD answered with headers only."""
        server, _ = self._server()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            resp = urllib.request.urlopen(url, timeout=5)
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            body = resp.read()
            assert b"up_total" in body
            head_req = urllib.request.Request(url, method="HEAD")
            head = urllib.request.urlopen(head_req, timeout=5)
            assert head.status == 200
            assert head.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            assert int(head.headers["Content-Length"]) == len(body)
            assert head.read() == b""
        finally:
            server.stop()

    def test_two_concurrent_scrapes(self):
        """Satellite regression: two scrapers hitting the ThreadingHTTP
        endpoint at once both get complete, parseable expositions."""
        server, _ = self._server()
        results: list[bytes] = []
        errors: list[Exception] = []

        def scrape():
            try:
                url = f"http://127.0.0.1:{server.port}/metrics"
                results.append(urllib.request.urlopen(url, timeout=10).read())
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        try:
            threads = [threading.Thread(target=scrape) for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert not errors
            assert len(results) == 2
            for body in results:
                series = _parse_exposition(body.decode())
                assert float(series["up_total"]) == 1.0
        finally:
            server.stop()

    def test_openmetrics_negotiation_carries_exemplars(self):
        """An OpenMetrics Accept switches the exemplar-carrying series to
        bucket histograms with `# {trace_id=...}` exemplars and an EOF
        terminator; the default scrape is unchanged 0.0.4."""
        server, _ = self._server()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            req = urllib.request.Request(
                url, headers={"Accept": "application/openmetrics-text"})
            resp = urllib.request.urlopen(req, timeout=5)
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            body = resp.read().decode()
            assert "# TYPE serving_ttft_seconds histogram" in body
            assert 'serving_ttft_seconds_bucket{le="+Inf"} 1' in body
            assert f'trace_id="{"ee" * 16}"' in body
            assert body.rstrip().endswith("# EOF")
            # OpenMetrics 1.0: counter FAMILY without _total, sample
            # with it — a strict OM parser rejects the scrape otherwise
            assert "# TYPE up counter" in body
            assert "# TYPE up_total counter" not in body
            assert "up_total 1.0" in body
            plain = urllib.request.urlopen(url, timeout=5).read().decode()
            assert "trace_id" not in plain and "# EOF" not in plain
            assert "# TYPE serving_ttft_seconds summary" in plain
            assert "# TYPE up_total counter" in plain  # 0.0.4 unchanged
        finally:
            server.stop()

    def test_exemplar_bounded_and_reset(self):
        h = StreamingHistogram()
        for i in range(1, 200):
            h.record(float(i), exemplar=f"t{i}")
        assert len(h.exemplars()) <= h._MAX_EXEMPLARS
        # the tail is kept: the largest value's bucket still has one
        assert any(v[1] == "t199" for v in h.exemplars().values())
        h.reset()
        assert h.exemplars() == {} and h.count == 0


# ---------------------------------------------------------------------------
# incident bundles (ISSUE 8 tentpole c)
# ---------------------------------------------------------------------------


class TestIncidentBundles:
    def _fire(self, tmp_path, dumps=None, registry=None):
        now = [0.0]
        wd = StallWatchdog(5.0, clock=lambda: now[0], logger=_SilentLogger(),
                           incident_dir=str(tmp_path), registry=registry,
                           dumps=dumps, name="unit")
        now[0] = 6.0
        return wd.check()

    def test_stall_writes_complete_bundle(self, tmp_path):
        configure_tracing(enabled=True, annotate=False)
        with span("last-act"):
            pass
        r = MetricsRegistry()
        r.counter("serving_tokens_out_total").inc(7)
        report = self._fire(tmp_path, registry=r,
                            dumps=lambda: {"scheduler": {"queue_depth": 2}})
        assert "bundle_path" in report
        path = report["bundle_path"]
        files = sorted(os.listdir(path))
        for fname in ("manifest.json", "report.json", "stacks.txt",
                      "trace.json", "metrics.json", "metrics.prom",
                      "scheduler.json"):
            assert fname in files, files
        manifest = json.load(open(os.path.join(path, "manifest.json")))
        assert manifest["version"] >= 1
        assert manifest["silence_s"] == pytest.approx(6.0)
        assert set(manifest["files"]) == set(files) - {"manifest.json"}
        trace_doc = json.load(open(os.path.join(path, "trace.json")))
        assert any(e["name"] == "last-act" for e in trace_doc["traceEvents"])
        sched = json.load(open(os.path.join(path, "scheduler.json")))
        assert sched == {"queue_depth": 2}
        prom = open(os.path.join(path, "metrics.prom")).read()
        assert "serving_tokens_out_total 7.0" in prom
        assert "incident" in os.path.basename(path)

    def test_no_incident_dir_means_no_bundle(self, tmp_path, monkeypatch):
        monkeypatch.delenv("ACCELERATE_TPU_INCIDENT_DIR", raising=False)
        now = [0.0]
        wd = StallWatchdog(5.0, clock=lambda: now[0], logger=_SilentLogger())
        now[0] = 6.0
        report = wd.check()
        assert "bundle_path" not in report
        assert os.listdir(tmp_path) == []

    def test_env_var_arms_bundles(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ACCELERATE_TPU_INCIDENT_DIR", str(tmp_path))
        now = [0.0]
        wd = StallWatchdog(5.0, clock=lambda: now[0], logger=_SilentLogger())
        assert wd.incident_dir == str(tmp_path)
        now[0] = 6.0
        report = wd.check()
        assert report["bundle_path"].startswith(str(tmp_path))

    def test_dumps_failure_costs_only_the_dump_files(self, tmp_path):
        """Review regression: dumps() walks live engine state and may
        throw mid-stall — the bundle (stacks/trace/metrics) must still
        land, with the failure recorded in a dumps_error file."""
        r = MetricsRegistry()
        r.counter("alive_total").inc()

        def exploding_dumps():
            raise RuntimeError("deque mutated during iteration")

        report = self._fire(tmp_path, registry=r, dumps=exploding_dumps)
        assert "bundle_path" in report, report.get("bundle_error")
        files = set(os.listdir(report["bundle_path"]))
        assert {"manifest.json", "report.json", "stacks.txt",
                "trace.json", "metrics.json", "dumps_error.json"} <= files
        err = json.load(open(os.path.join(report["bundle_path"],
                                          "dumps_error.json")))
        assert "deque mutated" in err["error"]

    def test_bundle_failure_does_not_mask_the_report(self, tmp_path):
        """Forensics must never break the stall report: an unwritable
        bundle dir degrades to bundle_error, the report still lands."""
        bad = tmp_path / "file-not-dir"
        bad.write_text("x")
        now = [0.0]
        wd = StallWatchdog(5.0, clock=lambda: now[0], logger=_SilentLogger(),
                           incident_dir=str(bad))
        now[0] = 6.0
        report = wd.check()
        assert report is not None and "bundle_error" in report

    def test_exception_report_shape(self, tmp_path):
        from accelerate_tpu.telemetry import (
            build_exception_report,
            write_incident_bundle,
        )

        try:
            raise RuntimeError("drive loop died")
        except RuntimeError as e:
            report = build_exception_report(e, name="drive-loop")
        assert "drive loop died" in report["error"]
        assert any("drive loop died" in ln for ln in report["traceback"])
        assert report["stacks"]
        path = write_incident_bundle(str(tmp_path), report,
                                     name="drive-loop")
        manifest = json.load(open(os.path.join(path, "manifest.json")))
        assert manifest["kind"] == "drive-loop"
        assert "drive loop died" in manifest["error"]

    def test_same_second_bundles_get_distinct_dirs(self, tmp_path):
        from accelerate_tpu.telemetry import write_incident_bundle

        p1 = write_incident_bundle(str(tmp_path), {"stacks": {}}, name="x")
        p2 = write_incident_bundle(str(tmp_path), {"stacks": {}}, name="x")
        assert p1 != p2 and os.path.isdir(p1) and os.path.isdir(p2)

    def test_incident_cli_list_and_show(self, tmp_path, capsys):
        """`accelerate-tpu incident` renders bundles: list newest-first
        with indices, show by index/name/path, sane exit codes."""
        from accelerate_tpu.commands.accelerate_cli import main
        from accelerate_tpu.telemetry import write_incident_bundle

        assert main(["incident", "list", "--dir", str(tmp_path)]) == 1
        capsys.readouterr()
        report = {"silence_s": 7.5, "stacks": {"MainThread-1": ["  fake\n"]},
                  "flight_recorder": [
                      {"name": "serving.decode", "dur_ns": 1000,
                       "trace_id": "ab" * 16, "span_id": 1, "parent_id": 0}]}
        path = write_incident_bundle(str(tmp_path), report, name="stall")
        assert main(["incident", "list", "--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "[0]" in out and "stall" in out and "7.5s" in out
        for ref in ("0", os.path.basename(path), path):
            assert main(["incident", "show", ref,
                         "--dir", str(tmp_path)]) == 0
            out = capsys.readouterr().out
            assert "silence  7.5s" in out
            assert "serving.decode" in out
        assert main(["incident", "show", "nope",
                     "--dir", str(tmp_path)]) == 2
        rc = main(["incident", "list", "--dir", str(tmp_path),
                   "--format", "json"])
        assert rc == 0
        listed = json.loads(capsys.readouterr().out)
        assert listed[0]["path"] == path

    def test_incident_cli_requires_a_dir(self, monkeypatch, capsys):
        from accelerate_tpu.commands.accelerate_cli import main

        monkeypatch.delenv("ACCELERATE_TPU_INCIDENT_DIR", raising=False)
        assert main(["incident", "list"]) == 2


# ---------------------------------------------------------------------------
# overhead guards (CI satellite): observability off must stay ~free
# ---------------------------------------------------------------------------


class TestOverheadGuards:
    N = 20_000

    def _time(self, fn) -> float:
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    def test_disabled_span_cost_bounded(self):
        """Disabled spans sit on dispatch-path code permanently; their cost
        must stay within a generous multiple of a plain function call (and
        an absolute per-iteration ceiling, so tier-1 stays deterministic
        on slow shared runners)."""
        assert not tracing_enabled()

        def noop():
            pass

        def baseline():
            for _ in range(self.N):
                noop()

        def with_span():
            for _ in range(self.N):
                with span("x"):
                    pass

        baseline()  # warm both paths
        with_span()
        base = min(self._time(baseline) for _ in range(3))
        spanned = min(self._time(with_span) for _ in range(3))
        per_iter_us = spanned / self.N * 1e6
        assert per_iter_us < 50.0, f"disabled span {per_iter_us:.2f}us/iter"
        assert spanned < max(base, 1e-9) * 100, (spanned, base)

    def test_registry_increment_cost_bounded(self):
        r = MetricsRegistry()
        c = r.counter("n")
        h = r.histogram("h")

        def work():
            for _ in range(self.N):
                c.inc()
                h.record(0.001)

        work()
        best = min(self._time(work) for _ in range(3))
        per_iter_us = best / self.N * 1e6
        assert per_iter_us < 100.0, f"inc+record {per_iter_us:.2f}us/iter"


# ---------------------------------------------------------------------------
# StepTimer on the shared histograms
# ---------------------------------------------------------------------------


class TestStepTimerTelemetry:
    def test_summary_reports_tail_latency(self):
        from accelerate_tpu.profiler import StepTimer

        timer = StepTimer(warmup_steps=0)
        for v in [0.1] * 90 + [1.0] * 10:
            timer._step_hist.record(v)
        s = timer.summary()
        assert s["step_time_p50_s"] == pytest.approx(0.1, rel=0.03)
        assert s["step_time_p99_s"] == pytest.approx(1.0, rel=0.03)
        assert s["mean_step_time_s"] == pytest.approx(0.19, rel=0.01)

    def test_registry_backed_timer_publishes_series(self):
        from accelerate_tpu.profiler import StepTimer

        r = MetricsRegistry()
        timer = StepTimer(warmup_steps=0, registry=r, name="train")
        with timer.dispatch():
            pass
        timer.tick()
        timer.tick()
        snap = r.snapshot()
        assert snap["histograms"]["train_time_seconds"]["count"] == 1.0
        assert snap["histograms"]["train_dispatch_seconds"]["count"] == 1.0
        # the exporter sees the same series
        assert "train_time_seconds" in render_prometheus(r)

    def test_fresh_timer_does_not_inherit_shared_series(self):
        """Registry series are shared by name: a NEW StepTimer must be
        able to start clean (reset) without unregistering the series."""
        from accelerate_tpu.profiler import StepTimer

        r = MetricsRegistry()
        warm = StepTimer(warmup_steps=0, registry=r, name="train")
        warm.tick()
        warm.tick()
        assert warm.steps_recorded == 1
        fresh = StepTimer(warmup_steps=0, registry=r, name="train")
        fresh.reset()                       # the warmup-window pattern
        assert fresh.steps_recorded == 0
        fresh.tick()
        fresh.tick()
        assert fresh.steps_recorded == 1    # only its own samples
        # still the same registered series object for the exporter
        assert r.histogram("train_time_seconds") is fresh._step_hist

    def test_serving_metrics_percentiles_use_shared_helper(self):
        """Satellite (dedup): ServingMetrics percentiles come from the
        shared StreamingHistogram and agree with numpy.percentile."""
        from accelerate_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        rng = np.random.default_rng(0)
        samples = rng.lognormal(-3, 0.5, 5000)
        for v in samples:
            m.ttft_s.record(float(v))
        s = m.summary()
        for q, key in ((50, "ttft_p50_ms"), (99, "ttft_p99_ms")):
            exact = float(np.percentile(samples, q)) * 1e3
            assert s[key] == pytest.approx(exact, rel=0.03)


# ---------------------------------------------------------------------------
# tier-1 collection + import guards
# ---------------------------------------------------------------------------


def test_telemetry_tests_are_tier1_collected():
    """The ROADMAP tier-1 command runs `pytest tests/ -m 'not slow'`; this
    file must be collected by it (mirror of the guard in
    tests/test_prefetch.py)."""
    roadmap = os.path.join(os.path.dirname(__file__), os.pardir, "ROADMAP.md")
    with open(roadmap) as f:
        text = f.read()
    assert "-m 'not slow'" in text and "pytest tests/" in text, (
        "tier-1 command changed; update this guard"
    )


def test_telemetry_imports_without_jax_device_init():
    """`accelerate_tpu.telemetry` must be importable in collectors/CLI
    tools without initializing a jax backend (device init is expensive and
    can hang on a dead TPU tunnel)."""
    code = (
        "import accelerate_tpu.telemetry as t\n"
        "t.get_registry().counter('probe').inc()\n"
        "assert t.render_prometheus(t.get_registry())\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge.backends_are_initialized(), "
        "'telemetry import initialized a jax backend'\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
