"""Big-model inference stack tests (SURVEY.md §2.4; ref tests/test_big_modeling.py,
test_modeling_utils.py, test_offload.py — meta init, device-map planner,
dispatch, checkpoint streaming, disk offload, streamed forward)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    RowGroups,
    cpu_offload,
    disk_offload,
    dispatch_model,
    init_empty_weights,
    load_checkpoint_and_dispatch,
    streamed_forward,
)
from accelerate_tpu.checkpointing import save_model
from accelerate_tpu.utils.modeling import (
    compute_module_sizes,
    dtype_byte_size,
    find_stacked_modules,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
)
from accelerate_tpu.utils.offload import (
    OffloadedWeightsLoader,
    load_offload_index,
    offload_state_dict,
)

L, D, V = 6, 8, 32


def tiny_init(key):
    keys = jax.random.split(key, 4)
    return {
        "embed": {"embedding": jax.random.normal(keys[0], (V, D))},
        "layers": {
            "w1": jax.random.normal(keys[1], (L, D, 4 * D)),
            "w2": jax.random.normal(keys[2], (L, 4 * D, D)),
        },
        "head": {"kernel": jax.random.normal(keys[3], (D, V))},
    }


def tiny_forward(params, ids):
    x = params["embed"]["embedding"][ids]

    def body(x, layer):
        return x + jnp.tanh(x @ layer["w1"]) @ layer["w2"], None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x @ params["head"]["kernel"]


def test_init_empty_weights_allocates_nothing():
    abstract = init_empty_weights(tiny_init, jax.random.key(0))
    leaf = abstract["layers"]["w1"]
    assert isinstance(leaf, jax.ShapeDtypeStruct)
    assert leaf.shape == (L, D, 4 * D)


def test_find_stacked_and_sizes():
    abstract = init_empty_weights(tiny_init, jax.random.key(0))
    stacked = find_stacked_modules(abstract)
    assert stacked == {"layers": L}
    sizes = compute_module_sizes(abstract)
    assert sizes["layers.0"] == sizes["layers"] // L
    assert sizes[""] == sizes["embed"] + sizes["layers"] + sizes["head"]
    assert dtype_byte_size(jnp.bfloat16) == 2


def test_infer_auto_device_map_splits_layers():
    abstract = init_empty_weights(tiny_init, jax.random.key(0))
    sizes = compute_module_sizes(abstract)
    # room for embed + head + 3 layers on device 0, rest spills to cpu
    # (planning order is pytree order: embed, head, layers.*)
    budget = sizes["embed"] + sizes["head"] + 3 * sizes["layers.0"] + 100
    dmap = infer_auto_device_map(abstract, max_memory={0: budget, "cpu": 2**40})
    assert dmap["embed"] == 0
    assert dmap["head"] == 0
    assert dmap["layers.0"] == 0
    assert dmap["layers.5"] == "cpu"
    targets = {dmap[f"layers.{i}"] for i in range(L)}
    assert targets == {0, "cpu"}


def test_get_max_memory_parses_strings():
    mm = get_max_memory({0: "1GiB", "cpu": "500MB"})
    assert mm[0] == 2**30 and mm["cpu"] == 500 * 10**6


def test_partial_row_map_rejected():
    params = tiny_init(jax.random.key(0))
    # rows 1..L-1 uncovered must raise, not silently go to cpu
    with pytest.raises(ValueError, match="addressed per-row"):
        dispatch_model(params, {"embed": 0, "head": 0, "layers.0": 0})


def test_row_key_on_unstacked_module_rejected():
    params = tiny_init(jax.random.key(0))
    dmap = {"embed.0": 0, "head": 0, "layers": 0}
    with pytest.raises(ValueError):
        dispatch_model(params, dmap)


def test_scalar_offload_roundtrip(tmp_path):
    from accelerate_tpu.utils.offload import load_offloaded_weight, offload_weight

    idx = {}
    offload_weight(np.float32(3.0), "s", str(tmp_path), idx)
    back = load_offloaded_weight(str(tmp_path / "s.dat"), idx["s"])
    assert back.shape == () and float(back) == 3.0


def test_dispatch_sharded_runs_in_jit():
    params = tiny_init(jax.random.key(0))
    dispatched = dispatch_model(params, "sharded")
    ids = jnp.arange(8, dtype=jnp.int32)[None]
    ref = tiny_forward(params, ids)
    out = jax.jit(tiny_forward)(dispatched, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_dispatch_rowgroups_and_streamed_forward(tmp_path):
    params = tiny_init(jax.random.key(0))
    dmap = {"embed": 0, "head": 0}
    dmap.update({f"layers.{i}": (0 if i < 2 else ("cpu" if i < 4 else "disk")) for i in range(L)})
    dispatched = dispatch_model(params, dmap, offload_folder=str(tmp_path))
    w1 = dispatched["layers"]["w1"]
    assert isinstance(w1, RowGroups)
    kinds = [type(a) for _, _, a in w1.groups]
    assert len(w1.groups) == 3
    # disk rows are memmaps
    assert isinstance(w1.groups[-1][2], np.memmap)
    np.testing.assert_allclose(np.asarray(w1.row(3)), np.asarray(params["layers"]["w1"][3]))

    ids = jnp.arange(8, dtype=jnp.int32)[None]
    ref = tiny_forward(params, ids)

    layer_step = jax.jit(
        lambda layer, x: x + jnp.tanh(x @ layer["w1"]) @ layer["w2"]
    )
    out = streamed_forward(
        dispatched,
        ids,
        embed_fn=lambda res, i: res["embed"]["embedding"][i],
        layer_fn=lambda layer, x, i: layer_step(layer, x),
        final_fn=lambda res, x: x @ res["head"]["kernel"],
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_cpu_and_disk_offload(tmp_path):
    params = tiny_init(jax.random.key(0))
    off = cpu_offload(params, keep_modules=("head",))
    assert isinstance(off["embed"]["embedding"], np.ndarray)
    assert isinstance(off["head"]["kernel"], jax.Array)
    doff = disk_offload(params, str(tmp_path), keep_modules=("embed",))
    assert isinstance(doff["layers"]["w1"], np.memmap)
    idx = load_offload_index(str(tmp_path))
    assert "layers.w1" in idx


def test_offloaded_weights_loader(tmp_path):
    sd = {"a": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.ones((4,), np.int32)}
    offload_state_dict(str(tmp_path), sd)
    loader = OffloadedWeightsLoader(
        state_dict={"c": np.zeros(2)}, offload_folder=str(tmp_path)
    )
    assert set(loader) == {"a", "b", "c"}
    np.testing.assert_array_equal(np.asarray(loader["a"]), sd["a"])
    assert len(loader) == 3


def test_load_checkpoint_and_dispatch_roundtrip(tmp_path):
    params = tiny_init(jax.random.key(0))
    ckpt_dir = tmp_path / "ckpt"
    save_model(params, str(ckpt_dir))
    abstract = init_empty_weights(tiny_init, jax.random.key(0))

    loaded, _ = load_checkpoint_in_model(abstract, str(ckpt_dir))
    np.testing.assert_allclose(
        np.asarray(loaded["head"]["kernel"]), np.asarray(params["head"]["kernel"])
    )

    # with a device map spilling to cpu+disk, streamed forward still matches
    dmap = {"embed": 0, "head": "cpu"}
    dmap.update({f"layers.{i}": ("cpu" if i % 2 else "disk") for i in range(L)})
    dispatched = load_checkpoint_and_dispatch(
        abstract, str(ckpt_dir), device_map=dmap, offload_folder=str(tmp_path / "off")
    )
    ids = jnp.arange(4, dtype=jnp.int32)[None]
    ref = tiny_forward(params, ids)
    layer_step = jax.jit(lambda layer, x: x + jnp.tanh(x @ layer["w1"]) @ layer["w2"])
    out = streamed_forward(
        dispatched,
        ids,
        embed_fn=lambda res, i: res["embed"]["embedding"][i],
        layer_fn=lambda layer, x, i: layer_step(layer, x),
        final_fn=lambda res, x: x @ res["head"]["kernel"],
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_llama_forward_offloaded_matches_forward(tmp_path):
    from accelerate_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    dmap = {"embed_tokens": 0, "norm": 0, "lm_head": 0}
    n = cfg.num_hidden_layers
    dmap.update({f"layers.{i}": ("disk" if i >= n - 1 else "cpu") for i in range(n)})
    dispatched = dispatch_model(params, dmap, offload_folder=str(tmp_path))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    ref = llama.forward(cfg, params, ids)
    out = llama.forward_offloaded(cfg, dispatched, ids, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_load_checkpoint_shape_mismatch_raises(tmp_path):
    params = tiny_init(jax.random.key(0))
    save_model(params, str(tmp_path / "ckpt"))
    bad = init_empty_weights(tiny_init, jax.random.key(0))
    bad["head"]["kernel"] = jax.ShapeDtypeStruct((D, V + 1), jnp.float32)
    with pytest.raises(ValueError, match="shape mismatch"):
        load_checkpoint_in_model(bad, str(tmp_path / "ckpt"))


def test_torch_bin_import(tmp_path):
    torch = pytest.importorskip("torch")
    sd = {"embed.embedding": torch.randn(V, D), "head.kernel": torch.randn(D, V)}
    path = tmp_path / "pytorch_model.bin"
    torch.save(sd, str(path))
    from accelerate_tpu.utils.modeling import load_state_dict

    out = load_state_dict(str(path))
    assert out["embed.embedding"].shape == (V, D)
    np.testing.assert_allclose(out["head.kernel"], sd["head.kernel"].numpy())


def test_get_balanced_memory_spreads_evenly():
    from accelerate_tpu.utils.modeling import get_balanced_memory

    abstract = init_empty_weights(tiny_init, jax.random.key(0))
    sizes = compute_module_sizes(abstract)
    total = sizes[""]
    big = 100 * total
    mm = get_balanced_memory(abstract, max_memory={0: big, 1: big, 2: big, 3: big})
    # clamped devices get ~total/4 + buffer, far below the raw cap
    assert mm[0] < big and mm[1] < big and mm[2] < big
    assert mm[3] == big  # last device stays the sink
    assert mm[0] >= total // 4  # but still fits its fair share
    # the balanced caps actually spread the map across devices
    dmap = infer_auto_device_map(abstract, max_memory=mm)
    used = {v for v in dmap.values()}
    assert len(used - {"cpu", "disk"}) >= 2


def test_get_balanced_memory_low_zero():
    from accelerate_tpu.utils.modeling import get_balanced_memory

    abstract = init_empty_weights(tiny_init, jax.random.key(0))
    total = compute_module_sizes(abstract)[""]
    big = 100 * total
    mm = get_balanced_memory(
        abstract, max_memory={0: big, 1: big, 2: big, 3: big}, low_zero=True
    )
    assert mm[0] < mm[1]  # device 0 keeps headroom for generation buffers


# --- streamed (offloaded) generate: the reference benchmark's cpu-offload
# rows (ref benchmarks/README.md:27-36) -------------------------------------


def _randomize_scales(params, key):
    """Perturb every norm `scale`/`bias` leaf: unit-scale init makes norms
    argmax-invariant, which would mask a skipped final norm in the streamed
    projection (code-review r3 finding on llama's streamed path)."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for i, (path, leaf) in enumerate(leaves):
        names = [getattr(p, "key", "") for p in path]
        if "scale" in names or ("bias" in names and leaf.ndim <= 2):
            k = jax.random.fold_in(key, i)
            leaf = leaf + jax.random.uniform(k, leaf.shape, leaf.dtype,
                                             0.1, 0.9)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


@pytest.mark.parametrize(
    "family", ["llama", "gpt2", "gptj", "gpt_neox", "opt"])
def test_streamed_generate_matches_generate(family):
    import importlib

    mod = importlib.import_module(f"accelerate_tpu.models.{family}")
    cfg_cls = {
        "llama": "LlamaConfig", "gpt2": "GPT2Config", "gptj": "GPTJConfig",
        "gpt_neox": "GPTNeoXConfig", "opt": "OPTConfig",
    }[family]
    cfg = getattr(mod, cfg_cls).tiny()
    params = _randomize_scales(mod.init_params(cfg, jax.random.key(40)),
                               jax.random.key(44))
    ids = jnp.ones((2, 5), jnp.int32) * 3
    want = mod.generate(cfg, params, ids, max_new_tokens=6)
    off = cpu_offload(params)
    got = mod.streamed_generate(cfg, off, ids, max_new_tokens=6,
                                dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_t5_streamed_generate_matches_generate():
    """Hybrid path: streamed encoder + resident decoder must reproduce the
    fully on-device generate (randomized norm scales so a skipped norm
    would flip tokens)."""
    from accelerate_tpu.models import t5

    cfg = t5.T5Config.tiny()
    params = _randomize_scales(t5.init_params(cfg, jax.random.key(43)),
                               jax.random.key(45))
    ids = jnp.ones((2, 6), jnp.int32) * 5
    want = t5.generate(cfg, params, ids, max_new_tokens=5)
    off = cpu_offload(params)
    got = t5.streamed_generate(cfg, off, ids, max_new_tokens=5,
                               dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_decode_mask_must_span_cache():
    """A prompt-length mask on the kv_caches path must fail loudly: the
    decode mask spans the whole cache (code-review r3 finding)."""
    from accelerate_tpu.models import opt

    cfg = opt.OPTConfig.tiny()
    params = opt.init_params(cfg, jax.random.key(46))
    ids = jnp.ones((1, 4), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(4), (1, 4))
    caches = opt.init_kv_caches(cfg, 1, 8)
    with pytest.raises(ValueError, match="span the whole cache"):
        opt.forward(cfg, params, ids,
                    attention_mask=jnp.ones((1, 4), jnp.int32),
                    positions=positions, kv_caches=caches)
    # masked cached prefill without explicit positions: loud error (OPT
    # derives positions from the mask only on the uncached path)
    with pytest.raises(ValueError, match="explicit `positions`"):
        opt.forward(cfg, params, ids,
                    attention_mask=jnp.ones((1, 8), jnp.int32),
                    kv_caches=caches)
    # a full-cache mask with explicit positions works
    full = jnp.ones((1, 8), jnp.int32)
    logits, _ = opt.forward(cfg, params, ids, attention_mask=full,
                            positions=positions, kv_caches=caches)
    assert logits.shape == (1, 4, cfg.vocab_size)
