"""Collectives/ops tests (ref tests/test_utils.py + test_utils/scripts/test_ops.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.utils import (
    broadcast,
    broadcast_object_list,
    concatenate,
    convert_to_fp32,
    find_batch_size,
    find_device,
    gather,
    gather_object,
    get_data_structure,
    initialize_tensors,
    listify,
    pad_across_processes,
    pad_input_tensors,
    recursively_apply,
    reduce,
    send_to_device,
    slice_tensors,
)


def test_send_to_device_pytree():
    batch = {"x": np.ones((2, 3)), "y": [np.zeros(4), "keep-me"]}
    out = send_to_device(batch, jax.devices()[0])
    assert isinstance(out["x"], jax.Array)
    assert out["y"][1] == "keep-me"
    assert list(out["x"].devices())[0] == jax.devices()[0]


def test_send_to_device_skip_keys():
    batch = {"x": np.ones(2), "meta": np.zeros(2)}
    out = send_to_device(batch, jax.devices()[1], skip_keys=["meta"])
    assert isinstance(out["x"], jax.Array)
    assert isinstance(out["meta"], np.ndarray)


def test_gather_sharded_global_array():
    """gather() on a mesh-sharded array returns the full value."""
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("data",))
    sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
    x = jax.device_put(jnp.arange(16.0).reshape(16, 1), sharding)
    out = gather(x)
    np.testing.assert_array_equal(np.asarray(out), np.arange(16.0).reshape(16, 1))


def test_gather_host_local_single_process():
    out = gather({"a": np.ones((2, 2))})
    np.testing.assert_array_equal(out["a"], np.ones((2, 2)))


def test_gather_object_single():
    assert gather_object({"k": 1}) == [{"k": 1}]


def test_broadcast_and_object_list_single():
    x = {"a": np.arange(3)}
    np.testing.assert_array_equal(broadcast(x)["a"], np.arange(3))
    objs = ["a", 2]
    assert broadcast_object_list(objs) == ["a", 2]


def test_reduce_mean_sum():
    x = np.asarray([2.0, 4.0])
    np.testing.assert_allclose(reduce(x, "mean"), x)
    np.testing.assert_allclose(reduce(x, "sum"), x)
    with pytest.raises(ValueError):
        reduce(x, "max")


def test_reduce_sharded_array_identity():
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("data",))
    sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
    x = jax.device_put(jnp.ones((8,)), sharding)
    np.testing.assert_allclose(np.asarray(reduce(x, "mean")), np.ones(8))


def test_pad_across_processes_noop_and_dim():
    x = np.ones((3, 5))
    out = pad_across_processes(x, dim=1)
    assert out.shape == (3, 5)
    assert pad_across_processes(np.float32(1.0)) == 1.0


def test_pad_input_tensors():
    x = {"input_ids": np.arange(10).reshape(5, 2)}
    out = pad_input_tensors(x, batch_size=5, num_processes=4)
    assert out["input_ids"].shape == (8, 2)
    np.testing.assert_array_equal(out["input_ids"][5], out["input_ids"][4])


def test_concatenate_nested():
    a = {"x": np.ones((2, 3)), "y": (np.zeros(1),)}
    b = {"x": np.ones((4, 3)), "y": (np.ones(2),)}
    out = concatenate([a, b])
    assert out["x"].shape == (6, 3)
    assert out["y"][0].shape == (3,)


def test_structure_roundtrip():
    data = {"a": np.ones((2, 4), np.float32), "b": [np.zeros(3, np.int32)]}
    skeleton = get_data_structure(data)
    assert skeleton["a"].shape == (2, 4)
    zeros = initialize_tensors(skeleton)
    assert zeros["a"].dtype == np.float32
    assert find_batch_size(data) == 2
    assert listify(data)["b"][0] == [0, 0, 0]


def test_slice_and_find_device():
    data = {"x": jnp.ones((4, 2))}
    sliced = slice_tensors(data, slice(0, 2))
    assert sliced["x"].shape == (2, 2)
    assert find_device(data) in jax.devices()


def test_convert_to_fp32():
    out = convert_to_fp32({"x": jnp.ones(2, dtype=jnp.bfloat16), "i": jnp.ones(2, jnp.int32)})
    assert out["x"].dtype == jnp.float32
    assert out["i"].dtype == jnp.int32


def test_recursively_apply_error_on_other_type():
    with pytest.raises(TypeError):
        recursively_apply(lambda x: x, {"a": "str"}, error_on_other_type=True)
