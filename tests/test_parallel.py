"""Ring attention / pipeline / EP-MoE / flash attention correctness tests
(all against the einsum reference implementation on the 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models.common import dot_product_attention
from accelerate_tpu.ops.flash_attention import flash_attention
from accelerate_tpu.parallel import (
    expert_parallel_moe,
    pipeline_apply,
    ring_attention,
    stack_layers_into_stages,
    ulysses_attention,
)
from accelerate_tpu.utils import MeshConfig


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    """The ring/pipeline/MoE tests jit the same shifted-window and 1f1b
    programs repeatedly (forward, grad, value_and_grad variants share
    most of their HLO); the repo's persistent compilation cache turns
    the repeats into deserializes (same pattern as test_serving.py)."""
    import os

    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (the ISSUE 16 gotcha)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


def make_qkv(key, b=2, s=64, h=4, d=16, kv_heads=None):
    ks = jax.random.split(key, 3)
    kv_heads = kv_heads or h
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kv_heads, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kv_heads, d), jnp.float32)
    return q, k, v


# --- flash attention (interpret mode on CPU) --------------------------------


def test_flash_attention_matches_reference_causal():
    q, k, v = make_qkv(jax.random.key(0), s=256, d=64)
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_matches_reference_noncausal():
    q, k, v = make_qkv(jax.random.key(1), s=128, d=32)
    ref = dot_product_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_gradients_match():
    q, k, v = make_qkv(jax.random.key(2), s=128, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64, block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_flash_attention_irregular_length_pads():
    q, k, v = make_qkv(jax.random.key(3), s=50)
    ref = dot_product_attention(q, k, v, causal=True)
    # 50 -> block 32 (pow2 floor), padded to 64, kernel runs causally exact
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_tiny_length_einsum_fallback():
    q, k, v = make_qkv(jax.random.key(3), s=12)
    ref = dot_product_attention(q, k, v, causal=True)
    # 12 -> pow2 floor 8 < 16 (Mosaic sublane minimum) -> einsum fallback
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# --- ring attention ---------------------------------------------------------


def test_ring_attention_matches_reference():
    mesh = MeshConfig(axes={"seq": 8}).build()
    q, k, v = make_qkv(jax.random.key(4), s=64)
    ref = dot_product_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_noncausal():
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(5), s=32)
    ref = dot_product_attention(q, k, v, causal=False)
    out = ring_attention(q, k, v, causal=False, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_differentiable():
    mesh = MeshConfig(axes={"seq": 8}).build()
    q, k, v = make_qkv(jax.random.key(6), s=64)

    def loss(q):
        return jnp.sum(ring_attention(q, k, v, causal=True, mesh=mesh) ** 2)

    def ref_loss(q):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss)(q)
    gr = jax.grad(ref_loss)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-3)


def test_ring_attention_no_seq_axis_falls_back():
    mesh = MeshConfig(axes={"data": 8}).build()
    q, k, v = make_qkv(jax.random.key(7), s=16)
    out = ring_attention(q, k, v, causal=True, mesh=mesh)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# --- pipeline ---------------------------------------------------------------


def test_stack_layers_into_stages():
    params = {"w": jnp.arange(8.0).reshape(8, 1)}
    staged = stack_layers_into_stages(params, 4)
    assert staged["w"].shape == (4, 2, 1)
    with pytest.raises(ValueError):
        stack_layers_into_stages({"w": jnp.zeros((6, 1))}, 4)


def test_pipeline_apply_matches_sequential():
    """4-stage MLP pipeline == sequential application."""
    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    key = jax.random.key(0)
    L, H = 4, 16
    layer_params = {
        "w": jax.random.normal(key, (L, H, H)) * 0.3,
        "b": jnp.zeros((L, H)),
    }

    def layer_fn(p, x):  # one layer per stage
        return jnp.tanh(x @ p["w"][0] + p["b"][0])

    staged = stack_layers_into_stages(layer_params, 4)
    x = jax.random.normal(jax.random.key(1), (8, H))

    # sequential reference
    y_ref = x
    for i in range(L):
        y_ref = jnp.tanh(y_ref @ layer_params["w"][i] + layer_params["b"][i])

    y = pipeline_apply(layer_fn, staged, x, num_micro_batches=4, mesh=mesh)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)


def test_pipeline_apply_differentiable():
    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    L, H = 4, 8
    layer_params = {"w": jax.random.normal(jax.random.key(0), (L, H, H)) * 0.3}

    def layer_fn(p, x):
        return jnp.tanh(x @ p["w"][0])

    staged = stack_layers_into_stages(layer_params, 4)
    x = jax.random.normal(jax.random.key(1), (8, H))

    def loss(staged):
        return jnp.sum(pipeline_apply(layer_fn, staged, x, 4, mesh=mesh) ** 2)

    def ref_loss(params):
        y = x
        for i in range(L):
            y = jnp.tanh(y @ params["w"][i])
        return jnp.sum(y**2)

    g = jax.grad(loss)(staged)["w"].reshape(L, H, H)
    gr = jax.grad(ref_loss)(layer_params)["w"]
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-4)


def test_pipeline_apply_validates():
    mesh = MeshConfig(axes={"data": 8}).build()
    with pytest.raises(ValueError, match="stage"):
        pipeline_apply(lambda p, x: x, {"w": jnp.zeros((2, 1))}, jnp.zeros((4, 1)), 2,
                       mesh=mesh)


# --- expert-parallel MoE ----------------------------------------------------


def _expert_fn(p, x):  # single expert MLP: [C, H] -> [C, H]
    return jnp.tanh(x @ p["w"])


def test_ep_moe_matches_single_device():
    E, H, T = 4, 8, 32
    params = {"w": jax.random.normal(jax.random.key(0), (E, H, H)) * 0.5}
    x = jax.random.normal(jax.random.key(1), (T, H))
    logits = jax.random.normal(jax.random.key(2), (T, E))

    mesh = MeshConfig(axes={"expert": 4, "data": 2}).build()
    out = expert_parallel_moe(x, logits, params, _expert_fn, mesh=mesh,
                              capacity_factor=8.0)
    # reference with same capacity
    ref = expert_parallel_moe(x, logits, params, _expert_fn,
                              mesh=MeshConfig(axes={"data": 8}).build(),
                              axis_name="absent", capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ep_moe_capacity_drops_tokens():
    E, H, T = 2, 4, 16
    params = {"w": jnp.stack([jnp.eye(H), jnp.eye(H)])}
    x = jnp.ones((T, H))
    logits = jnp.stack([jnp.full((T,), 5.0), jnp.zeros((T,))], axis=-1)  # all -> e0
    out = expert_parallel_moe(
        x, logits, params, _expert_fn,
        mesh=MeshConfig(axes={"data": 8}).build(), axis_name="absent",
        capacity_factor=0.25,  # capacity = 2 slots for expert 0
    )
    nonzero_rows = int((np.abs(np.asarray(out)).sum(axis=-1) > 1e-6).sum())
    assert nonzero_rows == 2  # only 2 tokens fit; rest dropped to zero


def test_flash_attention_cross_attention_falls_back():
    """causal with sq != sk must use the reference path (alignment semantics)."""
    q, _, _ = make_qkv(jax.random.key(8), s=64, d=32)
    _, k, v = make_qkv(jax.random.key(9), s=128, d=32)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_padded_irregular_causal():
    """Lengths above one block that don't divide it run the kernel via
    pad+slice (causally exact), not the einsum fallback — the training loss
    slices inputs to S-1 and would otherwise never hit the kernel."""
    q, k, v = make_qkv(jax.random.key(7), s=161, d=32)
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_padded_gradients():
    q, k, v = make_qkv(jax.random.key(8), s=130, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64, block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


# --- flash attention masks ---------------------------------------------------


def _random_padding_mask(key, b, s, min_len=1):
    lengths = jax.random.randint(key, (b,), min_len, s + 1)
    return (jnp.arange(s)[None, :] < lengths[:, None])


def test_flash_attention_key_padding_mask_matches_einsum():
    q, k, v = make_qkv(jax.random.key(7), s=256)
    mask = _random_padding_mask(jax.random.key(8), q.shape[0], 256)
    ref = dot_product_attention(q, k, v, mask=mask, causal=True)
    out = flash_attention(q, k, v, causal=True, mask=mask)
    # compare only rows the loss would keep (valid query positions)
    keep = np.asarray(mask)[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(out) * keep, np.asarray(ref) * keep, atol=2e-2
    )


def test_flash_attention_mask_gradients_match():
    q, k, v = make_qkv(jax.random.key(9), s=128)
    mask = _random_padding_mask(jax.random.key(10), q.shape[0], 128)
    mkeep = jnp.asarray(mask, jnp.float32)[:, :, None, None]

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=True, mask=mask)
        return jnp.sum((out.astype(jnp.float32) * mkeep) ** 2)

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask, causal=True)
        return jnp.sum((out.astype(jnp.float32) * mkeep) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2)


def test_flash_attention_fully_masked_row_is_zero_and_finite_grads():
    q, k, v = make_qkv(jax.random.key(11), s=64)
    # valid keys only at the END: under causal attention rows 0..55 see no
    # valid key at all — exercises the l==0 / lse-pinned-to-0 kernel paths
    mask = jnp.zeros((q.shape[0], 64), bool).at[:, -8:].set(True)
    out = flash_attention(q, k, v, causal=True, mask=mask)
    out = np.asarray(out, np.float32)
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out[:, :56], 0.0, atol=1e-6)

    def loss(q):
        o = flash_attention(q, k, v, causal=True, mask=mask)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g, np.float32)).all()


def test_flash_attention_4d_broadcast_mask_accepted():
    q, k, v = make_qkv(jax.random.key(12), s=128)
    mask2d = _random_padding_mask(jax.random.key(13), q.shape[0], 128)
    out2 = flash_attention(q, k, v, causal=True, mask=mask2d)
    out4 = flash_attention(q, k, v, causal=True, mask=mask2d[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out4), atol=1e-6)


def test_llama_explicit_flash_masked_matches_einsum():
    """attention_backend="flash" + 2-D attention_mask must agree with the
    einsum path. (The auto backend only picks flash on real TPU hosts at
    s >= 1024, so auto-routing itself isn't exercisable on the CPU CI.)"""
    from accelerate_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(max_position_embeddings=64)
    params = llama.init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg.vocab_size)
    mask = _random_padding_mask(jax.random.key(2), 2, 64, min_len=16)
    flash_cfg = llama.LlamaConfig.tiny(max_position_embeddings=64,
                                       attention_backend="flash")
    out_flash = llama.forward(flash_cfg, params, ids, attention_mask=mask)
    out_einsum = llama.forward(cfg, params, ids, attention_mask=mask)
    keep = np.asarray(mask)[:, :, None]
    np.testing.assert_allclose(
        np.asarray(out_flash) * keep, np.asarray(out_einsum) * keep,
        atol=5e-2,
    )


# --- 1F1B pipeline schedule --------------------------------------------------


def _mlp_stage(p, x):
    return jnp.tanh(x @ p["w"][0] + p["b"][0])


def _mse(y, t):
    return jnp.mean((y - t) ** 2)


def _pipeline_ref(layer_params, x, targets, L, M):
    """Sequential reference: mean over micro-batches of per-micro loss."""
    mb = x.shape[0] // M

    def total(params):
        losses = []
        for m in range(M):
            y = x[m * mb:(m + 1) * mb]
            for i in range(L):
                y = jnp.tanh(y @ params["w"][i] + params["b"][i])
            losses.append(_mse(y, targets[m * mb:(m + 1) * mb]))
        return jnp.mean(jnp.stack(losses))

    return jax.value_and_grad(total)(layer_params)


@pytest.mark.parametrize("M", [4, 8])
def test_pipeline_1f1b_matches_sequential(M):
    from accelerate_tpu.parallel import pipeline_value_and_grad

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    L, H, B = 4, 16, 16
    key = jax.random.key(0)
    layer_params = {
        "w": jax.random.normal(key, (L, H, H)) * 0.3,
        "b": jnp.zeros((L, H)),
    }
    staged = stack_layers_into_stages(layer_params, 4)
    x = jax.random.normal(jax.random.key(1), (B, H))
    targets = jax.random.normal(jax.random.key(2), (B, H))

    loss_ref, grads_ref = _pipeline_ref(layer_params, x, targets, L, M)
    loss, grads = pipeline_value_and_grad(
        _mlp_stage, _mse, staged, x, targets, M, mesh=mesh, schedule="1f1b")
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    for k in ("w", "b"):
        got = np.asarray(grads[k]).reshape(np.asarray(grads_ref[k]).shape)
        np.testing.assert_allclose(got, np.asarray(grads_ref[k]), atol=1e-5)


def test_pipeline_1f1b_matches_gpipe():
    from accelerate_tpu.parallel import pipeline_value_and_grad

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    L, H, B, M = 4, 8, 8, 4
    layer_params = {
        "w": jax.random.normal(jax.random.key(0), (L, H, H)) * 0.3,
        "b": jnp.zeros((L, H)),
    }
    staged = stack_layers_into_stages(layer_params, 4)
    x = jax.random.normal(jax.random.key(1), (B, H))
    targets = jax.random.normal(jax.random.key(2), (B, H))
    l1, g1 = pipeline_value_and_grad(
        _mlp_stage, _mse, staged, x, targets, M, mesh=mesh, schedule="1f1b")
    l2, g2 = pipeline_value_and_grad(
        _mlp_stage, _mse, staged, x, targets, M, mesh=mesh, schedule="gpipe")
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(g1[k]), np.asarray(g2[k]), atol=1e-5)


def test_pipeline_1f1b_micro_fewer_than_stages():
    """M < S must still be exact (warmup/drain masking)."""
    from accelerate_tpu.parallel import pipeline_value_and_grad

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    L, H, B, M = 4, 8, 4, 2
    layer_params = {
        "w": jax.random.normal(jax.random.key(3), (L, H, H)) * 0.3,
        "b": jnp.zeros((L, H)),
    }
    staged = stack_layers_into_stages(layer_params, 4)
    x = jax.random.normal(jax.random.key(4), (B, H))
    targets = jax.random.normal(jax.random.key(5), (B, H))
    loss_ref, grads_ref = _pipeline_ref(layer_params, x, targets, L, M)
    loss, grads = pipeline_value_and_grad(
        _mlp_stage, _mse, staged, x, targets, M, mesh=mesh, schedule="1f1b")
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    got = np.asarray(grads["w"]).reshape(np.asarray(grads_ref["w"]).shape)
    np.testing.assert_allclose(got, np.asarray(grads_ref["w"]), atol=1e-5)


def test_pipeline_value_and_grad_validates_schedule():
    from accelerate_tpu.parallel import pipeline_value_and_grad

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    with pytest.raises(ValueError, match="schedule"):
        pipeline_value_and_grad(
            _mlp_stage, _mse, {}, jnp.zeros((4, 8)), jnp.zeros((4, 8)), 2,
            mesh=mesh, schedule="2f2b")


def test_ep_moe_top2_matches_replicated_reference():
    """top_k=2 EP dispatch over the expert axis == single-device dispatch."""
    E, H, T = 4, 8, 32
    params = {"w": jax.random.normal(jax.random.key(0), (E, H, H)) * 0.5}
    x = jax.random.normal(jax.random.key(1), (T, H))
    logits = jax.random.normal(jax.random.key(2), (T, E))

    mesh = MeshConfig(axes={"expert": 4, "data": 2}).build()
    out = expert_parallel_moe(x, logits, params, _expert_fn, mesh=mesh,
                              capacity_factor=8.0, top_k=2)
    ref = expert_parallel_moe(x, logits, params, _expert_fn,
                              mesh=MeshConfig(axes={"data": 8}).build(),
                              axis_name="absent", capacity_factor=8.0,
                              top_k=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # top-2 must differ from top-1 (second expert contributes)
    ref1 = expert_parallel_moe(x, logits, params, _expert_fn,
                               mesh=MeshConfig(axes={"data": 8}).build(),
                               axis_name="absent", capacity_factor=8.0,
                               top_k=1)
    assert not np.allclose(np.asarray(ref), np.asarray(ref1), atol=1e-3)


def test_ep_moe_top2_matches_manual_dense_reference():
    """Sort-dispatch top-2 at ample capacity == explicit dense top-2 math."""
    E, H, T = 4, 8, 16
    params = {"w": jax.random.normal(jax.random.key(3), (E, H, H)) * 0.5}
    x = jax.random.normal(jax.random.key(4), (T, H))
    logits = jax.random.normal(jax.random.key(5), (T, E))
    probs = jax.nn.softmax(logits, axis=-1)
    gates, idx = jax.lax.top_k(probs, 2)
    y_all = jnp.einsum("th,ehf->tef", x, params["w"])
    y_all = jnp.tanh(y_all)  # [T, E, H]
    ref = sum(
        jnp.take_along_axis(
            y_all, idx[:, j][:, None, None].repeat(H, 2), axis=1
        )[:, 0] * gates[:, j][:, None]
        for j in range(2)
    )
    out = expert_parallel_moe(
        x, logits, params, _expert_fn,
        mesh=MeshConfig(axes={"data": 8}).build(), axis_name="absent",
        capacity_factor=8.0, top_k=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# --- flash-kernel ring attention ---------------------------------------------


def test_ring_flash_matches_reference_large_chunks():
    """s_local >= 16 routes through the flash-kernel ring."""
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(20), b=1, s=128, h=2, d=32)
    ref = dot_product_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3)


def test_ring_flash_noncausal():
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(21), b=1, s=128, h=2, d=32)
    ref = dot_product_attention(q, k, v, causal=False)
    out = ring_attention(q, k, v, causal=False, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3)


def test_ring_flash_gradients_match():
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(22), b=1, s=64, h=2, d=32)

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=True, mesh=mesh) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


def test_ring_flash_gqa_unrepeated_kv():
    """K/V ring with fewer (kv) heads; output matches repeated reference,
    and grads flow back to the kv-headed tensors."""
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    from accelerate_tpu.models.common import repeat_kv

    q, k, v = make_qkv(jax.random.key(23), b=1, s=64, h=4, d=32, kv_heads=2)
    ref = dot_product_attention(q, repeat_kv(k, 2), repeat_kv(v, 2),
                                causal=True)
    out = ring_attention(q, k, v, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3)

    def loss(k):
        return jnp.sum(ring_attention(q, k, v, causal=True, mesh=mesh) ** 2)

    def ref_loss(k):
        return jnp.sum(dot_product_attention(
            q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True) ** 2)

    g = jax.grad(loss)(k)
    gr = jax.grad(ref_loss)(k)
    assert g.shape == k.shape  # kv-headed gradient
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=5e-3)


def test_pipeline_1f1b_llama_layers_match_sequential():
    """Real transformer stages through the 1F1B schedule: llama layer stacks
    as stage_fn, loss+grads equal to the unpipelined forward."""
    from functools import partial

    from accelerate_tpu.models import llama
    from accelerate_tpu.models.common import rope_frequencies
    from accelerate_tpu.parallel import (
        pipeline_value_and_grad,
        stack_layers_into_stages,
    )

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    cfg = llama.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=16, attention_backend="einsum",
    )
    params = llama.init_params(cfg, jax.random.key(0))
    B, S, M = 8, 16, 4
    x = jax.random.normal(jax.random.key(1), (B, S, cfg.hidden_size))
    tgt = jax.random.normal(jax.random.key(2), (B, S, cfg.hidden_size))
    # stage_fn sees MICRO batches (B/M rows); the reference sees all B
    positions = jnp.broadcast_to(jnp.arange(S), (B // M, S))
    ref_positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    cos, sin = rope_frequencies(cfg.head_dim, S, cfg.rope_theta)

    def stage_fn(layer_stack, h):
        # one stage = its slice of stacked llama layers, scanned
        def body(carry, layer):
            y, _, _ = llama._layer_body(cfg, carry, layer, cos, sin,
                                        positions, None)
            return y, None

        out, _ = jax.lax.scan(body, h, layer_stack)
        return out

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    staged = stack_layers_into_stages(params["layers"], 4)
    loss, grads = pipeline_value_and_grad(
        stage_fn, loss_fn, staged, x, tgt, num_micro_batches=M, mesh=mesh,
        schedule="1f1b")

    # sequential reference over the same layers
    def ref(layers):
        def body(carry, layer):
            y, _, _ = llama._layer_body(cfg, carry, layer, cos, sin,
                                        ref_positions, None)
            return y, None

        out, _ = jax.lax.scan(body, x, layers)
        return jnp.mean((out - tgt) ** 2)

    loss_ref, grads_ref = jax.value_and_grad(ref)(params["layers"])
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    got = np.asarray(grads["attn"]["q_proj"]["kernel"])
    want = np.asarray(grads_ref["attn"]["q_proj"]["kernel"])
    np.testing.assert_allclose(got.reshape(want.shape), want, atol=2e-5)


# --- masked ring / ulysses (padded batches keep CP fast paths) ---------------


def _pad_mask(b, s, lens):
    m = np.zeros((b, s), np.int32)
    for i, n in enumerate(lens):
        m[i, :n] = 1
    return jnp.asarray(m)


def _masked_ref(q, k, v, mask, causal=True, n_rep=1):
    from accelerate_tpu.models.common import repeat_kv

    return dot_product_attention(q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
                                 mask=mask, causal=causal)


def test_ring_einsum_masked_matches_reference():
    """Small chunks route the einsum ring; key-padding mask must match the
    plain masked attention on real (unpadded) rows."""
    mesh = MeshConfig(axes={"seq": 8}).build()
    q, k, v = make_qkv(jax.random.key(30), s=64)
    mask = _pad_mask(2, 64, [40, 64])
    ref = _masked_ref(q, k, v, mask)
    out = ring_attention(q, k, v, causal=True, mask=mask, mesh=mesh)
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out)[real], np.asarray(ref)[real],
                               atol=2e-5)


def test_ring_flash_masked_matches_reference():
    """s_local >= 16 routes the flash-kernel ring; padded batch parity."""
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(31), b=2, s=128, h=2, d=32)
    mask = _pad_mask(2, 128, [72, 128])
    ref = _masked_ref(q, k, v, mask)
    out = ring_attention(q, k, v, causal=True, mask=mask, mesh=mesh)
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out)[real], np.asarray(ref)[real],
                               atol=2e-3)


def test_ring_flash_masked_gradients_match():
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(32), b=2, s=64, h=2, d=32)
    mask = _pad_mask(2, 64, [40, 64])
    # weight the loss by the mask so padded-row outputs (zeros vs garbage)
    # cannot leak into the comparison
    w = mask.astype(jnp.float32)[:, :, None, None]

    def loss(q, k, v):
        return jnp.sum((ring_attention(q, k, v, causal=True, mask=mask,
                                       mesh=mesh) * w) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum((_masked_ref(q, k, v, mask) * w) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


def test_ring_flash_masked_gqa():
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(33), b=2, s=64, h=4, d=32, kv_heads=2)
    mask = _pad_mask(2, 64, [48, 64])
    ref = _masked_ref(q, k, v, mask, n_rep=2)
    out = ring_attention(q, k, v, causal=True, mask=mask, mesh=mesh)
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out)[real], np.asarray(ref)[real],
                               atol=2e-3)


def test_ulysses_masked_matches_reference():
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(34), b=2, s=64, h=4, d=16)
    mask = _pad_mask(2, 64, [40, 64])
    ref = _masked_ref(q, k, v, mask)
    out = ulysses_attention(q, k, v, causal=True, mask=mask, mesh=mesh)
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out)[real], np.asarray(ref)[real],
                               atol=2e-3)


def test_ulysses_gqa_unrepeated_wire():
    """GQA K/V scatter un-repeated when kv heads divide the axis; parity
    with the repeated reference, and kv-shaped gradients."""
    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(35), b=1, s=64, h=8, d=16, kv_heads=4)
    ref = _masked_ref(q, k, v, None, n_rep=2)
    out = ulysses_attention(q, k, v, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3)

    def loss(k):
        return jnp.sum(ulysses_attention(q, k, v, causal=True, mesh=mesh) ** 2)

    g = jax.grad(loss)(k)
    assert g.shape == k.shape


def test_llama_padded_batch_keeps_ring_backend(monkeypatch):
    """End-to-end: a padded batch through attention_backend='ring' must hit
    the ring (not silently fall back) and match the einsum forward."""
    from accelerate_tpu.models import llama

    mesh = MeshConfig(axes={"seq": 8}).build()
    from accelerate_tpu.state import PartialState
    PartialState._reset_state()
    st = PartialState(mesh_config=MeshConfig(axes={"seq": 8}))

    cfg_ring = llama.LlamaConfig.tiny(attention_backend="ring",
                                      max_position_embeddings=64)
    cfg_ein = llama.LlamaConfig.tiny(attention_backend="einsum",
                                     max_position_embeddings=64)
    params = llama.init_params(cfg_ring, jax.random.key(36))
    ids = jax.random.randint(jax.random.key(37), (2, 64), 0, 256)
    mask = _pad_mask(2, 64, [40, 64])

    import importlib

    rmod = importlib.import_module("accelerate_tpu.parallel.ring_attention")
    called = {}
    orig = rmod.ring_attention

    def spy(*a, **kw):
        called["mask"] = kw.get("mask")
        return orig(*a, **kw)

    # llama re-imports the symbol from the module inside _attention, so
    # patching the module attribute intercepts the call
    monkeypatch.setattr(rmod, "ring_attention", spy)

    out_ring = llama.forward(cfg_ring, params, ids, attention_mask=mask)
    out_ein = llama.forward(cfg_ein, params, ids, attention_mask=mask)
    assert called.get("mask") is not None, "ring fell back / dropped the mask"
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out_ring)[real],
                               np.asarray(out_ein)[real], atol=3e-2)
    PartialState._reset_state()


# --- sliding-window flash attention ------------------------------------------


def test_flash_window_matches_reference():
    """Band mask in the kernel must equal the einsum windowed attention,
    across window widths incl. ones splitting blocks."""
    q, k, v = make_qkv(jax.random.key(40), b=2, s=128, h=2, d=32)
    for w in (8, 33, 100):
        ref = dot_product_attention(q, k, v, causal=True, window=w)
        out = flash_attention(q, k, v, causal=True, window=w,
                              block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, err_msg=f"window={w}")


def test_flash_window_with_padding_mask():
    q, k, v = make_qkv(jax.random.key(41), b=2, s=64, h=2, d=32)
    mask = _pad_mask(2, 64, [40, 64])
    ref = dot_product_attention(q, k, v, mask=mask, causal=True, window=10)
    out = flash_attention(q, k, v, causal=True, mask=mask, window=10,
                          block_q=16, block_k=16)
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(np.asarray(out)[real], np.asarray(ref)[real],
                               atol=2e-3)


def test_flash_window_gradients_match():
    q, k, v = make_qkv(jax.random.key(42), b=1, s=64, h=2, d=32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=9,
                                       block_q=16, block_k=16) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True,
                                             window=9) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


def test_flash_window_wider_than_sequence_is_plain_causal():
    q, k, v = make_qkv(jax.random.key(43), b=1, s=32, h=2, d=16)
    ref = flash_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, window=1000)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_flash_window_requires_causal():
    q, k, v = make_qkv(jax.random.key(44), b=1, s=32, h=2, d=16)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=8)


# --- interleaved virtual-stage pipeline --------------------------------------


def _mlp_stage_fn(params, x):
    """Tiny residual MLP stage: scan over the chunk's layers."""
    def body(carry, layer):
        return carry + jnp.tanh(carry @ layer["w"]) * 0.5, None

    y, _ = jax.lax.scan(body, x, params)
    return y


def _mlp_layers(key, n_layers, dim):
    return {"w": jax.random.normal(key, (n_layers, dim, dim)) * 0.3}


def test_interleaved_forward_matches_sequential():
    """V chunks per device: the interleaved clock must reproduce the plain
    sequential layer application for every micro count, incl. M not a
    multiple of S."""
    from accelerate_tpu.parallel import stack_layers_into_virtual_stages

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    S, V, dim = 4, 2, 16
    layers = _mlp_layers(jax.random.key(60), 16, dim)  # 16 = V*S*2
    x = jax.random.normal(jax.random.key(61), (12, dim))

    ref = _mlp_stage_fn(layers, x)
    vparams = stack_layers_into_virtual_stages(layers, S, V)
    for M in (4, 6, 12):
        if 12 % M:
            continue
        out = pipeline_apply(_mlp_stage_fn, vparams, x, M, mesh=mesh,
                             virtual_stages=V)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, err_msg=f"M={M}")


def test_interleaved_value_and_grad_matches_1f1b_and_sequential():
    from accelerate_tpu.parallel import (
        pipeline_value_and_grad,
        stack_layers_into_stages,
        stack_layers_into_virtual_stages,
    )

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    S, V, dim, B, M = 4, 2, 16, 8, 4
    layers = _mlp_layers(jax.random.key(62), 8, dim)
    x = jax.random.normal(jax.random.key(63), (B, dim))
    tgt = jax.random.normal(jax.random.key(64), (B, dim))

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    # sequential reference
    def ref_loss(layers):
        ym = _mlp_stage_fn(layers, x)
        per = jax.vmap(loss_fn)(
            ym.reshape(M, B // M, dim), tgt.reshape(M, B // M, dim))
        return jnp.mean(per)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(layers)

    vparams = stack_layers_into_virtual_stages(layers, S, V)
    li, gi = pipeline_value_and_grad(
        _mlp_stage_fn, loss_fn, vparams, x, tgt, M, mesh=mesh,
        schedule="interleaved", virtual_stages=V)
    np.testing.assert_allclose(float(li), float(ref_l), atol=1e-5)
    gi_flat = gi["w"].reshape(8, dim, dim)
    np.testing.assert_allclose(np.asarray(gi_flat), np.asarray(ref_g["w"]),
                               atol=1e-4)

    sparams = stack_layers_into_stages(layers, S)
    l1, _ = pipeline_value_and_grad(
        _mlp_stage_fn, loss_fn, sparams, x, tgt, M, mesh=mesh,
        schedule="1f1b")
    np.testing.assert_allclose(float(li), float(l1), atol=1e-5)


def test_interleaved_requires_two_chunks():
    from accelerate_tpu.parallel import pipeline_value_and_grad

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    with pytest.raises(ValueError, match="virtual_stages"):
        pipeline_value_and_grad(
            _mlp_stage_fn, lambda y, t: jnp.mean(y), {"w": jnp.zeros((4, 4, 4))},
            jnp.zeros((4, 4)), jnp.zeros((4, 4)), 2, mesh=mesh,
            schedule="interleaved", virtual_stages=1)


# --- token-sharded MoE all-to-all dispatch -----------------------------------


def _moe_inputs(key, T=64, H=16, E=8, scale=1.0):
    ks = jax.random.split(key, 3)
    x = jax.random.normal(ks[0], (T, H))
    logits = jax.random.normal(ks[1], (T, E)) * scale
    params = {"w": jax.random.normal(ks[2], (E, H, H)) * 0.3}
    return x, logits, params


def _expert_fn_moe(p, xs):
    return jnp.tanh(xs @ p["w"])


def test_moe_a2a_matches_replicated_dispatch():
    """At generous capacity the token-sharded all_to_all dispatch must equal
    the replicated-routing path bit-for-bit semantics-wise."""
    from accelerate_tpu.parallel import (
        expert_parallel_moe,
        expert_parallel_moe_a2a,
    )

    mesh = MeshConfig(axes={"expert": 8}).build()
    for k in (1, 2):
        x, logits, params = _moe_inputs(jax.random.key(70 + k))
        # jitted: each eager shard_map call dispatched op-by-op across
        # the forced 8-device mesh (~3.5s/call; 4 calls put this test at
        # the top of the tier-1 top-30) — one compile each is ~8x faster
        # and bit-identical
        ref = jax.jit(lambda x, l, p, k=k: expert_parallel_moe(
            x, l, p, _expert_fn_moe, mesh=mesh, capacity_factor=8.0,
            top_k=k))(x, logits, params)
        out = jax.jit(lambda x, l, p, k=k: expert_parallel_moe_a2a(
            x, l, p, _expert_fn_moe, mesh=mesh, capacity_factor=8.0,
            top_k=k))(x, logits, params)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, err_msg=f"top_k={k}")


def test_moe_a2a_differentiable():
    from accelerate_tpu.parallel import (
        expert_parallel_moe,
        expert_parallel_moe_a2a,
    )

    mesh = MeshConfig(axes={"expert": 8}).build()
    x, logits, params = _moe_inputs(jax.random.key(73))

    def loss(params, impl):
        y = impl(x, logits, params, _expert_fn_moe, mesh=mesh,
                 capacity_factor=8.0, top_k=2)
        return jnp.sum(y ** 2)

    # jitted grads (static impl): the eager backward dispatched op-by-op
    # across the forced 8-device mesh — same trim as the dispatch test
    g = jax.jit(jax.grad(loss), static_argnums=1)(
        params, expert_parallel_moe_a2a)
    gr = jax.jit(jax.grad(loss), static_argnums=1)(
        params, expert_parallel_moe)
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(gr["w"]),
                               atol=1e-4)


def test_moe_a2a_per_source_capacity_drops():
    """Over capacity, drops are per (expert, source device): a device
    flooding one expert cannot evict other devices' rows, and earlier local
    tokens win slots (Switch semantics within each source)."""
    from accelerate_tpu.parallel import expert_parallel_moe_a2a

    mesh = MeshConfig(axes={"expert": 8}).build()
    T, H, E = 64, 8, 8
    x = jax.random.normal(jax.random.key(74), (T, H))
    # every token routes to expert 0 with prob ~1
    logits = jnp.full((T, E), -20.0).at[:, 0].set(20.0)
    params = {"w": jnp.stack([jnp.eye(H)] * E)}
    out = expert_parallel_moe_a2a(
        x, logits, params, lambda p, xs: xs @ p["w"], mesh=mesh,
        capacity_factor=1.0, top_k=1)
    # capacity per source = 1*1*8/8 = 1: the FIRST token of each device's
    # 8-token shard survives, the rest drop to ~zero (gate ~1, identity
    # expert => surviving rows ~= their inputs)
    out = np.asarray(out)
    for dev in range(8):
        first = dev * 8
        np.testing.assert_allclose(out[first], np.asarray(x[first]),
                                   atol=1e-3)
        assert np.abs(out[first + 1 : first + 8]).max() < 1e-6


def test_moe_a2a_fallback_warns_and_strict_raises():
    """VERDICT r3 weak #5: a divisibility failure must never silently switch
    comm patterns — it warns (default) or raises (strict=True)."""
    import warnings as _warnings

    from accelerate_tpu.parallel import (
        MoEFallbackWarning,
        expert_parallel_moe_a2a,
    )

    mesh = MeshConfig(axes={"expert": 8}).build()
    # 6 experts on an 8-wide axis: indivisible -> replicated fallback
    x = jax.random.normal(jax.random.key(80), (64, 16))
    logits = jax.random.normal(jax.random.key(81), (64, 6))
    params = {"w": jax.random.normal(jax.random.key(82), (6, 16, 16)) * 0.3}
    with pytest.warns(MoEFallbackWarning, match="num_experts=6"):
        out = expert_parallel_moe_a2a(x, logits, params, _expert_fn_moe,
                                      mesh=mesh, top_k=2)
    assert out.shape == x.shape
    with pytest.raises(ValueError, match="preconditions failed"):
        expert_parallel_moe_a2a(x, logits, params, _expert_fn_moe,
                                mesh=mesh, top_k=2, strict=True)
    # indivisible token count trips it too
    x65, l65 = x[:60], jax.random.normal(jax.random.key(83), (60, 8))
    p8 = {"w": jax.random.normal(jax.random.key(84), (8, 16, 16)) * 0.3}
    with pytest.raises(ValueError, match="tokens=60"):
        expert_parallel_moe_a2a(x65, l65, p8, _expert_fn_moe,
                                mesh=mesh, top_k=2, strict=True)
    # a clean call emits no MoEFallbackWarning
    l8 = jax.random.normal(jax.random.key(85), (64, 8))
    with _warnings.catch_warnings():
        _warnings.simplefilter("error", MoEFallbackWarning)
        expert_parallel_moe_a2a(x, l8, p8, _expert_fn_moe, mesh=mesh,
                                top_k=2, strict=True)
    # the replicated-dispatch entry point honors the same contract:
    # indivisible experts on a real axis -> loud replication warning
    from accelerate_tpu.parallel import expert_parallel_moe

    with pytest.warns(MoEFallbackWarning, match="replicate"):
        expert_parallel_moe(x, logits, params, _expert_fn_moe,
                            mesh=mesh, top_k=2)


def test_moe_dropped_fraction_stats():
    """return_stats=True surfaces the per-step dropped-assignment fraction;
    generous capacity -> 0, capacity 1/device with a flooded expert -> 7/8
    of assignments drop. a2a and replicated paths must agree."""
    from accelerate_tpu.parallel import (
        expert_parallel_moe,
        expert_parallel_moe_a2a,
    )

    mesh = MeshConfig(axes={"expert": 8}).build()
    x, logits, params = _moe_inputs(jax.random.key(85))
    # jitted: three eager shard_map calls ran op-by-op on the forced
    # 8-device mesh (a tier-1 top-30 cost) — compiled once each instead
    _, stats = jax.jit(lambda x, l, p: expert_parallel_moe_a2a(
        x, l, p, _expert_fn_moe, mesh=mesh, capacity_factor=8.0,
        top_k=2, return_stats=True))(x, logits, params)
    assert float(stats["moe_dropped_fraction"]) == 0.0

    T, H, E = 64, 8, 8
    xf = jax.random.normal(jax.random.key(86), (T, H))
    flood = jnp.full((T, E), -20.0).at[:, 0].set(20.0)
    pf = {"w": jnp.stack([jnp.eye(H)] * E)}
    ident = lambda p, xs: xs @ p["w"]  # noqa: E731
    # capacity per source device = 1*1*8/8 = 1: of each device's 8
    # assignments to expert 0, exactly 1 survives
    _, stats = jax.jit(lambda x, l, p: expert_parallel_moe_a2a(
        x, l, p, ident, mesh=mesh, capacity_factor=1.0, top_k=1,
        return_stats=True))(xf, flood, pf)
    np.testing.assert_allclose(float(stats["moe_dropped_fraction"]),
                               7.0 / 8.0, atol=1e-6)
    # replicated path reports its own (global-capacity) fraction: C=8,
    # 8 of 64 assignments survive -> same 7/8 here
    _, stats_rep = jax.jit(lambda x, l, p: expert_parallel_moe(
        x, l, p, ident, mesh=mesh, capacity_factor=1.0, top_k=1,
        return_stats=True))(xf, flood, pf)
    np.testing.assert_allclose(float(stats_rep["moe_dropped_fraction"]),
                               7.0 / 8.0, atol=1e-6)


def test_moe_topk_drop_ordering_matches_reference():
    """VERDICT weak #6: top-2 drop ordering under over-capacity must match a
    straightforward reference loop (earlier assignments win slots)."""
    from accelerate_tpu.parallel import expert_parallel_moe

    mesh = MeshConfig(axes={"expert": 8}).build()
    T, H, E, k, cf = 32, 8, 8, 2, 0.5
    x, logits, params = _moe_inputs(jax.random.key(75), T=T, H=H, E=E,
                                    scale=3.0)
    out = expert_parallel_moe(x, logits, params, _expert_fn_moe, mesh=mesh,
                              capacity_factor=cf, top_k=k)

    # reference: sequential fill, earlier (token, k-slot) assignments win
    capacity = max(int(cf * k * T / E), 1)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    order = np.argsort(-probs, axis=-1)[:, :k]
    gates = np.take_along_axis(probs, order, axis=-1)
    fill = {e: 0 for e in range(E)}
    want = np.zeros((T, H), np.float32)
    xs = np.asarray(x)
    w = np.asarray(params["w"])
    for t in range(T):
        for j in range(k):
            e = int(order[t, j])
            if fill[e] < capacity:
                fill[e] += 1
                want[t] += gates[t, j] * np.tanh(xs[t] @ w[e])
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)

def test_1f1b_interleaved_matches_autodiff_and_sequential():
    """VERDICT r3 weak #6: the memory-bounded interleaved 1F1B (V-chunk
    schedule with O(S*V) activation rings) must reproduce both the autodiff
    interleaved path and the plain sequential reference, for M a multiple
    of S and not."""
    from accelerate_tpu.parallel import (
        pipeline_value_and_grad,
        stack_layers_into_virtual_stages,
    )

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    S, V, dim, B = 4, 2, 16, 24
    layers = _mlp_layers(jax.random.key(65), 8, dim)
    x = jax.random.normal(jax.random.key(66), (B, dim))
    tgt = jax.random.normal(jax.random.key(67), (B, dim))

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    vparams = stack_layers_into_virtual_stages(layers, S, V)
    # M=8 (multiple of S=4) and M=6 (not) pin both schedule classes; each
    # extra M is three full pipeline recompiles (~15s) for the same code
    # paths — M=4/12 were dropped for the tier-1 time budget (CHANGES.md)
    for M in (6, 8):
        def ref_loss(layers, M=M):
            ym = _mlp_stage_fn(layers, x)
            per = jax.vmap(loss_fn)(
                ym.reshape(M, B // M, dim), tgt.reshape(M, B // M, dim))
            return jnp.mean(per)

        ref_l, ref_g = jax.value_and_grad(ref_loss)(layers)
        l2, g2 = pipeline_value_and_grad(
            _mlp_stage_fn, loss_fn, vparams, x, tgt, M, mesh=mesh,
            schedule="1f1b", virtual_stages=V)
        np.testing.assert_allclose(float(l2), float(ref_l), atol=1e-5,
                                   err_msg=f"M={M}")
        np.testing.assert_allclose(
            np.asarray(g2["w"].reshape(8, dim, dim)),
            np.asarray(ref_g["w"]), atol=1e-4, err_msg=f"M={M}")

        la, ga = pipeline_value_and_grad(
            _mlp_stage_fn, loss_fn, vparams, x, tgt, M, mesh=mesh,
            schedule="interleaved", virtual_stages=V)
        np.testing.assert_allclose(float(l2), float(la), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(g2["w"]), np.asarray(ga["w"]), atol=1e-5)


def test_1f1b_interleaved_three_chunks():
    """V=3 exercises chunk decode beyond the binary case."""
    from accelerate_tpu.parallel import (
        pipeline_value_and_grad,
        stack_layers_into_virtual_stages,
    )

    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    S, V, dim, B, M = 4, 3, 8, 8, 4
    layers = _mlp_layers(jax.random.key(68), S * V, dim)
    x = jax.random.normal(jax.random.key(69), (B, dim))
    tgt = jax.random.normal(jax.random.key(70), (B, dim))

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    def ref_loss(layers):
        ym = _mlp_stage_fn(layers, x)
        per = jax.vmap(loss_fn)(
            ym.reshape(M, B // M, dim), tgt.reshape(M, B // M, dim))
        return jnp.mean(per)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(layers)
    vparams = stack_layers_into_virtual_stages(layers, S, V)
    l2, g2 = pipeline_value_and_grad(
        _mlp_stage_fn, loss_fn, vparams, x, tgt, M, mesh=mesh,
        schedule="1f1b", virtual_stages=V)
    np.testing.assert_allclose(float(l2), float(ref_l), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(g2["w"].reshape(S * V, dim, dim)),
        np.asarray(ref_g["w"]), atol=1e-4)


# --- sliding-window context parallelism --------------------------------------


def test_ring_attention_sliding_window_matches_reference():
    """Windowed ring (einsum fold, global-position banding) == plain
    windowed attention, incl. gradients and GQA, across window sizes that
    span sub-chunk and multi-chunk reach."""
    from accelerate_tpu.parallel import ring_attention

    mesh = MeshConfig(axes={"seq": 8}).build()
    # w=5 sub-chunk, w=24 multi-chunk + GQA, w=64 full reach; the w=16
    # multi-chunk case was dropped for the tier-1 time budget — its forward
    # is exercised by the gradient-parity check below (CHANGES.md)
    for w, kv in ((5, None), (24, 2), (64, None)):
        q, k, v = make_qkv(jax.random.key(90 + w), s=64, kv_heads=kv)
        from accelerate_tpu.models.common import repeat_kv

        n_rep = q.shape[2] // k.shape[2]
        ref = dot_product_attention(q, repeat_kv(k, n_rep),
                                    repeat_kv(v, n_rep), causal=True,
                                    window=w)
        out = ring_attention(q, k, v, causal=True, mesh=mesh, window=w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, err_msg=f"w={w} kv={kv}")

    # gradients through the banded fold
    q, k, v = make_qkv(jax.random.key(95), s=64)
    g = jax.grad(lambda q: jnp.sum(
        ring_attention(q, k, v, causal=True, mesh=mesh, window=16) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(
        dot_product_attention(q, k, v, causal=True, window=16) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-3)

    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, k, v, causal=False, mesh=mesh, window=8)


def test_ulysses_attention_sliding_window_matches_reference():
    from accelerate_tpu.parallel import ulysses_attention

    mesh = MeshConfig(axes={"seq": 4, "data": 2}).build()
    q, k, v = make_qkv(jax.random.key(96), s=32)
    ref = dot_product_attention(q, k, v, causal=True, window=9)
    out = ulysses_attention(q, k, v, causal=True, mesh=mesh, window=9)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_window_with_padding_mask():
    """Sliding window + key-padding mask combine in the banded ring (and
    out-of-band chunk skipping changes nothing numerically)."""
    from accelerate_tpu.models.common import dot_product_attention
    from accelerate_tpu.parallel import ring_attention

    mesh = MeshConfig(axes={"seq": 8}).build()
    q, k, v = make_qkv(jax.random.key(97), s=64)
    mask = jnp.ones((2, 64), jnp.int32).at[:, 50:].set(0)
    ref = dot_product_attention(q, k, v, causal=True, window=12,
                                mask=mask)
    out = ring_attention(q, k, v, causal=True, mesh=mesh, window=12,
                         mask=mask)
    # padded queries attend nothing; compare real-token rows
    np.testing.assert_allclose(np.asarray(out)[:, :50],
                               np.asarray(ref)[:, :50], atol=2e-5)
