"""Native quantization (bnb replacement) + fp8 path (TE replacement) +
Ulysses attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.fp8 import Fp8Meta, fp8_dot, init_fp8_state, update_meta
from accelerate_tpu.ops.quant import (
    QuantizedTensor,
    dequantize,
    dequantize_params,
    quantize,
    quantize_params,
    quantized_matmul,
)
from accelerate_tpu.utils.dataclasses import QuantizationConfig


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    """The mixtral/t5/zoo fp8 convergence tests jit near-identical train
    steps over and over; the repo's persistent compilation cache turns
    the repeats into deserializes (same pattern as test_serving.py)."""
    import os

    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (the ISSUE 16 gotcha)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


# -- quantization -------------------------------------------------------------


@pytest.mark.parametrize("bits", [8, 4])
def test_quantize_roundtrip_error_bounded(bits):
    w = jax.random.normal(jax.random.key(0), (64, 256), jnp.float32)
    qt = quantize(w, bits=bits, block_size=64)
    back = dequantize(qt)
    rel = float(jnp.abs(back - w).max() / jnp.abs(w).max())
    assert rel < (0.02 if bits == 8 else 0.2), rel


def test_quantize_int4_packs_nibbles():
    w = jax.random.normal(jax.random.key(1), (8, 128))
    qt = quantize(w, bits=4, block_size=64)
    assert qt.data.shape == (8, 64)  # two codes per byte
    assert qt.nbytes < w.nbytes / 3.5


def test_quantized_matmul_close():
    k = jax.random.key(2)
    x = jax.random.normal(k, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(3), (64, 32), jnp.float32)
    qt = quantize(w, bits=8, block_size=32)
    out = jax.jit(quantized_matmul)(x, qt)
    ref = x @ w
    assert float(jnp.abs(out - ref).max() / jnp.abs(ref).max()) < 0.05


def test_quantized_tensor_is_pytree():
    qt = quantize(jnp.ones((4, 8)), bits=8)
    leaves = jax.tree_util.tree_leaves(qt)
    assert len(leaves) == 2  # data + scales
    rebuilt = jax.tree_util.tree_map(lambda x: x, qt)
    assert isinstance(rebuilt, QuantizedTensor)
    assert rebuilt.shape == (4, 8)


def test_quantize_params_skips_and_selects():
    params = {
        "layers": {"mlp": {"kernel": jnp.ones((16, 16)), "bias": jnp.ones((16,))}},
        "lm_head": {"kernel": jnp.ones((16, 8))},
    }
    qp = quantize_params(params, QuantizationConfig(load_in_8bit=True))
    assert isinstance(qp["layers"]["mlp"]["kernel"], QuantizedTensor)
    assert not isinstance(qp["layers"]["mlp"]["bias"], QuantizedTensor)  # 1-D kept
    assert not isinstance(qp["lm_head"]["kernel"], QuantizedTensor)  # skipped
    dq = dequantize_params(qp)
    np.testing.assert_allclose(np.asarray(dq["layers"]["mlp"]["kernel"]), 1.0,
                               rtol=0.01)


def test_load_and_quantize_params(tmp_path):
    from safetensors.numpy import save_file

    from accelerate_tpu.big_modeling import init_empty_weights, load_and_quantize_params

    rng = np.random.default_rng(0)
    sd = {
        "block.w": rng.normal(size=(32, 32)).astype(np.float32),
        "block.b": rng.normal(size=(32,)).astype(np.float32),
    }
    save_file(sd, str(tmp_path / "model.safetensors"))
    abstract = init_empty_weights(
        lambda: {"block": {"w": jnp.zeros((32, 32)), "b": jnp.zeros((32,))}}
    )
    qp = load_and_quantize_params(
        abstract, str(tmp_path), QuantizationConfig(load_in_8bit=True, skip_modules=()),
    )
    assert isinstance(qp["block"]["w"], QuantizedTensor)
    back = dequantize(qp["block"]["w"])
    assert float(jnp.abs(back - sd["block.w"]).max()) < 0.05


# -- fp8 ----------------------------------------------------------------------


def test_fp8_dot_close_to_f32():
    k1, k2 = jax.random.split(jax.random.key(0))
    x = jax.random.normal(k1, (8, 64), jnp.float32)
    w = jax.random.normal(k2, (64, 32), jnp.float32)
    xm, wm = Fp8Meta.init(), Fp8Meta.init()
    out, xm2, wm2 = jax.jit(fp8_dot)(x, w, xm, wm)
    ref = x @ w
    rel = float(jnp.abs(out.astype(jnp.float32) - ref).max() / jnp.abs(ref).max())
    assert rel < 0.15, rel
    # metas rolled: amax recorded, scale updated
    assert float(xm2.amax_history[0]) == pytest.approx(float(jnp.abs(x).max()), rel=1e-5)
    assert float(xm2.scale) != 1.0


def test_fp8_delayed_scaling_improves_second_step():
    """After one step the scale adapts to the tensor's range, so small-valued
    tensors lose less precision than with the initial unit scale."""
    x = jax.random.normal(jax.random.key(1), (16, 64)) * 1e-3
    w = jax.random.normal(jax.random.key(2), (64, 16)) * 1e-3
    ref = x @ w
    xm, wm = Fp8Meta.init(), Fp8Meta.init()
    out1, xm, wm = fp8_dot(x, w, xm, wm, out_dtype=jnp.float32)
    out2, xm, wm = fp8_dot(x, w, xm, wm, out_dtype=jnp.float32)
    err1 = float(jnp.abs(out1 - ref).max())
    err2 = float(jnp.abs(out2 - ref).max())
    assert err2 < err1


def test_update_meta_rolls_history():
    meta = Fp8Meta.init(history_len=4)
    meta = update_meta(meta, jnp.asarray(2.0))
    meta = update_meta(meta, jnp.asarray(8.0))
    assert float(meta.amax_history[0]) == 8.0
    assert float(meta.amax_history[1]) == 2.0
    assert float(meta.scale) == pytest.approx(448.0 / 8.0)


def test_init_fp8_state_matches_weights():
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    st = init_fp8_state(params)
    assert st["b"] is None
    assert isinstance(st["w"]["x"], Fp8Meta)


# -- ulysses ------------------------------------------------------------------


def test_ulysses_matches_plain_attention():
    from jax.sharding import Mesh

    from accelerate_tpu.models.common import dot_product_attention
    from accelerate_tpu.parallel.ulysses import ulysses_attention

    devices = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("seq",))
    b, s, h, d = 2, 32, 4, 16
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(k1, (b, s, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, s, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, s, h, d), jnp.float32)
    out = ulysses_attention(q, k, v, causal=True, mesh=mesh)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_falls_back_when_heads_dont_divide():
    from jax.sharding import Mesh

    from accelerate_tpu.parallel.ulysses import ulysses_attention

    devices = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("seq",))
    q = jnp.ones((1, 32, 3, 8))  # 3 heads % 4 != 0
    out = ulysses_attention(q, q, q, causal=False, mesh=mesh)
    assert out.shape == q.shape


def test_ulysses_grads_flow():
    from jax.sharding import Mesh

    from accelerate_tpu.parallel.ulysses import ulysses_attention

    devices = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("seq",))

    def loss(q):
        return ulysses_attention(q, q, q, causal=True, mesh=mesh).sum()

    g = jax.grad(loss)(jnp.ones((1, 16, 4, 8)))
    assert np.isfinite(np.asarray(g)).all()


def test_quantize_int4_odd_width_roundtrip():
    w = jax.random.normal(jax.random.key(5), (4, 7))
    qt = quantize(w, bits=4)
    back = dequantize(qt)
    assert back.shape == w.shape
    assert float(jnp.abs(back - w).max() / jnp.abs(w).max()) < 0.25


def test_quantize_numpy_host_side():
    """np input (e.g. memmap from an offload store) must quantize without
    touching a device."""
    w = np.random.default_rng(0).normal(size=(16, 32)).astype(np.float32)
    qt = quantize(w, bits=8, block_size=16)
    assert isinstance(qt.data, np.ndarray)  # stayed host-side
    back = dequantize(qt)
    assert float(jnp.abs(back - w).max() / np.abs(w).max()) < 0.02


def test_context_attention_mode_dispatch():
    from jax.sharding import Mesh

    from accelerate_tpu.models.common import dot_product_attention
    from accelerate_tpu.parallel import context_attention

    devices = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("seq",))
    q = jax.random.normal(jax.random.key(0), (1, 32, 4, 8), jnp.float32)
    ref = dot_product_attention(q, q, q, causal=True)
    for mode in ("ring", "ulysses"):
        out = context_attention(q, q, q, causal=True, mode=mode, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_context_parallel_plugin_validates_mode():
    from accelerate_tpu.utils.dataclasses import ContextParallelPlugin

    with pytest.raises(ValueError, match="mode"):
        ContextParallelPlugin(mode="allgather")
    assert ContextParallelPlugin(mode="ulysses").mode == "ulysses"


# -- fp8 end-to-end path ------------------------------------------------------


def test_fp8_dense_matches_f32_forward_and_grad():
    from accelerate_tpu.ops.fp8 import fp8_dense, init_fp8_state

    key = jax.random.key(0)
    x = jax.random.normal(key, (4, 32), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (32, 16), jnp.float32) * 0.1
    meta = {"x": Fp8Meta.init(), "w": Fp8Meta.init()}

    def loss8(x, w):
        out, _ = fp8_dense(x, w, meta)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss32(x, w):
        return jnp.sum(jnp.dot(x, w) ** 2)

    g8 = jax.grad(loss8, argnums=(0, 1))(x, w)
    g32 = jax.grad(loss32, argnums=(0, 1))(x, w)
    for a, b in zip(g8, g32):
        a, b = np.asarray(a, np.float32), np.asarray(b)
        # norm-relative: per-element fp8 noise is large on tiny entries, but
        # the gradient direction/magnitude must match closely
        rel = np.linalg.norm(a - b) / np.linalg.norm(b)
        assert rel < 0.1, rel


def test_fp8_dense_updates_meta():
    from accelerate_tpu.ops.fp8 import fp8_dense

    x = jnp.ones((2, 8)) * 3.0
    w = jnp.ones((8, 4)) * 0.5
    meta = {"x": Fp8Meta.init(), "w": Fp8Meta.init()}
    _, new_meta = fp8_dense(x, w, meta)
    assert float(new_meta["x"].amax_history[0]) == 3.0
    assert float(new_meta["w"].amax_history[0]) == 0.5
    assert float(new_meta["x"].scale) != 1.0


def test_llama_fp8_train_step_converges():
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    cfg = llama.LlamaConfig.tiny()
    acc = Accelerator(mixed_precision="fp8")
    params = llama.init_params(cfg, jax.random.key(0))
    ts = TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(5e-3),
        fp8_state=llama.init_fp8_state(cfg),
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (4, 33)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}
    step = acc.train_step(
        lambda p, b, fp8_state=None: llama.causal_lm_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    losses = []
    for _ in range(12):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    # delayed-scaling state actually updated
    scale = ts.fp8_state["layers"]["attn"]["q_proj"]["x"].scale
    assert scale.shape == (cfg.num_hidden_layers,)
    assert not np.allclose(np.asarray(scale), 1.0)


def test_fp8_without_state_hard_errors():
    import optax
    import pytest

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    cfg = llama.LlamaConfig.tiny()
    acc = Accelerator(mixed_precision="fp8")
    params = llama.init_params(cfg, jax.random.key(0))
    ts = TrainState.create(apply_fn=None, params=params, tx=optax.sgd(1e-3))
    step = acc.train_step(
        lambda p, b, fp8_state=None: llama.causal_lm_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    batch = {"input_ids": jnp.zeros((2, 9), jnp.int32)}
    with pytest.raises(ValueError, match="fp8"):
        step(ts, batch)


def test_fp8_loss_fn_without_kwarg_hard_errors():
    import pytest

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator(mixed_precision="fp8")
    with pytest.raises(ValueError, match="fp8"):
        acc.train_step(lambda p, b: jnp.float32(0.0))


def test_fp8_eager_path_hard_errors():
    import pytest

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator(mixed_precision="fp8")
    with pytest.raises(NotImplementedError, match="fp8"):
        acc.compute_gradients(lambda p: jnp.float32(0.0), {})


@pytest.mark.parametrize("impl", ["dense", "sparse"])
def test_mixtral_fp8_train_step_converges(impl):
    """fp8 beyond llama (round-2 gap): attention + expert-MLP projections in
    E4M3/E5M2 delayed scaling, state threaded through the fused step."""
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import mixtral
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    cfg = mixtral.MixtralConfig.tiny(moe_impl=impl)
    acc = Accelerator(mixed_precision="fp8")
    params = mixtral.init_params(cfg, jax.random.key(1))
    ts = TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(5e-3),
        fp8_state=mixtral.init_fp8_state(cfg),
    )
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, (4, 33)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}
    step = acc.train_step(
        lambda p, b, fp8_state=None: mixtral.causal_lm_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    losses = []
    for _ in range(12):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    # both the attention and the expert-MLP metas actually updated
    for path in (("attn", "q_proj"), ("moe", "gate_proj")):
        meta = ts.fp8_state["layers"][path[0]][path[1]]["x"]
        assert not np.allclose(np.asarray(meta.scale), 1.0), path


def test_mixtral_fp8_forward_close_to_f32():
    from accelerate_tpu.models import mixtral

    cfg = mixtral.MixtralConfig.tiny()
    params = mixtral.init_params(cfg, jax.random.key(2))
    ids = np.random.default_rng(2).integers(0, cfg.vocab_size,
                                            (2, 16)).astype(np.int32)
    ref, _ = mixtral.forward(cfg, params, ids)
    logits, _, new_fp8 = mixtral.forward(
        cfg, params, ids, fp8_state=mixtral.init_fp8_state(cfg))
    # first-step scales are 1.0: fp8 quantization noise only
    err = np.abs(np.asarray(logits) - np.asarray(ref)).max()
    assert err < 0.35, err
    assert new_fp8["layers"]["moe"]["down_proj"]["w"].scale.shape == (
        cfg.num_hidden_layers,)


def test_mixtral_fp8_a2a_close_to_sparse_fp8():
    """fp8 through the token-sharded a2a dispatch: logits close to the
    sparse-fp8 path on the same weights at generous capacity, and the moe
    metas actually update (amaxes ride the expert_aux channel)."""
    import dataclasses

    from accelerate_tpu.models import mixtral

    base = mixtral.MixtralConfig.tiny(num_local_experts=8)
    cfg_a2a = dataclasses.replace(
        base, moe_impl="a2a", capacity_factor=8.0)
    cfg_sparse = dataclasses.replace(
        base, moe_impl="sparse", capacity_factor=8.0)
    params = mixtral.init_params(base, jax.random.key(4))
    ids = np.random.default_rng(4).integers(0, base.vocab_size,
                                            (2, 16)).astype(np.int32)
    ref, _, _ = mixtral.forward(cfg_sparse, params, ids,
                                fp8_state=mixtral.init_fp8_state(cfg_sparse))
    out, _, new_fp8 = mixtral.forward(cfg_a2a, params, ids,
                                      fp8_state=mixtral.init_fp8_state(cfg_a2a))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.1)
    scale = new_fp8["layers"]["moe"]["down_proj"]["w"].scale
    assert scale.shape == (base.num_hidden_layers,)
    assert not np.allclose(np.asarray(scale), 1.0)


def test_mixtral_fp8_a2a_train_step_converges():
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import MeshConfig

    PartialState._reset_state()
    from accelerate_tpu.models import mixtral

    cfg = mixtral.MixtralConfig.tiny(num_local_experts=8, moe_impl="a2a")
    acc = Accelerator(mixed_precision="fp8",
                      mesh_config=MeshConfig(axes={"expert": 8}))
    params = mixtral.init_params(cfg, jax.random.key(5))
    ts = TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(5e-3),
        fp8_state=mixtral.init_fp8_state(cfg),
    )
    ids = np.random.default_rng(5).integers(0, cfg.vocab_size,
                                            (4, 33)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}
    step = acc.train_step(
        lambda p, b, fp8_state=None: mixtral.causal_lm_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    losses = []
    for _ in range(12):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


@pytest.mark.parametrize("family", ["gpt2", "gpt_neox", "opt", "gptj"])
def test_zoo_fp8_train_step_converges(family):
    """VERDICT r3 item 9 (fp8 breadth): gpt2/gpt_neox/opt train under
    mixed_precision='fp8' through the shared dense_maybe_fp8 swap point."""
    import importlib

    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.state import PartialState

    mod = importlib.import_module(f"accelerate_tpu.models.{family}")
    PartialState._reset_state()
    cfg = mod.tiny_config() if hasattr(mod, "tiny_config") else None
    if cfg is None:
        cfg_cls = {
            "gpt2": "GPT2Config", "gpt_neox": "GPTNeoXConfig",
            "opt": "OPTConfig", "gptj": "GPTJConfig",
        }[family]
        cfg = getattr(mod, cfg_cls).tiny()
    acc = Accelerator(mixed_precision="fp8")
    params = mod.init_params(cfg, jax.random.key(0))
    ts = TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(5e-3),
        fp8_state=mod.init_fp8_state(cfg),
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (4, 33)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}
    step = acc.train_step(
        lambda p, b, fp8_state=None: mod.causal_lm_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    losses = []
    for _ in range(12):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    # delayed-scaling metas actually updated
    leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(
            lambda x: x, ts.fp8_state["layers"],
        )
    )
    assert any(
        not np.allclose(np.asarray(leaf), 1.0)
        for leaf in leaves if leaf.ndim == 1
    )


@pytest.mark.parametrize("family", ["gpt2", "gpt_neox", "opt", "gptj"])
def test_zoo_fp8_forward_close_to_f32(family):
    """fp8 logits stay close to the f32 forward on the same weights."""
    import importlib

    mod = importlib.import_module(f"accelerate_tpu.models.{family}")
    cfg_cls = {
        "gpt2": "GPT2Config", "gpt_neox": "GPTNeoXConfig",
        "opt": "OPTConfig", "gptj": "GPTJConfig",
    }[family]
    cfg = getattr(mod, cfg_cls).tiny()
    params = mod.init_params(cfg, jax.random.key(1))
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 17)),
        jnp.int32,
    )
    ref = mod.forward(cfg, params, ids)
    out, new_state = mod.forward(cfg, params, ids,
                                 fp8_state=mod.init_fp8_state(cfg))
    # first-step scales are 1.0: fp8 quantization noise only (same bound
    # as test_mixtral_fp8_forward_close_to_f32)
    err = np.abs(np.asarray(out) - np.asarray(ref)).max()
    assert err < 0.35, err
    assert jax.tree_util.tree_structure(new_state) is not None


@pytest.mark.parametrize("family", ["gpt2", "gpt_neox", "opt", "gptj"])
def test_zoo_fp8_decode_refused(family):
    import importlib

    mod = importlib.import_module(f"accelerate_tpu.models.{family}")
    cfg_cls = {
        "gpt2": "GPT2Config", "gpt_neox": "GPTNeoXConfig",
        "opt": "OPTConfig", "gptj": "GPTJConfig",
    }[family]
    cfg = getattr(mod, cfg_cls).tiny()
    params = mod.init_params(cfg, jax.random.key(2))
    caches = mod.init_kv_caches(cfg, 2, 16)
    with pytest.raises(ValueError, match="fp8"):
        mod.forward(cfg, params, jnp.zeros((2, 4), jnp.int32),
                    kv_caches=caches, fp8_state=mod.init_fp8_state(cfg))


def test_t5_fp8_train_step_converges():
    """fp8 across the enc-dec T5 family: the seq2seq loss threads
    encoder/decoder metas and trains under mixed_precision='fp8'."""
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import t5
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    cfg = t5.T5Config.tiny()
    acc = Accelerator(mixed_precision="fp8")
    params = t5.init_params(cfg, jax.random.key(6))
    ts = TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(5e-3),
        fp8_state=t5.init_fp8_state(cfg),
    )
    rng = np.random.default_rng(6)
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)),
                                 jnp.int32),
        "decoder_input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (4, 12)), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 12)),
                              jnp.int32),
    }
    step = acc.train_step(
        lambda p, b, fp8_state=None: t5.seq2seq_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    losses = []
    for _ in range(12):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    scale = ts.fp8_state["decoder"]["layers"]["cross_attn"]["q"]["x"].scale
    assert scale.shape == (cfg.num_decoder_layers,)
    assert not np.allclose(np.asarray(scale), 1.0)


def test_t5_fp8_forward_close_to_f32():
    from accelerate_tpu.models import t5

    cfg = t5.T5Config.tiny()
    params = t5.init_params(cfg, jax.random.key(7))
    rng = np.random.default_rng(7)
    enc_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    dec_ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    ref = t5.forward(cfg, params, enc_ids, dec_ids)
    out, new_state = t5.forward(cfg, params, enc_ids, dec_ids,
                                fp8_state=t5.init_fp8_state(cfg))
    err = np.abs(np.asarray(out) - np.asarray(ref)).max()
    assert err < 0.35, err
    assert "encoder" in new_state and "decoder" in new_state


def test_t5_fp8_ungated_variant():
    """relu (non-gated) T5 has a different mlp projection set — the metas
    layout must follow is_gated_act."""
    from accelerate_tpu.models import t5

    cfg = t5.T5Config.tiny(is_gated_act=False)
    st = t5.init_fp8_state(cfg)
    assert set(st["encoder"]["layers"]["mlp"]) == {"wi", "wo"}
    params = t5.init_params(cfg, jax.random.key(8))
    ids = jnp.zeros((1, 8), jnp.int32)
    out, _ = t5.forward(cfg, params, ids, ids, fp8_state=st)
    assert np.isfinite(np.asarray(out)).all()


def test_bert_fp8_train_step_converges():
    """The classifier example model trains under mixed_precision='fp8' —
    no family-level exceptions remain."""
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import bert
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    cfg = bert.BertConfig.tiny()
    acc = Accelerator(mixed_precision="fp8")
    params = bert.init_params(cfg, jax.random.key(9))
    ts = TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(5e-3),
        fp8_state=bert.init_fp8_state(cfg),
    )
    rng = np.random.default_rng(9)
    ids = rng.integers(4, cfg.vocab_size, (16, 24)).astype(np.int32)
    labels = rng.integers(0, 2, (16,)).astype(np.int32)
    ids[labels == 1, 4:8] = 20  # learnable signal
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}
    step = acc.train_step(
        lambda p, b, fp8_state=None: bert.classification_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    losses = []
    for _ in range(20):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.6, losses
    scale = ts.fp8_state["layers"]["mlp"]["up_proj"]["x"].scale
    assert not np.allclose(np.asarray(scale), 1.0)


def test_mixtral_fp8_with_remat_trains():
    """remat wraps the scan body AROUND the fp8 meta threading — the
    combination must train (activation recompute replays the fp8 casts)."""
    import dataclasses

    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import mixtral
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    cfg = dataclasses.replace(mixtral.MixtralConfig.tiny(), remat=True)
    acc = Accelerator(mixed_precision="fp8")
    params = mixtral.init_params(cfg, jax.random.key(10))
    ts = TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(5e-3),
        fp8_state=mixtral.init_fp8_state(cfg),
    )
    ids = np.random.default_rng(10).integers(0, cfg.vocab_size,
                                             (4, 17)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids)}
    step = acc.train_step(
        lambda p, b, fp8_state=None: mixtral.causal_lm_loss(
            cfg, p, b, fp8_state=fp8_state
        )
    )
    losses = []
    for _ in range(9):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    # the guarded regression: remat must not drop the fp8 meta updates
    scale = ts.fp8_state["layers"]["attn"]["q_proj"]["x"].scale
    assert not np.allclose(np.asarray(scale), 1.0)


# -- fp8 checkpoint window migration ------------------------------------------


def test_adapt_history_len_truncates_newest_first_and_pads():
    from accelerate_tpu.ops.fp8 import adapt_history_len, fp8_state_history_len

    meta = Fp8Meta(scale=jnp.float32(3.0),
                   amax_history=jnp.arange(8, dtype=jnp.float32))
    tree = {"w": {"x": meta}}
    small = adapt_history_len(tree, 4)
    assert fp8_state_history_len(small) == 4
    # index 0 is the newest entry; truncation keeps the newest window
    np.testing.assert_array_equal(
        np.asarray(small["w"]["x"].amax_history), [0.0, 1.0, 2.0, 3.0]
    )
    assert float(small["w"]["x"].scale) == 3.0
    grown = adapt_history_len(small, 6)
    np.testing.assert_array_equal(
        np.asarray(grown["w"]["x"].amax_history), [0.0, 1.0, 2.0, 3.0, 0.0, 0.0]
    )
    # abstract leaves resize too (checkpoint like-trees)
    abstract = jax.tree_util.tree_map(
        lambda m: Fp8Meta(scale=jax.ShapeDtypeStruct((), jnp.float32),
                          amax_history=jax.ShapeDtypeStruct((2, 8), jnp.float32)),
        tree, is_leaf=lambda x: isinstance(x, Fp8Meta))
    res = adapt_history_len(abstract, 16)
    assert res["w"]["x"].amax_history.shape == (2, 16)


def test_fp8_checkpoint_restores_across_history_len_change(tmp_path):
    """A checkpoint written under a long amax window (the old TE-style 1024
    default) restores into today's short window by keeping the newest
    entries, instead of failing orbax's shape check."""
    import optax

    from accelerate_tpu.checkpointing import (
        load_accelerator_state,
        save_accelerator_state,
    )
    from accelerate_tpu.ops.fp8 import adapt_history_len, fp8_state_history_len
    from accelerate_tpu.training import TrainState

    params = {"w": jnp.ones((8, 8))}
    old = adapt_history_len(init_fp8_state(params), 1024)
    old = jax.tree_util.tree_map(
        lambda m: Fp8Meta(scale=m.scale * 2,
                          amax_history=m.amax_history.at[..., 0].set(7.0)),
        old, is_leaf=lambda x: isinstance(x, Fp8Meta))
    ts = TrainState.create(apply_fn=None, params=params, tx=optax.sgd(1e-3),
                           fp8_state=old)
    save_accelerator_state(str(tmp_path), train_states=[ts])

    ts2 = TrainState.create(apply_fn=None, params=params, tx=optax.sgd(1e-3),
                            fp8_state=init_fp8_state(params))
    load_accelerator_state(str(tmp_path), train_states=[ts2])
    assert fp8_state_history_len(ts2.fp8_state) == 16
    meta = ts2.fp8_state["w"]["x"]
    assert float(np.asarray(meta.amax_history)[0]) == 7.0
    assert float(np.asarray(meta.scale)) == 2.0
