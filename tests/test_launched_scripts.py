"""The bundled launch-and-assert scripts (ref tests/test_multigpu.py pattern,
SURVEY.md §4): each script carries rank-level asserts; here they run in the
pytest 8-device CPU world, and (slow) under `accelerate-tpu launch` with a
real 2-process jax.distributed world.
"""

import importlib.util

import pytest

from accelerate_tpu.test_utils import (
    execute_subprocess,
    launch_command_for,
    bundled_script_path,
    multiprocess_backend_supported,
)


def _require_multiprocess_backend():
    """Real 2-process worlds need a jaxlib whose CPU client implements
    cross-process computations; some builds raise INVALID_ARGUMENT at the
    first global compile. One cached probe gates the whole matrix."""
    if not multiprocess_backend_supported():
        pytest.skip(
            "this jaxlib's CPU backend cannot run multi-process "
            "computations (cross-process collectives not built in); the "
            "2-process launch matrix needs a capable jaxlib"
        )

SCRIPTS = [
    "test_sync.py",
    "test_ops.py",
    "test_distributed_data_loop.py",
    "test_uneven_inputs.py",
    "test_cli.py",
    "test_notebook.py",
    "external_deps/test_checkpointing.py",
    "external_deps/test_metrics.py",
    "external_deps/test_performance.py",
    "external_deps/test_peak_memory_usage.py",
    "external_deps/test_pipeline_inference.py",
    "external_deps/test_zero3_integration.py",
    "test_grad_parity.py",
]

# a real 2-process `accelerate-tpu launch` world runs in DEFAULT CI for this
# subset (the multi-host regression surface round-1 bugs hid in); the full
# matrix stays behind RUN_SLOW=1
SMOKE_SCRIPTS = [
    "test_ops.py",
    "test_uneven_inputs.py",
    # checkpointing + metrics are precisely where multi-host regressions
    # hide (round-2 review); pipeline-inference + zero3 + grad-parity
    # promoted r5 now the 2-process matrix is fast and hang-proofed
    # (VERDICT r4 #4/#5); the rest of the matrix stays nightly
    "external_deps/test_checkpointing.py",
    "external_deps/test_metrics.py",
    "external_deps/test_pipeline_inference.py",
    "external_deps/test_zero3_integration.py",
    "test_grad_parity.py",
]


def _run_in_process(name: str) -> None:
    spec = importlib.util.spec_from_file_location(
        name.removesuffix(".py").replace("/", "."), bundled_script_path(name)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


@pytest.mark.parametrize("script", SCRIPTS)
def test_script_in_process(script):
    _run_in_process(script)


@pytest.mark.slow
@pytest.mark.parametrize("script", SCRIPTS)
def test_script_two_process_world(script):
    if script == "test_notebook.py":
        pytest.skip("notebook_launcher spawns its own worlds; running it "
                    "inside a launched world nests coordinators")
    if script in SMOKE_SCRIPTS:
        pytest.skip("runs in default CI via test_script_two_process_smoke")
    _require_multiprocess_backend()
    # one virtual device per process: the surface under test is the
    # 2-process world (rendezvous + cross-process collectives). Children
    # otherwise inherit pytest's 8-device XLA_FLAGS and build a 16-rank
    # gloo mesh whose loopback latency puts the heavy scripts
    # (test_performance: 18 training epochs) past any sane timeout.
    cmd = launch_command_for(bundled_script_path(script), num_processes=2,
                             extra=["--num_virtual_devices", "1"])
    out = execute_subprocess(cmd)
    # test_cli mirrors the reference's success line; everything else prints
    # the shared marker
    assert "ALL CHECKS PASSED" in out or "Successfully ran on" in out


@pytest.mark.parametrize("script", SMOKE_SCRIPTS)
def test_script_two_process_smoke(script):
    _require_multiprocess_backend()
    cmd = launch_command_for(bundled_script_path(script), num_processes=2)
    out = execute_subprocess(cmd)
    assert "ALL CHECKS PASSED" in out


def test_elastic_restart_two_process_world(tmp_path, monkeypatch):
    """--max_restarts relaunches a crashed world; the script resumes from
    its checkpoint (runs in DEFAULT CI — the elasticity surface)."""
    _require_multiprocess_backend()
    monkeypatch.setenv("ACCELERATE_TPU_TEST_STATE_DIR", str(tmp_path))
    cmd = launch_command_for(
        bundled_script_path("test_elastic_restart.py"), num_processes=2,
        extra=["--max_restarts", "1"],
    )
    out = execute_subprocess(cmd)
    assert "ALL CHECKS PASSED" in out
    assert (tmp_path / "crashed_once").exists()


def test_elastic_restart_exhausted_fails(tmp_path, monkeypatch):
    """Without restarts left, the crash propagates as a failure."""
    monkeypatch.setenv("ACCELERATE_TPU_TEST_STATE_DIR", str(tmp_path))
    cmd = launch_command_for(
        bundled_script_path("test_elastic_restart.py"), num_processes=2
    )
    with pytest.raises(RuntimeError, match="failed with code"):
        execute_subprocess(cmd)
