"""The bundled launch-and-assert scripts (ref tests/test_multigpu.py pattern,
SURVEY.md §4): each script carries rank-level asserts; here they run in the
pytest 8-device CPU world, and (slow) under `accelerate-tpu launch` with a
real 2-process jax.distributed world.
"""

import importlib.util

import pytest

from accelerate_tpu.test_utils import (
    execute_subprocess,
    launch_command_for,
    bundled_script_path,
)

SCRIPTS = [
    "test_sync.py",
    "test_ops.py",
    "test_distributed_data_loop.py",
    "test_cli.py",
    "test_notebook.py",
    "external_deps/test_checkpointing.py",
    "external_deps/test_metrics.py",
    "external_deps/test_performance.py",
    "external_deps/test_peak_memory_usage.py",
    "external_deps/test_pipeline_inference.py",
    "external_deps/test_zero3_integration.py",
]


def _run_in_process(name: str) -> None:
    spec = importlib.util.spec_from_file_location(
        name.removesuffix(".py").replace("/", "."), bundled_script_path(name)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


@pytest.mark.parametrize("script", SCRIPTS)
def test_script_in_process(script):
    _run_in_process(script)


@pytest.mark.slow
@pytest.mark.parametrize("script", SCRIPTS)
def test_script_two_process_world(script):
    if script == "test_notebook.py":
        pytest.skip("notebook_launcher spawns its own worlds; running it "
                    "inside a launched world nests coordinators")
    cmd = launch_command_for(bundled_script_path(script), num_processes=2)
    out = execute_subprocess(cmd)
    # test_cli mirrors the reference's success line; everything else prints
    # the shared marker
    assert "ALL CHECKS PASSED" in out or "Successfully ran on" in out
