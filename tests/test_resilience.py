"""Goodput-grade resilient training (ISSUE 20): step-overlapped saves,
preemption-tolerant auto-resume, straggler closed loop.

The fault-injection harness the issue asks for: every scenario asserts
loss-curve-exact continuation (resume restores step count + state, the
trajectory after the fault is identical to an unfaulted run) and the
goodput A/B quotes `StepTimer.goodput` with overlapped vs blocking saves
on the SAME schedule.
"""

from __future__ import annotations

import os
import signal
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import checkpointing as ckpt
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.profiler import StepTimer
from accelerate_tpu.training import ResilienceReport, TrainState, run_resilient

_W = 64


def _make_state():
    def apply_fn(p, x):
        return x @ p["w"]

    return TrainState.create(
        apply_fn=apply_fn,
        params={"w": jnp.eye(_W) * 0.5},
        tx=optax.adam(1e-2),
    )


def _loss(params, batch):
    return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)


@jax.jit
def _jit_step(state, batch):
    loss, grads = jax.value_and_grad(_loss)(state.params, batch)
    return state.apply_gradients(grads), {"loss": loss}


def _step_fn(state, batch):
    out = _jit_step(state, batch)
    jax.block_until_ready(out[0].params)
    return out


_X = np.random.RandomState(0).randn(8, _W).astype("float32")
_Y = np.random.RandomState(1).randn(8, _W).astype("float32")


def _batch_fn(i):
    return {"x": jnp.asarray(_X), "y": jnp.asarray(_Y)}


def _losses(num_steps):
    """The unfaulted reference trajectory."""
    state = _make_state()
    out = []
    for i in range(num_steps):
        state, m = _step_fn(state, _batch_fn(i))
        out.append(float(m["loss"]))
    return out


def test_run_resilient_plain_loop(tmp_path):
    acc = Accelerator()
    rep = run_resilient(acc, _make_state(), _step_fn, _batch_fn, 6,
                        str(tmp_path), save_every=3)
    assert isinstance(rep, ResilienceReport)
    assert rep.steps_completed == 6 and rep.resumes == 0
    assert rep.saves == 2  # one periodic + the final commit
    assert rep.last_commit_dir and ckpt.is_complete_checkpoint(
        rep.last_commit_dir)
    assert "step" in rep.taxonomy


def test_crash_auto_resume_loss_curve_exact(tmp_path):
    """A step-time crash rolls back to the last commit and the trajectory
    re-converges EXACTLY with the unfaulted run."""
    reference = _losses(8)
    acc = Accelerator()
    seen: dict[int, float] = {}
    fault = {"armed": True}

    def on_step(i, state, metrics):
        if fault["armed"] and i == 5:
            fault["armed"] = False
            raise RuntimeError("injected step-time fault")
        seen[i] = float(metrics["loss"])

    rep = run_resilient(acc, _make_state(), _step_fn, _batch_fn, 8,
                        str(tmp_path), save_every=2, on_step=on_step)
    assert rep.resumes == 1 and rep.steps_completed == 8
    for i, loss in seen.items():
        assert loss == pytest.approx(reference[i], abs=1e-6), i


def test_crash_with_nothing_committed_reraises(tmp_path):
    acc = Accelerator()

    def on_step(i, state, metrics):
        raise RuntimeError("crash before any save")

    with pytest.raises(RuntimeError, match="crash before any save"):
        run_resilient(acc, _make_state(), _step_fn, _batch_fn, 4,
                      str(tmp_path), save_every=2, on_step=on_step)


def test_max_resumes_exhausted_reraises(tmp_path):
    acc = Accelerator()

    def on_step(i, state, metrics):
        raise RuntimeError("persistent fault")

    # seed one commit so every retry has somewhere to resume from
    acc.step = 0
    acc.save_state(os.path.join(str(tmp_path), "step_00000000"),
                   state=_make_state())
    with pytest.raises(RuntimeError, match="persistent fault"):
        run_resilient(acc, _make_state(), _step_fn, _batch_fn, 4,
                      str(tmp_path), save_every=2, max_resumes=2,
                      on_step=on_step)


def test_sigterm_drains_then_saves(tmp_path):
    """SIGTERM mid-run: finish the in-flight step, commit a resume point,
    hand the machine back; the relaunch continues to completion on the
    exact reference trajectory."""
    reference = _losses(10)
    acc = Accelerator()
    prev_handler = signal.getsignal(signal.SIGTERM)

    def send_sigterm(i, state, metrics):
        if i == 4:
            os.kill(os.getpid(), signal.SIGTERM)

    rep = run_resilient(acc, _make_state(), _step_fn, _batch_fn, 10,
                        str(tmp_path), save_every=100, on_step=send_sigterm)
    assert rep.preempted and rep.saves == 1
    assert rep.steps_completed == 5  # step 4 finished, then drained
    assert signal.getsignal(signal.SIGTERM) is prev_handler  # restored

    seen: dict[int, float] = {}
    rep2 = run_resilient(
        acc, _make_state(), _step_fn, _batch_fn, 10, str(tmp_path),
        save_every=100,
        on_step=lambda i, s, m: seen.__setitem__(i, float(m["loss"])))
    assert rep2.start_step == 5 and not rep2.preempted
    assert sorted(seen) == list(range(5, 10))
    for i, loss in seen.items():
        assert loss == pytest.approx(reference[i], abs=1e-6), i


def _timed_run(tmp_path, blocking: bool, label: str) -> ResilienceReport:
    acc = Accelerator()
    timer = StepTimer(warmup_steps=1, name=f"goodput_{label}")

    def slow_step(state, batch):
        out = _jit_step(state, batch)
        jax.block_until_ready(out[0].params)
        time.sleep(0.06)  # a 60ms device step the host can't observe
        return out

    return run_resilient(
        acc, _make_state(), slow_step, _batch_fn, 10,
        os.path.join(str(tmp_path), label), save_every=3, timer=timer,
        blocking_saves=blocking)


def test_goodput_async_vs_blocking(tmp_path):
    """THE acceptance number: on the same save schedule, step-overlapped
    saves keep goodput >= 0.9 while blocking saves sit measurably lower
    (the full sync write lands inside the step window)."""
    ckpt.warm_async_checkpointer()  # one-time writer setup, outside the A/B
    rep_async = _timed_run(tmp_path, blocking=False, label="overlapped")
    if rep_async.goodput < 0.9:  # one retry: absorb a transient load spike
        rep_async = _timed_run(tmp_path, blocking=False, label="overlapped2")
    rep_block = _timed_run(tmp_path, blocking=True, label="blocking")
    assert rep_async.goodput >= 0.9, rep_async.taxonomy
    assert rep_block.goodput < rep_async.goodput - 0.05, (
        rep_async.goodput, rep_block.goodput, rep_block.taxonomy)
    # the taxonomy attributes where the blocking run's time went
    assert rep_block.taxonomy.get("checkpoint", 0.0) > \
        rep_async.taxonomy.get("checkpoint_stage", 0.0)


def test_resume_latest_empty_dir_is_fresh_start(tmp_path):
    acc = Accelerator()
    assert acc.resume_latest(str(tmp_path)) is None


def test_resume_latest_skips_torn_save(tmp_path):
    """A later save whose manifest never committed is invisible: resume
    picks the older COMPLETE checkpoint."""
    acc = Accelerator()
    state = _make_state()
    good = os.path.join(str(tmp_path), "step_00000002")
    acc.step = 2
    acc.save_state(good, state=state)
    torn = os.path.join(str(tmp_path), "step_00000004")
    acc.step = 4
    acc.save_state(torn, state=state)
    os.remove(os.path.join(torn, ckpt.MANIFEST_NAME))  # crash before commit
    restored = acc.resume_latest(str(tmp_path), state=state)
    assert restored is not None
    assert restored["checkpoint_dir"] == os.path.abspath(good)
    assert restored["step"] == 2 and acc.step == 2


def test_async_save_commits_only_after_drain(tmp_path):
    acc = Accelerator()
    target = os.path.join(str(tmp_path), "step_00000001")
    acc.step = 1
    acc.save_state(target, state=_make_state(), async_save=True)
    acc.wait_for_checkpoints()
    assert ckpt.is_complete_checkpoint(target)
    restored = acc.resume_latest(str(tmp_path), state=_make_state())
    assert restored is not None and restored["step"] == 1


def test_prune_checkpoints_never_deletes_newest(tmp_path):
    acc = Accelerator()
    state = _make_state()
    for s in (1, 2, 3):
        acc.step = s
        acc.save_state(os.path.join(str(tmp_path), f"step_{s:08d}"),
                       state=state)
    removed = ckpt.prune_checkpoints(str(tmp_path), keep_last_n=1)
    assert len(removed) == 2
    assert ckpt.latest_complete_checkpoint(
        str(tmp_path)).endswith("step_00000003")


def test_stall_taxonomy_buckets():
    timer = StepTimer(warmup_steps=0, name="taxonomy")
    timer.tick()
    with timer.input_stall():
        time.sleep(0.01)
    with timer.overhead("checkpoint_stage"):
        time.sleep(0.01)
    timer.tick()
    timer.note_lost("straggler", 0.5)
    tax = timer.stall_taxonomy()
    assert tax["input"] >= 0.01
    assert tax["checkpoint_stage"] >= 0.01
    assert tax["straggler"] == pytest.approx(0.5)
    assert tax["step"] >= 0.0


def test_straggler_monitor_closed_loop(tmp_path):
    from accelerate_tpu.telemetry.registry import MetricsRegistry
    from accelerate_tpu.telemetry.straggler import StragglerMonitor

    reg = MetricsRegistry()
    fired = []
    timer = StepTimer(warmup_steps=0, name="straggler_timer")
    mon = StragglerMonitor("step_time_seconds", ratio_threshold=1.5,
                           patience=2, registry=reg,
                           incident_dir=str(tmp_path),
                           on_straggler=fired.append, timer=timer)

    def agg(slowest, mean=0.010):
        return {"num_hosts": 4, "histograms": {"step_time_seconds": {
            "count": 64.0, "mean": mean, "slowest_host_mean": slowest}}}

    timer.tick()
    timer.tick()  # taxonomy is empty until a step interval records

    assert mon.observe(agg(0.011)) is None          # healthy
    assert mon.observe(agg(0.020)) is None          # strike 1
    report = mon.observe(agg(0.020))                # strike 2: fires once
    assert report is not None and fired == [report]
    assert report["kind"] == "straggler"
    assert report["ratio"] == pytest.approx(2.0)
    assert os.path.isdir(report["bundle_path"])
    assert mon.observe(agg(0.020)) is None          # same episode: silent
    # the lost time was attributed into the goodput taxonomy
    assert timer.stall_taxonomy().get("straggler", 0.0) > 0.0
    assert mon.observe(agg(0.010)) is None          # recovers: re-arms
    assert mon.observe(agg(0.030)) is None
    assert mon.observe(agg(0.030)) is not None      # fresh episode fires
    assert reg.counter("straggler_incidents_total").value == 2.0


def test_straggler_monitor_rejects_bad_threshold():
    from accelerate_tpu.telemetry.straggler import StragglerMonitor

    with pytest.raises(ValueError):
        StragglerMonitor(ratio_threshold=1.0)


def test_run_resilient_restart_on_straggler(tmp_path):
    """A persistent straggler past threshold requests an elastic drain:
    the loop commits a resume point and reports preempted."""
    from accelerate_tpu.telemetry.registry import MetricsRegistry
    from accelerate_tpu.telemetry.straggler import StragglerMonitor

    reg = MetricsRegistry()
    mon = StragglerMonitor("step_time_seconds", ratio_threshold=1.5,
                           patience=1, registry=reg,
                           incident_dir=str(tmp_path))
    # a single-host poll can never see slowest_host > fleet mean, so feed
    # the monitor a 4-host aggregate where one host runs 3x slow
    mon.poll = lambda: mon.observe({
        "num_hosts": 4,
        "histograms": {"step_time_seconds": {
            "count": 64.0, "mean": 0.01, "slowest_host_mean": 0.03}}})
    acc = Accelerator()
    rep = run_resilient(acc, _make_state(), _step_fn, _batch_fn, 12,
                        os.path.join(str(tmp_path), "ck"), save_every=100,
                        straggler_monitor=mon, poll_every=2,
                        restart_on_straggler=True)
    assert rep.preempted and rep.incidents
    assert rep.incidents[0]["kind"] == "straggler"
    assert ckpt.latest_complete_checkpoint(
        os.path.join(str(tmp_path), "ck")) is not None
