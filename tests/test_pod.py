"""Pod-scale serving (serving/pod): SPMD mesh sharding + MPMD
disaggregation.

CPU contracts on the virtual mesh: the mesh-sharded engine and the
disaggregated prefill->decode pod are byte-identical to the
single-device engine on the same seeded trace; per-role compile counts
stay flat (incl. the extract/install shipping programs); backpressure
stalls the router, never a prefill worker; the HTTP front door runs
unchanged over a pod engine; and the forced-host-device subprocess
harness proves the same exactness with the WHOLE backend at N=2 and N=4
devices (the ISSUE 9 acceptance shape)."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.analysis.contracts import (
    pod_program_contracts,
    serving_program_contracts,
)
from accelerate_tpu.models import gpt2, llama
from accelerate_tpu.serving import Engine, EngineConfig, RequestStatus
from accelerate_tpu.serving.pod import (
    KVPageShipment,
    PodConfig,
    PodEngine,
    cache_state_shardings,
    shard_params,
    sharded_engine,
    tensor_mesh,
)


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    """Every engine/pod here compiles the same tiny programs; the
    persistent compilation cache turns the repeats into deserializes
    (same fixture as tests/test_serving.py — fresh tmp dir, so the
    sub-second-entry segfault documented in conftest.py can't poison
    later runs)."""
    from accelerate_tpu.utils.environment import configure_compilation_cache

    cache_dir = str(tmp_path_factory.mktemp("xla_cache"))
    prev = {k: os.environ.get(k)
            for k in ("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS",
                      "ACCELERATE_TPU_COMPILATION_CACHE")}
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    # exported: the forced-device children (pod_exactness_script at N=2
    # then N=4) opt in via configure_compilation_cache() and share this
    # dir — the single-device reference programs compile once across
    # both runs instead of once per child (tier-1 budget)
    os.environ["ACCELERATE_TPU_COMPILATION_CACHE"] = cache_dir
    configure_compilation_cache(cache_dir, force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (ISSUE 16 hit this the moment
    # an engine module sorted before test_launched_scripts)
    for k, v in prev.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _ec(**overrides):
    defaults = dict(num_slots=3, max_len=64, prefill_chunk=8,
                    cache_dtype=jnp.float32)
    defaults.update(overrides)
    return EngineConfig(**defaults)


def _run_trace(engine, cfg, budgets=(6, 6, 4, 4), temps=(0.0, 0.7, 0.0, 1.1)):
    """Seeded staggered mix, identical for every engine flavor."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 11, 3, 17)]
    reqs = [engine.submit(prompts[0], max_new_tokens=budgets[0],
                          temperature=temps[0])]
    for _ in range(3):
        engine.step()
    for p, b, t in zip(prompts[1:], budgets[1:], temps[1:]):
        reqs.append(engine.submit(p, max_new_tokens=b, temperature=t))
    engine.run_until_idle()
    return reqs


# ---------------------------------------------------------------------------
# contracts + config units (model-free)
# ---------------------------------------------------------------------------


def test_pod_config_validates_roles():
    with pytest.raises(ValueError, match="at least one worker"):
        PodConfig(prefill_workers=0)
    with pytest.raises(ValueError, match="at least one worker"):
        PodConfig(decode_workers=0)
    with pytest.raises(ValueError, match="tensor_parallel"):
        PodConfig(tensor_parallel=0)


def test_pod_program_contracts_pin_the_new_collectives():
    """The sharded programs must REQUIRE communication where the
    single-device contract forbade it — the 'no collectives' promise is
    explicitly not carried over (ISSUE 9 satellite)."""
    pod = pod_program_contracts(num_layers=2)
    single = serving_program_contracts()
    assert set(pod) == {"admit", "prefill", "decode", "extract", "install"}
    # admit stays collective-free even sharded (per-slot scalars)
    assert pod["admit"].exhaustive and "all-reduce" in pod["admit"].forbid
    for name in ("prefill", "decode"):
        c = pod[name]
        assert ("all-reduce", "reduce-scatter") in c.require
        assert dict(c.at_least)["all-reduce"] == 2
        assert "all-to-all" in c.forbid
        # a program satisfying the single-device contract (no
        # collectives at all) VIOLATES the pod contract, and vice versa
        assert single[name].check("add(f32[] a, f32[] b)") == []
        assert c.check("add(f32[] a, f32[] b)") != []
    for name in ("extract", "install"):
        assert "all-reduce" in pod[name].forbid


def test_shipment_page_bytes_counts_prompt_pages_only():
    ship = KVPageShipment(
        prompt=np.arange(20, dtype=np.int32), first_token=1,
        n_prompt_pages=2,
        k_pages=np.zeros((1, 5, 8, 2, 4), np.float32),
        v_pages=np.zeros((1, 5, 8, 2, 4), np.float32),
        key_raw=np.zeros((2,), np.uint32), temperature=0.0,
        max_new_tokens=4, eos_token_id=None)
    per_page = 2 * 1 * 8 * 2 * 4 * 4  # k+v, L*ps*H*D * itemsize
    assert ship.page_bytes == 2 * per_page


# ---------------------------------------------------------------------------
# layer 1: mesh-sharded engine
# ---------------------------------------------------------------------------


def test_sharded_engine_token_exact_and_compile_flat(gpt2_setup):
    """The N=2 mesh engine reproduces the single-device token streams
    byte for byte — greedy AND sampled — through exactly one compile per
    program, with strict="error" proving the pod contract audit passes
    on every sharded lowering."""
    cfg, params = gpt2_setup
    ref = [r.tokens for r in _run_trace(Engine(gpt2, cfg, params, _ec()),
                                        cfg)]
    eng = sharded_engine(gpt2, cfg, params, _ec(strict="error"),
                         mesh=tensor_mesh(2))
    got = [r.tokens for r in _run_trace(eng, cfg)]
    assert got == ref
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}


def test_sharded_engine_nondividing_heads_stays_compile_flat():
    """GQA regression: llama-tiny has 2 KV heads — on a 4-device mesh the
    pool can't shard over heads and replicates. Without the engine's
    out_shardings pin GSPMD never converged on an output layout and the
    decode compile count crept per step (measured: 13 compiles for one
    short trace); the pin holds it at one."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    ref_eng = Engine(llama, cfg, params, _ec(num_slots=2))
    ref = [r.tokens for r in _run_trace(ref_eng, cfg, budgets=(5, 5, 3, 3))]
    eng = sharded_engine(llama, cfg, params, _ec(num_slots=2),
                         mesh=tensor_mesh(4))
    got = [r.tokens for r in _run_trace(eng, cfg, budgets=(5, 5, 3, 3))]
    assert got == ref
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}


def test_sharded_engine_one_device_mesh_degrades_to_single(gpt2_setup):
    """A 1-device 'mesh' IS single-device serving: sharded_engine with
    tensor_parallel=1 (a single-chip host) must serve under
    strict='error' instead of tripping the meshed audit, which demands
    sharded args and TP reductions a lone chip can never have (review
    find: this crashed with ATP101 before the normalization)."""
    cfg, params = gpt2_setup
    ref = Engine(gpt2, cfg, params, _ec())
    rng = np.random.default_rng(31)
    p = rng.integers(0, cfg.vocab_size, (7,)).astype(np.int32)
    r0 = ref.submit(p, max_new_tokens=5)
    ref.run_until_idle()
    eng = sharded_engine(gpt2, cfg, params, _ec(strict="error"),
                         tensor_parallel=1)
    assert eng.engine_config.mesh is None  # normalized away
    r1 = eng.submit(p, max_new_tokens=5)
    eng.run_until_idle()
    assert r1.tokens == r0.tokens
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}


def test_allocator_rollback_inverts_allocate(gpt2_setup):
    """PagedAllocator.rollback: the adopt-race path's inverse-of-allocate
    must restore the pool and the prefix books exactly (no leak, no
    double-free, counters unwound)."""
    from accelerate_tpu.serving import PagedAllocator
    from accelerate_tpu.serving.scheduler import Request

    alloc = PagedAllocator(page_size=4, num_pages=16)
    req = Request(prompt=np.arange(10, dtype=np.int32), max_new_tokens=4)
    before = (alloc.pages_free, alloc.lookups, alloc.hits,
              alloc.tokens_reused, alloc.index.mapped_pages)
    a = alloc.allocate(req)
    assert a is not None and alloc.pages_free < before[0]
    alloc.rollback(a)
    assert (alloc.pages_free, alloc.lookups, alloc.hits,
            alloc.tokens_reused, alloc.index.mapped_pages) == before


def test_cache_state_shardings_spec_shapes(gpt2_setup):
    cfg, params = gpt2_setup
    eng = Engine(gpt2, cfg, params, _ec())
    mesh = tensor_mesh(2)
    cache_sh, rep = cache_state_shardings(eng.cache, mesh)
    assert cache_sh.k.spec == jax.sharding.PartitionSpec(
        None, None, None, "model")
    assert rep.spec == jax.sharding.PartitionSpec()
    # non-dividing heads (gpt2-tiny has 4): a 3-device mesh replicates
    cache_sh3, _ = cache_state_shardings(eng.cache, tensor_mesh(3))
    assert cache_sh3.k.spec == jax.sharding.PartitionSpec()


def test_single_engine_strict_still_rejects_leaked_mesh_params(gpt2_setup):
    """The ATP101 placement check kept its old teeth: params on a mesh
    WITHOUT EngineConfig(mesh=...) is still a strict-mode violation."""
    from accelerate_tpu.analysis import AnalysisViolation

    cfg, params = gpt2_setup
    placed = shard_params(params, tensor_mesh(2))
    eng = Engine(gpt2, cfg, placed, _ec(strict="error"))
    with pytest.raises(AnalysisViolation, match="ATP101"):
        _run_trace(eng, cfg)


# ---------------------------------------------------------------------------
# layer 2: disaggregated pod
# ---------------------------------------------------------------------------


def test_pod_token_exact_vs_single_engine(gpt2_setup):
    """2 prefill + 2 decode workers shipping KV pages reproduce the
    single engine's streams byte for byte on the same seeded trace —
    including sampled temperatures (the router mirrors the engine's
    key-derivation) — with per-role compile counts flat at one."""
    cfg, params = gpt2_setup
    ref = [r.tokens for r in _run_trace(Engine(gpt2, cfg, params, _ec()),
                                        cfg)]
    pod = PodEngine(gpt2, cfg, params, _ec(),
                    PodConfig(prefill_workers=2, decode_workers=2))
    reqs = _run_trace(pod, cfg)
    assert [r.tokens for r in reqs] == ref
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    assert pod.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1,
                                   "extract": 1, "install": 1}
    s = pod.metrics_summary()
    assert s["pod_shipments"] == 4.0
    assert s["pod_pages_shipped"] >= 4.0
    assert s["requests_finished"] == 4.0


def test_pod_budget_one_and_eos_finish_at_prefill(gpt2_setup):
    """A request done at its first token (budget 1, or EOS immediately)
    finishes at the prefill worker — nothing ships."""
    cfg, params = gpt2_setup
    ref_eng = Engine(gpt2, cfg, params, _ec())
    rng = np.random.default_rng(3)
    p = rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32)
    r_ref = ref_eng.submit(p, max_new_tokens=1)
    ref_eng.run_until_idle()

    pod = PodEngine(gpt2, cfg, params, _ec())
    r = pod.submit(p, max_new_tokens=1)
    pod.run_until_idle()
    assert r.status is RequestStatus.FINISHED
    assert r.tokens == r_ref.tokens
    assert pod.metrics_summary()["pod_shipments"] == 0.0

    # EOS on the first token: same short-circuit, same exact token
    r2 = pod.submit(p, max_new_tokens=8, eos_token_id=r_ref.tokens[0])
    pod.run_until_idle()
    assert r2.status is RequestStatus.FINISHED
    assert r2.tokens == r_ref.tokens
    assert pod.metrics_summary()["pod_shipments"] == 0.0


def test_pod_worker_drop_carries_shed_code(gpt2_setup):
    """ATP212 regression (ISSUE 13 self-lint finding): when a prefill
    worker drops an internal (the defensive wedge path), the user's
    EXPIRED terminal must carry the machine-readable shed_code and a
    retry hint — this path previously shipped prose only, invisible to
    shed accounting."""
    from accelerate_tpu.serving.scheduler import SHED_WORKER_DROP

    cfg, params = gpt2_setup
    pod = PodEngine(gpt2, cfg, params, _ec(prefill_chunk=4))
    rng = np.random.default_rng(11)
    p = rng.integers(0, cfg.vocab_size, (17,)).astype(np.int32)
    user = pod.submit(p, max_new_tokens=6)
    flight = pod._flights[id(user)]
    assert flight.phase == "prefill"
    # simulate a worker-side wedge: the internal dies mid-prefill (the
    # router's harvest must also clean up the admit-hook page snapshot
    # — the step-end sanitizer validates that)
    assert pod.prefill_workers[flight.worker].cancel(flight.internal)
    pod.step()
    assert user.status is RequestStatus.EXPIRED
    assert user.shed_code == SHED_WORKER_DROP
    assert user.retry_after_s is not None
    assert pod.metrics_summary()["requests_expired"] == 1.0
    # the flight is gone and the pod keeps serving
    assert id(user) not in pod._flights
    r2 = pod.submit(p, max_new_tokens=3)
    pod.run_until_idle()
    assert r2.status is RequestStatus.FINISHED


def test_pod_backpressure_stalls_router_not_prefill(gpt2_setup):
    """With a single decode slot and a shipment buffer of one, a burst
    of prompts must (a) still finish token-exact, (b) record
    backpressure stalls, and (c) keep the prefill side working ahead —
    the stall parks shipments at the router; it never wedges."""
    cfg, params = gpt2_setup
    ec = _ec(num_slots=1, max_queue=16)
    ref_eng = Engine(gpt2, cfg, params, dataclasses.replace(ec, num_slots=3))
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (6, 9, 4, 11)]
    ref = []
    for p in prompts:
        r = ref_eng.submit(p, max_new_tokens=5)
        ref_eng.run_until_idle()
        ref.append(r.tokens)

    pod = PodEngine(gpt2, cfg, params, ec,
                    PodConfig(prefill_workers=1, decode_workers=1,
                              prefill_slots=3, max_pending_shipments=1))
    reqs = [pod.submit(p, max_new_tokens=5) for p in prompts]
    pod.run_until_idle()
    assert [r.tokens for r in reqs] == ref
    assert pod.metrics_summary()["pod_backpressure_stalls"] > 0
    assert pod.metrics_summary()["pod_shipments"] == 4.0


def test_pod_cancel_everywhere(gpt2_setup):
    """Cancel is honored in every flight phase: front-queued, decoding,
    and the handle reports CANCELLED with pages freed."""
    cfg, params = gpt2_setup
    ec = _ec(num_slots=1, max_queue=8)
    pod = PodEngine(gpt2, cfg, params, ec,
                    PodConfig(prefill_workers=1, decode_workers=1))
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (6, 7, 8)]
    a = pod.submit(prompts[0], max_new_tokens=16)
    b = pod.submit(prompts[1], max_new_tokens=16)
    c = pod.submit(prompts[2], max_new_tokens=16)
    # drive until a is decoding
    for _ in range(40):
        pod.step()
        if a.tokens:
            break
    assert a.tokens, "a never reached decode"
    assert pod.cancel(c)          # still queued/parked
    assert pod.cancel(a)          # mid-decode
    assert not pod.cancel(a)      # idempotent
    pod.run_until_idle()
    assert a.status is RequestStatus.CANCELLED
    assert c.status is RequestStatus.CANCELLED
    assert b.status is RequestStatus.FINISHED and len(b.tokens) == 16
    # every worker drained: all pages back except prefix-tree cached ones
    for w in pod.decode_workers + pod.prefill_workers:
        assert w.scheduler.live_slots == 0
    s = pod.metrics_summary()
    assert s["requests_cancelled"] == 2.0
    assert s["requests_finished"] == 1.0


def test_pod_finish_early_is_finished(gpt2_setup):
    """The server's stop-sequence path: finish() retires a decoding
    request as FINISHED with the tokens delivered so far."""
    cfg, params = gpt2_setup
    pod = PodEngine(gpt2, cfg, params, _ec())
    rng = np.random.default_rng(11)
    p = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    r = pod.submit(p, max_new_tokens=24)
    for _ in range(60):
        pod.step()
        if len(r.tokens) >= 3:
            break
    assert len(r.tokens) >= 3
    assert pod.finish(r)
    assert r.status is RequestStatus.FINISHED
    assert pod.metrics_summary()["requests_finished"] == 1.0
    pod.run_until_idle()


def test_pod_stream_matches_handle(gpt2_setup):
    cfg, params = gpt2_setup
    ref_eng = Engine(gpt2, cfg, params, _ec())
    rng = np.random.default_rng(13)
    p = rng.integers(0, cfg.vocab_size, (7,)).astype(np.int32)
    r_ref = ref_eng.submit(p, max_new_tokens=6)
    ref_eng.run_until_idle()

    pod = PodEngine(gpt2, cfg, params, _ec())
    r = pod.submit(p, max_new_tokens=6)
    streamed = list(pod.stream(r))
    assert streamed == r.tokens == r_ref.tokens


def test_pod_rejects_and_sheds_like_an_engine(gpt2_setup):
    """Admission control stays at the front door: over-long requests
    REJECT with the engine's shed vocabulary, and queue overflow carries
    retry_after_s — no pod internals leak into the failure surface."""
    cfg, params = gpt2_setup
    ec = _ec(max_queue=1, num_slots=1)
    pod = PodEngine(gpt2, cfg, params, ec,
                    PodConfig(prefill_workers=1, decode_workers=1,
                              prefill_slots=1, max_pending_shipments=1))
    too_long = pod.submit(np.arange(60, dtype=np.int32) % cfg.vocab_size,
                          max_new_tokens=32)
    assert too_long.status is RequestStatus.REJECTED
    assert too_long.shed_code == "too_long"
    rng = np.random.default_rng(17)
    keep = []
    rejected = []
    for _ in range(8):
        r = pod.submit(rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32),
                       max_new_tokens=8)
        (rejected if r.status is RequestStatus.REJECTED else keep).append(r)
    assert rejected, "queue bound never bit"
    assert all(r.shed_code == "queue_full" for r in rejected)
    assert all(r.retry_after_s is not None for r in rejected)
    pod.run_until_idle()
    assert all(r.status is RequestStatus.FINISHED for r in keep)


def test_pod_debug_views(gpt2_setup):
    cfg, params = gpt2_setup
    pod = PodEngine(gpt2, cfg, params, _ec(),
                    PodConfig(prefill_workers=1, decode_workers=2))
    rng = np.random.default_rng(19)
    for n in (5, 8):
        pod.submit(rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32),
                   max_new_tokens=4)
    pod.run_until_idle()
    dp = pod.debug_pod()
    assert [w["worker"] for w in dp["roles"]["decode"]] == [0, 1]
    assert dp["shipments_total"] == 2
    assert dp["pages_shipped_total"] >= 2
    assert dp["in_flight"] == {}
    slots = pod.debug_slots()
    assert {e["role"] for e in slots} == {"prefill", "decode"}
    pages = pod.debug_pages()
    assert pages["pages_shipped"] >= 2
    assert len(pages["workers"]) == 3
    sched = pod.debug_scheduler()
    assert sched["pod"]["in_flight"] == 0
    import json

    json.dumps({"pod": dp, "slots": slots, "pages": pages, "sched": sched})


def test_pod_page_transfer_span_joins_request_trace(gpt2_setup):
    """The shipping hop is visible in the request's trace: a
    serving.page_transfer span parented on the request root, carrying
    the page count (ISSUE 9 telemetry satellite)."""
    from accelerate_tpu.telemetry.trace import configure_tracing, trace_events

    cfg, params = gpt2_setup
    configure_tracing(enabled=True, annotate=False)
    try:
        pod = PodEngine(gpt2, cfg, params, _ec())
        rng = np.random.default_rng(23)
        p = rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32)
        r = pod.submit(p, max_new_tokens=4)
        pod.run_until_idle()
        assert r.trace_id is not None
        events = trace_events(r.trace_id)
        names = [e["name"] for e in events]
        assert "serving.page_transfer" in names
        assert "serving.queue_wait" in names
        assert "serving.request" in names
        hop = next(e for e in events if e["name"] == "serving.page_transfer")
        root = next(e for e in events if e["name"] == "serving.request")
        assert hop["attrs"]["pages"] >= 1
        assert hop["parent_id"] == root["span_id"]
    finally:
        configure_tracing(enabled=False, sample_rates={},
                          default_sample_rate=1.0)


def test_pod_role_metrics_exported(gpt2_setup):
    """The pod registry carries the satellite series: shipment counters
    and per-role occupancy gauges, visible to any exporter."""
    cfg, params = gpt2_setup
    pod = PodEngine(gpt2, cfg, params, _ec())
    rng = np.random.default_rng(29)
    pod.submit(rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32),
               max_new_tokens=4)
    pod.run_until_idle()
    series = {(name, dict(labels).get("role"))
              for kind, name, labels, _ in pod.registry.items()}
    assert ("serving_pod_pages_shipped_total", None) in series
    assert ("serving_pod_role_occupancy", "prefill") in series
    assert ("serving_pod_role_occupancy", "decode") in series
    assert ("serving_pod_pending_shipments", None) in series


# ---------------------------------------------------------------------------
# the HTTP front door runs unchanged over a pod
# ---------------------------------------------------------------------------


def test_http_front_door_over_pod_engine(gpt2_setup):
    """The PR 6 server stack — protocol, SSE streaming, debug gating —
    drives a PodEngine exactly like a single engine: one streaming
    completion returns the pod's byte stream, /debug/pod serves router
    state when gated on, and 404s for EVERY method when off."""
    import asyncio
    import json

    from accelerate_tpu.server.config import ServerConfig
    from accelerate_tpu.server.http import HttpFrontDoor
    from accelerate_tpu.server.service import InferenceService
    from accelerate_tpu.server.tokenizer import get_tokenizer

    cfg, params = gpt2_setup
    ref_eng = Engine(gpt2, cfg, params, _ec())
    prompt = list(range(1, 8))
    r_ref = ref_eng.submit(np.asarray(prompt, np.int32), max_new_tokens=5)
    ref_eng.run_until_idle()

    pod = PodEngine(gpt2, cfg, params, _ec())
    scfg = ServerConfig(port=0, model_id="pod-test", tokenizer="numeric",
                        debug_endpoints=True)
    service = InferenceService(
        pod, get_tokenizer("numeric", cfg.vocab_size), scfg)
    door = HttpFrontDoor(service, scfg)

    async def req(port, verb, path, body=None):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        payload = json.dumps(body).encode() if body is not None else b""
        writer.write(
            f"{verb} {path} HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
        await writer.drain()
        head = await reader.readuntil(b"\r\n\r\n")
        status = int(head.split(b" ")[1])
        data = await reader.read()
        writer.close()
        return status, data

    async def drive():
        await door.start()
        port = door.port
        status, data = await req(
            port, "POST", "/v1/completions",
            {"prompt": prompt, "max_tokens": 5, "temperature": 0,
             "stream": True})
        assert status == 200
        ids = []
        for frame in data.split(b"\n\n"):
            if frame.startswith(b"data: ") and b"[DONE]" not in frame:
                row = json.loads(frame[len(b"data: "):])
                ids += row["choices"][0].get("token_ids", [])
        status, body = await req(port, "GET", "/debug/pod")
        assert status == 200
        dbg = json.loads(body.partition(b"\r\n\r\n")[0] or body)
        await door.stop()
        return ids, dbg

    ids, dbg = asyncio.run(drive())
    assert ids == r_ref.tokens
    assert dbg["shipments_total"] >= 1
    assert "roles" in dbg

    # gate off: 404 for every method, pod or not (fingerprint-proof)
    scfg_off = ServerConfig(port=0, model_id="pod-test", tokenizer="numeric",
                            debug_endpoints=False)
    service2 = InferenceService(
        pod, get_tokenizer("numeric", cfg.vocab_size), scfg_off)
    door2 = HttpFrontDoor(service2, scfg_off)

    async def gate():
        await door2.start()
        out = [(await req(door2.port, verb, "/debug/pod"))[0]
               for verb in ("GET", "POST", "HEAD")]
        await door2.stop()
        return out

    assert asyncio.run(gate()) == [404, 404, 404]
    pod.close()


# ---------------------------------------------------------------------------
# forced-host-device acceptance (subprocess, N=2 and N=4)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_devices", [2, 4])
def test_pod_exactness_under_forced_devices(forced_device_run, n_devices):
    """The ISSUE 9 acceptance: in a process whose ENTIRE backend is N
    forced host devices, the mesh-sharded engine (strict audit on) and
    the disaggregated TP-N pod both reproduce the single-device token
    streams byte for byte, compile-flat (see pod_exactness_script.py)."""
    script = os.path.join(os.path.dirname(__file__),
                          "pod_exactness_script.py")
    out = forced_device_run(script, n_devices, args=(n_devices,),
                            timeout=420)
    assert "POD_EXACTNESS_OK" in out


# ---------------------------------------------------------------------------
# ISSUE 10: int8 KV shipments + page-dim pool sharding
# ---------------------------------------------------------------------------


def test_pod_int8_shipments_byte_identical_to_single_engine(gpt2_setup):
    """kv_dtype="int8" through the pod: every worker's pool quantizes
    and shipments carry codes + scales verbatim (no dequant/requant
    round-trip that would drift the codes) — pod output matches the
    single int8 engine byte for byte, with the kernel-backed decode
    worker variant too."""
    cfg, params = gpt2_setup
    ref = [r.tokens for r in _run_trace(
        Engine(gpt2, cfg, params, _ec(kv_dtype="int8")), cfg)]
    for pa in (False, True):
        pod = PodEngine(gpt2, cfg, params,
                        _ec(kv_dtype="int8", paged_attention=pa),
                        PodConfig(prefill_workers=1, decode_workers=1))
        reqs = _run_trace(pod, cfg)
        assert [r.tokens for r in reqs] == ref, f"paged_attention={pa}"
        assert pod.metrics_summary()["pod_shipments"] == 4.0


def test_shipment_page_bytes_halve_under_int8():
    """The wire-bytes claim: an int8 shipment's page_bytes are the code
    bytes (half of bf16) plus the scale blocks — (D+2)/2D of the bf16
    payload for the same page geometry."""
    L, P, ps, H, D = 1, 5, 8, 2, 4
    common = dict(prompt=np.arange(20, dtype=np.int32), first_token=1,
                  n_prompt_pages=2, key_raw=np.zeros((2,), np.uint32),
                  temperature=0.0, max_new_tokens=4, eos_token_id=None)
    bf16 = KVPageShipment(
        k_pages=np.zeros((L, P, ps, H, D), np.dtype("bfloat16")
                         if hasattr(np, "bfloat16") else np.float16),
        v_pages=np.zeros((L, P, ps, H, D), np.float16), **common)
    i8 = KVPageShipment(
        k_pages=np.zeros((L, P, ps, H, D), np.int8),
        v_pages=np.zeros((L, P, ps, H, D), np.int8),
        k_scales=np.zeros((L, P, ps, H), np.float16),
        v_scales=np.zeros((L, P, ps, H), np.float16), **common)
    assert i8.page_bytes / bf16.page_bytes == (D + 2) / (2 * D)


def test_pool_page_dim_sharding_when_heads_dont_divide():
    """ISSUE 10 satellite (pod GQA follow-up from PR 9): llama-tiny's 2
    KV heads don't divide a 4-wide mesh — the pool used to fully
    replicate per chip. With a page count the mesh divides (pages+1 %
    n == 0) it now shards over the PAGE dim instead, stays token-exact,
    and holds the compile count; when neither dim divides it still
    falls back to replication (the old behavior, pinned by
    test_sharded_engine_nondividing_heads_stays_compile_flat)."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    ref_eng = Engine(llama, cfg, params, _ec(num_slots=2, num_pages=11))
    ref = [r.tokens for r in _run_trace(ref_eng, cfg, budgets=(5, 5, 3, 3))]
    # pages+1 = 12 divides the 4-wide mesh -> page-dim sharded pool
    eng = sharded_engine(llama, cfg, params,
                         _ec(num_slots=2, num_pages=11),
                         mesh=tensor_mesh(4))
    assert tuple(eng.cache.k.sharding.spec) == (None, "model")
    got = [r.tokens for r in _run_trace(eng, cfg, budgets=(5, 5, 3, 3))]
    assert got == ref
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}
    # replicate fallback: neither heads (2) nor pages+1 (11) divide 4
    fallback = cache_state_shardings(
        Engine(llama, cfg, params, _ec(num_slots=2, num_pages=10)).cache,
        tensor_mesh(4))[0]
    assert fallback.k.is_fully_replicated


def test_contract_factories_name_paged_kernel_variant():
    """ISSUE 10: both contract factories gain the kernel-backed decode
    variant — same clauses (a pallas custom call is chip-local, not a
    collective), distinct name so audit reports say which decode flavor
    they checked. A kernel-backed engine under strict mode resolves to
    the variant automatically (pinned by
    test_paged_kernel_gqa_and_slot_reuse_token_exact's strict=error)."""
    plain = serving_program_contracts()
    kern = serving_program_contracts(paged_kernel=True)
    assert kern["decode"].name == "serving.decode.paged-kernel"
    assert plain["decode"].name == "serving.decode"
    assert kern["decode"].forbid == plain["decode"].forbid
    assert kern["decode"].exhaustive
    pod_kern = pod_program_contracts(num_layers=2, paged_kernel=True)
    assert pod_kern["decode"].name == "serving.pod.decode.paged-kernel"
    assert pod_kern["decode"].require == pod_program_contracts(
        num_layers=2)["decode"].require


def test_pod_logprobs_ride_shipments(gpt2_setup):
    """ISSUE 12: per-token logprobs survive disaggregation — the first
    token's logprob rides the KVPageShipment, later ones mirror from the
    decode worker, so the pod's user-facing handle carries the same
    logprobs (index-aligned with its tokens) as the single engine."""
    cfg, params = gpt2_setup
    ref_eng = Engine(gpt2, cfg, params, _ec())
    ref = _run_trace(ref_eng, cfg)
    pod = PodEngine(gpt2, cfg, params, _ec(),
                    PodConfig(prefill_workers=1, decode_workers=1))
    reqs = _run_trace(pod, cfg)
    for r_ref, r_pod in zip(ref, reqs):
        assert r_pod.tokens == r_ref.tokens
        assert len(r_pod.logprobs) == len(r_pod.tokens)
        assert r_pod.logprobs == pytest.approx(r_ref.logprobs, abs=1e-5)
