"""True multi-host pod acceptance script (run as a subprocess).

Launched by tests/test_pod_distributed.py through `execute_subprocess`:
this process is the ROUTER; it binds a `ChannelListener` and spawns real
`accelerate-tpu pod-worker` OS processes (via `spawn_socket_workers`)
that dial back over TCP. Proves, across genuine process boundaries:

- phase 1 (exactness): greedy AND sampled requests routed prefill ->
  shipment -> decode over the socket wire produce byte-identical tokens
  and logprobs to a single in-process Engine built from the same spec,
  with worker compile counts flat at admit/prefill/decode/extract/
  install = 1;
- phase 2 (recovery): SIGKILLing the decode worker's PROCESS mid-stream
  recovers every in-flight request by re-prefill-from-prompt on the
  survivor (soft roles: the prefill worker serves decode once the
  decode pool is empty), byte-identical, nothing lost or duplicated;
- tracing (ISSUE 18): the whole run samples every request
  (`ACCELERATE_TPU_TRACE=1` inherited by the worker processes), so the
  SIGKILL also proves the observability tentpole: the killed flight's
  fleet incident bundle holds ONE merged chrome trace with spans from
  BOTH worker processes rebased into router time and monotonically
  ordered (prefill end <= shipment <= install), the replay span is
  linked to the failed dispatch with recovery_reason=channel_drop, and
  `accelerate-tpu incident show` renders the bundle.

Prints POD_DIST_OK on success; any mismatch asserts (the parent test
surfaces the child's output).
"""

import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ACCELERATE_TPU_SANITIZE", "1")
# tracing + incident capture on for THIS process and (via env
# inheritance through spawn_socket_workers) every pod-worker process
os.environ.setdefault("ACCELERATE_TPU_TRACE", "1")
_INCIDENT_DIR = os.environ.setdefault(
    "ACCELERATE_TPU_INCIDENT_DIR",
    tempfile.mkdtemp(prefix="pod_incidents_"))

import jax  # noqa: E402

# the hosted image pins jax_platforms to the tunnel backend at import
# time, silently overriding the env var (tests/conftest.py gotcha)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

# opt in to the parent fixture's exported compilation cache (no-op when
# the env var is unset): the router's reference engine reuses compiles
# already paid by earlier in-process tests in the same module
from accelerate_tpu.utils.environment import (  # noqa: E402
    configure_compilation_cache)

configure_compilation_cache()

from accelerate_tpu.commands.pod import spawn_socket_workers  # noqa: E402
from accelerate_tpu.serving.pod.distributed import (  # noqa: E402
    ChannelListener,
    DistributedPodConfig,
    DistributedPodRouter,
)
from accelerate_tpu.serving.pod.distributed.worker import (  # noqa: E402
    build_worker_engine,
    engine_config_from_spec,
)
from accelerate_tpu.telemetry import (  # noqa: E402
    configure_tracing,
    trace_events,
)

# the env var enabled recording at import; head-sample 100% so every
# plain submit below is a traced request
configure_tracing(enabled=True, annotate=False, default_sample_rate=1.0)

SPEC = {"family": "gpt2", "seed": 0, "num_slots": 3, "max_len": 64,
        "prefill_chunk": 8, "page_size": 8, "cache_dtype": "float32"}


def traffic(rng_seed=7):
    rng = np.random.default_rng(rng_seed)
    prompts = [rng.integers(1, 256, size=n).tolist() for n in (5, 11, 3, 9)]
    budgets = [8, 8, 6, 6]
    temps = [0.0, 0.7, 0.0, 1.1]   # greedy AND sampled, same trace
    return prompts, budgets, temps


def drive(router, reqs, deadline_s=120.0):
    deadline = time.monotonic() + deadline_s
    while not all(r.done for r in reqs):
        router.step()
        assert time.monotonic() < deadline, (
            "pod wedged: " + repr(router.debug_pod()))
        time.sleep(0.002)


def main() -> None:
    # spawn the workers FIRST: their engine builds (the wall-clock
    # dominator) overlap the parent's reference build below
    listener = ChannelListener("127.0.0.1", 0)
    procs = spawn_socket_workers(
        listener.port, SPEC, ["prefill", "decode"],
        heartbeat_interval_s=0.05, env=dict(os.environ),
        stderr=sys.stderr)

    # the single-process reference: same spec -> same params bytes
    _family, _cfg, _params, ref_engine = build_worker_engine(SPEC)
    prompts, budgets, temps = traffic()
    # phase 2 streams LONGER: the SIGKILL window needs a flight that is
    # still mid-decode after both workers' spans have ridden a heartbeat
    # into the router — with a warm compile cache an 8-token stream can
    # finish before the first span-bearing heartbeat is even processed
    budgets2 = [24, 24, 16, 16]
    # the trace runs TWICE (phase 1 exactness, phase 2 recovery) and
    # sampling keys fold in the request id, so the reference must burn
    # the same ids: batch one gets ids 1..4, batch two ids 5..8
    ref_batches = []
    for bs in (budgets, budgets2):
        ref_reqs = [ref_engine.submit(np.asarray(p, np.int32),
                                      max_new_tokens=b, temperature=t)
                    for p, b, t in zip(prompts, bs, temps)]
        ref_engine.run_until_idle()
        ref_batches.append(([list(r.tokens) for r in ref_reqs],
                            [list(r.logprobs) for r in ref_reqs]))
    (ref_tokens, ref_logprobs), (ref_tokens2, ref_logprobs2) = ref_batches
    router = DistributedPodRouter(
        engine_config=engine_config_from_spec(SPEC),
        pod_config=DistributedPodConfig(
            prefill_workers=1, decode_workers=1,
            # a worker handling its FIRST prefill is compiling and can't
            # heartbeat — the timeout must dwarf a loaded-box compile
            # (phase 2's SIGKILL is caught instantly via channel_drop,
            # which doesn't wait on this)
            heartbeat_interval_s=0.05, heartbeat_timeout_s=120.0,
            # generous: on a loaded box the first prefill includes the
            # compile, and a spurious "stalled" replay would break the
            # phase-1 logprob EXACTNESS bar (replayed logprob = one ulp)
            flight_timeout_s=300.0, rebalance=False),
        listener=listener)
    try:
        deadline = time.monotonic() + 180.0
        while sum(1 for w in router.workers.values() if w.alive) < 2:
            router.step()
            assert all(p.poll() is None for p in procs), \
                [p.returncode for p in procs]
            assert time.monotonic() < deadline, "workers never joined"
            time.sleep(0.05)

        # phase 1: byte-exactness across the process boundary
        reqs = [router.submit(p, max_new_tokens=b, temperature=t)
                for p, b, t in zip(prompts, budgets, temps)]
        drive(router, reqs)
        got = [list(r.tokens) for r in reqs]
        assert got == ref_tokens, f"{got} != {ref_tokens}"
        lps = [list(r.logprobs) for r in reqs]
        assert lps == ref_logprobs, "logprobs diverged"
        # give the post-completion heartbeats a beat to land, then check
        # the fleet-wide compile envelope stayed flat
        hb_deadline = time.monotonic() + 10.0
        while time.monotonic() < hb_deadline:
            router.step()
            if router.compile_stats() == {
                    "admit": 1, "prefill": 1, "decode": 1,
                    "extract": 1, "install": 1}:
                break
            time.sleep(0.05)
        stats = router.compile_stats()
        assert stats == {"admit": 1, "prefill": 1, "decode": 1,
                         "extract": 1, "install": 1}, stats
        print("PHASE1_EXACT_OK", flush=True)

        # phase 2: SIGKILL the decode worker process mid-stream
        reqs = [router.submit(p, max_new_tokens=b, temperature=t)
                for p, b, t in zip(prompts, budgets2, temps)]
        victim = next(w for w in router.workers.values()
                      if w.role == "decode")
        # wait for a decode flight AND for both workers' spans of its
        # trace (prefill from worker A, install from worker B) to ride a
        # heartbeat into the router's recorder — the fleet bundle below
        # must contain the whole cross-process timeline
        deadline = time.monotonic() + 120.0
        candidates = {}
        while not candidates:
            router.step()
            # a candidate must still owe >= 2 tokens: a flight whose
            # remaining tokens already sit in the router's socket buffer
            # finishes instead of replaying
            candidates = {
                f.user.request_id: f.user.trace_id
                for f in router._flights.values()
                if f.phase == "decode" and f.worker == victim.worker_id
                and len(f.user.tokens) <= f.user.max_new_tokens - 2
                and {"serving.pod.prefill", "serving.pod.install"}
                <= {e["name"] for e in trace_events(f.user.trace_id)}}
            assert not all(r.done for r in reqs), \
                "phase-2 batch drained before a traced kill window opened"
            assert time.monotonic() < deadline, \
                "no traced decode flight landed"
            time.sleep(0.002)
        worker_pids = {w.pid for w in router.workers.values() if w.pid}
        procs[victim.worker_id].kill()
        drive(router, reqs)
        got = [list(r.tokens) for r in reqs]
        assert got == ref_tokens2, (
            f"recovery diverged: {got} != {ref_tokens2}")
        # tokens are byte-exact; the REPLAYED token's logprob is
        # recomputed by the chunked prefill program instead of the
        # original decode step — same math, different reduction order,
        # so it can differ by a float32 ulp
        for a, b in zip((list(r.logprobs) for r in reqs), ref_logprobs2):
            assert np.allclose(a, b, rtol=0, atol=1e-5), (a, b)
        ms = router.metrics_summary()
        assert ms["pod_workers_lost"] == 1.0, ms
        assert ms["pod_requests_replayed"] >= 1.0, ms
        reasons = {e["recovery_reason"] for e in router.recovery_log}
        assert reasons <= {"channel_drop", "heartbeat_timeout"}, reasons
        print("PHASE2_RECOVERY_OK", flush=True)

        # the observability tentpole, across real process boundaries:
        # 1) the replay span lives in the killed request's own trace,
        #    linked to the failed attempt's dispatch span
        replayed = {e["request_id"] for e in router.recovery_log
                    if e["recovery_reason"] == "channel_drop"}
        hit = [tid for rid, tid in candidates.items() if rid in replayed]
        assert hit, (candidates, list(router.recovery_log))
        killed_tid = hit[0]
        events = trace_events(killed_tid)
        replays = [e for e in events if e["name"] == "serving.replay"]
        assert replays, sorted({e["name"] for e in events})
        dispatch_ids = {e["span_id"] for e in events
                        if e["name"] == "serving.pod.dispatch"}
        assert any(e["attrs"]["recovery_reason"] == "channel_drop"
                   and set(e.get("links", ())) & dispatch_ids
                   for e in replays), replays
        # 2) the worker loss wrote ONE fleet bundle holding the killed
        #    flight's merged chrome trace: spans from BOTH worker
        #    processes rebased into router time, monotonically ordered
        import json

        bundles = sorted(d for d in os.listdir(_INCIDENT_DIR)
                         if f"fleet-loss-w{victim.worker_id}" in d)
        assert bundles, os.listdir(_INCIDENT_DIR)
        bundle = os.path.join(_INCIDENT_DIR, bundles[-1])
        with open(os.path.join(bundle, "flights_trace.json")) as f:
            traces = json.load(f)
        doc = traces.get(str(killed_tid))
        assert doc, (sorted(traces), killed_tid)
        tes = doc["traceEvents"]
        pids = {e["pid"] for e in tes}
        assert worker_pids <= pids, (worker_pids, pids)
        end = {}
        for e in tes:
            end[e["name"]] = max(end.get(e["name"], float("-inf")),
                                 e["ts"] + e["dur"])
        with open(os.path.join(bundle, "clock_offsets.json")) as f:
            offsets = json.load(f)
        # clock-alignment error bound: the estimator is honest about its
        # own precision (+-rtt/2 per worker, EWMA-lagged) — on a loaded
        # single-core box "rtt" includes whole engine steps, so the
        # bound must come from the measured rtt, not a localhost guess
        tol_us = (0.1 + sum(w.get("rtt_s") or 0.0
                            for w in offsets.values())) * 1e6
        assert end["serving.pod.prefill"] \
            <= end["serving.page_transfer"] + tol_us \
            <= end["serving.pod.install"] + 2 * tol_us, (end, offsets)
        assert offsets[str(victim.worker_id)]["lost"], offsets
        with open(os.path.join(bundle,
                               f"worker_{victim.worker_id}.json")) as f:
            dead = json.load(f)
        assert "worker_error" in dead, dead   # the honest hole
        survivor = next(w for w in router.workers.values()
                        if w.worker_id != victim.worker_id)
        with open(os.path.join(bundle,
                               f"worker_{survivor.worker_id}.json")) as f:
            alive = json.load(f)
        assert "jobs" in alive and "engine" in alive, sorted(alive)
        # 3) the CLI renders the fleet view of that bundle
        import contextlib
        import io

        from accelerate_tpu.commands.incident import _run_show

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = _run_show(_INCIDENT_DIR, os.path.basename(bundle),
                           "text")
        shown = buf.getvalue()
        assert rc == 0, shown
        assert "fleet clock offsets" in shown, shown
        assert f"worker {victim.worker_id}: UNREACHABLE" in shown, shown
        assert "in-flight traces" in shown, shown
        print("PHASE2_TRACE_OK", flush=True)
    finally:
        router.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except Exception:
                p.kill()

    print("POD_DIST_OK")


if __name__ == "__main__":
    main()
