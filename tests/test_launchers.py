"""Launchers + LocalSGD: real multi-process localhost worlds.

Replaces the reference's debug_launcher/gloo tests (ref tests/test_cpu.py,
test_grad_sync.py:51): N OS processes rendezvous through the JAX coordinator
on localhost, so cross-process collectives and LocalSGD averaging run for
real — the launch-and-assert pattern of SURVEY.md §4.
"""

import numpy as np
import pytest

from accelerate_tpu.launchers import debug_launcher, notebook_launcher


def _world_worker():
    import jax

    from accelerate_tpu.state import PartialState

    state = PartialState()
    assert state.num_processes == 2, state.num_processes
    assert jax.process_count() == 2


def _object_collective_worker():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils.operations import broadcast_object_list, gather_object

    state = PartialState()
    rank = state.process_index
    gathered = gather_object({"rank": rank})
    assert [g["rank"] for g in gathered] == [0, 1], gathered
    objs = broadcast_object_list([f"from-{rank}", rank * 10])
    assert objs == ["from-0", 0], objs


def _local_sgd_worker():
    import jax.numpy as jnp

    from accelerate_tpu.local_sgd import LocalSGD
    from accelerate_tpu.state import PartialState

    state = PartialState()
    rank = state.process_index
    params = {"w": jnp.full((4,), float(rank + 1))}
    with LocalSGD(local_sgd_steps=2) as lsgd:
        params = lsgd.step(params)  # step 1: no sync, stays local
        assert float(params["w"][0]) == rank + 1
        params = lsgd.step(params)  # step 2: boundary -> cross-host mean
        np.testing.assert_allclose(np.asarray(params["w"]), 1.5)
        params = lsgd.step(params)  # step 3: local again
        params = lsgd.flush(params)  # explicit final average
        np.testing.assert_allclose(np.asarray(params["w"]), 1.5)


def _failing_worker():
    raise ValueError("worker boom")


@pytest.mark.slow
def test_debug_launcher_world():
    debug_launcher(_world_worker, num_processes=2)


@pytest.mark.slow
def test_debug_launcher_object_collectives():
    debug_launcher(_object_collective_worker, num_processes=2)


@pytest.mark.slow
def test_debug_launcher_local_sgd():
    debug_launcher(_local_sgd_worker, num_processes=2)


@pytest.mark.slow
def test_debug_launcher_propagates_failure():
    with pytest.raises(RuntimeError, match="worker boom"):
        debug_launcher(_failing_worker, num_processes=2)


def test_notebook_launcher_runs_in_process():
    out = []
    notebook_launcher(out.append, args=(42,), num_processes=1)
    assert out == [42]


def test_local_sgd_single_process_passthrough():
    import jax.numpy as jnp

    from accelerate_tpu.local_sgd import LocalSGD

    params = {"w": jnp.ones((2,))}
    with LocalSGD(local_sgd_steps=4) as lsgd:
        assert not lsgd.enabled  # single process: disabled (ref local_sgd.py:30-36)
        out = lsgd.step(params)
    assert out is params


def test_local_sgd_rejects_bad_steps():
    from accelerate_tpu.local_sgd import LocalSGD

    with pytest.raises(ValueError):
        LocalSGD(local_sgd_steps=0)
