"""Pod-scale serving acceptance script (run under forced host devices).

Launched by tests/test_pod.py through the `forced_device_run` fixture
with `XLA_FLAGS=--xla_force_host_platform_device_count=N`: proves, in a
process whose WHOLE backend is the N-device mesh, that

- the mesh-sharded engine (serving.pod.sharded_engine over all N
  devices, strict="error" so every sharded program passes the
  pod_program_contracts audit) produces byte-identical token streams to
  the single-device engine on the same seeded trace, with compile
  counts flat at admit/prefill/decode = 1;
- the disaggregated prefill->decode pod (1+1 workers, each
  tensor-parallel over the same N devices — layer 1 composed under
  layer 2) produces the same byte-identical streams, with the
  extract/install programs also compiling exactly once.

Prints POD_EXACTNESS_OK on success; any mismatch asserts (the parent
test surfaces the child's output).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# the hosted image pins jax_platforms to the tunnel backend at import
# time, silently overriding the env var (tests/conftest.py gotcha)
jax.config.update("jax_platforms", "cpu")

import dataclasses  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# opt in to the parent fixture's exported compilation cache (no-op when
# the env var is unset): the N=2 and N=4 children share the
# single-device reference compiles instead of each paying them
from accelerate_tpu.utils.environment import (  # noqa: E402
    configure_compilation_cache)

configure_compilation_cache()

from accelerate_tpu.models import gpt2  # noqa: E402
from accelerate_tpu.serving import Engine, EngineConfig  # noqa: E402
from accelerate_tpu.serving.pod import (  # noqa: E402
    PodConfig,
    PodEngine,
    sharded_engine,
)


def run_trace(engine, cfg):
    """Seeded multi-request mix: staggered arrivals, greedy + sampled
    temperatures, a budget-1 request, and an interleaved long prompt."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 11, 3, 17, 6)]
    reqs = [engine.submit(prompts[0], max_new_tokens=6)]
    for _ in range(3):
        engine.step()
    reqs.append(engine.submit(prompts[1], max_new_tokens=6, temperature=0.7))
    reqs.append(engine.submit(prompts[2], max_new_tokens=4))
    reqs.append(engine.submit(prompts[3], max_new_tokens=4, temperature=1.1))
    reqs.append(engine.submit(prompts[4], max_new_tokens=1))
    engine.run_until_idle()
    assert all(r.status.value == "finished" for r in reqs), \
        [(r.status.value, r.reject_reason) for r in reqs]
    return [r.tokens for r in reqs]


def main() -> None:
    n = int(sys.argv[1])
    assert jax.device_count() == n, (
        f"expected {n} forced host devices, got {jax.devices()}")

    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    ec = EngineConfig(num_slots=3, max_len=64, prefill_chunk=8,
                      cache_dtype=jnp.float32)

    ref = run_trace(Engine(gpt2, cfg, params, ec), cfg)

    # layer 1: one engine sharded over the full N-device mesh, strict
    sh = sharded_engine(gpt2, cfg, params,
                        dataclasses.replace(ec, strict="error"),
                        tensor_parallel=n)
    got = run_trace(sh, cfg)
    assert got == ref, f"sharded N={n} diverged: {got} != {ref}"
    stats = sh.compile_stats()
    assert stats == {"admit": 1, "prefill": 1, "decode": 1}, stats

    # layer 2 (composed with layer 1): disaggregated pod, TP-N workers,
    # strict audit on — every sharded program incl. extract/install must
    # satisfy the pod contracts
    pod = PodEngine(gpt2, cfg, params, dataclasses.replace(ec, strict="error"),
                    PodConfig(prefill_workers=1, decode_workers=1,
                              tensor_parallel=n))
    got = run_trace(pod, cfg)
    assert got == ref, f"pod N={n} diverged: {got} != {ref}"
    stats = pod.compile_stats()
    assert stats == {"admit": 1, "prefill": 1, "decode": 1,
                     "extract": 1, "install": 1}, stats
    assert pod.metrics_summary()["pod_shipments"] >= 3

    print("POD_EXACTNESS_OK")


if __name__ == "__main__":
    main()
