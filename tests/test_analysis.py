"""Static analysis subsystem (ISSUE 4): source passes, program passes,
contracts, CLI, baselines, and strict mode.

The per-rule fixtures live in tests/analysis_fixtures/ — one known-positive
and one known-negative file per rule ID, so every rule's firing condition
AND its non-firing idiom are pinned. The self-lint test is the CI gate: the
source passes run in-process over accelerate_tpu/ against the checked-in
baseline (tests/analysis_baseline.json), so any NEW finding fails tier-1.
"""

import json
import os
import subprocess
import sys
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from accelerate_tpu.analysis import (
    AnalysisViolation,
    CollectiveContract,
    RULES,
    audit_replication,
    collective_counts,
    contract_for,
    find_host_transfers,
    lint_file,
    lint_paths,
    lint_target,
    lint_text,
    new_findings,
    render_human,
    render_json,
    save_baseline,
)
from accelerate_tpu.commands.accelerate_cli import main as cli_main
from accelerate_tpu.utils.imports import resolve_shard_map

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)
FIXTURES = os.path.join(TESTS_DIR, "analysis_fixtures")
BASELINE = os.path.join(TESTS_DIR, "analysis_baseline.json")

ALL_RULE_IDS = [f"ATP00{i}" for i in range(1, 9)]
# ATP2xx (ISSUE 13): the lifecycle auditor — paired resources, request
# FSM, thread confinement — same fixture scheme, same pipeline
LIFECYCLE_RULE_IDS = ["ATP201", "ATP202", "ATP203",
                      "ATP211", "ATP212", "ATP221"]
# ATP3xx (ISSUE 19): the concurrency auditor — shared-state locksets,
# lock-order cycles, blocking calls on the loop, condvar protocol,
# thread shutdown — same fixture scheme, same pipeline
CONCURRENCY_RULE_IDS = ["ATP301", "ATP302", "ATP303", "ATP304", "ATP305"]


# ---------------------------------------------------------------------------
# source passes: one positive + one negative fixture per rule
# ---------------------------------------------------------------------------


class TestSourceRules:
    @pytest.mark.parametrize("rule", ALL_RULE_IDS + LIFECYCLE_RULE_IDS
                             + CONCURRENCY_RULE_IDS)
    def test_positive_fixture_fires(self, rule):
        path = os.path.join(FIXTURES, f"{rule.lower()}_pos.py")
        got = {f.rule for f in lint_file(path)}
        assert rule in got, f"{path} did not produce {rule} (got {got})"

    @pytest.mark.parametrize("rule", ALL_RULE_IDS + LIFECYCLE_RULE_IDS
                             + CONCURRENCY_RULE_IDS)
    def test_negative_fixture_is_clean(self, rule):
        path = os.path.join(FIXTURES, f"{rule.lower()}_neg.py")
        found = [f for f in lint_file(path) if f.rule == rule]
        assert not found, (
            f"false positive: {path} produced "
            f"{[f.render() for f in found]}"
        )

    def test_parse_error_is_a_finding_not_a_crash(self):
        findings = lint_text("def broken(:\n", "broken.py")
        assert [f.rule for f in findings] == ["ATP000"]

    def test_rule_catalog_is_stable(self):
        """Rule IDs are public API: renumbering breaks suppressions and
        baselines in user trees."""
        for rid in ALL_RULE_IDS + ["ATP000", "ATP101", "ATP102", "ATP103"]:
            assert rid in RULES
        assert RULES["ATP001"].name == "host-sync-item"
        assert RULES["ATP101"].name == "collective-contract"

    def test_host_code_is_never_linted(self):
        """The same hazards OUTSIDE traced code are legitimate host idioms."""
        src = (
            "import numpy as np\n"
            "def metrics_loop(history):\n"
            "    v = history[-1].item()\n"
            "    arr = np.asarray(history)\n"
            "    print(arr)\n"
            "    if v > 0:\n"
            "        np.random.seed(0)\n"
            "    return float(v)\n"
        )
        assert lint_text(src, "host.py") == []


class TestScalarAnnotations:
    def test_float_annotated_param_stays_tainted(self):
        """`x: float` on a jitted fn is a traced weak-typed scalar (loss
        scale, temperature — the classic branch-on-a-tracer hazards);
        unlike int/str/bool config annotations it must stay tainted."""
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(state, loss_scale: float):\n"
            "    if loss_scale > 0:\n"
            "        return state\n"
            "    return state\n"
        )
        assert "ATP006" in {f.rule for f in lint_text(src, "t.py")}
        src_int = src.replace("loss_scale: float", "n_layers: int")
        assert "ATP006" not in {f.rule for f in lint_text(src_int, "t.py")}


class TestSuppression:
    def test_line_and_file_suppression(self):
        findings = lint_file(os.path.join(FIXTURES, "suppressed.py"))
        # file-wide ATP004 gone, line-suppressed ATP001 gone; the
        # unsuppressed .item() in g() must survive
        assert [f.rule for f in findings] == ["ATP001"]
        (f,) = findings
        assert "item" in f.source

    def test_bare_disable_suppresses_all_rules_on_line(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return float(x.sum().item())  # atp: disable\n"
        )
        from accelerate_tpu.analysis import apply_suppressions

        assert apply_suppressions(lint_text(src, "t.py"), src) == []

    def test_prose_mention_of_syntax_does_not_suppress(self):
        """The directive must END its line: a comment or docstring that
        merely *documents* `# atp: disable-file` (trailing text) must not
        silently suppress the whole file."""
        from accelerate_tpu.analysis import apply_suppressions
        from accelerate_tpu.analysis.findings import parse_suppressions

        src = (
            '"""Docs: `# atp: disable-file` suppresses file-wide."""\n'
            "# the `# atp: disable=ATP001` marker goes at line end\n"
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.sum().item()\n"
        )
        file_rules, per_line = parse_suppressions(src)
        assert file_rules == set() and per_line == {}
        assert [f.rule for f in
                apply_suppressions(lint_text(src, "t.py"), src)] == ["ATP001"]
        # the suppression module's own documentation must not disarm it
        import accelerate_tpu.analysis.findings as findings_mod

        with open(findings_mod.__file__) as fh:
            own_file_rules, _ = parse_suppressions(fh.read())
        assert own_file_rules == set()


class TestBaseline:
    def test_roundtrip_and_new_finding_detection(self, tmp_path):
        pos = os.path.join(FIXTURES, "atp001_pos.py")
        findings = lint_file(pos, root=REPO)
        assert findings
        bl = tmp_path / "bl.json"
        save_baseline(str(bl), findings)
        data = json.loads(bl.read_text())
        assert data["version"] == 1
        # everything accepted -> nothing new
        assert new_findings(findings, data) == []
        # one extra occurrence of the same pattern overflows its count
        assert len(new_findings(findings + findings[:1], data)) == 1
        # a different rule is always new
        other = lint_file(os.path.join(FIXTURES, "atp005_pos.py"), root=REPO)
        assert new_findings(other, data) == other

    def test_fingerprints_survive_line_drift(self):
        src = "import jax\n@jax.jit\ndef f(x):\n    return x.item()\n"
        moved = "import jax\n\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
        (a,) = lint_text(src, "t.py")
        (b,) = lint_text(moved, "t.py")
        assert a.line != b.line and a.fingerprint == b.fingerprint


# ---------------------------------------------------------------------------
# CLI: exit codes 0/1/2, json format, module targets, baseline flags
# ---------------------------------------------------------------------------


class TestLintCLI:
    def test_findings_exit_1_human(self, capsys):
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp001_pos.py")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "ATP001" in out and "host-sync-item" in out

    def test_clean_exit_0(self, capsys):
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp001_neg.py")])
        assert rc == 0
        assert "0 finding(s)" in capsys.readouterr().out

    def test_internal_error_exit_2(self, capsys):
        rc = cli_main(["lint", "/nonexistent/not_a_module_either"])
        assert rc == 2
        assert "internal error" in capsys.readouterr().err

    def test_unknown_rule_exit_2(self, capsys):
        rc = cli_main(["lint", FIXTURES, "--rules", "ATP999"])
        assert rc == 2

    def test_json_format_is_machine_readable(self, capsys):
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp008_pos.py"),
                       "--format", "json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["summary"]["count"] >= 1
        assert payload["summary"]["by_rule"].get("ATP008") == 1
        (f,) = [x for x in payload["findings"] if x["rule"] == "ATP008"]
        assert f["line"] > 0 and f["fingerprint"]
        assert payload["rules"]["ATP008"]["name"] == "donation-aliasing"

    def test_module_target_resolution(self, capsys):
        rc = cli_main(["lint", "accelerate_tpu.analysis"])
        assert rc == 0

    def test_baseline_workflow(self, tmp_path, capsys):
        bl = str(tmp_path / "bl.json")
        rc = cli_main(["lint", FIXTURES, "--root", REPO,
                       "--write-baseline", bl])
        assert rc == 0 and os.path.exists(bl)
        capsys.readouterr()
        rc = cli_main(["lint", FIXTURES, "--root", REPO, "--baseline", bl])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "accepted by baseline" in out

    def test_rule_selection(self, capsys):
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp002_pos.py"),
                       "--rules", "ATP006", "--format", "json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert set(payload["summary"]["by_rule"]) == {"ATP006"}

    def test_lint_does_not_initialize_a_backend(self):
        """`accelerate-tpu lint` must run on boxes that cannot init an
        accelerator backend (same guard as the telemetry import test)."""
        code = (
            "from accelerate_tpu.commands.accelerate_cli import main\n"
            f"rc = main(['lint', {FIXTURES!r}])\n"
            "assert rc == 1, rc\n"
            "import sys\n"
            "if 'jax' in sys.modules:\n"
            "    from jax._src import xla_bridge\n"
            "    assert not xla_bridge.backends_are_initialized(), (\n"
            "        'lint initialized a jax backend')\n"
        )
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=120,
                             cwd=REPO, stdin=subprocess.DEVNULL)
        assert out.returncode == 0, out.stderr[-2000:]

    def test_python_m_lint_is_not_a_silent_noop(self):
        """`python -m accelerate_tpu.commands.lint` must lint, not import-and-
        exit-0 — a CI job wired that way would otherwise always pass."""
        out = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.commands.lint",
             os.path.join(FIXTURES, "atp001_pos.py")],
            capture_output=True, text=True, timeout=120, cwd=REPO,
            stdin=subprocess.DEVNULL)
        assert out.returncode == 1, (out.returncode, out.stderr[-2000:])
        assert "ATP001" in out.stdout


# ---------------------------------------------------------------------------
# the CI gates: self-lint + examples false-positive guard
# ---------------------------------------------------------------------------


class TestSelfLint:
    def test_accelerate_tpu_clean_against_checked_in_baseline(self):
        """THE tier-1 gate: new findings in accelerate_tpu/ fail CI. Runs
        in-process (AST only), so the gate is cheap."""
        t0 = time.monotonic()
        _, fresh = lint_target(
            os.path.join(REPO, "accelerate_tpu"), root=REPO,
            baseline=BASELINE)
        elapsed = time.monotonic() - t0
        assert fresh == [], (
            "NEW static-analysis findings (fix them, suppress with a "
            "justified `# atp: disable=`, or re-baseline via `accelerate-tpu "
            "lint accelerate_tpu --root . --write-baseline "
            "tests/analysis_baseline.json`):\n" + render_human(fresh)
        )
        assert elapsed < 10.0, f"self-lint took {elapsed:.1f}s; gate must stay cheap"

    def test_self_lint_gate_covers_the_server_package(self):
        """ISSUE 7: the gate's tree walk must include the HTTP front door
        (accelerate_tpu/server/) — if the walker ever grew an exclusion
        that swallowed it, new server hazards would ship unlinted."""
        from accelerate_tpu.analysis.runner import iter_python_files

        files = iter_python_files(os.path.join(REPO, "accelerate_tpu"))
        server_files = [f for f in files
                       if os.sep + "server" + os.sep in f]
        assert any(f.endswith("http.py") for f in server_files), \
            "accelerate_tpu/server must be inside the self-lint tree"
        assert any(f.endswith("service.py") for f in server_files)

    def test_self_lint_gate_covers_the_pod_package(self):
        """ISSUE 13: serving/pod/ is where the lifecycle passes found
        their genuine bugs — a tree-walk exclusion that silently dropped
        it would un-audit exactly the router code the ATP2xx family
        exists for."""
        from accelerate_tpu.analysis.runner import iter_python_files

        files = iter_python_files(os.path.join(REPO, "accelerate_tpu"))
        pod_files = [f for f in files
                     if (os.sep + "serving" + os.sep + "pod" + os.sep) in f]
        for name in ("router.py", "transfer.py", "mesh.py"):
            assert any(f.endswith(name) for f in pod_files), \
                f"serving/pod/{name} must be inside the self-lint tree"

    def test_self_lint_gate_runs_the_lifecycle_rules(self):
        """The gate runs with NO rule restriction, so the ATP2xx passes
        are part of it by construction — pinned by planting a
        known-leaky file next to the tree and asserting lint_target's
        pipeline reports its ATP201."""
        for rid in LIFECYCLE_RULE_IDS:
            assert rid in RULES, rid
        findings = lint_paths(
            [os.path.join(FIXTURES, "atp201_pos.py")], root=REPO)
        assert any(f.rule == "ATP201" for f in findings)

    def test_examples_are_clean(self):
        """False-positive guard: examples/ is idiomatic user code — the
        linter flagging any of it means a rule is too aggressive."""
        findings = lint_paths([os.path.join(REPO, "examples")], root=REPO)
        assert findings == [], render_human(findings)

    def test_render_json_on_empty(self):
        payload = json.loads(render_json([]))
        assert payload["summary"]["count"] == 0


# ---------------------------------------------------------------------------
# ATP2xx lifecycle passes (ISSUE 13)
# ---------------------------------------------------------------------------


class TestLifecyclePasses:
    def test_rule_catalog_is_stable(self):
        assert RULES["ATP201"].name == "lifecycle-leak-on-path"
        assert RULES["ATP211"].name == "terminal-bypasses-finalizer"
        assert RULES["ATP221"].name == "cross-thread-state-mutation"

    def test_pairing_table_one_line_extension(self):
        """The declarative recipe: a NEW resource registers in one
        ResourcePair line and the whole CFG machinery audits it."""
        import ast as ast_mod

        from accelerate_tpu.analysis.lifecycle import (
            PAIRING_TABLE,
            ResourcePair,
            lint_lifecycle,
        )

        table = PAIRING_TABLE + (ResourcePair(
            "shipment-buffer", acquire=("checkout",),
            release=("checkin",), receivers=("shipments",)),)
        src = (
            "class Router:\n"
            "    def leaky(self, req):\n"
            "        buf = self.shipments.checkout(req)\n"
            "        if buf is None:\n"
            "            return None\n"
            "        if req.cancelled:\n"
            "            return False   # leak\n"
            "        self.shipments.checkin(buf)\n"
            "        return True\n"
        )
        findings = []
        lint_lifecycle(ast_mod.parse(src), src, "t.py", src.splitlines(),
                       findings, table=table)
        assert [f.rule for f in findings] == ["ATP201"]
        assert findings[0].data["resource"] == "shipment-buffer"
        # without the extra row the same code is silent
        findings2 = []
        lint_lifecycle(ast_mod.parse(src), src, "t.py", src.splitlines(),
                       findings2)
        assert findings2 == []

    def test_findings_carry_structured_data(self):
        """The JSON satellite: ATP2xx findings name the resource/state
        and the offending path's line span — actionable without
        rereading the pass."""
        fs = [f for f in lint_file(os.path.join(FIXTURES, "atp201_pos.py"))
              if f.rule == "ATP201"]
        assert fs
        for f in fs:
            assert f.data["resource"]
            assert f.data["acquire_line"] >= 1
            lo, hi = f.data["span"]
            assert lo <= hi
        fs = [f for f in lint_file(os.path.join(FIXTURES, "atp212_pos.py"))
              if f.rule == "ATP212"]
        assert fs and fs[0].data["state"] == "EXPIRED"
        assert fs[0].data["target"] == "user"
        # every lifecycle rule keeps the span contract (a consumer may
        # read data["span"] unconditionally)
        for fixture, rule in (("atp202_pos.py", "ATP202"),
                              ("atp203_pos.py", "ATP203"),
                              ("atp211_pos.py", "ATP211"),
                              ("atp221_pos.py", "ATP221")):
            fs = [f for f in lint_file(os.path.join(FIXTURES, fixture))
                  if f.rule == rule]
            assert fs and all(len(f.data["span"]) == 2 for f in fs), rule

    def test_json_output_includes_data(self, capsys):
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp201_pos.py"),
                       "--format", "json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        rows = [f for f in payload["findings"] if f["rule"] == "ATP201"]
        assert rows and all(r["data"]["resource"] for r in rows)
        assert all("span" in r["data"] for r in rows)

    def test_rules_group_alias(self, capsys):
        """`--rules atp2` selects the whole lifecycle family: the ATP001
        fixture is clean under it, the ATP201 fixture is not, and a bad
        token still exits 2."""
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp001_pos.py"),
                       "--rules", "atp2"])
        capsys.readouterr()
        assert rc == 0
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp201_pos.py"),
                       "--rules", "atp2"])
        out = capsys.readouterr().out
        assert rc == 1 and "ATP201" in out
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp201_pos.py"),
                       "--rules", "atp9"])
        assert rc == 2

    def test_regression_shapes_of_the_fixed_bugs(self):
        """The three genuine serving/ findings this PR fixed, as inline
        shapes: reverting any fix re-creates code the self-lint gate
        rejects."""
        # (1) cache.PagedAllocator.allocate pre-fix: a raising on_evict
        # callback between acquire and release leaked the refcounts
        src = (
            "class A:\n"
            "    def allocate(self, request, nodes):\n"
            "        self.index.acquire(nodes)\n"
            "        private = self.pool.alloc(2)\n"
            "        if private is None:\n"
            "            self.on_evict(3)\n"
            "            private = self.pool.alloc(2)\n"
            "        if private is None:\n"
            "            self.index.release(nodes)\n"
            "            return None\n"
            "        return self.build(nodes, private)\n"
        )
        assert "ATP201" in {f.rule for f in lint_text(src, "t.py")}
        # (2) pod router._harvest pre-fix: EXPIRED without shed_code
        src = (
            "class R:\n"
            "    def _finalize(self, r):\n"
            "        self.metrics.observe_request(r)\n"
            "    def harvest(self, user, now):\n"
            "        user.status = RequestStatus.EXPIRED\n"
            "        user.reject_reason = 'worker dropped'\n"
            "        user.finished_at = now\n"
            "        self._finalize(user)\n"
        )
        assert "ATP212" in {f.rule for f in lint_text(src, "t.py")}
        # (3) the PR 6 class: scheduler.submit without a drain
        src = (
            "class E:\n"
            "    def _finalize_request(self, r):\n"
            "        self.metrics.observe_request(r)\n"
            "    def submit(self, req):\n"
            "        self.scheduler.submit(req)\n"
            "        if req.done:\n"
            "            self._finalize_request(req)\n"
            "        return req\n"
        )
        assert "ATP211" in {f.rule for f in lint_text(src, "t.py")}

    def test_suppression_and_baseline_apply_to_lifecycle_rules(self,
                                                               tmp_path):
        """ATP2xx rides the whole existing pipeline: line suppressions
        disarm a finding, baselines accept it."""
        pos = os.path.join(FIXTURES, "atp212_pos.py")
        findings = lint_file(pos, root=REPO)
        assert any(f.rule == "ATP212" for f in findings)
        src = open(pos).read()
        suppressed = src.replace(
            "user.status = RequestStatus.EXPIRED",
            "user.status = RequestStatus.EXPIRED  # atp: disable=ATP212")
        from accelerate_tpu.analysis import apply_suppressions

        left = apply_suppressions(lint_text(suppressed, "t.py"), suppressed)
        assert not any(f.rule == "ATP212" for f in left)
        bl = tmp_path / "bl.json"
        save_baseline(str(bl), findings)
        assert new_findings(findings, json.loads(bl.read_text())) == []


# ---------------------------------------------------------------------------
# ATP3xx concurrency passes (ISSUE 19)
# ---------------------------------------------------------------------------


class TestConcurrencyPasses:
    def test_rule_catalog_is_stable(self):
        assert RULES["ATP301"].name == "shared-state-no-common-lock"
        assert RULES["ATP302"].name == "lock-order-cycle"
        assert RULES["ATP303"].name == "blocking-call-in-async"
        assert RULES["ATP304"].name == "condvar-misuse"
        assert RULES["ATP305"].name == "thread-never-joined"

    def test_self_lint_gate_runs_the_concurrency_rules(self):
        """The gate runs with NO rule restriction, so the ATP3xx passes
        are part of it by construction — pinned the same way the
        lifecycle gate is: lint_paths' full pipeline must report the
        planted fixture's findings."""
        for rid in CONCURRENCY_RULE_IDS:
            assert rid in RULES, rid
        findings = lint_paths(
            [os.path.join(FIXTURES, "atp302_pos.py")], root=REPO)
        assert any(f.rule == "ATP302" for f in findings)
        findings = lint_paths(
            [os.path.join(FIXTURES, "atp301_pos.py")], root=REPO)
        assert any(f.rule == "ATP301" for f in findings)

    def test_findings_carry_structured_data(self):
        """The JSON contract: ATP302 names the full cycle path and the
        participating locks; ATP301 names the attribute, the contexts,
        and each context's locks; ATP303 names the call and the async
        entry path. Every rule keeps the span contract."""
        fs = [f for f in lint_file(os.path.join(FIXTURES, "atp302_pos.py"))
              if f.rule == "ATP302"]
        assert fs
        cycle = fs[0].data["cycle"]
        assert cycle[0] == cycle[-1] and len(cycle) >= 3
        assert set(fs[0].data["locks"]) == {"Pod._books_lock",
                                            "Pod._wire_lock"}
        fs = [f for f in lint_file(os.path.join(FIXTURES, "atp301_pos.py"))
              if f.rule == "ATP301"]
        assert fs and fs[0].data["attribute"] == "books"
        assert len(fs[0].data["contexts"]) >= 2
        assert isinstance(fs[0].data["locks"], dict)
        fs = [f for f in lint_file(os.path.join(FIXTURES, "atp303_pos.py"))
              if f.rule == "ATP303"]
        assert fs
        by_call = {f.data["call"]: f for f in fs}
        assert by_call["time.sleep"].data["async_entry"] == "drive"
        # the sync helper's finding carries the hop path from the loop
        assert by_call["self.inbox.get"].data["via"] == \
            ["drive", "_pump_once"]
        for fixture, rule in (("atp304_pos.py", "ATP304"),
                              ("atp305_pos.py", "ATP305")):
            fs = [f for f in lint_file(os.path.join(FIXTURES, fixture))
                  if f.rule == rule]
            assert fs and all(len(f.data["span"]) == 2 for f in fs), rule

    def test_json_output_includes_data(self, capsys):
        """`--rules atp3 --format json` emits the structured payload the
        acceptance criteria pin: lock names and the cycle path ride
        `data`, and the run exits 1 on findings."""
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp302_pos.py"),
                       "--rules", "atp3", "--format", "json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert set(payload["summary"]["by_rule"]) == {"ATP302"}
        (row,) = payload["findings"]
        assert row["data"]["cycle"][0] == row["data"]["cycle"][-1]
        assert row["data"]["locks"]

    def test_rules_group_alias(self, capsys):
        """`--rules atp3` selects the whole concurrency family and
        nothing else: the ATP201 fixture is clean under it, every ATP3xx
        fixture is not, and the clean exit is 0."""
        rc = cli_main(["lint", os.path.join(FIXTURES, "atp201_pos.py"),
                       "--rules", "atp3"])
        capsys.readouterr()
        assert rc == 0
        for rid in CONCURRENCY_RULE_IDS:
            rc = cli_main(["lint",
                           os.path.join(FIXTURES, f"{rid.lower()}_pos.py"),
                           "--rules", "atp3"])
            out = capsys.readouterr().out
            assert rc == 1 and rid in out, (rid, out)

    def test_blocking_table_one_line_extension(self):
        """The declarative recipe: a NEW blocking shape registers in one
        BlockingCall row and the reachability machinery audits it."""
        import ast as ast_mod

        from accelerate_tpu.analysis import BLOCKING_CALLS, BlockingCall
        from accelerate_tpu.analysis.concurrency import lint_concurrency

        table = BLOCKING_CALLS + (BlockingCall(
            "fetch_sync", "synchronous RPC stalls the loop"),)
        src = (
            "class S:\n"
            "    async def drive(self):\n"
            "        reply = self.stub.fetch_sync()\n"
        )
        findings = []
        lint_concurrency(ast_mod.parse(src), src, "t.py",
                         src.splitlines(), findings, blocking=table)
        assert [f.rule for f in findings] == ["ATP303"]
        assert findings[0].data["call"] == "self.stub.fetch_sync"
        # without the extra row the same code is silent
        findings2 = []
        lint_concurrency(ast_mod.parse(src), src, "t.py",
                         src.splitlines(), findings2)
        assert findings2 == []

    def test_thread_entries_task_extension(self):
        """ISSUE 19's THREAD_ENTRIES extension: asyncio task creation is
        a concurrent context. Dropping task_constructors from the table
        silences the thread-vs-task race the atp301 fixture pins."""
        import ast as ast_mod

        from accelerate_tpu.analysis import ThreadEntries
        from accelerate_tpu.analysis.concurrency import lint_concurrency

        # plain unlocked writes, one thread + one task: WITH task
        # recognition the pair is thread-vs-task (preemptive race, ours);
        # WITHOUT it the async def is just drive-loop code, which is
        # ATP221's one-thread-vs-drive territory and ATP301 stays silent
        src = (
            "import threading\n"
            "class R:\n"
            "    def start(self, loop):\n"
            "        self._t = threading.Thread(target=self._pump)\n"
            "        self._t.start()\n"
            "        loop.create_task(self._drive())\n"
            "    def _pump(self):\n"
            "        self.depth = 1\n"
            "    async def _drive(self):\n"
            "        self.depth = 2\n"
        )
        tree = ast_mod.parse(src)
        findings = []
        lint_concurrency(tree, src, "t.py", src.splitlines(), findings)
        hits = [f for f in findings if f.rule == "ATP301"]
        assert hits and hits[0].data["attribute"] == "depth"
        assert sorted(hits[0].data["contexts"]) == ["_drive", "_pump"]
        no_tasks = ThreadEntries(task_constructors=())
        findings2 = []
        lint_concurrency(tree, src, "t.py", src.splitlines(), findings2,
                         entries=no_tasks)
        assert not any(f.rule == "ATP301" for f in findings2)

    def test_suppression_and_baseline_apply_to_concurrency_rules(
            self, tmp_path):
        """ATP3xx rides the whole existing pipeline: line suppressions
        disarm a finding, baselines accept it. The tree itself carries a
        justified `# atp: disable=ATP303` (droute's incident-capture
        sleep), so the real-code path is exercised by the self-lint gate
        too."""
        pos = os.path.join(FIXTURES, "atp303_pos.py")
        findings = lint_file(pos, root=REPO)
        assert any(f.rule == "ATP303" for f in findings)
        src = open(pos).read()
        # the directive must END its line, so replace the trailing prose
        suppressed = src.replace(
            "# parks every task on the loop",
            "# atp: disable=ATP303")
        from accelerate_tpu.analysis import apply_suppressions

        left = apply_suppressions(lint_text(suppressed, "t.py"), suppressed)
        assert not any(f.rule == "ATP303" and "sleep" in f.source
                       for f in left)
        bl = tmp_path / "bl.json"
        save_baseline(str(bl), findings)
        assert new_findings(findings, json.loads(bl.read_text())) == []
        # the in-tree justified suppression is really there
        droute = os.path.join(REPO, "accelerate_tpu", "serving", "pod",
                              "distributed", "droute.py")
        assert "# atp: disable=ATP303" in open(droute).read()

    def test_regression_shapes_of_the_fixed_bugs(self):
        """The genuine ATP3xx findings this PR fixed, as inline shapes:
        reverting any fix re-creates code the self-lint gate rejects."""
        # (1) transport.SocketChannel pre-fix: reader/writer threads
        # started in __init__, close() never joined them
        src = (
            "import threading\n"
            "class Chan:\n"
            "    def __init__(self, sock):\n"
            "        self._reader = threading.Thread(target=self._rl)\n"
            "        self._reader.start()\n"
            "    def _rl(self):\n"
            "        pass\n"
            "    def close(self):\n"
            "        self._closed = True\n"
        )
        assert "ATP305" in {f.rule for f in lint_text(src, "t.py")}
        # (2) droute pre-fix: step() slept inline, and astream (an async
        # def) calls step() — a time.sleep on the event loop
        src = (
            "import time\n"
            "class Router:\n"
            "    async def astream(self, req):\n"
            "        while not self.step():\n"
            "            pass\n"
            "    def step(self):\n"
            "        worked = self.pump()\n"
            "        if not worked:\n"
            "            time.sleep(0.001)\n"
            "        return worked\n"
        )
        assert "ATP303" in {f.rule for f in lint_text(src, "t.py")}
        # (3) data._PrefetchIterator pre-fix: worker thread with no
        # close/stop path at all
        src = (
            "import threading\n"
            "class Prefetch:\n"
            "    def __init__(self, it):\n"
            "        self._thread = threading.Thread(target=self._w)\n"
            "        self._thread.start()\n"
            "    def _w(self):\n"
            "        pass\n"
        )
        assert "ATP305" in {f.rule for f in lint_text(src, "t.py")}


# ---------------------------------------------------------------------------
# program passes
# ---------------------------------------------------------------------------


def _psum_program():
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("i",))
    sm = resolve_shard_map()
    f = sm(lambda x: jax.lax.psum(x, "i"), mesh=mesh,
           in_specs=P("i"), out_specs=P())
    return jax.jit(f), jnp.arange(8.0)


class TestCollectiveCounts:
    def test_counts_from_jaxpr(self):
        fn, x = _psum_program()
        counts = collective_counts(jax.make_jaxpr(fn)(x))
        assert counts["all-reduce"] == 1

    def test_counts_from_lowered_stablehlo(self):
        fn, x = _psum_program()
        counts = collective_counts(fn.lower(x))
        assert counts["all-reduce"] >= 1

    def test_counts_from_compiled_hlo_text(self):
        fn, x = _psum_program()
        counts = collective_counts(fn.lower(x).compile().as_text())
        assert counts["all-reduce"] >= 1

    def test_async_pairs_not_double_counted(self):
        text = ("%ag = all-gather-start(...)\n"
                "%agd = all-gather-done(...)\n")
        assert collective_counts(text)["all-gather"] == 1


class TestCollectiveContract:
    def test_undeclared_extra_psum_produces_atp101(self):
        """Acceptance: an extra psum nothing declared -> its rule ID."""
        fn, x = _psum_program()
        contract = CollectiveContract(name="quiet_program", exhaustive=True)
        findings = contract.check(fn.lower(x).as_text())
        assert [f.rule for f in findings] == ["ATP101"]
        assert "all-reduce" in findings[0].message
        with pytest.raises(AnalysisViolation):
            contract.enforce(fn.lower(x).as_text())

    def test_exact_forbid_require_clauses(self):
        counts_text = "all-reduce\nall-gather\nall-gather\n"
        ok = CollectiveContract(
            name="ok", exact={"all-gather": 2},
            require=("all-reduce",), forbid=("collective-permute",))
        assert ok.check(counts_text) == []
        bad = CollectiveContract(name="bad", exact={"all-gather": 1})
        (f,) = bad.check(counts_text)
        assert "expected exactly 1, got 2" in f.message

    def test_require_group_accepts_alternatives(self):
        c = CollectiveContract(
            name="rs", require=(("reduce-scatter", "all-to-all"),))
        assert c.check("all-to-all\n") == []
        assert len(c.check("all-reduce\n")) == 1

    def test_non_exhaustive_ignores_undeclared(self):
        c = CollectiveContract(name="loose", require=("all-reduce",))
        assert c.check("all-reduce\ncollective-permute\n") == []

    def test_contract_table_resolves_per_flavor(self):
        native = contract_for("ring_attention.forward", flavor="native")
        exp = contract_for("ring_attention.forward", flavor="experimental")
        assert dict(native.exact)["collective-permute"] == 2
        assert dict(exp.exact)["collective-permute"] == 8
        assert "all-gather" in native.forbid and "all-gather" in exp.forbid
        with pytest.raises(KeyError):
            contract_for("no_such_program")


class TestTransferDetector:
    def test_pure_callback_in_jaxpr(self):
        def f(x):
            return jax.pure_callback(
                lambda v: np.asarray(v), jax.ShapeDtypeStruct((4,), jnp.float32), x)

        findings = find_host_transfers(jax.make_jaxpr(f)(jnp.ones(4)),
                                       name="cb_program")
        assert [f_.rule for f_ in findings] == ["ATP102"]
        assert "pure_callback" in findings[0].message

    def test_device_put_in_jaxpr(self):
        def f(x):
            return jax.device_put(x) * 2

        findings = find_host_transfers(jax.make_jaxpr(f)(jnp.ones(4)))
        assert any("device_put" in f_.message for f_ in findings)

    def test_clean_program(self):
        fn, x = _psum_program()
        assert find_host_transfers(jax.make_jaxpr(fn)(x)) == []

    def test_hlo_text_callback_targets(self):
        text = 'custom-call(...), custom_call_target="xla_python_cpu_callback"'
        (f,) = find_host_transfers(text, name="p")
        assert f.rule == "ATP102"


class TestReplicationAudit:
    def _mesh(self):
        return Mesh(np.array(jax.devices()).reshape(8), ("data",))

    def test_replicated_big_leaf_flags(self):
        mesh = self._mesh()
        rep = jax.device_put(np.zeros((512, 1024), np.float32),
                             NamedSharding(mesh, P()))  # 2 MiB replicated
        (f,) = audit_replication({"w": rep}, threshold_bytes=1 << 20)
        assert f.rule == "ATP103" and "'w'" in f.message

    def test_sharded_and_small_leaves_pass(self):
        mesh = self._mesh()
        sharded = jax.device_put(np.zeros((512, 1024), np.float32),
                                 NamedSharding(mesh, P("data")))
        small = jax.device_put(np.zeros((8,), np.float32),
                               NamedSharding(mesh, P()))
        assert audit_replication(
            {"w": sharded, "b": small}, threshold_bytes=1 << 20) == []

    def test_threshold_is_respected(self):
        mesh = self._mesh()
        rep = jax.device_put(np.zeros((512, 1024), np.float32),
                             NamedSharding(mesh, P()))
        assert audit_replication({"w": rep}, threshold_bytes=1 << 30) == []


# ---------------------------------------------------------------------------
# strict mode: Accelerator + serving engine
# ---------------------------------------------------------------------------


def _loss_fn(p, b):
    return jnp.mean((b["x"] @ p["w"]) ** 2)


def _dp_accelerator(strict):
    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.utils import MeshConfig

    acc = Accelerator(mesh_config=MeshConfig(axes={"data": 8}), strict=strict)
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params={"w": np.ones((16, 16), np.float32)},
        tx=optax.sgd(1e-2)))
    loader = acc.prepare([{"x": np.ones((8, 16), np.float32)}])
    (batch,) = list(loader)
    return acc, ts, batch


class TestStrictMode:
    def test_error_mode_raises_at_trace_time_on_contract_violation(self):
        """Acceptance: strict='error' + a train step whose lowered
        collectives violate its declared contract -> AnalysisViolation
        before the program ever dispatches."""
        acc, ts, batch = _dp_accelerator("error")
        try:
            step = acc.train_step(_loss_fn, contract=CollectiveContract(
                name="dp_step", forbid=("all-reduce",)))  # DP MUST all-reduce
            with pytest.raises(AnalysisViolation, match="ATP101"):
                step(ts, batch)
            # a violating program raises on EVERY dispatch, not just #1
            with pytest.raises(AnalysisViolation):
                step(ts, batch)
        finally:
            acc.end_training()

    def test_error_mode_clean_contract_trains(self):
        acc, ts, batch = _dp_accelerator("error")
        try:
            step = acc.train_step(_loss_fn, contract=CollectiveContract(
                name="dp_step", require=("all-reduce",)))
            ts, m = step(ts, batch)
            assert bool(jax.device_get(jnp.isfinite(m["loss"])))
        finally:
            acc.end_training()

    def test_warn_mode_warns_and_counts_findings(self):
        acc, ts, batch = _dp_accelerator("warn")
        try:
            counter = acc.telemetry.counter(
                "analysis_findings_total", rule="ATP101")
            before = counter.value
            step = acc.train_step(_loss_fn, contract=CollectiveContract(
                name="dp_step", forbid=("all-reduce",)))
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                ts, _ = step(ts, batch)  # runs despite the finding
            assert any("ATP101" in str(x.message) for x in w)
            assert counter.value == before + 1
            # steady state: second call with the same layout never re-audits
            with warnings.catch_warnings(record=True) as w2:
                warnings.simplefilter("always")
                ts, _ = step(ts, batch)
            assert not any("ATP101" in str(x.message) for x in w2)
            assert counter.value == before + 1
        finally:
            acc.end_training()

    def test_error_mode_counts_findings_once_across_retries(self):
        """A caller that catches AnalysisViolation and retries must not
        inflate analysis_findings_total: the violation is cached per
        (layout, batch-sig) and re-raised without re-running the audit."""
        acc, ts, batch = _dp_accelerator("error")
        try:
            counter = acc.telemetry.counter(
                "analysis_findings_total", rule="ATP101")
            before = counter.value
            step = acc.train_step(_loss_fn, contract=CollectiveContract(
                name="dp_step", forbid=("all-reduce",)))
            for _ in range(3):
                with pytest.raises(AnalysisViolation):
                    step(ts, batch)
            assert counter.value == before + 1
        finally:
            acc.end_training()

    def test_batch_shape_drift_fallback_still_audits(self):
        """The identity-fast-path retry (batch shape drifts mid-loop, the
        stale AOT executable rejects the args) must route the NEW batch
        signature through the audit, not sidestep strict mode via the
        bare jit fallback."""
        from accelerate_tpu.data import make_global_batch

        acc, ts, batch = _dp_accelerator("warn")
        try:
            step = acc.train_step(_loss_fn, contract=CollectiveContract(
                name="dp_step", forbid=("all-reduce",)))
            with warnings.catch_warnings(record=True) as w1:
                warnings.simplefilter("always")
                ts, _ = step(ts, batch)  # audits signature A
            assert any("ATP101" in str(x.message) for x in w1)
            batch_b = make_global_batch(
                {"x": np.ones((16, 16), np.float32)}, acc.mesh)
            with warnings.catch_warnings(record=True) as w2:
                warnings.simplefilter("always")
                # ts is the previous output -> identity fast path -> the
                # signature-A executable rejects batch B -> fallback
                ts, _ = step(ts, batch_b)
            assert any("ATP101" in str(x.message) for x in w2), (
                "shape-drift fallback bypassed the strict audit")
        finally:
            acc.end_training()

    def test_transfer_guard_armed_and_restored(self):
        from accelerate_tpu.accelerator import Accelerator
        from accelerate_tpu.utils import MeshConfig

        prev = getattr(jax.config, "jax_transfer_guard_device_to_host",
                       "allow") or "allow"
        acc = Accelerator(mesh_config=MeshConfig(axes={"data": 8}),
                          strict="error")
        try:
            assert jax.config.jax_transfer_guard_device_to_host == "disallow"
        finally:
            acc.end_training()
        assert (getattr(jax.config, "jax_transfer_guard_device_to_host")
                or "allow") == prev

    def test_strict_rejects_bad_value(self):
        from accelerate_tpu.accelerator import Accelerator

        with pytest.raises(ValueError, match="strict"):
            Accelerator(strict="yes please")

    def test_strict_rejected_before_metrics_and_watchdog_start(self):
        """A bad strict value must not leak a bound metrics port or a live
        watchdog thread (same ordering guarantee as EngineConfig.strict)."""
        from accelerate_tpu.accelerator import Accelerator

        threads_before = {t.name for t in threading.enumerate()}
        with pytest.raises(ValueError, match="strict"):
            Accelerator(metrics_port=0, stall_timeout_s=60, strict="eror")
        leaked = {t.name for t in threading.enumerate()} - threads_before
        assert not leaked, f"failed init leaked threads: {leaked}"

    def test_warn_mode_replication_audit_flags_big_replicated_state(self):
        """The replication auditor reaches strict mode end to end: a DP
        state whose params exceed the (lowered) threshold is fully
        replicated by design and must be reported."""
        acc, ts, batch = _dp_accelerator("warn")
        try:
            step = acc.train_step(_loss_fn, replication_threshold=256)
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                step(ts, batch)
            assert any("ATP103" in str(x.message) for x in w)
        finally:
            acc.end_training()


class TestServingStrict:
    def _engine(self, **kw):
        from accelerate_tpu.models import gpt2
        from accelerate_tpu.serving.engine import Engine, EngineConfig

        cfg = gpt2.GPT2Config.tiny()
        params = gpt2.init_params(cfg, jax.random.key(0))
        return Engine(gpt2, cfg, params, EngineConfig(
            num_slots=2, max_len=64, prefill_chunk=8, **kw))

    def test_default_contracts_pass_on_clean_engine(self):
        eng = self._engine(strict="error")
        try:
            req = eng.submit(np.arange(5), max_new_tokens=3)
            eng.run_until_idle()
            assert len(req.tokens) == 3
            # every program audited, all recorded clean (None)
            assert eng._audited == {
                "admit": None, "prefill": None, "decode": None}
            snap = eng.registry.snapshot()
            assert not any("analysis_findings" in k
                           for k in snap["counters"])
        finally:
            eng.close()

    def test_violating_contract_raises_in_error_mode(self):
        eng = self._engine(
            strict="error",
            contracts={"prefill": CollectiveContract(
                name="serving.prefill", require=("all-reduce",))})
        try:
            eng.submit(np.arange(5), max_new_tokens=2)
            with pytest.raises(AnalysisViolation, match="ATP101"):
                eng.run_until_idle()
        finally:
            eng.close()

    def test_invalid_strict_rejected_before_side_effects(self):
        """A bad strict value must raise BEFORE the metrics port binds or
        the watchdog thread starts — nothing to leak on a failed init."""
        import threading

        from accelerate_tpu.models import gpt2
        from accelerate_tpu.serving.engine import Engine, EngineConfig

        cfg = gpt2.GPT2Config.tiny()
        params = gpt2.init_params(cfg, jax.random.key(0))
        threads_before = {t.name for t in threading.enumerate()}
        with pytest.raises(ValueError, match="strict"):
            Engine(gpt2, cfg, params, EngineConfig(
                num_slots=2, max_len=64, prefill_chunk=8,
                metrics_port=0, watchdog_timeout_s=60, strict="eror"))
        leaked = {t.name for t in threading.enumerate()} - threads_before
        assert not leaked, f"failed init leaked threads: {leaked}"

    def test_warn_mode_survives_audit_infrastructure_failure(self, monkeypatch):
        """strict='warn' promises 'warn and keep going': a crash in the
        audit machinery itself (not a finding) must not take down a
        serving step — same guarantee as the Accelerator's warn mode."""
        from accelerate_tpu.analysis import program as program_mod

        def boom(*a, **k):
            raise RuntimeError("audit infrastructure down")

        monkeypatch.setattr(program_mod, "find_host_transfers", boom)
        eng = self._engine(strict="warn")
        try:
            req = eng.submit(np.arange(5), max_new_tokens=3)
            eng.run_until_idle()
            assert len(req.tokens) == 3
        finally:
            eng.close()

    def test_error_mode_counts_findings_once_across_retries(self):
        eng = self._engine(
            strict="error",
            contracts={"prefill": CollectiveContract(
                name="serving.prefill", require=("all-reduce",))})
        try:
            eng.submit(np.arange(5), max_new_tokens=2)
            # every step() retries the same pending prefill: each attempt
            # re-raises the cached violation, the finding counts ONCE
            for _ in range(3):
                with pytest.raises(AnalysisViolation, match="ATP101"):
                    eng.step()
            snap = eng.registry.snapshot()
            assert snap["counters"][
                'analysis_findings_total{rule="ATP101"}'] == 1.0
        finally:
            eng.close()

    def test_mesh_placed_params_flagged(self):
        """'Params leaked onto a mesh': GSPMD inserts its collectives
        after the lowering the audit reads, so multi-device argument
        placement is caught directly at the placement."""
        from accelerate_tpu.models import gpt2
        from accelerate_tpu.serving.engine import Engine, EngineConfig

        cfg = gpt2.GPT2Config.tiny()
        params = gpt2.init_params(cfg, jax.random.key(0))
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        params = jax.device_put(
            params, NamedSharding(mesh, P()))  # replicated over 8 devices
        eng = Engine(gpt2, cfg, params, EngineConfig(
            num_slots=2, max_len=64, prefill_chunk=8, strict="error"))
        try:
            with pytest.raises(AnalysisViolation, match="devices"):
                eng.submit(np.arange(5), max_new_tokens=2)
                eng.run_until_idle()
        finally:
            eng.close()

    def test_violating_contract_warns_and_counts_in_warn_mode(self):
        eng = self._engine(
            strict="warn",
            contracts={"decode": CollectiveContract(
                name="serving.decode", require=("all-gather",))})
        try:
            req = eng.submit(np.arange(5), max_new_tokens=2)
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                eng.run_until_idle()
            assert any("ATP101" in str(x.message) for x in w)
            assert req.tokens  # engine kept serving
            snap = eng.registry.snapshot()
            assert snap["counters"][
                'analysis_findings_total{rule="ATP101"}'] == 1.0
        finally:
            eng.close()


class TestCheckpointSnapshotPair:
    """ISSUE 20: the stage/commit pair guarding the async-checkpoint
    manifest protocol is a declarative PAIRING_TABLE row — a staged
    snapshot that can leak past an exception path without commit() or
    rollback() is exactly the bug that publishes no manifest and strands
    a complete-on-disk checkpoint invisible."""

    def test_pair_is_registered(self):
        from accelerate_tpu.analysis.lifecycle import PAIRING_TABLE

        pair = next(p for p in PAIRING_TABLE
                    if p.name == "checkpoint-snapshot")
        assert pair.acquire == ("stage",)
        assert set(pair.release) == {"commit", "rollback"}
        assert pair.receivers == ("stager",)
        assert pair.returns_handle

    def test_staged_snapshot_leak_is_flagged(self):
        src = (
            "class Saver:\n"
            "    def save(self, output_dir, step):\n"
            "        pending = self.stager.stage(output_dir, step)\n"
            "        if step < 0:\n"
            "            return None\n"          # leaks the staged handle
            "        self.stager.commit(pending)\n"
        )
        findings = [f for f in lint_text(src, "t.py") if f.rule == "ATP201"]
        assert findings
        assert findings[0].data["resource"] == "checkpoint-snapshot"

    def test_rollback_on_error_path_is_clean(self):
        src = (
            "class Saver:\n"
            "    def save(self, output_dir, step):\n"
            "        pending = self.stager.stage(output_dir, step)\n"
            "        try:\n"
            "            self.write(pending)\n"
            "        except BaseException:\n"
            "            self.stager.rollback(pending)\n"
            "            raise\n"
            "        self.stager.commit(pending, deferred=True)\n"
        )
        assert not [f for f in lint_text(src, "t.py")
                    if f.rule == "ATP201"]

    def test_real_checkpointing_module_is_clean(self):
        """The production save path must pass its own guard rule."""
        path = os.path.join(REPO, "accelerate_tpu", "checkpointing.py")
        findings = lint_paths([path], root=REPO)
        assert not [f for f in findings if f.rule.startswith("ATP2")], \
            [(f.rule, f.line, f.data) for f in findings
             if f.rule.startswith("ATP2")]
