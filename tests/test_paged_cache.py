"""Paged KV cache + radix-tree prefix reuse (serving/cache.py, ISSUE 5).

Host-side contracts (no model): page pool accounting, radix-tree
match/insert/refcounts, LRU eviction that never touches a mapped page,
deferred admission under pool exhaustion. Engine contracts (tiny gpt2):
a prefix-hit request is token-exact vs the cold path with strictly fewer
prefill chunks, copy-on-write sharing isolates concurrent sharers from
each other's cancellation/retirement, the compile count stays flat
across hit/miss/eviction mixes, and strict-mode audits pass on the
gather/scatter programs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import gpt2
from accelerate_tpu.serving import (
    Engine,
    EngineConfig,
    PagedAllocator,
    PagedKVCache,
    PagePool,
    PrefixIndex,
    Request,
    RequestStatus,
)
from accelerate_tpu.serving.scheduler import Slot


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    """Engines here compile the same three tiny programs as
    tests/test_serving.py; the persistent cache turns repeats into
    deserializes."""
    import os

    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (ISSUE 16 hit this the moment
    # an engine module sorted before test_launched_scripts)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _engine(cfg, params, **overrides):
    defaults = dict(num_slots=2, max_len=64, prefill_chunk=8, page_size=8,
                    cache_dtype=jnp.float32)
    defaults.update(overrides)
    return Engine(gpt2, cfg, params, EngineConfig(**defaults))


def _ref_tokens(cfg, params, prompt, n):
    out = gpt2.generate(cfg, params, jnp.asarray(prompt)[None, :],
                        max_new_tokens=n)
    return np.asarray(out)[0, len(prompt):].tolist()


def _req(tokens, mnt=4):
    return Request(prompt=np.asarray(tokens, np.int32), max_new_tokens=mnt)


def _slot(alloc, req, index=0, prompt_done=None):
    s = Slot(index)
    s.alloc, s.request = alloc, req
    # a finished slot has prefilled its whole prompt; release() caps the
    # insertable range at prompt_done (a finish_early mid-prefill must
    # not cache garbage pages — see its regression test below)
    s.prompt_done = req.prompt_len if prompt_done is None else prompt_done
    return s


# ---------------------------------------------------------------------------
# host-side accounting (no model, no jit)
# ---------------------------------------------------------------------------


def test_page_pool_alloc_release_exact():
    pool = PagePool(4)
    assert pool.free_count == 4 and pool.used_count == 0
    got = pool.alloc(3)
    assert len(got) == 3 and len(set(got)) == 3
    assert pool.alloc(2) is None          # only 1 left: no partial grants
    assert pool.free_count == 1           # failed alloc changed nothing
    pool.release(got)
    assert pool.free_count == 4


def test_prefix_index_match_caps_below_full_prompt():
    """Reuse never covers the whole prompt: the last token must prefill
    to produce the first output logits."""
    idx = PrefixIndex(page_size=4)
    prompt = np.arange(8, dtype=np.int32)
    idx.insert(prompt, [0, 1], 2)         # both full pages cached
    assert len(idx.match(prompt)) == 1    # (8-1)//4 = 1, not 2
    longer = np.arange(9, dtype=np.int32)
    assert [n.page for n in idx.match(longer)] == [0, 1]


def test_prefix_index_insert_dedupes_concurrent_equal_chunks():
    idx = PrefixIndex(page_size=4)
    prompt = np.arange(8, dtype=np.int32)
    assert idx.insert(prompt, [0, 1], 2) == []
    # a second request computed the same prefix into different pages:
    # the tree keeps the first copy, the duplicates come back to free
    assert idx.insert(prompt, [5, 6], 2) == [5, 6]
    assert idx.cached_pages == 2


def test_allocator_lru_eviction_never_evicts_mapped_pages():
    al = PagedAllocator(page_size=4, num_pages=8, pad_slack=0)
    A = _req(list(range(100, 112)), mnt=0)    # 3 pages
    B = _req(list(range(200, 212)), mnt=0)    # 3 pages
    for r in (A, B):
        al.release(_slot(al.allocate(r), r), finished=True)
    assert al.index.cached_pages == 6 and al.pages_free == 2
    # touch A (now most-recent AND mapped), then demand 4 cold pages:
    # only B's pages are evictable, leaf-first, oldest-first
    a2 = al.allocate(_req(list(range(100, 112)) + [7], mnt=0))
    assert a2.reused_len == 12
    c = al.allocate(_req(list(range(300, 316)), mnt=0))
    assert c is not None and c.reused_len == 0
    assert al.evictions == 3                  # exactly B's three pages
    assert all(n.parent is not None for n in a2.nodes)  # A survived


def test_allocator_defers_admission_until_pages_free():
    al = PagedAllocator(page_size=4, num_pages=4, pad_slack=0)
    D = _req(list(range(16)), mnt=0)          # takes the whole pool
    d = al.allocate(D)
    assert d is not None
    E = _req(list(range(50, 62)), mnt=0)
    assert al.allocate(E) is None             # mapped pages: unevictable
    al.release(_slot(d, D), finished=True)    # retire -> pages cached
    e = al.allocate(E)                        # now evictable
    assert e is not None and al.evictions == 3


def test_allocate_releases_refcounts_when_on_evict_raises():
    """ATP201 regression (ISSUE 13 self-lint finding): on_evict is a
    caller-supplied callback running MID-allocate; if it raises, the
    matched prefix nodes' refcounts must not leak (a leaked refcount
    pins its whole root path unevictable forever)."""
    def boom(n):
        raise RuntimeError("exporter fell over")

    al = PagedAllocator(page_size=4, num_pages=8, pad_slack=0,
                        on_evict=boom)
    A = _req(list(range(100, 108)), mnt=0)    # 2 pages
    C = _req(list(range(200, 208)), mnt=0)    # 2 pages
    for r in (A, C):
        al.release(_slot(al.allocate(r), r), finished=True)
    assert al.index.cached_pages == 4 and al.pages_free == 4
    # B reuses A's prefix (2 acquired nodes) and needs 5 private pages:
    # eviction fires, on_evict raises mid-protocol
    B = _req(list(range(100, 108)) + list(range(300, 304)), mnt=16)
    with pytest.raises(RuntimeError, match="exporter fell over"):
        al.allocate(B)
    assert al.index.mapped_pages == 0         # the refcounts came back
    # the allocator still works once the callback behaves
    al.on_evict = None
    b = al.allocate(B)
    assert b is not None and b.reused_len == 8


def test_release_after_early_finish_caches_only_prefilled_pages():
    """finish_early can retire a slot whose prefill is still mid-flight;
    release(finished=True) must cap the cached range at prompt_done —
    pages past it were never written and caching them would serve
    garbage KV to the next prefix hit (ISSUE 13 lifecycle-audit fix)."""
    al = PagedAllocator(page_size=4, num_pages=8, pad_slack=0)
    A = _req(list(range(100, 116)), mnt=0)    # 16 tokens, 4 pages
    a = al.allocate(A)
    slot = _slot(a, A, prompt_done=6)         # prefill stopped mid-page 2
    al.release(slot, finished=True)
    assert al.index.cached_pages == 1         # only the COMPLETED page
    b = al.allocate(_req(list(range(100, 116)), mnt=0))
    assert b.reused_len == 4                  # and reuse stops there
    # all other pages went back to the free list, nothing leaked:
    # 8 total - 1 cached+remapped - 3 private for b
    assert al.pages_free == 4


def test_failed_admission_evicts_nothing():
    """evict_lru is all-or-nothing: when even full eviction cannot cover
    the queue head, the cached prefixes survive untouched — a too-big
    request waiting in queue must not strip reuse from everyone else."""
    al = PagedAllocator(page_size=4, num_pages=8, pad_slack=0)
    A = _req(list(range(100, 112)), mnt=0)    # 3 pages, caches 3
    al.release(_slot(al.allocate(A), A), finished=True)
    B = _req(list(range(200, 212)), mnt=0)    # 3 more pages mapped
    b = al.allocate(B)
    assert b is not None                      # free: 8 - 3 - 3 = 2
    big = _req(list(range(300, 324)), mnt=0)  # needs 6 > 2 free + 3 cached
    assert al.index.mapped_pages == 0         # B's pages are all private
    assert al.allocate(big) is None
    assert al.evictions == 0                  # nothing was destroyed
    assert al.index.cached_pages == 3         # A's prefix still reusable
    a2 = al.allocate(_req(list(range(100, 113)), mnt=0))
    assert a2 is not None and a2.reused_len == 12
    assert al.index.mapped_pages == 3         # the evictable-count books


def test_allocator_cancel_caches_nothing():
    """A cancelled request's pages may hold garbage mid-prefill: they go
    to the free list, never into the tree."""
    al = PagedAllocator(page_size=4, num_pages=8, pad_slack=0)
    A = _req(list(range(16)), mnt=0)
    a = al.allocate(A)
    al.release(_slot(a, A), finished=False)
    assert al.index.cached_pages == 0 and al.pages_free == 8


def test_allocator_prefix_cache_off_is_always_cold():
    al = PagedAllocator(page_size=4, num_pages=8, pad_slack=0,
                        prefix_cache=False)
    A = _req(list(range(16)), mnt=0)
    al.release(_slot(al.allocate(A), A), finished=True)
    assert al.index.cached_pages == 0
    assert al.allocate(A).reused_len == 0
    assert al.hits == 0


def test_paged_cache_shapes_and_pytree():
    cache = PagedKVCache.create(num_layers=2, num_slots=3, max_len=16,
                                num_kv_heads=4, head_dim=8,
                                dtype=jnp.float32, page_size=8, pad_slack=4)
    # ceil((16+4)/8) = 3 pages/slot, default pool 9 pages + 1 trash
    assert cache.pages_per_slot == 3 and cache.num_pages == 9
    assert cache.k.shape == (2, 10, 8, 4, 8)
    assert cache.rows == 24 and cache.trash_page == 9
    leaves, treedef = jax.tree_util.tree_flatten(cache)
    assert len(leaves) == 3
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.page_size == 8 and rebuilt.pages_per_slot == 3
    with pytest.raises(ValueError):
        PagedKVCache.create(2, 3, 16, 4, 8, page_size=8, num_pages=1)


# ---------------------------------------------------------------------------
# engine contracts
# ---------------------------------------------------------------------------


def test_prefix_hit_is_token_exact_and_skips_prefill(gpt2_setup):
    """The acceptance contract: a request sharing a cached prefix decodes
    token-identically to the cold path while running strictly fewer
    prefill chunks, through the same three compiled programs."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, cfg.vocab_size, (24,)).astype(np.int32)
    p1 = np.concatenate([prefix,
                         rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)])
    p2 = np.concatenate([prefix,
                         rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)])
    r1 = eng.submit(p1, max_new_tokens=6)
    eng.run_until_idle()
    cold_chunks = eng.metrics.prefill_chunks
    r2 = eng.submit(p2, max_new_tokens=6)
    eng.run_until_idle()
    warm_chunks = eng.metrics.prefill_chunks - cold_chunks
    assert r1.tokens == _ref_tokens(cfg, params, p1, 6)
    assert r2.tokens == _ref_tokens(cfg, params, p2, 6)
    assert eng.metrics.prefix_hits == 1
    assert eng.metrics.prefix_tokens_reused == 24
    assert warm_chunks < cold_chunks
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}


def test_cow_sharers_isolated_under_cancel_and_retire(gpt2_setup):
    """Two live requests mapping the same cached prefix pages: cancelling
    one (and letting the other retire first/later) never perturbs the
    survivor's tokens — shared pages are refcounted, never written, and a
    release only frees PRIVATE pages."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params)
    rng = np.random.default_rng(1)
    prefix = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)

    def with_suffix(n):
        return np.concatenate(
            [prefix, rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)])

    warm = eng.submit(with_suffix(4), max_new_tokens=2)
    eng.run_until_idle()                      # prefix pages now cached
    # equal suffix lengths + budgets keep the reference `generate` to
    # ONE compiled shape across c and d (tier-1 budget)
    pb, pc = with_suffix(5), with_suffix(7)
    b = eng.submit(pb, max_new_tokens=10)
    c = eng.submit(pc, max_new_tokens=10)
    for _ in range(6):                        # both mid-flight, sharing
        eng.step()
    assert eng.metrics.prefix_hits == 2
    assert eng.cancel(b)
    eng.run_until_idle()
    assert b.status is RequestStatus.CANCELLED
    assert c.status is RequestStatus.FINISHED
    assert c.tokens == _ref_tokens(cfg, params, pc, 10)
    # and the prefix is STILL reusable after both sharers are gone
    pd = with_suffix(7)
    d = eng.submit(pd, max_new_tokens=10)
    eng.run_until_idle()
    assert eng.metrics.prefix_hits == 3
    assert d.tokens == _ref_tokens(cfg, params, pd, 10)


def test_eviction_under_pool_pressure_stays_exact(gpt2_setup):
    """A pool sized below the cached working set forces LRU evictions;
    outputs stay exact and no compiled program is added."""
    cfg, params = gpt2_setup
    # pool at the floor (pages_per_slot = ceil((64+8)/8) = 9): each
    # 40-token prompt needs ceil((40+4+8)/8) = 7 pages but a retired one
    # caches 5, so every later admission must evict. Equal lengths keep
    # the reference `generate` to ONE compiled shape (tier-1 budget).
    eng = _engine(cfg, params, num_pages=9)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, cfg.vocab_size, (40,)).astype(np.int32)
               for _ in range(3)]
    for p in prompts:
        r = eng.submit(p, max_new_tokens=4)
        eng.run_until_idle()
        assert r.tokens == _ref_tokens(cfg, params, p, 4)
    assert eng.metrics.page_evictions > 0
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1}
    s = eng.metrics_summary()
    assert s["page_evictions"] > 0
    assert s["pages_in_use"] + s["pages_free"] == 9


def test_compile_count_flat_across_hit_miss_eviction_mix(gpt2_setup):
    """The PR 2 guard extended per ISSUE 5: shared-prefix hits, cold
    misses, and eviction churn are all DATA — page tables and reused
    lengths are traced, so the program count never moves."""
    cfg, params = gpt2_setup
    # pool at the floor (pages_per_slot = 9): the evictor wave MUST churn
    eng = _engine(cfg, params, num_pages=9)
    rng = np.random.default_rng(3)
    prefix = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    waves = [
        np.concatenate([prefix, rng.integers(0, cfg.vocab_size, (4,))
                        .astype(np.int32)]),               # cold prefix
        np.concatenate([prefix, rng.integers(0, cfg.vocab_size, (9,))
                        .astype(np.int32)]),               # hit
        rng.integers(0, cfg.vocab_size, (40,)).astype(np.int32),  # evictor
        np.concatenate([prefix, rng.integers(0, cfg.vocab_size, (2,))
                        .astype(np.int32)]),               # re-miss or hit
    ]
    for wave, (p, temp) in enumerate(zip(waves, (0.0, 1.0, 0.0, 0.7))):
        r = eng.submit(p, max_new_tokens=3, temperature=temp)
        eng.run_until_idle()
        assert r.status is RequestStatus.FINISHED
        counts = eng.compile_stats()
        assert counts == {"admit": 1, "prefill": 1, "decode": 1}, (
            f"wave {wave} recompiled: {counts}")
    assert eng.metrics.prefix_hits >= 1
    assert eng.metrics.page_evictions > 0


def test_strict_error_passes_on_paged_programs(gpt2_setup):
    """Acceptance: EngineConfig(strict="error") audits the paged
    gather/scatter programs (admit/prefill/decode) clean — page-axis
    gathers are data movement, not collectives — including on the
    prefix-hit path."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, strict="error")
    rng = np.random.default_rng(4)
    p = rng.integers(0, cfg.vocab_size, (20,)).astype(np.int32)
    r1 = eng.submit(p, max_new_tokens=4)
    eng.run_until_idle()
    r2 = eng.submit(np.concatenate([p, [7, 8]]).astype(np.int32),
                    max_new_tokens=4)
    eng.run_until_idle()
    assert r1.status is RequestStatus.FINISHED
    assert r2.status is RequestStatus.FINISHED
    assert eng.metrics.prefix_hits == 1
    assert float(eng.registry.counter("analysis_findings_total").value) == 0


def test_prefix_reuse_vs_no_reuse_same_trace(gpt2_setup):
    """The serve_bench A/B, deterministically: the same prompt trace
    through a reuse engine and a prefix_cache=False engine yields
    token-identical outputs with strictly fewer prefill chunks (and a
    hit rate > 0) on the reuse side."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(5)
    pool = [rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
            for _ in range(2)]
    trace = [np.concatenate(
        [pool[int(rng.integers(2))],
         rng.integers(0, cfg.vocab_size, (int(rng.integers(2, 6)),))
         .astype(np.int32)]) for _ in range(8)]

    results = {}
    for reuse in (True, False):
        eng = _engine(cfg, params, prefix_cache=reuse)
        reqs = [eng.submit(p, max_new_tokens=4) for p in trace]
        eng.run_until_idle()
        assert all(r.status is RequestStatus.FINISHED for r in reqs)
        results[reuse] = ([r.tokens for r in reqs],
                          eng.metrics.prefill_chunks,
                          eng.metrics_summary().get("prefix_hit_rate", 0.0))
    tokens_reuse, chunks_reuse, hit_rate = results[True]
    tokens_cold, chunks_cold, _ = results[False]
    assert tokens_reuse == tokens_cold
    assert hit_rate > 0
    assert chunks_reuse < chunks_cold, (chunks_reuse, chunks_cold)


def test_prometheus_exposition_carries_page_and_prefix_series(gpt2_setup):
    """The new pool gauges and prefix counters ride the same per-engine
    registry the exporter serves."""
    import urllib.request

    cfg, params = gpt2_setup
    eng = _engine(cfg, params, metrics_port=0)
    try:
        rng = np.random.default_rng(6)
        p = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
        eng.submit(p, max_new_tokens=3)
        eng.run_until_idle()
        eng.submit(np.concatenate([p, [1]]).astype(np.int32),
                   max_new_tokens=3)
        eng.run_until_idle()
        url = f"http://127.0.0.1:{eng.metrics_server.port}/metrics"
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        for series in ("serving_pages_in_use", "serving_pages_free",
                       "serving_prefix_hits_total",
                       "serving_prefix_tokens_reused_total",
                       "serving_page_evictions_total"):
            assert series in body, f"{series} missing from exposition"
        assert "serving_prefix_hits_total 1.0" in body
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# ISSUE 10: int8 KV pool mode
# ---------------------------------------------------------------------------


def test_int8_pool_shapes_pytree_and_page_nbytes():
    """Quantized create: int8 codes + bf16 per-row-per-head scales as
    extra pytree children; page_nbytes is the HBM-math unit ((D+2)/2D of
    a bf16 page — half the code bytes plus the 2/D scale overhead)."""
    kw = dict(num_layers=2, num_slots=2, max_len=32, num_kv_heads=2,
              head_dim=16, page_size=8, pad_slack=8)
    bf = PagedKVCache.create(**kw)
    q = PagedKVCache.create(**kw, kv_dtype="int8")
    assert q.quantized and not bf.quantized
    assert q.k.dtype == jnp.int8
    assert q.k_scale.shape == q.k.shape[:-1]
    assert q.k_scale.dtype == jnp.bfloat16
    D = kw["head_dim"]
    assert q.page_nbytes / bf.page_nbytes == (D + 2) / (2 * D)
    assert q.nbytes() == q.k.nbytes * 2 + q.k_scale.nbytes * 2
    leaves, treedef = jax.tree_util.tree_flatten(q)
    assert len(leaves) == 5  # k, v, lengths, k_scale, v_scale
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.quantized and rebuilt.compute_dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="kv_dtype"):
        PagedKVCache.create(**kw, kv_dtype="int4")


def test_int8_write_then_view_roundtrips_and_leaves_other_rows_bitstable():
    """Row-granular quantized writes: a later chunk's write never
    re-encodes earlier rows (an int8 round-trip is not idempotent, so
    whole-page rewrites would drift shared bytes — the COW hazard the
    per-row design removes)."""
    from accelerate_tpu.serving.cache import paged_slot_view, paged_write_slot

    rng = np.random.default_rng(0)
    cache = PagedKVCache.create(num_layers=1, num_slots=1, max_len=16,
                                num_kv_heads=2, head_dim=8, page_size=8,
                                pad_slack=8, kv_dtype="int8",
                                dtype=jnp.float32)
    table_row = jnp.arange(cache.pages_per_slot, dtype=jnp.int32)
    R = cache.rows
    chunk = 8

    def payload(seed):
        return jnp.asarray(rng.normal(size=(1, 1, R, 2, 8)), jnp.float32)

    first = payload(1)
    cache = paged_write_slot(cache, table_row, jnp.int32(0), first, first,
                             jnp.int32(5), chunk)  # rows 0..7, 5 real
    codes_after_first = np.asarray(cache.k).copy()
    scales_after_first = np.asarray(cache.k_scale).copy()
    cache = paged_write_slot(cache, table_row, jnp.int32(0), payload(2),
                             payload(2), jnp.int32(8), chunk)  # rows 5..12
    # rows 0..4 (written only by the first chunk) are bit-identical
    np.testing.assert_array_equal(np.asarray(cache.k)[:, 0, :5],
                                  codes_after_first[:, 0, :5])
    np.testing.assert_array_equal(np.asarray(cache.k_scale)[:, 0, :5],
                                  scales_after_first[:, 0, :5])
    # and the dense view dequantizes to within the int8 error of the
    # payload on the real rows
    ks, _, length = paged_slot_view(cache, table_row, jnp.int32(0))
    assert int(length) == 13
    got = np.asarray(ks[0, 0, :5], np.float32)
    want = np.asarray(first[0, 0, :5], np.float32)
    absmax = np.abs(want).max(-1, keepdims=True)
    assert np.all(np.abs(got - want) <= absmax * (1 / 254 + 2 ** -8) + 1e-6)


def test_int8_append_rows_quantizes_one_row_per_live_slot():
    from accelerate_tpu.serving.cache import paged_append_rows

    cache = PagedKVCache.create(num_layers=1, num_slots=2, max_len=16,
                                num_kv_heads=2, head_dim=8, page_size=8,
                                pad_slack=0, kv_dtype="int8",
                                dtype=jnp.float32)
    import dataclasses

    cache = dataclasses.replace(cache,
                                lengths=jnp.asarray([3, 0], jnp.int32))
    table = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
    rng = np.random.default_rng(1)
    row_k = jnp.asarray(rng.normal(size=(1, 2, 2, 8)), jnp.float32)
    row_v = jnp.asarray(rng.normal(size=(1, 2, 2, 8)), jnp.float32)
    out = paged_append_rows(cache, table, row_k, row_v,
                            jnp.asarray([True, False]))
    assert out.lengths.tolist() == [4, 0]  # only the live lane advances
    # slot 0 row landed at page 0 offset 3, quantized
    from accelerate_tpu.ops.quant import kv_dequantize_rows

    got = kv_dequantize_rows(out.k[0, 0, 3], out.k_scale[0, 0, 3],
                             jnp.float32)
    want = np.asarray(row_k[0, 0], np.float32)
    absmax = np.abs(want).max(-1, keepdims=True)
    assert np.all(np.abs(np.asarray(got) - want)
                  <= absmax * (1 / 254 + 2 ** -8) + 1e-6)


def test_int8_prefix_reuse_vs_no_reuse_same_trace(gpt2_setup):
    """COW sharing under quantization: the reuse-vs-cold A/B stays
    token-identical with int8 pages (shared pages' codes are never
    re-encoded — bit-stable however many sharers race) and still saves
    prefill chunks."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(6)
    shared = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    trace = [np.concatenate(
        [shared, rng.integers(0, cfg.vocab_size,
                              (int(rng.integers(2, 6)),)).astype(np.int32)])
        for _ in range(6)]
    results = {}
    for reuse in (True, False):
        eng = _engine(cfg, params, prefix_cache=reuse, kv_dtype="int8")
        reqs = [eng.submit(p, max_new_tokens=4) for p in trace]
        eng.run_until_idle()
        assert all(r.status is RequestStatus.FINISHED for r in reqs)
        results[reuse] = ([r.tokens for r in reqs],
                          eng.metrics.prefill_chunks)
    assert results[True][0] == results[False][0]
    assert results[True][1] < results[False][1]
