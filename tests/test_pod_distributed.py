"""True multi-host pod (serving/pod/distributed): wire transport,
worker heartbeats + failure recovery, elastic rebalancing.

CPU contracts, all deterministic: the frame codec round-trips shipments
byte-identically (incl. int8 codes+scales) and rejects malformed frames
without executing anything; the in-process distributed pod (LocalChannel
pairs through the real codec, fake clock) is byte-identical to the
single engine on the seeded greedy+sampled trace with compile counts
flat; every injected failure — dropped shipments, duplicated frames,
killed decode worker mid-stream, killed prefill worker mid-prefill, a
hung (heartbeat-silent) worker, random flake storms — recovers every
in-flight request by re-prefill-from-prompt with NO lost or duplicated
tokens; rebalancing converts at most one role per window; worker
registry snapshots merge into the router's exposition; and the
cross-process sanitizer invariants catch corrupted router books. The
two-OS-process socket smoke (pod_distributed_script.py) proves the same
exactness + kill-recovery across real process boundaries."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import gpt2
from accelerate_tpu.serving import Engine, EngineConfig
from accelerate_tpu.serving.pod import KVPageShipment
from accelerate_tpu.serving.pod.distributed import (
    DistributedPodConfig,
    FlakyTransport,
    LocalChannel,
    Message,
    SocketChannel,
    build_local_distributed_pod,
    decode_message,
    encode_message,
    shipment_from_message,
    shipment_to_message,
)
from accelerate_tpu.serving.pod.distributed.transport import ChannelListener
from accelerate_tpu.serving.pod.distributed.wire import MAGIC, WireError
from accelerate_tpu.serving.sanitizer import (
    SanitizerViolation,
    check_distributed_router,
)


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    """Same engine programs compile over and over across pods here; the
    persistent cache turns repeats into deserializes (see test_pod.py
    for the threshold/segfault caveats this fixture handles). The dir is
    ALSO exported so the two-process smoke's children — the script and
    its spawned pod-workers, three processes compiling the same spec —
    compile once and deserialize twice (tier-1 budget)."""
    from accelerate_tpu.utils.environment import configure_compilation_cache

    cache_dir = str(tmp_path_factory.mktemp("xla_cache"))
    prev = {k: os.environ.get(k)
            for k in ("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS",
                      "ACCELERATE_TPU_COMPILATION_CACHE")}
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    os.environ["ACCELERATE_TPU_COMPILATION_CACHE"] = cache_dir
    configure_compilation_cache(cache_dir, force=True)
    yield
    for k, v in prev.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _ec(**overrides):
    defaults = dict(num_slots=3, max_len=64, prefill_chunk=8, page_size=8,
                    cache_dtype=jnp.float32)
    defaults.update(overrides)
    return EngineConfig(**defaults)


def _traffic(cfg):
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
               for n in (5, 11, 3, 17)]
    return prompts, (6, 6, 4, 4), (0.0, 0.7, 0.0, 1.1)


@pytest.fixture(scope="module")
def ref_outputs(gpt2_setup):
    """Single-engine tokens AND logprobs for the seeded trace; sampling
    keys fold in the request id, so any pod that submits the same trace
    in the same order must reproduce these byte for byte."""
    cfg, params = gpt2_setup
    engine = Engine(gpt2, cfg, params, _ec())
    prompts, budgets, temps = _traffic(cfg)
    reqs = [engine.submit(p, max_new_tokens=b, temperature=t)
            for p, b, t in zip(prompts, budgets, temps)]
    engine.run_until_idle()
    assert all(r.status.value == "finished" for r in reqs)
    return ([list(r.tokens) for r in reqs],
            [list(r.logprobs) for r in reqs])


def _fake_clock():
    t = [0.0]

    def clock():
        t[0] += 0.01
        return t[0]

    return clock


def _build_pod(cfg, params, pf=1, dec=1, wrap=None, **pc_kwargs):
    pc_kwargs.setdefault("heartbeat_interval_s", 0.0)
    pc_kwargs.setdefault("rebalance", False)
    return build_local_distributed_pod(
        gpt2, cfg, params, engine_config=_ec(),
        pod_config=DistributedPodConfig(
            prefill_workers=pf, decode_workers=dec, **pc_kwargs),
        clock=_fake_clock(), channel_wrap=wrap)


def _drive(router, reqs, max_steps=5000):
    for _ in range(max_steps):
        router.step()
        if all(r.done for r in reqs):
            return
    raise AssertionError(f"pod wedged: {router.debug_pod()}")


def _submit_traffic(router, cfg):
    prompts, budgets, temps = _traffic(cfg)
    return [router.submit(p, max_new_tokens=b, temperature=t)
            for p, b, t in zip(prompts, budgets, temps)]


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_message_frame_roundtrip_byte_identical():
    msg = Message("tokens",
                  {"flight_id": 3, "attempt": 2, "nested": {"a": [1, 2]}},
                  [np.arange(12, dtype=np.float32).reshape(3, 4),
                   np.array([7, 8, 9], dtype=np.int32),
                   np.array([1, 2], dtype=np.uint32)])
    got = decode_message(encode_message(msg))
    assert got.kind == msg.kind and got.meta == msg.meta
    assert len(got.buffers) == 3
    for a, b in zip(got.buffers, msg.buffers):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()
    # re-encoding the decode is bit-stable (no lossy hop anywhere)
    assert encode_message(got) == encode_message(msg)


def test_message_frame_roundtrip_extension_dtype():
    """bfloat16 is an ml_dtypes extension type whose `.str` is an opaque
    void tag ("<V2") — the codec must ship the registered NAME instead,
    or every bf16 KV pool decodes as void and the install jit rejects
    it. Regression for the serve_bench socket arm (cache_dtype=bf16)."""
    import ml_dtypes

    a = (np.arange(12, dtype=np.float32) / 7.0).astype(
        ml_dtypes.bfloat16).reshape(3, 4)
    got = decode_message(encode_message(Message("x", {}, [a])))
    assert got.buffers[0].dtype == a.dtype
    assert got.buffers[0].tobytes() == a.tobytes()


def _mk_shipment(quantized):
    L, pages, ps, H, D = 2, 5, 8, 2, 4
    rng = np.random.default_rng(3)
    kw = dict(
        prompt=np.arange(20, dtype=np.int32),
        first_token=17, n_prompt_pages=3,
        key_raw=np.array([123, 456], np.uint32),
        temperature=0.7, max_new_tokens=9, eos_token_id=None,
        src_worker=2, extracted_at=1.25, first_logprob=-0.5)
    if quantized:
        kw["k_pages"] = rng.integers(-128, 128, (L, pages, ps, H, D)
                                     ).astype(np.int8)
        kw["v_pages"] = rng.integers(-128, 128, (L, pages, ps, H, D)
                                     ).astype(np.int8)
        kw["k_scales"] = rng.random((L, pages, ps, H)).astype(np.float32)
        kw["v_scales"] = rng.random((L, pages, ps, H)).astype(np.float32)
    else:
        kw["k_pages"] = rng.random((L, pages, ps, H, D)).astype(np.float32)
        kw["v_pages"] = rng.random((L, pages, ps, H, D)).astype(np.float32)
    return KVPageShipment(**kw)


@pytest.mark.parametrize("quantized", [False, True])
def test_shipment_wire_roundtrip_byte_identical(quantized):
    """The hot path contract: a codes+scales shipment crosses the frame
    format with every tensor byte intact and every scalar field exact —
    int8 pools ship codes verbatim (no dequant/requant drift)."""
    ship = _mk_shipment(quantized)
    msg = decode_message(encode_message(
        shipment_to_message(ship, flight_id=5, attempt=1, worker_id=2)))
    assert msg.meta["flight_id"] == 5 and msg.meta["attempt"] == 1
    got = shipment_from_message(msg)
    for name in ("k_pages", "v_pages"):
        a, b = getattr(got, name), getattr(ship, name)
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()
    if quantized:
        for name in ("k_scales", "v_scales"):
            assert getattr(got, name).tobytes() == \
                getattr(ship, name).tobytes()
    else:
        assert got.k_scales is None and got.v_scales is None
    assert got.prompt.tolist() == ship.prompt.tolist()
    assert got.key_raw.tolist() == ship.key_raw.tolist()
    for name in ("first_token", "n_prompt_pages", "temperature",
                 "max_new_tokens", "eos_token_id", "src_worker",
                 "extracted_at", "first_logprob"):
        assert getattr(got, name) == getattr(ship, name), name
    assert got.page_bytes == ship.page_bytes


def test_malformed_frames_raise_wire_error():
    frame = encode_message(Message("x", {"a": 1},
                                   [np.arange(4, dtype=np.float32)]))
    # truncation at every boundary class
    with pytest.raises(WireError):
        decode_message(frame[:8])
    with pytest.raises(WireError):
        decode_message(frame[:-3])
    # trailing junk: body longer than the descriptors account for
    with pytest.raises(WireError):
        decode_message(frame + b"JUNK")
    # bad magic
    with pytest.raises(WireError):
        decode_message(b"NOPE" + frame[4:])
    # header that is not JSON
    broken = bytearray(frame)
    broken[16] ^= 0xFF
    with pytest.raises(WireError):
        decode_message(bytes(broken))
    # descriptor that overruns the body it claims to describe
    big = Message("x", {}, [np.arange(100, dtype=np.float32)])
    small = encode_message(Message("x", {}, [np.arange(2, dtype=np.float32)]))
    header = encode_message(big)[:16]
    with pytest.raises(WireError):
        decode_message(header + small[16:])
    # a shipment frame with the wrong buffer count
    ship_msg = shipment_to_message(_mk_shipment(True))
    ship_msg.buffers = ship_msg.buffers[:3]
    with pytest.raises(WireError):
        shipment_from_message(ship_msg)


# ---------------------------------------------------------------------------
# channels
# ---------------------------------------------------------------------------


def test_local_channel_pair_crosses_the_codec():
    a, b = LocalChannel.pair()
    a.send(Message("ping", {"n": 1}, [np.arange(3, dtype=np.int32)]))
    b.send(Message("pong", {"n": 2}))
    got_b = b.poll()
    got_a = a.poll()
    assert [m.kind for m in got_b] == ["ping"]
    assert got_b[0].buffers[0].tolist() == [0, 1, 2]
    assert [m.kind for m in got_a] == ["pong"]
    assert a.bytes_sent > 0 and b.bytes_received == a.bytes_sent
    b.close()
    assert a.closed and b.closed
    with pytest.raises(ConnectionError):
        a.send(Message("ping", {}))


def test_socket_channel_roundtrip_and_close_detection():
    listener = ChannelListener("127.0.0.1", 0)
    try:
        client = SocketChannel.connect("127.0.0.1", listener.port)
        server = None
        deadline = 200
        while server is None and deadline:
            got = listener.accept_all()
            server = got[0] if got else None
            deadline -= 1
        assert server is not None
        client.send(Message("hello", {"worker_id": 7},
                            [np.arange(5, dtype=np.uint32)]))
        msgs = []
        for _ in range(500):
            msgs = server.poll()
            if msgs:
                break
            import time
            time.sleep(0.01)
        assert msgs and msgs[0].kind == "hello"
        assert msgs[0].meta["worker_id"] == 7
        assert msgs[0].buffers[0].tolist() == [0, 1, 2, 3, 4]
        # peer death is visible as `.closed`, and sends then raise
        server.close()
        for _ in range(500):
            if client.closed:
                break
            import time
            time.sleep(0.01)
        assert client.closed
        with pytest.raises(ConnectionError):
            client.send(Message("x", {}))
    finally:
        listener.close()


def test_socket_send_queue_bounded_backpressure():
    """The backpressure semantic: with the writer stalled and the
    bounded queue full, `send` BLOCKS (the router's forwarding step is
    the thing that waits) until space frees — then completes."""
    import socket as socketlib
    import threading
    import time

    listener = ChannelListener("127.0.0.1", 0)
    try:
        raw = socketlib.create_connection(("127.0.0.1", listener.port))
        ch = SocketChannel(raw, send_queue_depth=1)
        # stop the writer thread deterministically, then fill the queue
        ch._sendq.put(None)
        ch._writer.join(timeout=5)
        ch._sendq.put(b"filler")
        done = threading.Event()

        def sender():
            ch.send(Message("shipment", {"flight_id": 1}))
            done.set()

        t = threading.Thread(target=sender, daemon=True)
        t.start()
        time.sleep(0.25)
        assert not done.is_set(), "send returned despite a full queue"
        assert ch._sendq.get() == b"filler"   # drain one slot
        assert done.wait(timeout=5), "send never unblocked"
        ch.close()
        with pytest.raises(ConnectionError):
            ch.send(Message("x", {}))
    finally:
        listener.close()


def test_socket_close_joins_io_threads():
    """ATP305 regression: `close()` must reap the reader/writer threads,
    not just mark the channel closed — a leaked IO thread pins its
    socket and races interpreter teardown. The reader also closes the
    channel from its OWN thread on peer death, so the join has to guard
    against self-join instead of deadlocking."""
    import time

    listener = ChannelListener("127.0.0.1", 0)
    try:
        client = SocketChannel.connect("127.0.0.1", listener.port)
        server = None
        for _ in range(200):
            got = listener.accept_all()
            if got:
                server = got[0]
                break
            time.sleep(0.01)
        assert server is not None
        assert client._reader.is_alive() and client._writer.is_alive()
        client.close()
        assert not client._reader.is_alive(), "reader leaked past close()"
        assert not client._writer.is_alive(), "writer leaked past close()"
        # peer death path: server's reader notices and closes from inside
        # the reader thread itself — must finish, not self-join-wedge
        for _ in range(500):
            if server.closed:
                break
            time.sleep(0.01)
        assert server.closed
        server.close()
        for _ in range(500):
            if not (server._reader.is_alive() or server._writer.is_alive()):
                break
            time.sleep(0.01)
        assert not server._reader.is_alive()
        assert not server._writer.is_alive()
    finally:
        listener.close()


def test_step_never_sleeps_on_the_callers_thread(gpt2_setup, monkeypatch):
    """ATP303 regression: `step()` runs inline on the asyncio drive loop
    (astream), so a sleep inside it parks every coroutine on the loop.
    Pacing belongs to the sync callers, keyed off `last_step_worked` —
    step itself must never block, idle or busy."""
    import threading
    import time as time_mod

    import accelerate_tpu.serving.pod.distributed.droute as droute_mod

    cfg, params = gpt2_setup
    router, _ = _build_pod(cfg, params)
    main = threading.current_thread()
    slept = []
    real_sleep = time_mod.sleep

    def spy(seconds):
        if threading.current_thread() is main:
            slept.append(seconds)
        real_sleep(seconds)

    monkeypatch.setattr(droute_mod.time, "sleep", spy)
    for _ in range(20):
        router.step()                  # idle pod: nothing to do
    assert router.last_step_worked is False
    assert slept == [], "idle step() slept on the caller's thread"
    reqs = _submit_traffic(router, cfg)
    _drive(router, reqs)
    assert all(r.done for r in reqs)
    assert slept == [], "busy step() slept on the caller's thread"
    router.close()


def test_flaky_transport_is_deterministic_and_injects_all_faults():
    def run_once():
        a, b = LocalChannel.pair()
        flaky = FlakyTransport(a, flake_rate=0.5, seed=42, delay_ticks=1)
        for i in range(20):
            b.send(Message("m", {"i": i}))
            flaky.send(Message("r", {"i": i}))
        seen = [m.meta["i"] for m in flaky.poll()]
        for _ in range(5):   # tick held delay entries out
            seen += [m.meta["i"] for m in flaky.poll()]
        return seen, dict(flaky.faults), [m.meta["i"] for m in b.poll()]

    first, second = run_once(), run_once()
    assert first == second, "seeded fault plan must replay identically"
    seen, faults, _ = first
    assert faults, "flake_rate=0.5 over 40 messages injected nothing"
    assert len(seen) != 20 or seen != list(range(20)), \
        "faults must be observable (drops/dups/reorders)"
    # scripted rules hit exactly the messages they name
    log = []
    a, b = LocalChannel.pair()
    flaky = FlakyTransport(
        a, rules=lambda d, kind, seq: {1: "drop", 2: "dup"}.get(seq, "ok"))
    for i in range(4):
        b.send(Message("m", {"i": i}))
    log = [m.meta["i"] for m in flaky.poll()]
    assert log == [0, 2, 2, 3]   # 1 dropped, 2 duplicated
    assert flaky.faults == {"recv:drop": 1, "recv:dup": 1}


def test_flaky_transport_hang_and_kill():
    a, b = LocalChannel.pair()
    flaky = FlakyTransport(a)
    flaky.hang()
    flaky.send(Message("m", {}))
    assert b.poll() == []            # swallowed silently
    b.send(Message("m", {}))
    assert flaky.poll() == []        # drained, never delivered
    assert not flaky.closed          # a hung link still LOOKS open
    flaky.kill()
    assert flaky.closed
    with pytest.raises(ConnectionError):
        flaky.send(Message("m", {}))


# ---------------------------------------------------------------------------
# in-process distributed pod: exactness
# ---------------------------------------------------------------------------


def test_distributed_pod_byte_identical_to_single_engine(
        gpt2_setup, ref_outputs):
    """The layer-3 exactness bar: greedy AND sampled requests routed
    through submit -> wire -> prefill worker -> shipment frame -> decode
    worker -> token sync reproduce the single engine's tokens and
    logprobs byte for byte, with every worker's compile count flat."""
    cfg, params = gpt2_setup
    router, _workers = _build_pod(cfg, params, pf=1, dec=2)
    reqs = _submit_traffic(router, cfg)
    _drive(router, reqs)
    ref_tokens, ref_logprobs = ref_outputs
    assert [list(r.tokens) for r in reqs] == ref_tokens
    assert [list(r.logprobs) for r in reqs] == ref_logprobs
    assert router.compile_stats() == {
        "admit": 1, "prefill": 1, "decode": 1, "extract": 1, "install": 1}
    ms = router.metrics_summary()
    assert ms["pod_shipments"] == 4.0
    assert ms["pod_workers_lost"] == 0.0
    assert ms["pod_requests_replayed"] == 0.0
    # the streaming surface matches the terminal token lists
    router.close()


def test_distributed_pod_stream_iterates_tokens(gpt2_setup, ref_outputs):
    cfg, params = gpt2_setup
    router, _ = _build_pod(cfg, params, pf=1, dec=1)
    prompts, budgets, temps = _traffic(cfg)
    req = router.submit(prompts[0], max_new_tokens=budgets[0],
                        temperature=temps[0])
    got = list(router.stream(req))
    assert got == ref_outputs[0][0]
    router.close()


# ---------------------------------------------------------------------------
# failure recovery — every path byte-exact, nothing lost or duplicated
# ---------------------------------------------------------------------------


def _wrap_capture(flaky_by_wid, **flaky_kwargs):
    def wrap(wid, role, ch):
        flaky_by_wid[wid] = FlakyTransport(ch, **flaky_kwargs)
        return flaky_by_wid[wid]

    return wrap


def test_dropped_shipment_recovers_via_stalled_replay(
        gpt2_setup, ref_outputs):
    """Losing a KV shipment frame strands its flight in `prefill`; the
    flight watchdog replays it from the prompt — tokens still exact."""
    cfg, params = gpt2_setup
    state = {"dropped": 0}

    def rules(direction, kind, seq):
        if direction == "recv" and kind == "shipment" \
                and state["dropped"] == 0:
            state["dropped"] += 1
            return "drop"
        return "ok"

    def wrap(wid, role, ch):
        return FlakyTransport(ch, rules=rules) if role == "prefill" else ch

    router, _ = _build_pod(cfg, params, pf=1, dec=1, wrap=wrap,
                           flight_timeout_s=1.0)
    reqs = _submit_traffic(router, cfg)
    _drive(router, reqs)
    assert [list(r.tokens) for r in reqs] == ref_outputs[0]
    assert state["dropped"] == 1
    ms = router.metrics_summary()
    assert ms["pod_requests_replayed"] >= 1.0
    assert ms["pod_workers_lost"] == 0.0   # the worker was fine
    assert any(e["recovery_reason"] == "stalled"
               for e in router.recovery_log)
    router.close()


def test_duplicated_shipment_is_dropped_as_stale(gpt2_setup, ref_outputs):
    """At-least-once delivery: a duplicated shipment frame must land as
    a stale no-op (the flight already advanced), never as a second
    install — tokens exact, stale counter ticks."""
    cfg, params = gpt2_setup

    def rules(direction, kind, seq):
        return "dup" if direction == "recv" and kind == "shipment" else "ok"

    def wrap(wid, role, ch):
        return FlakyTransport(ch, rules=rules) if role == "prefill" else ch

    router, _ = _build_pod(cfg, params, pf=1, dec=1, wrap=wrap)
    reqs = _submit_traffic(router, cfg)
    _drive(router, reqs)
    assert [list(r.tokens) for r in reqs] == ref_outputs[0]
    ms = router.metrics_summary()
    assert ms["pod_stale_messages"] >= 1.0
    assert ms["pod_requests_replayed"] == 0.0
    router.close()


def test_killed_decode_worker_recovers_all_flights_exactly(
        gpt2_setup, ref_outputs):
    """THE acceptance: kill the decode worker that holds live streams
    mid-decode; every in-flight request is replayed by re-prefilling
    prompt+delivered-tokens elsewhere and finishes byte-identical —
    no lost tokens, no duplicated tokens."""
    cfg, params = gpt2_setup
    flaky = {}
    router, _ = _build_pod(cfg, params, pf=1, dec=2,
                           wrap=_wrap_capture(flaky))
    reqs = _submit_traffic(router, cfg)
    for _ in range(6):
        router.step()
    victims = {f.worker for f in router._flights.values()
               if f.phase == "decode"}
    assert victims, "no decode flight landed in 6 steps"
    victim = victims.pop()
    mid_stream = [len(f.user.tokens) for f in router._flights.values()
                  if f.phase == "decode" and f.worker == victim]
    assert any(0 < n for n in mid_stream), "kill happened before streaming"
    flaky[victim].kill()
    _drive(router, reqs)
    assert [list(r.tokens) for r in reqs] == ref_outputs[0]
    # the replayed token's logprob is recomputed by the chunked prefill
    # program instead of the original decode step — same math, different
    # reduction order, so allow a float32 ulp on it
    for got_lp, ref_lp in zip((list(r.logprobs) for r in reqs),
                              ref_outputs[1]):
        assert np.allclose(got_lp, ref_lp, rtol=0, atol=1e-5)
    ms = router.metrics_summary()
    assert ms["pod_workers_lost"] == 1.0
    assert ms["pod_requests_replayed"] >= 1.0
    assert all(e["recovery_reason"] == "channel_drop"
               for e in router.recovery_log)
    assert not router.workers[victim].alive
    router.close()


def test_killed_prefill_worker_requeues_flights(gpt2_setup, ref_outputs):
    """Prefill death mid-prefill: queued/prefilling flights re-queue and
    land on the survivor (soft roles: with the prefill pool empty, the
    decode worker serves prefill too) — tokens exact."""
    cfg, params = gpt2_setup
    flaky = {}
    router, _ = _build_pod(cfg, params, pf=1, dec=1,
                           wrap=_wrap_capture(flaky))
    reqs = _submit_traffic(router, cfg)
    router.step()
    assert any(f.phase == "prefill" for f in router._flights.values())
    flaky[0].kill()   # wid 0 is the prefill worker
    _drive(router, reqs)
    assert [list(r.tokens) for r in reqs] == ref_outputs[0]
    ms = router.metrics_summary()
    assert ms["pod_workers_lost"] == 1.0
    assert any(e["recovery_reason"] == "channel_drop"
               for e in router.recovery_log)
    router.close()


def test_hung_worker_detected_by_heartbeat_timeout(gpt2_setup, ref_outputs):
    """A hung link (open at the transport layer, silent both ways — the
    worker LOOKS alive) is only catchable by missed heartbeats; flights
    replay on the survivor, byte-exact."""
    cfg, params = gpt2_setup
    flaky = {}
    # busy_heartbeat_timeout_s: the victim's last delivered heartbeat may
    # announce busy=True (pre-compile), which legitimately defers the
    # heartbeat verdict — bound that deferral so the fake clock reaches it
    router, _ = _build_pod(cfg, params, pf=1, dec=2,
                           wrap=_wrap_capture(flaky),
                           heartbeat_timeout_s=1.0, flight_timeout_s=30.0,
                           busy_heartbeat_timeout_s=1.0)
    reqs = _submit_traffic(router, cfg)
    for _ in range(6):
        router.step()
    victims = {f.worker for f in router._flights.values()
               if f.phase == "decode"}
    assert victims
    victim = victims.pop()
    flaky[victim].hang()
    _drive(router, reqs)
    assert [list(r.tokens) for r in reqs] == ref_outputs[0]
    ms = router.metrics_summary()
    assert ms["pod_workers_lost"] == 1.0
    assert any(e["recovery_reason"] == "heartbeat_timeout"
               for e in router.recovery_log)
    router.close()


def test_no_lost_requests_under_flake_storm(gpt2_setup, ref_outputs):
    """Seeded random drop/dup/delay/reorder on EVERY link: recovery may
    replay as often as it needs, but every request must finish with the
    exact single-engine tokens — nothing lost, nothing doubled."""
    cfg, params = gpt2_setup
    flaky = {}
    router, _ = _build_pod(
        cfg, params, pf=1, dec=2,
        wrap=_wrap_capture(flaky, flake_rate=0.05, seed=11, delay_ticks=2),
        flight_timeout_s=1.0, max_attempts=10)
    reqs = _submit_traffic(router, cfg)
    _drive(router, reqs, max_steps=20000)
    assert all(r.status.value == "finished" for r in reqs), \
        [(r.status.value, r.reject_reason) for r in reqs]
    assert [list(r.tokens) for r in reqs] == ref_outputs[0]
    assert sum(f.faults.total() for f in flaky.values()) > 0, \
        "storm injected nothing — test is vacuous"
    router.close()


# ---------------------------------------------------------------------------
# elastic rebalancing
# ---------------------------------------------------------------------------


def test_rebalance_converts_idle_prefill_to_decode_once_per_window(
        gpt2_setup):
    """2 prefill + 1 decode with decode saturated: the router converts
    ONE idle prefill worker to decode (hysteresis band + one conversion
    per window — the second spare stays put), and the converted pod
    still finishes everything."""
    cfg, params = gpt2_setup
    router, _ = _build_pod(cfg, params, pf=2, dec=1, rebalance=True,
                           rebalance_window_s=0.2,
                           occupancy_high=0.5, occupancy_low=0.1)
    prompts, _, _ = _traffic(cfg)
    reqs = [router.submit(p, max_new_tokens=8)
            for p in prompts + prompts[:2]]
    _drive(router, reqs)
    ptd = router._c_conversions["prefill_to_decode"].value
    dtp = router._c_conversions["decode_to_prefill"].value
    assert ptd == 1.0, (ptd, router.debug_pod())
    assert dtp == 0.0
    roles = sorted(w.role for w in router.workers.values())
    assert roles == ["decode", "decode", "prefill"]
    assert all(r.status.value == "finished" for r in reqs)
    router.close()


def test_rebalance_window_blocks_flapping(gpt2_setup):
    """No conversion fires before the warm-up window elapses, no matter
    the queue pressure at startup (the first-step-flip regression)."""
    cfg, params = gpt2_setup
    router, _ = _build_pod(cfg, params, pf=1, dec=2, rebalance=True,
                           rebalance_window_s=1e9)
    prompts, _, _ = _traffic(cfg)
    reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
    _drive(router, reqs)
    assert router._c_conversions["prefill_to_decode"].value == 0.0
    assert router._c_conversions["decode_to_prefill"].value == 0.0
    assert sorted(w.role for w in router.workers.values()) == [
        "decode", "decode", "prefill"]
    router.close()


# ---------------------------------------------------------------------------
# telemetry merge + sanitizer
# ---------------------------------------------------------------------------


def test_worker_snapshots_merge_into_router_exposition(gpt2_setup):
    """Heartbeats carry each worker's registry snapshot; the /metrics
    registry holds the router's own series PLUS the transport-backed
    cross-worker merge (no jax process group) under origin=workers."""
    cfg, params = gpt2_setup
    router, _ = _build_pod(cfg, params, pf=1, dec=1)
    reqs = _submit_traffic(router, cfg)
    _drive(router, reqs)
    assert all(w.snapshot for w in router.workers.values()), \
        "heartbeats never delivered a registry snapshot"
    reg = router.exposition_registry()
    rows = {(kind, name, labels): metric
            for kind, name, labels, metric in reg.items()}
    # the router's own series, unlabelled
    assert any(name == "serving_pod_shipments_total" and not labels
               for (_k, name, labels) in rows)
    # worker counters merged as sums under origin=workers
    merged = [(name, labels, m) for (kind, name, labels), m in rows.items()
              if kind == "counter" and dict(labels).get("origin") == "workers"]
    assert merged, "no worker-origin series in the exposition"
    tokens = [m.value for (name, labels, m) in merged
              if name == "serving_tokens_out_total"]
    assert tokens and tokens[0] > 0
    # histogram sketches merged + the straggler signal derived from them
    assert any(name.endswith("__slowest_host_mean")
               for (_k, name, _l) in rows), rows.keys()
    router.close()


def test_sanitizer_catches_corrupted_router_books(gpt2_setup):
    """check_distributed_router: the cross-process joins only the router
    can see — corrupt each one and watch it fail loudly."""
    cfg, params = gpt2_setup
    router, _ = _build_pod(cfg, params, pf=1, dec=1)
    reqs = _submit_traffic(router, cfg)
    for _ in range(4):
        router.step()
    check_distributed_router(router)   # healthy mid-run state passes
    flight = next(iter(router._flights.values()))

    # unknown phase
    orig_phase = flight.phase
    flight.phase = "teleporting"
    with pytest.raises(SanitizerViolation):
        check_distributed_router(router)
    flight.phase = orig_phase

    # the no-zombie rule: a flight riding a dead worker
    handle = router.workers[flight.worker] if flight.worker >= 0 else None
    if handle is not None:
        handle.alive, handle.lost = False, True
        with pytest.raises(SanitizerViolation):
            check_distributed_router(router)
        handle.alive, handle.lost = True, False

    # pending deque referencing a flight that is not pending
    router._pending.append(flight.flight_id)
    with pytest.raises(SanitizerViolation):
        check_distributed_router(router)
    router._pending.pop()

    # user-index desync
    key, val = next(iter(router._by_user.items()))
    del router._by_user[key]
    with pytest.raises(SanitizerViolation):
        check_distributed_router(router)
    router._by_user[key] = val

    check_distributed_router(router)   # restored state passes again
    _drive(router, reqs)
    router.close()


# ---------------------------------------------------------------------------
# distributed tracing, clock alignment, fleet incident bundles (ISSUE 18)
# ---------------------------------------------------------------------------


@pytest.fixture
def _traced():
    """Head-sample every request so plain submits are traced; clean the
    global recorder afterwards (this module has no autouse tracing
    reset)."""
    from accelerate_tpu.telemetry import (clear_flight_recorder,
                                          configure_tracing)

    configure_tracing(enabled=True, annotate=False, default_sample_rate=1.0)
    yield
    configure_tracing(enabled=False, default_sample_rate=0.0)
    clear_flight_recorder()


def test_tracing_staleness_and_fleet_bundle_acceptance(
        gpt2_setup, ref_outputs, _traced, tmp_path, capsys):
    """The ISSUE-18 tentpole on one pod and one kill (tier-1 budget:
    these contracts share the engines and the traffic drive):

    1. propagation — every request's spans from router (dispatch,
       page_transfer), prefill worker (pod.prefill) and decode worker
       (pod.install) land in ONE trace, monotonically ordered, and
       tracing changes no tokens;
    2. replay forensics — the killed flights record `serving.replay`
       linked to the failed attempt's dispatch span, tagged
       recovery_reason=channel_drop;
    3. staleness-honest /metrics — the lost worker's frozen snapshot
       merges under stale="true", its snapshot-age gauge keeps
       counting, and a configured horizon drops it entirely;
    4. fleet incident bundle — worker loss writes ONE bundle (router
       dumps, per-worker stanzas with an honest worker_error hole for
       the dead one, clock offsets, merged chrome traces of in-flight
       requests) and `accelerate-tpu incident show` renders it.
    """
    import json as _json

    from accelerate_tpu.commands.incident import _run_show
    from accelerate_tpu.telemetry import trace_events

    cfg, params = gpt2_setup
    flaky = {}
    router, _ = build_local_distributed_pod(
        gpt2, cfg, params,
        engine_config=_ec(incident_dir=str(tmp_path)),
        pod_config=DistributedPodConfig(
            prefill_workers=1, decode_workers=2, rebalance=False,
            heartbeat_interval_s=0.0, fleet_bundle_min_interval_s=0.0),
        # REAL clock: worker spans are rebased by the NTP offset estimate,
        # and a +0.01/call fake clock ticks hundreds of times between a
        # heartbeat's stamping and its ingestion — the bogus offset would
        # shove rebased spans seconds out of timeline order
        channel_wrap=_wrap_capture(flaky))
    reqs = _submit_traffic(router, cfg)
    for _ in range(6):
        router.step()
    victims = {f.worker for f in router._flights.values()
               if f.phase == "decode"}
    assert victims, "no decode flight landed in 6 steps"
    victim = victims.pop()
    flaky[victim].kill()
    _drive(router, reqs)
    assert [list(r.tokens) for r in reqs] == ref_outputs[0]
    assert router.workers[victim].lost

    # 1. propagation: one ordered timeline per request, across roles
    for r in reqs:
        assert r.trace_sampled and isinstance(r.trace_id, str)
        by_name = {}
        for e in trace_events(r.trace_id):
            by_name.setdefault(e["name"], []).append(e)
        for name in ("serving.pod.dispatch", "serving.pod.prefill",
                     "serving.page_transfer", "serving.pod.install"):
            assert name in by_name, (r.trace_id, sorted(by_name))
        # the acceptance ordering: prefill end <= shipment arrival <=
        # install end, PER ATTEMPT — a replay whose re-prefill already
        # yields the final token finishes at shipment and never grows a
        # transfer/install leg, so attempts can't be compared to each
        # other's legs
        legs = ("serving.pod.prefill", "serving.page_transfer",
                "serving.pod.install")
        ends: dict = {}
        for name in legs:
            for e in by_name[name]:
                a = e["attrs"]["attempt"]
                by = ends.setdefault(a, {})
                by[name] = max(by.get(name, 0),
                               e["start_ns"] + e["dur_ns"])
        full = [by for by in ends.values() if len(by) == len(legs)]
        assert full, ends
        for by in full:
            assert by[legs[0]] <= by[legs[1]] <= by[legs[2]], ends
        # worker-side spans carry the worker attribute for the fleet view
        assert all("worker" in e.get("attrs", {})
                   for e in by_name["serving.pod.install"])

    # 2. replay forensics: linked to the failed dispatch, reason tagged
    replayed = [e["request_id"] for e in router.recovery_log
                if e["recovery_reason"] == "channel_drop"]
    assert replayed
    checked = 0
    for r in reqs:
        if r.request_id not in replayed:
            continue
        events = trace_events(r.trace_id)
        replays = [e for e in events if e["name"] == "serving.replay"]
        assert replays, [e["name"] for e in events]
        dispatch_ids = {e["span_id"] for e in events
                        if e["name"] == "serving.pod.dispatch"}
        for ev in replays:
            assert ev["attrs"]["recovery_reason"] == "channel_drop"
            assert ev.get("links"), "replay span lost its link"
            assert set(ev["links"]) & dispatch_ids, \
                "replay link does not point at a dispatch span"
        checked += 1
    assert checked

    # 3. staleness-honest scrape: kill-then-scrape
    rows = [(name, dict(labels))
            for _k, name, labels, _m in router.exposition_registry().items()]
    age_workers = {l["worker"] for n, l in rows
                   if n == "serving_pod_worker_snapshot_age_seconds"}
    assert str(victim) in age_workers and len(age_workers) >= 2
    assert any(l.get("stale") == "true" for _n, l in rows), \
        "lost worker's series lost their stale label"
    assert any(l.get("origin") == "workers" and l.get("stale") is None
               for _n, l in rows), "survivors' series vanished"
    # past the horizon the dead worker's numbers drop entirely
    import dataclasses as _dc

    router.pod_config = _dc.replace(router.pod_config,
                                    snapshot_stale_after_s=0.0)
    rows2 = [(name, dict(labels))
             for _k, name, labels, _m in router.exposition_registry().items()]
    assert not any(l.get("stale") == "true" for _n, l in rows2)
    assert any(l.get("origin") == "workers" for _n, l in rows2)

    # 4. the fleet bundle + its CLI rendering
    bundles = [p for p in tmp_path.iterdir()
               if p.name.startswith("incident-")]
    fleet = [p for p in bundles if f"fleet-loss-w{victim}" in p.name]
    assert fleet, [p.name for p in bundles]
    bundle = fleet[0]
    report = _json.loads((bundle / "report.json").read_text())
    assert report["kind"] == "fleet_incident"
    assert report["reason"] == "channel_drop"
    offsets = _json.loads((bundle / "clock_offsets.json").read_text())
    assert str(victim) in offsets and offsets[str(victim)]["lost"]
    dead = _json.loads((bundle / f"worker_{victim}.json").read_text())
    assert "worker_error" in dead        # the honest hole
    survivors = [p for p in bundle.glob("worker_*.json")
                 if p.name != f"worker_{victim}.json"]
    assert survivors
    alive = _json.loads(survivors[0].read_text())
    assert "jobs" in alive and "engine" in alive
    traces = _json.loads((bundle / "flights_trace.json").read_text())
    assert traces, "no in-flight trace captured at loss time"
    assert any((doc.get("traceEvents") or []) for doc in traces.values())
    rc = _run_show(str(tmp_path), bundle.name, "text")
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet clock offsets" in out
    assert f"worker {victim}: UNREACHABLE" in out
    assert "in-flight traces" in out
    router.close()


def test_clock_sync_span_ingest_and_busy_deferral(gpt2_setup, _traced):
    """The heartbeat-side mechanics on one idle pod (no traffic — these
    poke the router's handlers directly):

    - NTP clock estimate: one-way fallback on first contact, round-trip
      correction with EWMA smoothing, negative rtt discarded, one-way
      samples never regress a round-trip estimate, per-worker gauge;
    - span ingest: a heartbeat's batch lands rebased into router time
      exactly once (same `span_seq` = duplicated heartbeat = no-op);
    - busy deferral (the phantom-loss fix): an announced long block
      gets busy_heartbeat_timeout_s of silence, a quiet non-busy
      worker is lost at the tight timeout, and busy is a rope, not
      immortality.
    """
    from accelerate_tpu.telemetry import trace_events

    cfg, params = gpt2_setup
    now = [0.0]
    router, _ = build_local_distributed_pod(
        gpt2, cfg, params, engine_config=_ec(),
        pod_config=DistributedPodConfig(
            prefill_workers=1, decode_workers=1, rebalance=False,
            heartbeat_interval_s=1e9, heartbeat_timeout_s=0.5,
            busy_heartbeat_timeout_s=5.0),
        clock=lambda: now[0])
    handle = next(iter(router.workers.values()))

    # -- NTP estimate -------------------------------------------------------
    # in-process handles short-circuit to offset 0 (shared clock) — mask
    # `local` so the estimator treats this handle as a remote worker
    handle.local = None
    handle.clock_offset_s = handle.clock_rtt_s = None
    # first contact: no echo yet -> one-way T4 - T3
    router._sync_worker_clock(handle, {"t": 95.0}, 100.0)
    assert handle.clock_offset_s == pytest.approx(5.0)
    assert handle.clock_rtt_s is None
    # completed round trip: T1=100.5 T2=95.6 T3=96.0 T4=101.0
    router._sync_worker_clock(
        handle, {"t": 96.0, "ack": {"router_t": 100.5,
                                    "worker_recv_t": 95.6}}, 101.0)
    assert handle.clock_rtt_s == pytest.approx(0.1)
    # sample ((100.5-95.6)+(101-96))/2 = 4.95, EWMA 0.75*5 + 0.25*4.95
    assert handle.clock_offset_s == pytest.approx(4.9875)
    # a clock stepped mid-round (rtt < 0): the sample is discarded
    router._sync_worker_clock(
        handle, {"t": 200.0, "ack": {"router_t": 100.9,
                                     "worker_recv_t": 95.9}}, 101.0)
    assert handle.clock_offset_s == pytest.approx(4.9875)
    # a later echo-less heartbeat must not regress to the one-way guess
    router._sync_worker_clock(handle, {"t": 90.0}, 102.0)
    assert handle.clock_offset_s == pytest.approx(4.9875)
    gauges = {labels: m.value
              for kind, name, labels, m in router.registry.items()
              if name == "serving_pod_worker_clock_offset_seconds"}
    assert gauges[(("worker", str(handle.worker_id)),)] \
        == pytest.approx(4.9875)

    # -- span ingest + dedup ------------------------------------------------
    handle.clock_offset_s = 2.0
    before = router._c_spans.value
    ev = {"name": "w-side", "trace_id": "req-dedup",
          "start_ns": 1_000, "dur_ns": 5}
    router._ingest_worker_spans(handle, {"spans": [ev], "span_seq": 5}, 1.0)
    got = trace_events("req-dedup")
    assert len(got) == 1
    assert got[0]["start_ns"] == 1_000 + int(2.0 * 1e9)   # rebased
    # the duplicated heartbeat: same high-water mark, no double ingest
    router._ingest_worker_spans(handle, {"spans": [ev], "span_seq": 5}, 2.0)
    assert len(trace_events("req-dedup")) == 1
    # a genuinely new batch advances
    router._ingest_worker_spans(
        handle, {"spans": [dict(ev, span_id=9)], "span_seq": 6}, 3.0)
    assert len(trace_events("req-dedup")) == 2
    assert router._c_spans.value == before + 2

    # -- busy deferral of heartbeat_timeout ---------------------------------
    for h in router.workers.values():      # registered, not yet stepped:
        h.alive, h.last_heartbeat, h.busy = True, 0.0, True
    handle, other = list(router.workers.values())[:2]
    now[0] = 2.0                       # 4x the plain timeout, but busy
    router._detect_failures()
    assert not handle.lost and not other.lost, \
        "busy-not-dead became a phantom loss"
    handle.busy = False                # same silence, no busy announce
    router._detect_failures()
    assert handle.lost and not other.lost
    # and busy is a rope, not immortality
    now[0] = 6.0
    router._detect_failures()
    assert other.lost
    router.close()


# ---------------------------------------------------------------------------
# the two-OS-process socket smoke (the acceptance harness)
# ---------------------------------------------------------------------------


def test_socket_pod_two_process_smoke():
    """Real `pod-worker` OS processes dialing a ChannelListener over
    TCP: byte-exactness across the process boundary (greedy + sampled,
    compile-flat) AND SIGKILL-a-decode-worker recovery — see
    pod_distributed_script.py for the full contract."""
    from accelerate_tpu.test_utils import execute_subprocess

    script = os.path.join(os.path.dirname(__file__),
                          "pod_distributed_script.py")
    out = execute_subprocess(
        [sys.executable, script], env={"JAX_PLATFORMS": "cpu"}, timeout=420)
    assert "PHASE1_EXACT_OK" in out
    assert "PHASE2_RECOVERY_OK" in out
    assert "PHASE2_TRACE_OK" in out
    assert "POD_DIST_OK" in out
