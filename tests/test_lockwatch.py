"""Runtime lock-order sanitizer (ISSUE 19): TrackedLock ordering graph,
would-deadlock refusal, incident bundles, contention/held metrics, and
the deadlock-injection drill over the real pod transport.

The static twin (ATP302, tests/test_analysis.py) proves ordering over
locks it can name; these tests pin the runtime half: the process-wide
graph records per-thread acquisition order, a cycle-closing acquire
raises `LockOrderViolation` naming the full cycle BEFORE blocking (the
test suite sees a structured failure, not a wedged worker), and the
violation leaves a loadable incident bundle behind. The suite runs with
`ACCELERATE_TPU_LOCKWATCH=1` (tests/conftest.py), so the wired sites —
SocketChannel's inbox lock, the host-tier entry locks, the metrics
registry's create lock — are tracked across the whole tier-1 serving
surface."""

import os
import threading
import time

import pytest

from accelerate_tpu.telemetry import (
    LockOrderViolation,
    TrackedLock,
    lockwatch_enabled,
    lockwatch_state,
    maybe_tracked,
    reset_lockwatch,
)
from accelerate_tpu.telemetry.registry import MetricsRegistry
from accelerate_tpu.telemetry.watchdog import (
    list_incident_bundles,
    load_incident_bundle,
)


@pytest.fixture(autouse=True)
def _clean_graph():
    """Isolate the process-wide graph: edges recorded by other tests
    must not pre-order these locks, and the deliberate inversions below
    must not poison later pod tests."""
    reset_lockwatch()
    yield
    reset_lockwatch()


class TestGate:
    def test_disabled_returns_plain_lock(self):
        lock = maybe_tracked("x", setting=False)
        assert not isinstance(lock, TrackedLock)
        with lock:
            assert lock.locked()

    def test_env_parsing(self, monkeypatch):
        assert lockwatch_enabled(True) and not lockwatch_enabled(False)
        for raw, want in (("1", True), ("true", True), ("on", True),
                          ("0", False), ("", False), ("no", False)):
            monkeypatch.setenv("ACCELERATE_TPU_LOCKWATCH", raw)
            assert lockwatch_enabled() is want, raw

    def test_suite_runs_with_lockwatch_on(self):
        """The conftest gate: tier-1 runs the whole serving surface with
        tracked locks, like the PR 13 sanitizer."""
        assert os.environ.get("ACCELERATE_TPU_LOCKWATCH") == "1"
        assert lockwatch_enabled()


class TestTrackedLock:
    def test_duck_types_threading_lock(self):
        lock = TrackedLock("t-lock")
        assert not lock.locked()
        with lock:
            assert lock.locked()
        assert not lock.locked()
        assert lock.acquire(timeout=0.5)
        lock.release()
        assert lock.acquire(blocking=False)
        lock.release()
        assert "t-lock" in repr(lock)

    def test_nonblocking_acquire_of_held_lock_fails_fast(self):
        lock = TrackedLock("t-held")
        lock.acquire()
        got = []
        t = threading.Thread(
            target=lambda: got.append(lock.acquire(blocking=False)))
        t.start()
        t.join(timeout=5)
        assert got == [False]
        lock.release()

    def test_consistent_order_records_edges_no_violation(self):
        a, b = TrackedLock("order-a"), TrackedLock("order-b")
        for _ in range(3):
            with a:
                with b:
                    pass
        edges = lockwatch_state()["edges"]
        assert edges["order-a"]["order-b"]["count"] == 3
        assert lockwatch_state()["violations"] == []

    def test_inversion_raises_naming_the_cycle(self):
        a, b = TrackedLock("inv-a"), TrackedLock("inv-b")

        def first():
            with a:
                with b:
                    pass

        t = threading.Thread(target=first)
        t.start()
        t.join(timeout=5)
        with pytest.raises(LockOrderViolation) as exc:
            with b:
                with a:
                    pass
        err = exc.value
        assert err.cycle == ["inv-b", "inv-a", "inv-b"]
        assert err.held == ["inv-b"]
        assert "lock-order cycle" in str(err)
        # the refusal happened BEFORE acquiring: nothing stays locked
        assert not a.locked() and not b.locked()
        # and the graph remembers the violation for forensics
        (v,) = lockwatch_state()["violations"]
        assert v["cycle"] == err.cycle and v["acquiring"] == "inv-a"

    def test_three_lock_cycle_through_the_graph(self):
        a, b, c = (TrackedLock("tri-a"), TrackedLock("tri-b"),
                   TrackedLock("tri-c"))

        def run(outer, inner):
            with outer:
                with inner:
                    pass

        for outer, inner in ((a, b), (b, c)):
            t = threading.Thread(target=run, args=(outer, inner))
            t.start()
            t.join(timeout=5)
        with pytest.raises(LockOrderViolation) as exc:
            run(c, a)
        assert exc.value.cycle == ["tri-c", "tri-a", "tri-b", "tri-c"]

    def test_same_name_reacquire_is_not_a_cycle(self):
        """Two instances sharing a lock class (two channels, both
        "pod-channel") taken nested must not self-report: lock classes
        skip self-edges exactly like runtime lockdep."""
        a, b = TrackedLock("same-name"), TrackedLock("same-name")
        with a:
            with b:
                pass
        assert lockwatch_state()["violations"] == []

    def test_contention_and_held_metrics(self):
        reg = MetricsRegistry()
        lock = TrackedLock("metered", registry=reg)
        lock.acquire()

        def contender():
            assert lock.acquire(timeout=5)
            lock.release()

        t = threading.Thread(target=contender)
        t.start()
        time.sleep(0.05)
        lock.release()
        t.join(timeout=5)
        snap = reg.snapshot()
        assert 'lock_contention_total{lock="metered"}' in snap["counters"]
        assert 'lock_held_seconds{lock="metered"}' in snap["histograms"]

    def test_violation_writes_loadable_bundle(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ACCELERATE_TPU_INCIDENT_DIR", str(tmp_path))
        a, b = TrackedLock("bndl-a"), TrackedLock("bndl-b")

        def first():
            with a:
                with b:
                    pass

        t = threading.Thread(target=first)
        t.start()
        t.join(timeout=5)
        with pytest.raises(LockOrderViolation) as exc:
            with b:
                with a:
                    pass
        (manifest,) = list_incident_bundles(str(tmp_path))
        assert manifest["kind"] == "lockwatch"
        assert exc.value.bundle_path == manifest["path"]
        loaded = load_incident_bundle(manifest["path"])
        report = loaded["files"]["report.json"]
        assert report["kind"] == "lock_order_violation"
        assert report["cycle"] == exc.value.cycle
        assert report["acquiring"] == "bndl-a"
        # all-thread stacks ride along, like the stall watchdog's bundles
        assert "stacks.txt" in loaded["files"]

    def test_registry_lock_is_tracked_without_recursion(self):
        """The metrics registry's own create lock is in the ordering
        graph; creating series under ANOTHER tracked lock must neither
        recurse nor pollute the registry with its own lock metrics."""
        reg = MetricsRegistry()
        assert isinstance(reg._lock, TrackedLock)
        outer = TrackedLock("outer-of-registry", registry=reg)
        with outer:
            reg.counter("some_series").inc()
        edges = lockwatch_state()["edges"]
        assert "metrics-registry" in edges.get("outer-of-registry", {})
        snap = reg.snapshot()
        own = [k for bucket in snap.values()
               for k in bucket if 'lock="metrics-registry"' in k]
        assert own == [], (
            "the registry's own lock must not add series to the "
            "registries it guards")

    def test_overhead_is_bounded(self):
        """Loose guard for the <5% tier-1 budget: an uncontended tracked
        acquire/release pair is micro-fast (no graph work when nothing
        else is held)."""
        lock = TrackedLock("bench")
        t0 = time.perf_counter()
        for _ in range(10_000):
            with lock:
                pass
        assert time.perf_counter() - t0 < 2.0


class TestDeadlockInjection:
    """Satellite 3: the forced-inversion drill over the REAL transport.

    FlakyTransport.hang() wedges a live socket channel silently (the
    missed-heartbeat failure mode) — recovery code then runs while IO is
    stuck, which is exactly when ad-hoc lock ordering between the
    channel lock and the host-tier entry lock inverts. Lockwatch must
    name the cycle as a structured violation instead of letting the two
    threads deadlock, and leave a loadable bundle."""

    def test_hung_link_inversion_is_named_and_bundled(
            self, tmp_path, monkeypatch):
        from accelerate_tpu.serving.host_tier import _HostEntry
        from accelerate_tpu.serving.pod.distributed import (
            FlakyTransport, Message)
        from accelerate_tpu.serving.pod.distributed.transport import (
            ChannelListener, SocketChannel)

        monkeypatch.setenv("ACCELERATE_TPU_INCIDENT_DIR", str(tmp_path))
        listener = ChannelListener("127.0.0.1", 0)
        try:
            client = SocketChannel.connect("127.0.0.1", listener.port)
            server = None
            for _ in range(200):
                got = listener.accept_all()
                if got:
                    server = got[0]
                    break
                time.sleep(0.01)
            assert server is not None
            # the wired sites really are tracked under the suite env
            assert isinstance(client._lock, TrackedLock)
            assert client._lock.name == "pod-channel"
            entry = _HostEntry(node=None, device=None)
            assert isinstance(entry.lock, TrackedLock)
            assert entry.lock.name == "host-tier-entry"

            flaky = FlakyTransport(server)
            flaky.hang()            # silent wedge: open link, nothing moves
            client.send(Message("heartbeat", {"n": 1}))
            time.sleep(0.1)
            assert flaky.poll() == [], "hung link must swallow messages"
            assert flaky.faults["hang"] == 1

            # drain-thread side: entry lock held, then the channel is
            # polled for the shipment that will never arrive
            def drain_side():
                with entry.lock:
                    flaky.poll()    # takes the channel lock inside

            t = threading.Thread(target=drain_side, name="drain")
            t.start()
            t.join(timeout=5)
            assert not t.is_alive()

            # IO side inverts: channel lock held, entry lock wanted.
            # Without lockwatch this is the schedule-away deadlock; with
            # it the acquire refuses and NAMES the cycle.
            with pytest.raises(LockOrderViolation) as exc:
                with server._lock:
                    with entry.lock:
                        pass
            err = exc.value
            assert err.cycle == ["pod-channel", "host-tier-entry",
                                 "pod-channel"]
            assert err.thread == "MainThread"
            # nothing is left held: the suite continues, not wedges
            assert not server._lock.locked() and not entry.lock.locked()

            (manifest,) = list_incident_bundles(str(tmp_path))
            loaded = load_incident_bundle(manifest["path"])
            report = loaded["files"]["report.json"]
            assert report["cycle"] == err.cycle
            assert report["kind"] == "lock_order_violation"
            # the drain thread's ordering is in the recorded graph
            assert report["lock_graph"]["host-tier-entry"][
                "pod-channel"]["thread"] == "drain"
        finally:
            client.close()
            server.close()
            listener.close()
