"""Sharding planner tests — the FSDP/TP/MoE plugin re-target (SURVEY.md §7.6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from accelerate_tpu.sharding import (
    auto_fsdp_spec,
    batch_spec,
    plan_optimizer_sharding,
    plan_sharding,
    shard_pytree,
    transformer_rules,
)
from accelerate_tpu.utils import MeshConfig


def make_params():
    return {
        "embed_tokens": {"embedding": jnp.zeros((256, 64))},
        "layers": {
            "attn": {
                "q_proj": {"kernel": jnp.zeros((64, 64))},
                "o_proj": {"kernel": jnp.zeros((64, 64))},
            },
            "mlp": {
                "up_proj": {"kernel": jnp.zeros((64, 256))},
                "down_proj": {"kernel": jnp.zeros((256, 64))},
            },
            "norm": {"scale": jnp.ones((64,))},
        },
    }


def test_fsdp_only_mesh_plan():
    mesh = MeshConfig(axes={"fsdp": 8}).build()
    plan = plan_sharding(make_params(), mesh)
    # column-parallel template prunes model axis (absent) -> fsdp on dim0
    assert plan["layers"]["attn"]["q_proj"]["kernel"].spec == P("fsdp", None)
    assert plan["layers"]["mlp"]["down_proj"]["kernel"].spec == P(None, "fsdp")
    # small norm scale replicates (min_weight_size)
    assert plan["layers"]["norm"]["scale"].spec == P()


def test_tp_fsdp_mesh_plan():
    mesh = MeshConfig(axes={"fsdp": 2, "model": 4}).build()
    plan = plan_sharding(make_params(), mesh)
    assert plan["layers"]["attn"]["q_proj"]["kernel"].spec == P("fsdp", "model")
    assert plan["layers"]["attn"]["o_proj"]["kernel"].spec == P("model", "fsdp")
    assert plan["embed_tokens"]["embedding"].spec == P("model", "fsdp")


def test_fused_qkv_kernels_are_column_parallel():
    """The fused [in, 3h] qkv kernel must shard its OUT dim on the model
    axis like the split projections do. gpt2's `c_attn` matched no rule
    and silently REPLICATED the biggest attention matmul on a
    tensor-parallel serving mesh (ISSUE 9); neox's `query_key_value`
    only matched by the `value`-substring accident — both are pinned
    explicitly now."""
    params = {
        "layers": {
            "attn": {
                "c_attn": {"kernel": jnp.zeros((2, 64, 192))},
                "query_key_value": {"kernel": jnp.zeros((2, 64, 192))},
            },
        },
    }
    plan = plan_sharding(params, MeshConfig(axes={"fsdp": 2, "model": 4}).build())
    attn = plan["layers"]["attn"]
    assert attn["c_attn"]["kernel"].spec == P(None, "fsdp", "model")
    assert attn["query_key_value"]["kernel"].spec == P(None, "fsdp", "model")
    # a model-only serving mesh (serving.pod tensor_mesh): out dim sharded
    from accelerate_tpu.serving.pod import tensor_mesh

    plan = plan_sharding(params, tensor_mesh(4))
    assert plan["layers"]["attn"]["c_attn"]["kernel"].spec \
        == P(None, None, "model")


def test_replicated_plan_when_shard_params_false():
    mesh = MeshConfig(axes={"fsdp": 8}).build()
    plan = plan_sharding(make_params(), mesh, shard_params=False)
    specs = {s.spec for s in jax.tree_util.tree_leaves(plan)}
    assert specs == {P()}


def test_plan_from_eval_shape():
    """Meta planning: works on ShapeDtypeStructs without materializing."""
    mesh = MeshConfig(axes={"fsdp": 8}).build()
    shapes = jax.eval_shape(make_params)
    plan = plan_sharding(shapes, mesh)
    assert plan["layers"]["mlp"]["up_proj"]["kernel"].spec == P("fsdp", None)


def test_auto_fsdp_spec_picks_divisible_dim():
    mesh = MeshConfig(axes={"fsdp": 8}).build()
    assert auto_fsdp_spec((100, 64), mesh) == P(None, "fsdp")
    assert auto_fsdp_spec((100, 30), mesh) == P()  # nothing divisible
    assert auto_fsdp_spec((64, 128), mesh) == P(None, "fsdp")  # prefers larger/later


def test_indivisible_tp_dim_falls_back():
    mesh = MeshConfig(axes={"model": 8}).build()
    params = {"attn": {"q_proj": {"kernel": jnp.zeros((64, 100))}}}  # 100 % 8 != 0
    plan = plan_sharding(params, mesh)
    # model axis dropped on dim1; auto-fsdp has no fsdp axis -> replicated
    assert plan["attn"]["q_proj"]["kernel"].spec == P()


def test_shard_pytree_places_arrays():
    mesh = MeshConfig(axes={"fsdp": 8}).build()
    params = make_params()
    plan = plan_sharding(params, mesh)
    sharded = shard_pytree(params, plan)
    q = sharded["layers"]["attn"]["q_proj"]["kernel"]
    assert len(q.sharding.device_set) == 8
    assert q.addressable_shards[0].data.shape == (8, 64)


def test_shard_pytree_mixed_and_none_leaves():
    """The batched one-call placement path must keep the per-leaf
    semantics: non-array leaves pass through untouched, a None plan leaf
    means default placement, structure is preserved."""
    mesh = MeshConfig(axes={"fsdp": 8}).build()
    tree = {"a": np.ones((8, 4)), "n": 3, "s": "tag",
            "b": jnp.zeros((2,))}
    plan = {"a": jax.sharding.NamedSharding(mesh, P("fsdp", None)),
            "n": None, "s": None, "b": None}
    out = shard_pytree(tree, plan)
    assert out["n"] == 3 and out["s"] == "tag"
    assert len(out["a"].sharding.device_set) == 8
    assert isinstance(out["b"], jax.Array)
    # an all-static tree is a no-op, not a device_put([]) crash
    assert shard_pytree({"k": 1}, {"k": None}) == {"k": 1}


def test_optimizer_state_sharding_adam():
    import optax

    mesh = MeshConfig(axes={"fsdp": 8}).build()
    params = make_params()
    plan = plan_sharding(params, mesh)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    opt_plan = plan_optimizer_sharding(opt, opt_state, plan, mesh)
    # mu/nu adopt the param plan
    mu_q = opt_state[0].mu["layers"]["attn"]["q_proj"]["kernel"]
    mu_plan_q = opt_plan[0].mu["layers"]["attn"]["q_proj"]["kernel"]
    assert mu_plan_q.spec == P("fsdp", None)
    assert mu_q.shape == (64, 64)
    # count replicates
    assert opt_plan[0].count.spec == P()
    # and the plan is device_put-able
    sharded = shard_pytree(opt_state, opt_plan)
    assert len(sharded[0].mu["layers"]["attn"]["q_proj"]["kernel"].sharding.device_set) == 8


def test_composed_quantized_optimizer_keeps_zero_sharding():
    """Regression (advisor r5): a composed optimizer mixing quantized moments
    with plain param-shaped state (optax.chain(adamw_8bit, trace)) must keep
    ZeRO sharding for the NON-quantized moments — the old early-return
    replicated them silently — while quantized moments still shard on their
    blocks dim."""
    import optax

    from accelerate_tpu.optimizers import _Quantized, adamw_8bit

    mesh = MeshConfig(axes={"fsdp": 8}).build()
    params = make_params()
    plan = plan_sharding(params, mesh)
    opt = optax.chain(adamw_8bit(1e-3), optax.trace(decay=0.9))
    opt_state = opt.init(params)
    opt_plan = plan_optimizer_sharding(opt, opt_state, plan, mesh)
    # the trace's param-shaped moment adopts the param plan (ZeRO)
    trace_q = opt_plan[1].trace["layers"]["attn"]["q_proj"]["kernel"]
    assert trace_q.spec == P("fsdp", None)
    # quantized moments shard along the blocks dim
    mu_q = opt_plan[0].mu["layers"]["attn"]["q_proj"]["kernel"]
    assert isinstance(mu_q, _Quantized)
    assert mu_q.q.spec == P("fsdp", None)
    # scalars replicate; the full plan is device_put-able
    assert opt_plan[0].count.spec == P()
    sharded = shard_pytree(opt_state, opt_plan)
    placed = sharded[1].trace["layers"]["attn"]["q_proj"]["kernel"]
    assert len(placed.sharding.device_set) == 8


def test_batch_spec():
    mesh = MeshConfig(axes={"data": 2, "fsdp": 4}).build()
    assert batch_spec(mesh) == P(("data", "fsdp"))
    mesh2 = MeshConfig(axes={"data": 8}).build()
    assert batch_spec(mesh2, extra_dims=1) == P("data", None)
    mesh3 = MeshConfig(axes={"model": 8}).build()
    assert batch_spec(mesh3) == P(None)


def test_expert_rules():
    mesh = MeshConfig(axes={"expert": 4, "model": 2}).build()
    params = {"moe": {"experts": {"up_proj": {"kernel": jnp.zeros((4, 64, 128))}}}}
    plan = plan_sharding(params, mesh)
    assert plan["moe"]["experts"]["up_proj"]["kernel"].spec == P("expert", None, "model")


def test_mesh_split_dcn_factoring():
    """Multi-slice: the slice count factors out of the outermost axes."""
    from accelerate_tpu.utils import MeshConfig

    split = MeshConfig._split_dcn
    assert split({"data": 4, "model": 2}, 2) == ((2, 1), (2, 2))
    assert split({"data": 2, "fsdp": 4, "model": 2}, 2) == ((2, 1, 1), (1, 4, 2))
    # slice count spanning two axes: data=2 entirely DCN, fsdp contributes 2
    assert split({"data": 2, "fsdp": 4, "model": 2}, 4) == ((2, 2, 1), (1, 2, 2))
    # an unfactorable data axis is skipped; fsdp absorbs the slices
    assert split({"data": 3, "fsdp": 2, "model": 2}, 2) == ((1, 2, 1), (3, 1, 2))
    import pytest as _pytest

    # the model (tensor-parallel) axis must NOT absorb slices: per-layer
    # collectives over DCN would silently crater throughput
    with _pytest.raises(ValueError, match="cannot factor"):
        split({"data": 3, "model": 2}, 2)


def test_hybrid_mesh_requested_for_multislice(monkeypatch):
    """A device set spanning slices routes through create_hybrid_device_mesh
    with the factored dcn/ici shapes."""
    import numpy as np

    from accelerate_tpu.utils import MeshConfig
    from jax.experimental import mesh_utils
    import jax

    class FakeDev:
        def __init__(self, d, si):
            self._d = d
            self.slice_index = si
            self.platform = d.platform

    devices = [FakeDev(d, i % 2) for i, d in enumerate(jax.devices())]
    captured = {}

    def fake_hybrid(ici_shape, dcn_mesh_shape=None, devices=None, **kw):
        captured["ici"] = tuple(ici_shape)
        captured["dcn"] = tuple(dcn_mesh_shape)
        return np.asarray(devices).reshape(tuple(
            d * i for d, i in zip(dcn_mesh_shape, ici_shape)))

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)
    mesh = MeshConfig(axes={"data": 2, "model": 4}).build(devices)
    assert captured == {"dcn": (2, 1), "ici": (1, 4)}
    assert dict(mesh.shape) == {"data": 2, "model": 4}


def test_mesh_split_dcn_size_one_axis():
    from accelerate_tpu.utils import MeshConfig

    assert MeshConfig._split_dcn({"data": 1, "fsdp": 4, "model": 2}, 2) == (
        (1, 2, 1), (1, 2, 2)
    )


# --- sequence parallelism (VERDICT r3 missing #4: constrain() exercised) -----


def test_sp_constrain_shards_activations_on_seq_axis():
    """sp_constrain must actually shard [B, S, H] hidden states along the
    sequence dim (the demonstrated-SP ask, ref dataclasses.py:1249-1251)."""
    import jax.numpy as jnp

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models.common import sp_constrain
    from accelerate_tpu.state import PartialState

    Accelerator(mesh_config=MeshConfig(axes={"data": 2, "seq": 4}))
    x = jnp.ones((2, 8, 16))
    y = jax.jit(sp_constrain)(x)
    assert y.sharding.spec[1] == "seq"
    # Megatron flavor: no seq axis -> the TP 'model' axis carries SP.
    # (fresh shape: jit caches on the underlying function, and the first
    # trace baked in the 'seq' mesh)
    PartialState._reset_state()
    Accelerator(mesh_config=MeshConfig(axes={"data": 2, "model": 4}))
    y = jax.jit(sp_constrain)(jnp.ones((2, 12, 16)))
    assert y.sharding.spec[1] == "model"
    # indivisible seq stays a no-op rather than erroring
    z = jax.jit(sp_constrain)(jnp.ones((2, 7, 16)))
    assert z.shape == (2, 7, 16)


def test_llama_sequence_parallel_matches_unconstrained():
    """config.sequence_parallel=True only adds sharding hints: the loss (and
    its gradient) must match the unconstrained run."""
    import dataclasses as dc

    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import llama

    Accelerator(mesh_config=MeshConfig(axes={"data": 2, "model": 4}))
    cfg = llama.LlamaConfig.tiny()
    cfg_sp = dc.replace(cfg, sequence_parallel=True)
    params = llama.init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 33), 0, cfg.vocab_size)
    batch = {"input_ids": ids}

    loss, g = jax.jit(jax.value_and_grad(
        lambda p: llama.causal_lm_loss(cfg, p, batch)))(params)
    loss_sp, g_sp = jax.jit(jax.value_and_grad(
        lambda p: llama.causal_lm_loss(cfg_sp, p, batch)))(params)
    np.testing.assert_allclose(float(loss), float(loss_sp), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(g_sp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_mixtral_sequence_parallel_matches_unconstrained():
    import dataclasses as dc

    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.models import mixtral

    Accelerator(mesh_config=MeshConfig(axes={"data": 2, "model": 4}))
    cfg = mixtral.MixtralConfig.tiny()
    cfg_sp = dc.replace(cfg, sequence_parallel=True)
    params = mixtral.init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 33), 0, cfg.vocab_size)
    batch = {"input_ids": ids}
    loss = jax.jit(lambda p: mixtral.causal_lm_loss(cfg, p, batch))(params)
    loss_sp = jax.jit(lambda p: mixtral.causal_lm_loss(cfg_sp, p, batch))(params)
    np.testing.assert_allclose(float(loss), float(loss_sp), rtol=1e-5)
