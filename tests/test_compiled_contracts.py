"""Compiled-program performance contracts (VERDICT r4 #2).

The TPU tunnel is flaky, so throughput numbers can go stale for rounds at
a time. These tests are the hardware-independent guardrail: they lower the
key programs to optimized HLO on the virtual 8-device CPU mesh and assert
the *structure* GSPMD must produce — the collective pattern is what sets
the performance class of each parallelism mode, and it is identical on the
CPU and TPU SPMD partitioners even though wall-clock isn't measured.

Contracts (pattern: the reference's threshold-gate idea,
ref test_utils/scripts/external_deps/test_performance.py:195-203, applied
to program text instead of accuracy):

1. ZeRO-3 fwd+bwd all-gathers params and reduce-scatters grads — it must
   NOT degenerate to a replicated all-reduce step.
2. ZeRO-1 fwd+bwd is pure data-parallel: grads all-reduce, params are
   never all-gathered (they are already replicated).
3. ZeRO-1's full train step still shards the optimizer moments: the
   update path reduce-scatters grads into moment shards and all-gathers
   only the param delta.
4. One ring-attention rotation is exactly one collective-permute per
   rotated buffer (K and V) — and the ring never all-gathers the sequence.
5. `attention_backend='auto'` selects the pallas flash kernel at/beyond
   1024 tokens on TPU (pure-function contract; the kernel itself needs
   hardware).
6. Repeated `train_step` calls with same-shaped inputs hit the jit cache —
   no recompile.

Note: XLA's CPU backend lowers reduce-scatter to all-to-all(+local reduce)
in optimized HLO, so the reduce-scatter clauses accept either spelling
(`require` groups).

Contracts are `accelerate_tpu.analysis.CollectiveContract`s (ISSUE 4).
The per-shard_map-lowering collective-permute pins (native CSE'd vs 0.4.x
experimental duplicated bodies) live in ONE table —
`analysis.contracts._SHARD_MAP_TABLE` — resolved per running jax by
`contract_for`; the scattered `has_native_shard_map()` branches this file
used to carry are gone.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.analysis import (
    CollectiveContract,
    collective_counts,
    contract_for,
)
from accelerate_tpu.models import llama
from accelerate_tpu.utils import MeshConfig
from accelerate_tpu.utils.dataclasses import DeepSpeedPlugin


def _zero_step_and_batch(
    stage: int, grad_accum_steps: int = 1, use_grad_accum_buffer: bool = False
):
    acc = Accelerator(
        deepspeed_plugin=DeepSpeedPlugin(zero_stage=stage),
        gradient_accumulation_steps=grad_accum_steps,
    )
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(1e-3),
        use_grad_accum_buffer=use_grad_accum_buffer,
    ))
    ids = np.zeros((8, 65), dtype=np.int32)
    loader = acc.prepare([{"input_ids": ids}])
    (batch,) = list(loader)
    step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
    grad_only = jax.jit(jax.grad(lambda p, b: llama.causal_lm_loss(cfg, p, b)))
    return cfg, ts, batch, step, grad_only


class TestZeroCollectiveStructure:
    # params sharded on fsdp: fwd+bwd must materialize them via all-gather,
    # and grads must come back SHARDED (reduce-scatter, spelled all-to-all
    # + local reduce by the CPU partitioner), never as a replicated
    # all-reduce-only step
    ZERO3_FWD_BWD = CollectiveContract(
        name="zero3.fwd_bwd",
        at_least={"all-gather": 1},
        require=(("reduce-scatter", "all-to-all"),),
    )
    # ZeRO-1 params are replicated: an all-gather in fwd+bwd means the
    # planner sharded them; grads must all-reduce across the data shards
    ZERO1_FWD_BWD = CollectiveContract(
        name="zero1.fwd_bwd",
        forbid=("all-gather", "all-to-all"),
        at_least={"all-reduce": 1},
    )

    def test_zero3_gathers_params_and_scatters_grads(self):
        _, ts, batch, step, grad_only = _zero_step_and_batch(3)
        self.ZERO3_FWD_BWD.enforce(
            grad_only.lower(ts.params, batch).compile().as_text()
        )

    def test_zero1_fwd_bwd_never_gathers_params(self):
        _, ts, batch, step, grad_only = _zero_step_and_batch(1)
        self.ZERO1_FWD_BWD.enforce(
            grad_only.lower(ts.params, batch).compile().as_text()
        )

    def test_zero1_update_shards_moments(self):
        """The full ZeRO-1 step shards optimizer moments even though params
        replicate: grads reduce-scatter into moment shards and only the
        param delta is all-gathered (the r5 fix — before it, stages 1/2
        silently degenerated to DDP with replicated moments)."""
        _, ts, batch, step, _ = _zero_step_and_batch(1)
        # moments actually sharded on device
        big_moments = [
            leaf
            for leaf in jax.tree_util.tree_leaves(ts.opt_state)
            if hasattr(leaf, "sharding") and leaf.size > 1000
        ]
        assert big_moments, "no large optimizer-state leaves found"
        sharded = [
            leaf
            for leaf in big_moments
            if any(s is not None for s in leaf.sharding.spec)
        ]
        assert sharded, (
            "ZeRO-1 optimizer moments are fully replicated — the stage "
            "degenerated to DDP"
        )
        # the update path reduce-scatters grads into moment shards and
        # all-gathers only the param delta
        CollectiveContract(
            name="zero1.full_step",
            at_least={"all-gather": 1},
            require=(("reduce-scatter", "all-to-all"),),
        ).enforce(step.lower(ts, batch).compile().as_text())

    def test_zero3_step_executes(self):
        """The contract programs must also run (shape/dtype sanity)."""
        _, ts, batch, step, _ = _zero_step_and_batch(3)
        ts2, metrics = step(ts, batch)
        assert jnp.isfinite(metrics["loss"])


class TestRingCollectiveStructure:
    def _qkv(self):
        B, S, H, D = 2, 1024, 4, 32
        q = jnp.ones((B, S, H, D))
        k = jnp.ones((B, S, 2, D))  # GQA: fewer K/V heads ride the ring
        v = jnp.ones((B, S, 2, D))
        return q, k, v

    def test_ring_forward_is_two_permutes_no_gather(self):
        from accelerate_tpu.parallel.ring_attention import ring_attention

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        q, k, v = self._qkv()
        fwd = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, causal=True, mesh=mesh)
        )
        # exact permute pin per shard_map lowering + never-gather structure,
        # both from the shared per-jax-version table
        contract_for("ring_attention.forward").enforce(
            fwd.lower(q, k, v).compile().as_text()
        )

    def test_ring_backward_keeps_ring_structure(self):
        from accelerate_tpu.parallel.ring_attention import ring_attention

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        q, k, v = self._qkv()
        bwd = jax.jit(
            jax.grad(
                lambda q, k, v: ring_attention(
                    q, k, v, causal=True, mesh=mesh
                ).sum(),
                argnums=(0, 1, 2),
            )
        )
        # fwd K/V + bwd recompute K/V/mask-free + dK/dV return rings: the
        # exact figure is pinned (per lowering, in the shared table) so a
        # rewrite that silently gathers or doubles rotations fails here
        contract_for("ring_attention.backward").enforce(
            bwd.lower(q, k, v).compile().as_text()
        )


class TestAttentionAutoSelection:
    """Pure-function contract for the auto backend threshold; the pallas
    kernel itself is validated on hardware (benchmarks/sweep_attn.py)."""

    def test_long_context_on_tpu_selects_flash(self):
        sel = llama.select_attention_backend
        assert sel("auto", on_tpu=True, decoding=False, seq_len=1024) == "flash"
        assert sel("auto", on_tpu=True, decoding=False, seq_len=8192) == "flash"

    def test_short_context_keeps_einsum(self):
        sel = llama.select_attention_backend
        assert sel("auto", on_tpu=True, decoding=False, seq_len=512) == "einsum"

    def test_decode_keeps_einsum(self):
        sel = llama.select_attention_backend
        assert sel("auto", on_tpu=True, decoding=True, seq_len=4096) == "einsum"

    def test_cpu_keeps_einsum(self):
        sel = llama.select_attention_backend
        assert sel("auto", on_tpu=False, decoding=False, seq_len=4096) == "einsum"

    def test_explicit_backend_is_passed_through(self):
        sel = llama.select_attention_backend
        for b in ("einsum", "flash", "ring", "ulysses"):
            assert sel(b, on_tpu=False, decoding=False, seq_len=64) == b


class TestJitCacheStability:
    def test_train_step_does_not_recompile(self):
        """Same-shaped batches must reuse the compiled executable: a shape
        or dtype leak in the step (python scalars captured as weak types,
        re-built closures, ...) shows up here as a growing cache."""
        acc = Accelerator(mesh_config=MeshConfig(axes={"fsdp": 8}))
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.key(0))
        ts = acc.prepare(
            TrainState.create(
                apply_fn=None, params=params, tx=optax.adamw(1e-3)
            )
        )
        rng = np.random.default_rng(0)
        step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
        for _ in range(3):
            ids = rng.integers(0, cfg.vocab_size, (8, 65)).astype(np.int32)
            loader = acc.prepare([{"input_ids": ids}])
            (batch,) = list(loader)
            ts, metrics = step(ts, batch)
        assert step._cache_size() == 1, (
            f"train_step compiled {step._cache_size()} times for "
            "identically-shaped batches"
        )

    def test_eval_step_does_not_recompile(self):
        acc = Accelerator()
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.key(0))
        params = acc.prepare_params(params)
        ev = acc.eval_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
        ids = np.zeros((4, 33), dtype=np.int32)
        loader = acc.prepare([{"input_ids": ids}])
        (batch,) = list(loader)
        for _ in range(3):
            ev(params, batch)
        assert ev._cache_size() == 1


class TestTensorParallelStructure:
    def test_tp_fwd_syncs_activations_not_params(self):
        """Megatron-style TP: column/row-parallel matmuls communicate
        *activations* (all-reduce / reduce-scatter of the row-parallel
        output), never gather whole weight matrices."""
        acc = Accelerator(
            mesh_config=MeshConfig(axes={"fsdp": 2, "model": 4})
        )
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.key(0))
        params = acc.prepare_params(params)
        ids = np.zeros((8, 65), dtype=np.int32)
        loader = acc.prepare([{"input_ids": ids}])
        (batch,) = list(loader)
        grad_only = jax.jit(
            jax.grad(lambda p, b: llama.causal_lm_loss(cfg, p, b))
        )
        counts = collective_counts(
            grad_only.lower(params, batch).compile().as_text()
        )
        assert counts["all-reduce"] > 0, dict(counts)


class TestStepReuseAcrossLayouts:
    def test_step_repins_for_a_new_mesh_layout(self):
        """A train_step reused after re-preparing under a different mesh
        must get fresh output pins (new jit entry), not outputs silently
        forced back onto the first layout (r5 review finding)."""
        from accelerate_tpu.state import PartialState

        cfg = llama.LlamaConfig.tiny()
        ids = np.zeros((8, 65), dtype=np.int32)

        acc1 = Accelerator(mesh_config=MeshConfig(axes={"fsdp": 8}))
        params = llama.init_params(cfg, jax.random.key(0))
        ts1 = acc1.prepare(TrainState.create(
            apply_fn=None, params=params, tx=optax.adamw(1e-3)))
        loader = acc1.prepare([{"input_ids": ids}])
        (batch1,) = list(loader)
        step = acc1.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
        ts1, _ = step(ts1, batch1)

        PartialState._reset_state()
        acc2 = Accelerator(mesh_config=MeshConfig(axes={"data": 8}))
        params = llama.init_params(cfg, jax.random.key(0))
        ts2 = acc2.prepare(TrainState.create(
            apply_fn=None, params=params, tx=optax.adamw(1e-3)))
        loader = acc2.prepare([{"input_ids": ids}])
        (batch2,) = list(loader)
        ts2, m = step(ts2, batch2)
        assert jnp.isfinite(m["loss"])
        # outputs keep the SECOND layout (replicated params on the data
        # mesh), not the first (fsdp-sharded)
        big = max(
            jax.tree_util.tree_leaves(ts2.params), key=lambda x: x.size
        )
        assert not any(s is not None for s in big.sharding.spec), (
            f"output forced onto a stale layout: {big.sharding.spec}"
        )
        # and the steady state holds per layout: one more call, no growth
        before = step._cache_size()
        ts2, _ = step(ts2, batch2)
        assert step._cache_size() == before


class TestUlyssesCollectiveStructure:
    def test_ulysses_rides_all_to_all_only(self):
        """Ulysses scatters heads with all-to-all (sequence re-gathered
        per-head, never as a whole): the program must carry all-to-alls
        and NO sequence all-gather or ring permute. Counts are not pinned
        — XLA's CPU backend decomposes one logical a2a into per-pair ops."""
        from accelerate_tpu.parallel.ulysses import ulysses_attention

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        B, S, H, D = 2, 1024, 8, 32
        q = jnp.ones((B, S, H, D))
        k = jnp.ones((B, S, 8, D))
        v = jnp.ones((B, S, 8, D))
        contract = contract_for("ulysses.attention")
        for fn in (
            jax.jit(lambda q, k, v: ulysses_attention(
                q, k, v, causal=True, mesh=mesh)),
            jax.jit(jax.grad(
                lambda q, k, v: ulysses_attention(
                    q, k, v, causal=True, mesh=mesh).sum(),
                argnums=(0, 1, 2),
            )),
        ):
            contract.enforce(fn.lower(q, k, v).compile().as_text())


class TestZero2GradAccumSharding:
    def test_grad_accum_buffer_shards_like_moments(self):
        """ZeRO-2: the persistent gradient store (the accumulation buffer)
        shards on the fsdp axis along with the moments, while params stay
        replicated — and the accumulating step still runs."""
        cfg, ts, batch, step, _ = _zero_step_and_batch(
            2, grad_accum_steps=2, use_grad_accum_buffer=True
        )
        big_params = [
            leaf for leaf in jax.tree_util.tree_leaves(ts.params)
            if leaf.size > 1000
        ]
        assert all(
            not any(s is not None for s in leaf.sharding.spec)
            for leaf in big_params
        ), "ZeRO-2 params must replicate"
        big_accum = [
            leaf for leaf in jax.tree_util.tree_leaves(ts.grad_accum)
            if leaf.size > 1000
        ]
        assert big_accum and all(
            any(s is not None for s in leaf.sharding.spec)
            for leaf in big_accum
        ), "ZeRO-2 grad-accum buffer must shard on the fsdp axis"
        for _ in range(4):  # two full accumulation windows
            ts, m = step(ts, batch)
        assert jnp.isfinite(m["loss"])
        assert step._cache_size() == 1


class TestPipelineCollectiveStructure:
    def test_schedules_shift_activations_never_gather(self):
        """GPipe and 1F1B move activations stage-to-stage with
        collective-permute (one fwd shift + one bwd shift in the loop
        bodies) and must never all-gather activations or params across
        the stage axis; grads sync with all-reduce only."""
        from accelerate_tpu.parallel import (
            pipeline_value_and_grad,
            stack_layers_into_stages,
        )

        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "stage"))
        staged = stack_layers_into_stages(
            {"w": jax.random.normal(jax.random.key(1), (4, 16, 16)) * 0.1}, 4
        )
        x = jax.random.normal(jax.random.key(2), (8, 16))
        t = jax.random.normal(jax.random.key(3), (8, 16))
        for sched in ("gpipe", "1f1b"):
            fn = jax.jit(lambda sp, x, t, s=sched: pipeline_value_and_grad(
                lambda p, xx: jnp.tanh(xx @ p["w"][0]),
                lambda y, tt: jnp.mean((y - tt) ** 2),
                sp, x, t, num_micro_batches=4, mesh=mesh, schedule=s))
            contract_for("pipeline.step").enforce(
                fn.lower(staged, x, t).compile().as_text()
            )


class TestFp8StepStability:
    def test_fp8_train_step_does_not_recompile(self):
        """The fp8 metas thread through TrainState like optimizer state:
        repeated steps must reuse one executable (a meta that changed
        shape/dtype across steps would force a retrace here)."""
        acc = Accelerator(
            mixed_precision="fp8",
            mesh_config=MeshConfig(axes={"fsdp": 8}),
        )
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.key(0))
        ts = acc.prepare(TrainState.create(
            apply_fn=None, params=params, tx=optax.adamw(1e-3),
            fp8_state=llama.init_fp8_state(cfg),
        ))
        ids = np.zeros((8, 65), dtype=np.int32)
        loader = acc.prepare([{"input_ids": ids}])
        (batch,) = list(loader)
        step = acc.train_step(
            lambda p, b, **kw: llama.causal_lm_loss(cfg, p, b, **kw)
        )
        for _ in range(3):
            ts, m = step(ts, batch)
        assert jnp.isfinite(m["loss"])
        assert step._cache_size() == 1
        # the delayed-scaling state actually moved: a regression that
        # drops new_fp8 from the returned state would leave it identical
        # to a fresh init (scales at ones, histories at zeros)
        fresh = jax.tree_util.tree_leaves(llama.init_fp8_state(cfg))
        moved = [
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(ts.fp8_state), fresh)
        ]
        assert any(moved), "fp8 metas never updated across steps" 
