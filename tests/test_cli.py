"""CLI layer: launch env/cmd assembly, config store, estimate — all offline
(ref tests/test_cli.py, 511 LoC: multinode coverage by inspecting generated
env/cmd, never by launching nodes)."""

import argparse
import json

import pytest

from accelerate_tpu.commands.config.config_args import LaunchConfig
from accelerate_tpu.commands.estimate import count_model_params, estimate_table
from accelerate_tpu.commands.launch import add_launch_arguments
from accelerate_tpu.utils.constants import (
    ENV_COORDINATOR,
    ENV_MESH_SHAPE,
    ENV_MIXED_PRECISION,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
)
from accelerate_tpu.utils.launch import (
    build_script_cmd,
    build_tpu_pod_ssh_cmd,
    pod_relaunch_command,
    prepare_launch_env,
    prepare_multihost_env,
)


def parse_launch(argv):
    parser = argparse.ArgumentParser()
    add_launch_arguments(parser)
    return parser.parse_args(argv)


def test_prepare_launch_env_basic():
    args = parse_launch(
        ["--mixed_precision", "bf16", "--mesh_shape", "fsdp=4,model=2",
         "--gradient_accumulation_steps", "8", "--debug", "train.py"]
    )
    env = prepare_launch_env(args)
    assert env[ENV_MIXED_PRECISION] == "bf16"
    assert env[ENV_MESH_SHAPE] == "fsdp=4,model=2"
    assert env["ACCELERATE_TPU_GRADIENT_ACCUMULATION_STEPS"] == "8"
    assert env["ACCELERATE_TPU_DEBUG"] == "1"


def test_prepare_launch_env_only_set_keys():
    args = parse_launch(["train.py"])
    env = prepare_launch_env(args)
    assert ENV_MIXED_PRECISION not in env
    assert ENV_MESH_SHAPE not in env


def test_multihost_env_synthesized():
    """Multinode is covered offline by inspecting the generated env
    (SURVEY.md §4: never simulated)."""
    args = parse_launch(
        ["--num_machines", "4", "--machine_rank", "2",
         "--main_process_ip", "10.0.0.5", "--main_process_port", "1234",
         "train.py"]
    )
    env = prepare_multihost_env(args)
    assert env[ENV_COORDINATOR] == "10.0.0.5:1234"
    assert env[ENV_NUM_PROCESSES] == "4"
    assert env[ENV_PROCESS_ID] == "2"


def test_single_machine_has_no_coordinator():
    args = parse_launch(["train.py"])
    env = prepare_multihost_env(args)
    assert ENV_COORDINATOR not in env


def test_build_script_cmd_variants():
    args = parse_launch(["train.py", "--lr", "3"])
    assert build_script_cmd(args)[1:] == ["train.py", "--lr", "3"]
    args = parse_launch(["-m", "pkg.train"])
    assert build_script_cmd(args)[1:3] == ["-m", "pkg.train"]
    args = parse_launch(["--no_python", "./run.sh"])
    assert build_script_cmd(args) == ["./run.sh"]


def test_pod_ssh_cmd():
    args = parse_launch(
        ["--tpu_name", "pod-1", "--tpu_zone", "us-central2-b",
         "--mixed_precision", "bf16", "train.py", "--epochs", "2"]
    )
    relaunch = pod_relaunch_command(args)
    assert relaunch.startswith("accelerate-tpu launch")
    assert "--mixed_precision bf16" in relaunch
    assert "train.py --epochs 2" in relaunch
    cmd = build_tpu_pod_ssh_cmd(args, relaunch)
    assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "pod-1"]
    assert "--worker=all" in cmd
    assert "--zone" in cmd


def test_pod_requires_tpu_name():
    args = parse_launch(["train.py"])
    with pytest.raises(ValueError, match="tpu_name"):
        build_tpu_pod_ssh_cmd(args, "true")


def test_launch_config_roundtrip(tmp_path):
    config = LaunchConfig(num_machines=2, mixed_precision="bf16",
                          mesh_shape="data=2", main_process_ip="10.0.0.1")
    path = config.save(tmp_path / "cfg.yaml")
    loaded = LaunchConfig.load(path)
    assert loaded == config


def test_launch_config_rejects_unknown_keys(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("nonsense_key: 1\n")
    with pytest.raises(ValueError, match="nonsense_key"):
        LaunchConfig.load(p)


def test_config_merge_cli_wins(tmp_path):
    from accelerate_tpu.commands.launch import _merge_config

    LaunchConfig(mixed_precision="no", mesh_shape="data=4").save(
        tmp_path / "cfg.yaml"
    )
    args = parse_launch(
        ["--config_file", str(tmp_path / "cfg.yaml"),
         "--mixed_precision", "bf16", "train.py"]
    )
    args = _merge_config(args)
    assert args.mixed_precision == "bf16"  # CLI wins
    assert args.mesh_shape == "data=4"     # yaml fills the gap


def test_write_basic_config(tmp_path):
    from accelerate_tpu.commands.config.default import write_basic_config

    path = write_basic_config(config_file=tmp_path / "basic.yaml")
    config = LaunchConfig.load(path)
    assert config.distributed_type in ("TPU", "CPU")


def test_estimate_presets():
    total, per_module = count_model_params("llama-7b")
    assert 6.5e9 < total < 7.5e9, total
    rows = estimate_table("bert-base", ["float32", "int8"])
    assert rows[0]["total_size"] == pytest.approx(rows[1]["total_size"] * 4)
    total_bert, _ = count_model_params("bert-base")
    assert 0.9e8 < total_bert < 1.3e8, total_bert


def test_estimate_local_safetensors(tmp_path):
    # hand-build a minimal safetensors file: header + zero payload
    import numpy as np

    header = {
        "layer1.weight": {"dtype": "F32", "shape": [10, 4],
                          "data_offsets": [0, 160]},
        "layer2.weight": {"dtype": "F32", "shape": [5], "data_offsets": [160, 180]},
    }
    raw = json.dumps(header).encode()
    blob = len(raw).to_bytes(8, "little") + raw + b"\x00" * 180
    (tmp_path / "model.safetensors").write_bytes(blob)
    total, per_module = count_model_params(str(tmp_path))
    assert total == 45
    assert per_module == {"layer1": 40, "layer2": 5}


def test_estimate_hf_config(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "llama", "vocab_size": 1000, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
    }))
    total, _ = count_model_params(str(tmp_path))
    assert 0 < total < 1e7


def test_estimate_unknown_model():
    with pytest.raises(ValueError, match="not a preset"):
        count_model_params("no-such-model")


def test_cli_registers_all_subcommands():
    from accelerate_tpu.commands.accelerate_cli import build_parser

    parser = build_parser()
    sub = next(a for a in parser._actions
               if isinstance(a, argparse._SubParsersAction))
    for name in ("env", "config", "launch", "test", "estimate", "tpu-config"):
        assert name in sub.choices, name


def test_questionnaire_zero3_ring_cp_roundtrip(tmp_path, monkeypatch):
    """VERDICT r4 #6: config -> launch round-trip with NO hand-editing.
    The questionnaire emits a ZeRO-3 + ring-CP yaml; `launch` lowers it to
    the env protocol; Accelerator resolves that env into real plugins and a
    mesh with the seq axis."""
    import io
    import os
    import sys

    from accelerate_tpu.commands.config.cluster import interactive_config
    from accelerate_tpu.commands.launch import _merge_config
    from accelerate_tpu.utils.constants import (
        ENV_CP_DEGREE,
        ENV_CP_MODE,
        ENV_ZERO_STAGE,
    )

    answers = [
        "1",   # hosts
        "",    # pod launch? -> default no
        "1",   # mixed precision menu -> bf16
        "1",   # engine menu -> zero
        "2",   # ZeRO stage menu index 2 -> stage 3
        "1",   # CP menu -> ring
        "2",   # CP degree
        "1",   # gradient accumulation steps
        "",    # debug? -> default no
    ]
    monkeypatch.setattr(sys, "stdin", io.StringIO("\n".join(answers) + "\n"))
    config = interactive_config()
    assert config.zero_stage == 3
    assert config.context_parallel_mode == "ring"
    assert config.context_parallel_degree == 2
    assert config.mixed_precision == "bf16"

    path = config.save(tmp_path / "cfg.yaml")
    args = parse_launch(["--config_file", str(path), "train.py"])
    args = _merge_config(args)
    env = prepare_launch_env(args)
    assert env[ENV_ZERO_STAGE] == "3"
    assert env[ENV_CP_MODE] == "ring"
    assert env[ENV_CP_DEGREE] == "2"

    # the launched script's process: env -> plugins -> mesh
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    from accelerate_tpu.accelerator import Accelerator

    acc = Accelerator()
    assert acc.deepspeed_plugin is not None
    assert acc.deepspeed_plugin.zero_stage == 3
    assert acc.deepspeed_plugin.shard_params
    assert acc.context_parallel_plugin is not None
    assert acc.context_parallel_plugin.mode == "ring"
    assert acc.mesh.shape["seq"] == 2
    assert acc.mesh.shape["fsdp"] == 4  # -1 fill over the remaining devices


def test_launch_env_engine_flags():
    """CLI engine flags lower to the env protocol directly."""
    from accelerate_tpu.utils.constants import (
        ENV_CP_DEGREE,
        ENV_CP_MODE,
        ENV_FSDP_STRATEGY,
        ENV_ZERO_STAGE,
    )

    args = parse_launch(["--zero_stage", "2", "train.py"])
    env = prepare_launch_env(args)
    assert env[ENV_ZERO_STAGE] == "2"
    assert ENV_CP_MODE not in env

    args = parse_launch(
        ["--fsdp_sharding_strategy", "SHARD_GRAD_OP",
         "--context_parallel_mode", "ulysses",
         "--context_parallel_degree", "4", "train.py"]
    )
    env = prepare_launch_env(args)
    assert env[ENV_FSDP_STRATEGY] == "SHARD_GRAD_OP"
    assert env[ENV_CP_MODE] == "ulysses"
    assert env[ENV_CP_DEGREE] == "4"

    # 'none' must NOT serialize (the child would build a seq axis for it)
    args = parse_launch(["--context_parallel_mode", "none", "train.py"])
    env = prepare_launch_env(args)
    assert ENV_CP_MODE not in env


def test_pod_relaunch_carries_engine_flags():
    args = parse_launch(
        ["--tpu_name", "pod-1", "--zero_stage", "3",
         "--context_parallel_mode", "ring", "--context_parallel_degree", "2",
         "train.py"]
    )
    relaunch = pod_relaunch_command(args)
    assert "--zero_stage 3" in relaunch
    assert "--context_parallel_mode ring" in relaunch
    assert "--context_parallel_degree 2" in relaunch


def test_ambiguous_plugin_wildcards_keep_sharding_axis():
    """FSDP (fsdp=-1) + a default-degree CP plugin (seq=-1) is ambiguous:
    the memory-critical sharding axis must survive (previously the
    last-wins rule silently dropped fsdp, losing all parameter sharding)
    and the user is told what was dropped."""
    import warnings as _warnings

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.utils import (
        ContextParallelPlugin,
        FullyShardedDataParallelPlugin,
    )

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        acc = Accelerator(
            fsdp_plugin=FullyShardedDataParallelPlugin(),
            context_parallel_plugin=ContextParallelPlugin(),
        )
    assert acc.mesh.shape["fsdp"] == 8
    assert "seq" not in acc.mesh.shape
    assert any("fill-the-rest" in str(w.message) for w in caught)


def test_lone_cp_plugin_fills_data_axis():
    """A lone fixed-degree CP plugin must still cover every device."""
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.utils import ContextParallelPlugin

    acc = Accelerator(
        context_parallel_plugin=ContextParallelPlugin(seq_degree=2)
    )
    assert dict(acc.mesh.shape) == {"data": 4, "seq": 2}


def test_hybrid_shard_replicates_across_dcn_domains():
    """HYBRID_SHARD, TPU-natively: the fsdp (shard) axis spans the
    ICI-connected chips of each DCN domain, the data (replicate) axis
    spans domains — param gathers never cross the slow link. The degree
    comes from the LIVE topology at build time (DCN_FILL sentinel), not
    from env guessing: a single-domain world — one slice, however many
    hosts — degenerates to FULL_SHARD because everything rides ICI."""
    import jax

    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, MeshConfig
    from accelerate_tpu.utils.constants import DCN_FILL
    from accelerate_tpu.utils.dataclasses import count_dcn_domains

    plugin = FullyShardedDataParallelPlugin(sharding_strategy="HYBRID_SHARD")
    assert plugin.to_mesh_axes() == {"data": DCN_FILL, "fsdp": -1}
    assert plugin.shard_params

    # this process's 8 CPU devices are one domain -> FULL_SHARD
    mesh = MeshConfig(axes=plugin.to_mesh_axes()).build()
    assert dict(mesh.shape) == {"fsdp": 8}

    # domain counting: slice_index wins when present; process ownership
    # otherwise (multi-process CPU worlds talk over sockets)
    class Dev:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    tpu_pod = [Dev(platform="tpu", slice_index=i // 4, process_index=i // 2)
               for i in range(8)]
    assert count_dcn_domains(tpu_pod) == 2
    one_slice_pod = [Dev(platform="tpu", slice_index=0, process_index=i // 2)
                     for i in range(8)]
    assert count_dcn_domains(one_slice_pod) == 1
    # CPU devices carry a vacuous slice_index=0 in distributed mode: the
    # slice notion must only be trusted on TPU, else 2-process CPU worlds
    # read as one domain
    cpu_world = [Dev(platform="cpu", slice_index=0, process_index=i // 4)
                 for i in range(8)]
    assert count_dcn_domains(cpu_world) == 2
    assert count_dcn_domains(jax.devices()) == 1


def test_resolved_axes_rejects_unresolved_dcn_fill():
    """DCN_FILL needs live topology — direct resolution must raise, not
    leak a negative size through sign cancellation (r5 review)."""
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin, MeshConfig

    cfg = MeshConfig(
        axes=FullyShardedDataParallelPlugin("HYBRID_SHARD").to_mesh_axes()
    )
    with pytest.raises(ValueError, match="DCN_FILL"):
        cfg.resolved_axes(8)
    # build() resolves it fine (one domain here -> FULL_SHARD)
    assert dict(cfg.build().shape) == {"fsdp": 8}


# ---------------------------------------------------------------------------
# pod-router / pod-worker CLI (ISSUE 17) — jax-free validation surface
# ---------------------------------------------------------------------------


def test_pod_router_dry_run_prints_config(capsys):
    """--dry-run validates everything and prints ONE JSON line without
    binding a socket, spawning a worker, or importing jax."""
    from accelerate_tpu.commands.accelerate_cli import main

    rc = main(["pod-router", "--dry-run", "--family", "gpt2",
               "--slots", "3", "--max-len", "64", "--prefill-chunk", "8",
               "--page-size", "8", "--prefill-workers", "2",
               "--decode-workers", "1", "--no-rebalance"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    cfg = json.loads(out[-1])
    assert cfg["dry_run"] is True
    assert cfg["workers"] == ["prefill", "prefill", "decode"]
    assert cfg["engine"]["num_slots"] == 3
    assert cfg["engine"]["max_len"] == 64
    assert cfg["pod"]["rebalance"] is False
    assert "/v1/completions" in cfg["routes"]
    # the spec the router prints is exactly what each worker receives
    from accelerate_tpu.serving.pod.distributed.worker import ENGINE_SPEC_KEYS

    assert set(cfg["engine"]) == set(ENGINE_SPEC_KEYS)


def test_pod_router_rejects_bad_config(capsys):
    from accelerate_tpu.commands.accelerate_cli import main

    assert main(["pod-router", "--dry-run", "--prefill-workers", "0"]) == 2
    assert "at least 1 prefill" in capsys.readouterr().err
    assert main(["pod-router", "--dry-run", "--heartbeat-interval-s", "5",
                 "--heartbeat-timeout-s", "2"]) == 2
    assert "timeout must exceed" in capsys.readouterr().err
    assert main(["pod-router", "--dry-run", "--listen", "nonsense"]) == 2
    assert "HOST:PORT" in capsys.readouterr().err


def test_pod_worker_rejects_bad_args(capsys):
    from accelerate_tpu.commands.accelerate_cli import main

    assert main(["pod-worker", "--connect", "nonsense",
                 "--worker-id", "0"]) == 2
    assert "HOST:PORT" in capsys.readouterr().err
    assert main(["pod-worker", "--connect", "127.0.0.1:1",
                 "--worker-id", "0", "--engine-json", "[1]"]) == 2
    assert "JSON object" in capsys.readouterr().err
    with pytest.raises(SystemExit):  # argparse: --connect is required
        main(["pod-worker", "--worker-id", "0"])
