"""Distributed-inference tests (ref tests/test_pippy.py — but runnable on the
virtual 8-device CPU mesh instead of multi-GPU hardware)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.inference import (
    make_stage_fn,
    prepare_pipeline,
    prepare_sharded_inference,
)
from accelerate_tpu.utils import MeshConfig


def _layer_fn(layer, x):
    return jnp.tanh(x @ layer["w"] + layer["b"])


def _stacked_layers(key, num_layers=8, d=16):
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.normal(kw, (num_layers, d, d)) * 0.3,
        "b": jax.random.normal(kb, (num_layers, d)) * 0.1,
    }


def _sequential_reference(layers, x, num_layers):
    for i in range(num_layers):
        x = _layer_fn(jax.tree_util.tree_map(lambda p: p[i], layers), x)
    return x


def test_pipeline_matches_sequential():
    mesh = MeshConfig(axes={"stage": 4, "data": 2}).build()
    layers = _stacked_layers(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (8, 16))
    model = prepare_pipeline(_layer_fn, layers, mesh=mesh)
    assert model.num_stages == 4 and model.num_chunks == 4
    out = model(x)
    ref = _sequential_reference(layers, x, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_pre_post_fns():
    mesh = MeshConfig(axes={"stage": 2, "data": 4}).build()
    layers = _stacked_layers(jax.random.key(2), num_layers=4)
    x = jax.random.normal(jax.random.key(3), (4, 16))
    model = prepare_pipeline(
        _layer_fn, layers, mesh=mesh, num_chunks=2,
        pre_fn=lambda h: h * 2.0, post_fn=lambda h: h + 1.0,
    )
    ref = _sequential_reference(layers, x * 2.0, 4) + 1.0
    np.testing.assert_allclose(np.asarray(model(x)), np.asarray(ref), atol=1e-5)


def test_pipeline_requires_stage_axis():
    mesh = MeshConfig(axes={"data": 8}).build()
    layers = _stacked_layers(jax.random.key(4))
    with pytest.raises(ValueError, match="stage"):
        prepare_pipeline(_layer_fn, layers, mesh=mesh)


def test_make_stage_fn_scans_layers():
    layers = _stacked_layers(jax.random.key(5), num_layers=3)
    x = jax.random.normal(jax.random.key(6), (2, 16))
    out = make_stage_fn(_layer_fn)(layers, x)
    ref = _sequential_reference(layers, x, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_sharded_inference_matches_unsharded():
    mesh = MeshConfig(axes={"model": 4, "fsdp": 2}).build()
    d = 32
    params = {
        "layers": {
            "mlp": {
                "up_proj": {"kernel": jax.random.normal(jax.random.key(7), (4, d, d * 4)) * 0.1},
                "down_proj": {"kernel": jax.random.normal(jax.random.key(8), (4, d * 4, d)) * 0.1},
            }
        }
    }

    def forward(p, x):
        def body(h, layer):
            h = jnp.tanh(h @ layer["mlp"]["up_proj"]["kernel"])
            return h @ layer["mlp"]["down_proj"]["kernel"], None

        out, _ = jax.lax.scan(body, x, p["layers"])
        return out

    x = jax.random.normal(jax.random.key(9), (4, d))
    ref = forward(params, x)
    fn, sharded = prepare_sharded_inference(forward, params, mesh=mesh)
    out = fn(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
