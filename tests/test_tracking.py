"""Tracker tests (ref tests/test_tracking.py): registry completeness, the
native JSONL backend, filter_trackers selection, and the Accelerator surface."""

import json
import sys
import types

import pytest

from accelerate_tpu.tracking import (
    LOGGER_TYPE_TO_CLASS,
    DVCLiveTracker,
    GeneralTracker,
    JSONLTracker,
    filter_trackers,
)
from accelerate_tpu.utils.dataclasses import LoggerType


def test_registry_covers_all_logger_types():
    # every LoggerType except the "all" sentinel has a concrete class
    names = {str(t) for t in LoggerType if t != LoggerType.ALL}
    assert names == set(LOGGER_TYPE_TO_CLASS)


def test_jsonl_tracker_roundtrip(tmp_path):
    t = JSONLTracker("run", logging_dir=str(tmp_path))
    t.store_init_configuration({"lr": 1e-3, "layers": 2})
    t.log({"loss": 0.5}, step=1)
    t.log({"loss": 0.25}, step=2)
    t.finish()
    lines = [json.loads(l) for l in open(t.path)]
    assert lines[0]["event"] == "config" and lines[0]["config"]["lr"] == 1e-3
    assert lines[2]["loss"] == 0.25 and lines[2]["step"] == 2


def test_filter_trackers_selects_available(tmp_path):
    trackers = filter_trackers(["jsonl"], logging_dir=str(tmp_path))
    assert len(trackers) == 1 and isinstance(trackers[0], JSONLTracker)
    # unavailable backends are skipped, not fatal
    trackers = filter_trackers(["jsonl", "aim"], logging_dir=str(tmp_path))
    assert all(isinstance(t, GeneralTracker) for t in trackers)


def test_filter_trackers_all_includes_jsonl(tmp_path):
    trackers = filter_trackers(["all"], logging_dir=str(tmp_path))
    assert any(isinstance(t, JSONLTracker) for t in trackers)


def test_filter_trackers_rejects_unknown(tmp_path):
    with pytest.raises(ValueError):
        filter_trackers(["not_a_tracker"], logging_dir=str(tmp_path))


def test_filter_trackers_passes_instances(tmp_path):
    inst = JSONLTracker("run", logging_dir=str(tmp_path))
    assert filter_trackers([inst]) == [inst]


class _FakeLive:
    def __init__(self, **kwargs):
        self.params = None
        self.metrics = []
        self.step = None
        self.ended = False

    def log_params(self, values):
        self.params = values

    def log_metric(self, k, v):
        self.metrics.append((self.step, k, v))

    def next_step(self):
        pass

    def end(self):
        self.ended = True


def test_dvclive_tracker_with_stub(monkeypatch):
    monkeypatch.setitem(sys.modules, "dvclive", types.SimpleNamespace(Live=_FakeLive))
    t = DVCLiveTracker("run")
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 1.5, "note": "skipped-non-scalar"}, step=3)
    t.finish()
    assert t.live.params == {"lr": 0.1}
    assert t.live.metrics == [(3, "loss", 1.5)]
    assert t.live.ended


def test_dvclive_tracker_accepts_array_scalars(monkeypatch):
    import numpy as np

    monkeypatch.setitem(sys.modules, "dvclive", types.SimpleNamespace(Live=_FakeLive))
    t = DVCLiveTracker("run")
    t.log({"loss": np.float32(1.5), "acc": np.asarray(0.5)}, step=1)
    assert sorted(t.live.metrics) == [(1, "acc", 0.5), (1, "loss", 1.5)]
