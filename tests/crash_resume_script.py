"""Fault-injection victim for test_crash_resume.py: SIGKILL mid-save.

Trains a deterministic toy loop, commits one complete async checkpoint at
step 4 (enqueue + drain), runs two more steps, enqueues a second async
save for step 6 and SIGKILLs itself while the background persist is in
flight. No drain ever runs, so step 6's manifest must never publish —
whatever bytes landed, the directory is torn, and resume must fall back
to step 4's commit. Run with CRASH_DIR set; deliberately killed, so it
never exits normally.
"""

from __future__ import annotations

import os
import signal
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

_W = 64
NUM_STEPS = 8
COMMIT_STEP = 4
TORN_STEP = 6


def make_state():
    from accelerate_tpu.training import TrainState

    def apply_fn(p, x):
        return x @ p["w"]

    return TrainState.create(
        apply_fn=apply_fn,
        params={"w": jnp.eye(_W) * 0.5},
        tx=optax.adam(1e-2),
    )


def batch_fn(i):
    x = np.random.RandomState(0).randn(8, _W).astype("float32")
    y = np.random.RandomState(1).randn(8, _W).astype("float32")
    return {"x": jnp.asarray(x), "y": jnp.asarray(y)}


def _loss(params, batch):
    return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)


def step_fn(state, batch):
    @jax.jit
    def _step(state, batch):
        loss, grads = jax.value_and_grad(_loss)(state.params, batch)
        return state.apply_gradients(grads), {"loss": loss}

    out = _step(state, batch)
    jax.block_until_ready(out[0].params)
    return out


def main() -> None:
    from accelerate_tpu import checkpointing as ckpt

    base = os.environ["CRASH_DIR"]
    state = make_state()
    for i in range(TORN_STEP):
        state, metrics = step_fn(state, batch_fn(i))
        if i + 1 == COMMIT_STEP:
            ckpt.save_accelerator_state(
                os.path.join(base, f"step_{COMMIT_STEP:08d}"),
                train_states=[state], step=COMMIT_STEP, async_save=True)
            ckpt.wait_for_checkpoints()  # drain: step 4 COMMITS
    ckpt.save_accelerator_state(
        os.path.join(base, f"step_{TORN_STEP:08d}"),
        train_states=[state], step=TORN_STEP, async_save=True)
    print("ENQUEUED", flush=True)
    time.sleep(0.02)  # let the background persist get bytes in flight
    os.kill(os.getpid(), signal.SIGKILL)  # crash mid-save: no drain, ever


if __name__ == "__main__":
    main()
