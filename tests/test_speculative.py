"""Speculative decoding + COW request forking + real logprobs (ISSUE 12).

CPU contracts for the draft/verify/accept engine mode and engine-level
request forking: greedy output is byte-identical to the non-speculative
engine whatever the draft (exact-match accept), sampled output follows
the TARGET distribution exactly (rejection sampling — pinned against a
known closed-form distribution, with a deliberately skewed draft), the
compile count stays flat across the speculative x int8 config matrix,
strict="error" audits the five programs clean, an n-way fork fan-out
pays ONE prompt prefill (pinned by chunk count) with full COW isolation
under cancel/retire, and per-token logprobs match a hand computation
from the family forward."""

import dataclasses
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import gpt2
from accelerate_tpu.serving import Engine, EngineConfig, RequestStatus


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    """Every Engine() here compiles the same tiny programs; the repo's
    persistent compilation cache turns the repeats into deserializes."""
    import os

    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (ISSUE 16 hit this the moment
    # an engine module sorted before test_launched_scripts)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture(scope="module")
def draft_setup(gpt2_setup):
    """A DISAGREEING draft: same tiny architecture, different random
    init — its argmax/softmax differ from the target's, so exact-match
    accepts fail and the rejection/correction paths actually run."""
    cfg, _ = gpt2_setup
    return cfg, gpt2.init_params(cfg, jax.random.key(99))


def _engine(cfg, params, family=gpt2, **overrides):
    defaults = dict(num_slots=3, max_len=64, prefill_chunk=8,
                    cache_dtype=jnp.float32)
    defaults.update(overrides)
    return Engine(family, cfg, params, EngineConfig(**defaults))


def _prompt(rng, n, vocab):
    return rng.integers(0, vocab, (n,)).astype(np.int32)


def _run_wave(eng, prompts, temps, budget=7, keys=None):
    reqs = [eng.submit(p, max_new_tokens=budget, temperature=t,
                       key=None if keys is None else keys[i])
            for i, (p, t) in enumerate(zip(prompts, temps))]
    eng.run_until_idle()
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    return reqs


SPEC_PROGRAMS = {"admit": 1, "prefill": 1, "draft_prefill": 1,
                 "draft": 1, "verify": 1}


# ---------------------------------------------------------------------------
# the acceptance contract: greedy byte-identical, whatever the draft
# ---------------------------------------------------------------------------


def test_speculative_greedy_byte_identical_disagreeing_draft(
        gpt2_setup, draft_setup):
    """Exact-match accept means greedy output CANNOT depend on the draft:
    a disagreeing draft (different random init) only lowers the accept
    rate — the committed chain is the target's argmax chain, byte for
    byte, through staggered multi-slot traffic and prefix-reuse hits."""
    cfg, params = gpt2_setup
    _, dparams = draft_setup
    rng = np.random.default_rng(0)
    shared = _prompt(rng, 18, cfg.vocab_size)
    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (5, 13, 9)]
    prompts += [np.concatenate([shared, _prompt(rng, n, cfg.vocab_size)])
                for n in (3, 4)]
    temps = (0.0,) * len(prompts)

    def run(eng):
        # second shared-prefix prompt arrives in a second wave, so it
        # admits as a prefix HIT (target reuses pages; the draft runs
        # its catch-up chunks)
        out = [r.tokens for r in _run_wave(eng, prompts[:4], temps[:4])]
        return out + [r.tokens for r in _run_wave(eng, prompts[4:],
                                                  temps[4:])]

    plain = run(_engine(cfg, params, num_slots=2, page_size=8))
    eng = _engine(cfg, params, num_slots=2, page_size=8,
                  speculative=(gpt2, cfg, dparams), draft_k=4)
    spec = run(eng)
    assert spec == plain
    assert eng.compile_stats() == SPEC_PROGRAMS
    assert eng.metrics.prefix_hits >= 1
    m = eng.metrics_summary()
    # the disagreeing draft must actually disagree — otherwise the
    # rejection/correction path was never exercised
    assert 0.0 < m["spec_accept_rate"] < 1.0, m["spec_accept_rate"]
    assert m["spec_drafted_tokens"] > m["spec_accepted_tokens"]


def test_speculative_self_draft_hits_tokens_per_step_bar(gpt2_setup):
    """A perfectly-agreeing draft (the target drafts for itself) commits
    draft_k + 1-adjacent tokens per verify step: accept rate 1.0 and
    tokens-per-decode-step > 1.5 — the ISSUE 12 acceptance bar — while
    staying byte-identical to the plain engine."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(1)
    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (5, 12)]
    temps = (0.0, 0.0)
    # engine shapes deliberately MATCH the disagreeing-draft test's
    # (slots 2, page 8): the module's compile cache turns this test's
    # plain-engine programs into deserializes (tier-1 budget satellite)
    plain = [r.tokens for r in _run_wave(
        _engine(cfg, params, num_slots=2, page_size=8), prompts, temps,
        budget=10)]
    eng = _engine(cfg, params, num_slots=2, page_size=8,
                  speculative=(gpt2, cfg, params), draft_k=3)
    spec = [r.tokens for r in _run_wave(eng, prompts, temps, budget=10)]
    assert spec == plain
    m = eng.metrics_summary()
    assert m["spec_accept_rate"] == 1.0
    assert m["tokens_per_decode_step"] > 1.5, m["tokens_per_decode_step"]


# ---------------------------------------------------------------------------
# distribution preservation under sampling (the rejection-sampling pin)
# ---------------------------------------------------------------------------


def _const_logits_forward(bias):
    """A family forward whose logits are CONSTANT (independent of input)
    — the KV mechanics are gpt2's, so cache plumbing stays real, but
    every sampled token is an i.i.d. draw from softmax(bias). That makes
    the committed-token distribution checkable in closed form."""
    bias = jnp.asarray(bias, jnp.float32)

    def fwd(config, params, input_ids, positions=None, kv_caches=None):
        logits, caches = gpt2.forward(config, params, input_ids,
                                      positions=positions,
                                      kv_caches=kv_caches)
        return jnp.broadcast_to(bias, logits.shape), caches

    return fwd


def test_speculative_sampling_preserves_target_distribution(gpt2_setup):
    """The rejection-sampling correctness pin: with a KNOWN constant
    target distribution and a draft deliberately skewed toward a token
    the target (almost) never emits, the committed tokens must still
    follow the TARGET distribution — accepted proposals plus residual
    corrections reproduce it exactly. A broken accept rule (e.g.
    committing draft proposals unconditionally) floods token 0 and fails
    by a wide margin."""
    cfg, params = gpt2_setup
    V = cfg.vocab_size
    target_p = np.full((V,), 1e-12)
    target_p[1:5] = [0.4, 0.3, 0.2, 0.1]
    target_bias = np.log(target_p / target_p.sum())
    draft_p = np.full((V,), 1e-12)
    draft_p[0] = 0.5                       # the poison proposal
    draft_p[1:5] = 0.125
    draft_bias = np.log(draft_p / draft_p.sum())

    # 4 waves x budget 12 instead of 6 x 8: the same 192 samples, but a
    # third fewer admission/prefill cycles drive the eager host-side
    # wave loop (tier-1 budget satellite — batched deeper, same
    # closed-form statistics)
    eng = Engine(
        _const_logits_forward(target_bias), cfg, params,
        EngineConfig(num_slots=4, max_len=32, prefill_chunk=8,
                     cache_dtype=jnp.float32,
                     speculative=(_const_logits_forward(draft_bias),
                                  cfg, params),
                     draft_k=4))
    rng = np.random.default_rng(2)
    samples: list[int] = []
    for wave in range(4):
        prompts = [_prompt(rng, 4, V) for _ in range(4)]
        keys = [np.array([wave, i], np.uint32) for i in range(4)]
        reqs = _run_wave(eng, prompts, temps=(1.0,) * 4, budget=12,
                         keys=keys)
        for r in reqs:
            samples.extend(r.tokens)
    counts = np.bincount(samples, minlength=V)
    freq = counts / counts.sum()
    # token 0 is (essentially) impossible under the target: any real
    # mass here means draft proposals leaked through the accept rule
    assert freq[0] < 0.04, freq[:6]
    for tok, p in ((1, 0.4), (2, 0.3), (3, 0.2), (4, 0.1)):
        assert abs(freq[tok] - p) < 0.12, (tok, freq[tok], p)
    assert counts[5:].sum() == 0  # nothing outside the support
    # the skewed draft really was skewed: most proposals were rejected
    m = eng.metrics_summary()
    assert m["spec_accept_rate"] < 0.8, m["spec_accept_rate"]


# ---------------------------------------------------------------------------
# compile-count flatness + config validation + strict audit
# ---------------------------------------------------------------------------


def test_compile_flat_across_speculative_int8_and_k_mixes(gpt2_setup):
    """The compile-count guard over the new axes: a speculative engine
    per kv_dtype (bf16/int8 pools — the kernel axis is invalid with
    speculation, pinned in config validation below), driven through
    waves of different prompt lengths / budgets / temperatures / prefix
    hits — five programs, each compiled exactly once. draft_k=3 differs
    from the other suites' k=4 so two k values compile-flat overall."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(3)
    shared = _prompt(rng, 18, cfg.vocab_size)
    for kvd in (None, "int8"):
        eng = _engine(cfg, params, num_slots=2, max_len=48,
                      page_size=8, cache_dtype=jnp.bfloat16,
                      kv_dtype=kvd,
                      speculative=(gpt2, cfg, params), draft_k=3)
        for plen, mnt, temp in ((3, 2, 0.0), (13, 1, 1.0),
                                ("shared", 2, 0.5)):
            if plen == "shared":
                prompts = [np.concatenate(
                    [shared, _prompt(rng, 2 + i, cfg.vocab_size)])
                    for i in range(2)]
            else:
                prompts = [_prompt(rng, plen, cfg.vocab_size)
                           for _ in range(2)]
            reqs = [eng.submit(p, max_new_tokens=mnt, temperature=temp)
                    for p in prompts]
            eng.run_until_idle()
            assert all(r.status is RequestStatus.FINISHED
                       for r in reqs)
            assert eng.compile_stats() == SPEC_PROGRAMS, kvd


def test_speculative_config_validation(gpt2_setup):
    """Bad speculative configs fail LOUDLY at construction: k < 1,
    vocab mismatch, a non-triple, the Pallas kernel (single-token op vs
    K-token verify), and a meshed engine."""
    cfg, params = gpt2_setup
    spec = (gpt2, cfg, params)
    with pytest.raises(ValueError, match="draft_k"):
        _engine(cfg, params, speculative=spec, draft_k=0)
    with pytest.raises(ValueError, match="triple"):
        _engine(cfg, params, speculative=gpt2)
    bad_cfg = dataclasses.replace(cfg, vocab_size=cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="vocab_size"):
        _engine(cfg, params, speculative=(gpt2, bad_cfg, params))
    with pytest.raises(ValueError, match="paged_attention"):
        _engine(cfg, params, speculative=spec, paged_attention=True)
    with pytest.raises(ValueError, match="meshed"):
        _engine(cfg, params, speculative=spec,
                mesh=SimpleNamespace(size=2))
    # "auto" resolves to the dense verify path instead of erroring
    eng = _engine(cfg, params, speculative=spec, paged_attention="auto")
    assert not eng._use_paged_kernel


def test_speculative_strict_error_audits_clean(gpt2_setup):
    """strict="error" audits all five speculative programs (the
    exhaustive no-collectives contract names each) with no findings on
    a greedy + sampled wave; the contract factory exposes the five
    names."""
    from accelerate_tpu.analysis.contracts import serving_program_contracts

    contracts = serving_program_contracts(speculative=True)
    assert set(contracts) == set(SPEC_PROGRAMS)
    assert contracts["verify"].name == "serving.verify"

    cfg, params = gpt2_setup
    # shapes match the self-draft test's spec engine (slots 2, page 8,
    # k=3): the audit reads the lowering, the executables deserialize
    eng = _engine(cfg, params, num_slots=2, page_size=8,
                  speculative=(gpt2, cfg, params), draft_k=3,
                  strict="error")
    rng = np.random.default_rng(4)
    prompts = [_prompt(rng, n, cfg.vocab_size) for n in (5, 11)]
    _run_wave(eng, prompts, temps=(0.0, 0.9), budget=5)  # no raise = clean


def test_pod_router_strips_speculation(gpt2_setup):
    """PodEngine workers must not half-adopt speculation (the install
    path drives the classic admit program): the router strips the
    setting and the pod still serves the trace byte-identically to a
    plain single engine."""
    from accelerate_tpu.serving.pod import PodConfig, PodEngine

    cfg, params = gpt2_setup
    ec = EngineConfig(num_slots=3, max_len=64, prefill_chunk=8,
                      cache_dtype=jnp.float32,
                      speculative=(gpt2, cfg, params), draft_k=4)
    pod = PodEngine(gpt2, cfg, params, ec,
                    PodConfig(prefill_workers=1, decode_workers=1))
    for w in pod.prefill_workers + pod.decode_workers:
        assert w.engine_config.speculative is None
    rng = np.random.default_rng(5)
    p = _prompt(rng, 9, cfg.vocab_size)
    ref_eng = _engine(cfg, params)
    ref = ref_eng.submit(p, max_new_tokens=5)
    ref_eng.run_until_idle()
    req = pod.submit(p, max_new_tokens=5)
    pod.run_until_idle()
    assert req.status is RequestStatus.FINISHED
    assert req.tokens == ref.tokens


# ---------------------------------------------------------------------------
# COW request forking
# ---------------------------------------------------------------------------


def test_fork_fan_out_pays_one_prefill_pinned(gpt2_setup):
    """The ISSUE 12 fan-out bar at the engine level: 1 submit + 7 forks
    of an 80-token prompt (page_size 16, chunk 16) cost exactly ONE full
    prompt prefill (5 chunks) plus one catch-up chunk per fork (the
    final partial page — reuse is capped one token short, so the last
    token always prefills to produce first-token logits): 12 chunks,
    not the 40 of eight independent prefills."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=4, max_len=128, prefill_chunk=16,
                  page_size=16)
    rng = np.random.default_rng(6)
    prompt = _prompt(rng, 80, cfg.vocab_size)
    parent = eng.submit(prompt, max_new_tokens=5, temperature=0.8,
                        key=np.array([1, 0], np.uint32))
    forks = [eng.fork(parent, key=np.array([1, i + 1], np.uint32))
             for i in range(7)]
    eng.run_until_idle()
    assert all(r.status is RequestStatus.FINISHED
               for r in [parent] + forks)
    assert eng.metrics.prefill_chunks == 5 + 7, eng.metrics.prefill_chunks
    # distinct keys -> decorrelated sibling streams
    assert len({tuple(r.tokens) for r in forks}) > 1
    for f in forks:
        assert f.parent_id == parent.request_id


def test_fork_greedy_matches_parent_and_fresh_engine(gpt2_setup):
    """Greedy forks share the parent's argmax chain: reused prompt pages
    hold exactly the K/V a cold prefill would produce (COW rewrite is
    byte-identical), so parent, forks, and a fresh-engine submission all
    emit the same tokens AND the same per-token logprobs."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, page_size=8)
    rng = np.random.default_rng(7)
    prompt = _prompt(rng, 21, cfg.vocab_size)
    parent = eng.submit(prompt, max_new_tokens=6)
    forks = [eng.fork(parent) for _ in range(2)]
    eng.run_until_idle()
    ref_eng = _engine(cfg, params, page_size=8)
    ref = ref_eng.submit(prompt, max_new_tokens=6)
    ref_eng.run_until_idle()
    assert parent.tokens == forks[0].tokens == forks[1].tokens
    assert parent.tokens == ref.tokens
    assert parent.logprobs == pytest.approx(ref.logprobs, abs=1e-5)
    assert forks[0].logprobs == pytest.approx(ref.logprobs, abs=1e-5)


def test_fork_cow_isolation_under_cancel_and_retire(gpt2_setup):
    """COW isolation: cancelling the PARENT mid-decode leaves every
    fork's stream untouched (shared pages are refcounted, not owned),
    cancelling one FORK leaves its siblings untouched, and after all
    requests retire no page is still mapped."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(8)
    prompt = _prompt(rng, 40, cfg.vocab_size)
    keys = [np.array([9, i], np.uint32) for i in range(4)]

    # baseline: same (prompt, key) requests on a fresh engine — fork
    # streams are schedule-independent, so these are the ground truth
    base_eng = _engine(cfg, params, num_slots=2, page_size=8, max_len=96)
    base = [base_eng.submit(prompt, max_new_tokens=6, temperature=0.7,
                            key=k) for k in keys]
    base_eng.run_until_idle()

    eng = _engine(cfg, params, num_slots=2, page_size=8, max_len=96)
    parent = eng.submit(prompt, max_new_tokens=6, temperature=0.7,
                        key=keys[0])
    forks = [eng.fork(parent, key=keys[i]) for i in (1, 2, 3)]
    # run until the parent has a couple of tokens, then kill it
    while len(parent.tokens) < 2:
        eng.step()
    assert eng.cancel(parent)
    # kill one fork as soon as it produces a token
    while len(forks[0].tokens) < 1:
        eng.step()
    assert eng.cancel(forks[0])
    eng.run_until_idle()
    for i, f in zip((2, 3), forks[1:]):
        assert f.status is RequestStatus.FINISHED
        assert f.tokens == base[i].tokens, i
    assert parent.status is RequestStatus.CANCELLED
    assert eng.allocator.index.mapped_pages == 0
    assert eng.scheduler.live_slots == 0


def test_fork_of_finished_parent_and_no_prefix_cache(gpt2_setup):
    """A fork of a FINISHED parent maps the retirement-cached pages (one
    catch-up chunk only); with prefix_cache=False the fork still runs
    correctly — it just re-prefills (sharing needs the radix tree)."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2, prefill_chunk=8, page_size=8,
                  max_len=96)
    rng = np.random.default_rng(10)
    prompt = _prompt(rng, 32, cfg.vocab_size)
    parent = eng.submit(prompt, max_new_tokens=4)
    eng.run_until_idle()
    chunks_before = eng.metrics.prefill_chunks
    assert chunks_before == 4
    fork = eng.fork(parent)
    eng.run_until_idle()
    assert fork.status is RequestStatus.FINISHED
    assert fork.tokens == parent.tokens
    assert eng.metrics.prefill_chunks == chunks_before + 1

    cold = _engine(cfg, params, num_slots=2, prefill_chunk=8, page_size=8,
                   max_len=96, prefix_cache=False)
    p2 = cold.submit(prompt, max_new_tokens=4)
    f2 = cold.fork(p2)
    cold.run_until_idle()
    assert f2.tokens == p2.tokens == parent.tokens
    assert cold.metrics.prefill_chunks == 8  # two full prefills


def test_fork_parentage_visible_in_debug_views(gpt2_setup):
    """The satellite's introspection clause: /debug/requests entries
    carry forked_from on forks and fork_parent on the shared parent."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2, page_size=8, max_len=96)
    rng = np.random.default_rng(11)
    prompt = _prompt(rng, 24, cfg.vocab_size)
    parent = eng.submit(prompt, max_new_tokens=8)
    fork = eng.fork(parent)
    eng.step()
    dbg = eng.debug_requests()
    entries = dbg["running"] + dbg["queued"]
    by_id = {e["request_id"]: e for e in entries}
    assert by_id[parent.request_id].get("fork_parent") is True
    assert by_id[fork.request_id]["forked_from"] == parent.request_id
    eng.run_until_idle()


def test_fork_through_speculative_engine(gpt2_setup):
    """Forking composes with speculation: the verify commit's window
    scatter writes only PRIVATE pages (shared COW pages stay
    bit-stable), so greedy forks through a speculative engine match the
    plain engine's fork streams byte for byte."""
    cfg, params = gpt2_setup

    def run(eng):
        rng = np.random.default_rng(12)
        prompt = _prompt(rng, 24, cfg.vocab_size)
        parent = eng.submit(prompt, max_new_tokens=6)
        forks = [eng.fork(parent) for _ in range(2)]
        eng.run_until_idle()
        return [r.tokens for r in [parent] + forks]

    plain = run(_engine(cfg, params, num_slots=2, page_size=8, max_len=96))
    spec = run(_engine(cfg, params, num_slots=2, page_size=8, max_len=96,
                       speculative=(gpt2, cfg, params), draft_k=3))
    assert spec == plain


# ---------------------------------------------------------------------------
# real logprobs
# ---------------------------------------------------------------------------


def test_logprobs_match_hand_computed(gpt2_setup):
    """The engine's per-token logprobs equal log_softmax of the family
    forward's raw logits at the emitted token — recomputed here from
    one full-context forward, greedy AND sampled (the logprob is
    temperature-free, so both arms check against the same numbers)."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(13)
    prompt = _prompt(rng, 9, cfg.vocab_size)
    # ONE engine serves both arms concurrently (mixed temperatures are
    # one program), and the reference forward is jitted once — the two
    # full-context calls share a shape, so it compiles once (tier-1
    # budget satellite: was two engines + two eager op-by-op forwards)
    eng = _engine(cfg, params)
    reqs = {temp: eng.submit(prompt, max_new_tokens=6, temperature=temp,
                             key=np.array([3, 1], np.uint32))
            for temp in (0.0, 0.9)}
    eng.run_until_idle()
    ref_forward = jax.jit(lambda ids: gpt2.forward(cfg, params, ids))
    for temp, req in reqs.items():
        assert len(req.logprobs) == len(req.tokens) == 6
        full = np.concatenate([prompt, np.asarray(req.tokens, np.int32)])
        logits = ref_forward(jnp.asarray(full[None, :-1]))
        lsm = jax.nn.log_softmax(np.asarray(logits[0], np.float32), axis=-1)
        want = [float(lsm[len(prompt) - 1 + i, tok])
                for i, tok in enumerate(req.tokens)]
        assert req.logprobs == pytest.approx(want, abs=2e-3), temp
        assert req.cumulative_logprob == pytest.approx(sum(want), abs=1e-2)


def test_speculative_logprobs_match_plain_engine(gpt2_setup):
    """Speculative greedy emits the same tokens AND the same per-token
    logprobs as the plain engine (both are log-softmax of the target's
    raw logits at the committed token)."""
    cfg, params = gpt2_setup
    rng = np.random.default_rng(14)
    prompt = _prompt(rng, 7, cfg.vocab_size)
    # shapes match the disagreeing-draft test's engines (slots 2, page 8,
    # draft_k 4) so every program here deserializes from the module cache
    plain_eng = _engine(cfg, params, num_slots=2, page_size=8)
    plain = plain_eng.submit(prompt, max_new_tokens=6)
    plain_eng.run_until_idle()
    spec_eng = _engine(cfg, params, num_slots=2, page_size=8,
                       speculative=(gpt2, cfg, params), draft_k=4)
    spec = spec_eng.submit(prompt, max_new_tokens=6)
    spec_eng.run_until_idle()
    assert spec.tokens == plain.tokens
    assert spec.logprobs == pytest.approx(plain.logprobs, abs=2e-3)


def test_best_of_rank_uses_cumulative_logprob():
    """The server's best_of ranking (HttpFrontDoor._rank) orders by true
    cumulative logprob — hand-built candidates with known logprobs:
    highest sum wins, ties break to the lower index, a candidate with no
    logprobs ranks last. The documented length heuristic is gone."""
    from accelerate_tpu.server.http import HttpFrontDoor

    def cand(lps, n_tokens=None):
        r = SimpleNamespace(logprobs=list(lps),
                            tokens=[0] * (n_tokens if n_tokens is not None
                                          else len(lps)))
        r.cumulative_logprob = (sum(lps) if lps else None)
        return r

    # candidate 2 has the best (least negative) sum but the SHORTEST
    # completion — the old heuristic would rank it last, logprobs rank
    # it first
    reqs = [cand([-2.0, -2.0, -2.0, -2.0]),      # sum -8, longest
            cand([-1.0, -1.5]),                  # sum -2.5
            cand([-0.5]),                        # sum -0.5, shortest
            cand([])]                            # shed: no logprobs
    params = SimpleNamespace(best_of=4, n=3)
    ranked = HttpFrontDoor._rank(None, params, reqs)
    assert [r.cumulative_logprob for r in ranked] == [-0.5, -2.5, -8.0]
    # ties break to the lower candidate index
    tied = [cand([-1.0]), cand([-0.5, -0.5])]
    ranked = HttpFrontDoor._rank(None, SimpleNamespace(best_of=2, n=1),
                                 tied)
    assert ranked[0] is tied[0]


def test_catch_up_draft_length_survives_interleaved_decode(gpt2_setup):
    """Regression (review finding): a speculative decode step for OTHER
    slots must not clobber a mid-catch-up slot's draft length with the
    target's reused length — the draft rebuilds a prefix hit from zero,
    and a clobbered length shifts every later catch-up write onto wrong
    rows/positions (silent draft-state corruption: outputs stay correct
    because the accept rule reads target logits, but acceptance decays
    to draft-vs-garbage). Pinned white-box: while a slot prefills, its
    draft device length IS its host-tracked draft_done."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2, max_len=96, page_size=8,
                  speculative=(gpt2, cfg, params), draft_k=4)
    rng = np.random.default_rng(15)
    shared = _prompt(rng, 24, cfg.vocab_size)
    r1 = eng.submit(np.concatenate([shared, _prompt(rng, 4, cfg.vocab_size)]),
                    max_new_tokens=4)
    eng.run_until_idle()           # retires -> shared prefix pages cached
    r2 = eng.submit(_prompt(rng, 5, cfg.vocab_size), max_new_tokens=40)
    for _ in range(6):
        eng.step()                 # r2 decoding when the hit arrives
    assert not r2.done
    r3 = eng.submit(np.concatenate([shared, _prompt(rng, 6, cfg.vocab_size)]),
                    max_new_tokens=12)
    slot3 = next(s for s in eng.scheduler.slots if s.request is r3)
    assert slot3.alloc.reused_len > 0  # the scenario needs a prefix HIT
    checked = 0
    while slot3.request is r3 and slot3.prompt_done < r3.prompt_len:
        eng.step()                 # alternates r3 catch-up / r2 decode
        if slot3.request is r3 and slot3.draft_done < slot3.prompt_done:
            assert int(np.asarray(eng._draft_cache.lengths)[slot3.index]) \
                == slot3.draft_done
            checked += 1
    assert checked > 0             # the interleave actually happened
    eng.run_until_idle()
    assert r3.status is RequestStatus.FINISHED
    # self-draft over uncorrupted state accepts everything
    assert eng.metrics_summary()["spec_accept_rate"] == 1.0
