"""Pallas paged-attention decode kernel (ops/paged_attention.py).

Interpret-mode exactness vs the dense-gather reference across the page
geometry the serving engine actually produces — page-boundary lengths,
mid-page lengths, GQA head groups, trash-padded table rows, sliding
windows, reused (stale-content) pages — plus the int8-pool in-kernel
dequantization and the `PagedKV`/`PagedDecodeMeta` plumbing types the
family forwards thread."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.paged_attention import (
    PagedDecodeMeta,
    PagedKV,
    paged_decode_attention,
    paged_decode_reference,
)
from accelerate_tpu.ops.quant import kv_dequantize_rows, kv_quantize_rows


def _setup(seed=0, S=3, P=4, ps=8, Hkv=2, G=3, D=16, num_pages=12,
           quantized=False, dtype=jnp.float32):
    """A pool + table geometry exercising the engine's corner cases:
    slot 0 mid-page length, slot 1 exactly at a page boundary, slot 2
    nearly empty with a trash-padded table row."""
    rng = np.random.default_rng(seed)
    shape = (num_pages + 1, ps, Hkv, D)
    pool_k = jnp.asarray(rng.normal(size=shape), dtype)
    pool_v = jnp.asarray(rng.normal(size=shape), dtype)
    table = np.full((S, P), num_pages, np.int32)  # trash-padded
    fills = ([0, 1, 2], [3, 4], [5])
    for s in range(S):
        f = fills[s % 3][:P]
        table[s, :len(f)] = f
    lengths = jnp.asarray([min(ps + 5, P * ps - 1), min(2 * ps, P * ps),
                           2][:S], jnp.int32)
    q = jnp.asarray(rng.normal(size=(S, 1, Hkv * G, D)), jnp.float32)
    kn = jnp.asarray(rng.normal(size=(S, 1, Hkv, D)), jnp.float32)
    vn = jnp.asarray(rng.normal(size=(S, 1, Hkv, D)), jnp.float32)
    if quantized:
        ck, sk = kv_quantize_rows(pool_k)
        cv, sv = kv_quantize_rows(pool_v)
        pk = PagedKV(ck, sk, compute_dtype=dtype)
        pv = PagedKV(cv, sv, compute_dtype=dtype)
    else:
        pk, pv = PagedKV(pool_k), PagedKV(pool_v)
    meta = PagedDecodeMeta(jnp.asarray(table), lengths, rows=P * ps)
    return q, kn, vn, pk, pv, meta


def _assert_close(out, ref, tol=2e-5):
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < tol, f"max err {err}"


@pytest.mark.parametrize("window", [None, 5, 1000])
def test_kernel_matches_reference_geometry_matrix(window):
    """Mid-page / page-boundary / trash-padded slots, GQA groups, and
    sliding windows (incl. one wider than the cache = plain causal) all
    match the dense reference."""
    q, kn, vn, pk, pv, meta = _setup()
    out, (k_row, v_row) = paged_decode_attention(q, kn, vn, pk, pv, meta,
                                                 window=window)
    ref, (rk, rv) = paged_decode_reference(q, kn, vn, pk, pv, meta,
                                           window=window)
    _assert_close(out, ref)
    # the rows handed back for the engine to scatter are identical too
    # (same cast — the fold and the write must see the same bytes)
    assert jnp.array_equal(k_row, rk) and jnp.array_equal(v_row, rv)


def test_kernel_matches_reference_single_page_and_single_head():
    """Degenerate geometry: one page per slot, MHA (G=1)."""
    q, kn, vn, pk, pv, meta = _setup(S=2, P=1, ps=4, Hkv=3, G=1, D=8,
                                     num_pages=4)
    meta = PagedDecodeMeta(meta.table[:2, :1],
                           jnp.asarray([3, 0], jnp.int32), rows=4)
    out, _ = paged_decode_attention(q, kn, vn, pk, pv, meta)
    ref, _ = paged_decode_reference(q, kn, vn, pk, pv, meta)
    _assert_close(out, ref)


def test_kernel_length_zero_slot_attends_only_new_token():
    """A fresh slot (length 0, all-trash table) attends exactly its own
    new K/V — the output is vn, not trash-page garbage."""
    q, kn, vn, pk, pv, meta = _setup()
    meta = PagedDecodeMeta(meta.table,
                           jnp.zeros_like(meta.lengths), rows=meta.rows)
    out, _ = paged_decode_attention(q, kn, vn, pk, pv, meta)
    S, _, H, D = q.shape
    G = H // vn.shape[2]
    expect = jnp.repeat(vn[:, 0], G, axis=1).reshape(S, 1, H, D)
    _assert_close(out, expect)


def test_kernel_ignores_stale_rows_in_reused_pages():
    """Rows at or past `length` — stale K/V from a previous tenant of
    the page (slot reuse), or allocation slack — never leak into the
    output: poisoning them with huge values changes nothing."""
    q, kn, vn, pk, pv, meta = _setup()
    out0, _ = paged_decode_attention(q, kn, vn, pk, pv, meta)
    ps = pk.data.shape[1]
    poisoned_k, poisoned_v = np.asarray(pk.data).copy(), np.asarray(
        pv.data).copy()
    table, lengths = np.asarray(meta.table), np.asarray(meta.lengths)
    for s in range(table.shape[0]):
        for j, page in enumerate(table[s]):
            for r in range(ps):
                if j * ps + r >= lengths[s]:
                    poisoned_k[page, r] = 900.0
                    poisoned_v[page, r] = -900.0
    out1, _ = paged_decode_attention(
        q, kn, vn, PagedKV(jnp.asarray(poisoned_k)),
        PagedKV(jnp.asarray(poisoned_v)), meta)
    _assert_close(out1, out0, tol=1e-6)


def test_kernel_int8_pool_dequantizes_in_kernel():
    """int8 pool: the kernel's in-VMEM dequantization matches the dense
    reference's gather-then-dequantize bit for bit (same math)."""
    q, kn, vn, pk, pv, meta = _setup(quantized=True)
    assert pk.data.dtype == jnp.int8
    out, (k_row, v_row) = paged_decode_attention(q, kn, vn, pk, pv, meta)
    ref, _ = paged_decode_reference(q, kn, vn, pk, pv, meta)
    _assert_close(out, ref)
    # rows come back in the pool's compute dtype, ready to quantize+append
    assert k_row.dtype == pk.row_dtype


def test_kernel_under_jit_and_vmap_free_batching():
    """The op is jit-compatible with traced tables/lengths (how the
    engine's decode program calls it)."""
    q, kn, vn, pk, pv, meta = _setup()

    @jax.jit
    def run(q, kn, vn, pk, pv, table, lengths):
        m = PagedDecodeMeta(table, lengths, rows=meta.rows)
        return paged_decode_attention(q, kn, vn, pk, pv, m)[0]

    out = run(q, kn, vn, pk, pv, meta.table, meta.lengths)
    ref, _ = paged_decode_reference(q, kn, vn, pk, pv, meta)
    _assert_close(out, ref)


def test_kernel_rejects_multi_token_and_mismatched_heads():
    q, kn, vn, pk, pv, meta = _setup()
    with pytest.raises(ValueError, match="one token per slot"):
        paged_decode_attention(jnp.concatenate([q, q], axis=1), kn, vn,
                               pk, pv, meta)
    with pytest.raises(ValueError, match="not a multiple"):
        paged_decode_attention(q[:, :, :5], kn, vn, pk, pv, meta)


def test_paged_types_are_pytrees_and_meta_add_is_noop():
    """PagedKV/PagedDecodeMeta flatten/unflatten (they ride lax.scan in
    the family forwards), and the dense-path `cache_len + S` convention
    is absorbed as a no-op (length advance is the engine's live-masked
    job)."""
    q, kn, vn, pk, pv, meta = _setup(quantized=True)
    leaves, treedef = jax.tree_util.tree_flatten((pk, pv, meta))
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt[0].quantized and rebuilt[2].rows == meta.rows
    assert (meta + 1) is meta
    assert getattr(pk, "is_paged_kv") and getattr(meta, "is_paged_meta")
    # bf16 pool: scales child is None, flattening still round-trips
    bf = PagedKV(pk.data.astype(jnp.bfloat16))
    leaves, treedef = jax.tree_util.tree_flatten(bf)
    assert not jax.tree_util.tree_unflatten(treedef, leaves).quantized


def test_kv_quantize_roundtrip_error_bound():
    """Per-row symmetric int8: round-trip error bounded by ~scale/2 per
    element (relative to the row's absmax)."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(5, 7, 16)), jnp.float32)
    codes, scales = kv_quantize_rows(x)
    assert codes.dtype == jnp.int8 and scales.shape == (5, 7)
    back = kv_dequantize_rows(codes, scales, jnp.float32)
    absmax = np.abs(np.asarray(x)).max(-1, keepdims=True)
    # bf16 scale storage adds up to 2^-8 relative on top of the 1/254 step
    bound = absmax * (1 / 254 + 2 ** -8) + 1e-6
    assert np.all(np.abs(np.asarray(back - x)) <= bound)


def test_decode_attention_dispatches_paged_vs_dense():
    """models/decode.decode_attention routes a paged cache through the
    kernel and a dense tuple through the classic path, with matching
    numerics on equivalent state."""
    from accelerate_tpu.models.decode import decode_attention

    q, kn, vn, pk, pv, meta = _setup(G=2)
    out_paged, (k_row, v_row, m2) = decode_attention(
        q, kn, vn, (pk, pv, meta), positions=meta.lengths[:, None],
        n_rep=2)
    assert m2 is meta
    ref, _ = paged_decode_reference(q, kn, vn, pk, pv, meta)
    _assert_close(out_paged, ref)
    with pytest.raises(ValueError, match="paged decode path"):
        decode_attention(q, kn, vn, (pk, pv, meta),
                         positions=meta.lengths[:, None],
                         mask=jnp.ones((3, 1), bool))
