"""Shipped harness: bundled test_script runs under the launcher
(the reference's `accelerate test` path, ref commands/test.py)."""

import pytest

from accelerate_tpu.test_utils import (
    execute_subprocess,
    launch_command_for,
    main_test_script_path,
)


def test_test_script_in_process():
    """All rank-level checks pass on the pytest 8-device CPU world."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bundled_test_script", main_test_script_path()
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


@pytest.mark.slow
def test_accelerate_test_two_process_world():
    """`accelerate-tpu launch --num_processes 2` of the bundled script: the
    reference's launch-and-assert pattern (SURVEY.md §4) end to end."""
    cmd = launch_command_for(main_test_script_path(), num_processes=2)
    out = execute_subprocess(cmd)
    assert "ALL CHECKS PASSED" in out


def test_regression_workload_deterministic():
    from accelerate_tpu.test_utils.training import RegressionDataset

    a, b = RegressionDataset(seed=7), RegressionDataset(seed=7)
    assert (a.x == b.x).all() and (a.y == b.y).all()


def test_are_the_same_tensors():
    import jax.numpy as jnp

    from accelerate_tpu.test_utils import are_the_same_tensors

    assert are_the_same_tensors(jnp.ones((3,)))
