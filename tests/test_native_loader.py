"""Native C++ token loader: build, correctness vs fallback, sharding,
determinism, epoch reshuffle, prefetch ordering under threads."""

from __future__ import annotations

import os

import numpy as np
import pytest

from accelerate_tpu import native

NATIVE = native.is_available()


@pytest.fixture
def token_file(tmp_path):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 1000, size=10_000, dtype=np.int32)
    path = str(tmp_path / "corpus.bin")
    native.write_token_file(path, tokens)
    return path, tokens


def _collect(loader):
    return [b["input_ids"] for b in loader]


@pytest.mark.skipif(not NATIVE, reason=f"native build unavailable: {native.build_error()}")
def test_native_builds_and_iterates(token_file):
    path, tokens = token_file
    loader = native.TokenCorpusLoader(path, sample_len=128, batch_size=4, seed=3)
    batches = _collect(loader)
    assert len(batches) == len(loader) == (10_000 // 128) // 4
    for b in batches:
        assert b.shape == (4, 128) and b.dtype == np.int32
    loader.close()


@pytest.mark.skipif(not NATIVE, reason="native build unavailable")
def test_native_covers_each_sample_once(token_file):
    path, tokens = token_file
    n_samples = 10_000 // 128
    loader = native.TokenCorpusLoader(
        path, sample_len=128, batch_size=1, seed=7, drop_last=False
    )
    rows = np.concatenate(_collect(loader))
    # every sample window appears exactly once per epoch
    assert len(rows) == n_samples
    seen = {r.tobytes() for r in rows}
    want = {tokens[i * 128 : (i + 1) * 128].tobytes() for i in range(n_samples)}
    assert seen == want


@pytest.mark.skipif(not NATIVE, reason="native build unavailable")
def test_native_deterministic_and_reshuffles(token_file):
    path, _ = token_file
    a = _collect(native.TokenCorpusLoader(path, 128, 4, seed=11))
    b = _collect(native.TokenCorpusLoader(path, 128, 4, seed=11))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    l2 = native.TokenCorpusLoader(path, 128, 4, seed=11)
    first = _collect(l2)
    second = _collect(l2)  # epoch advanced -> different order
    assert any(
        not np.array_equal(x, y) for x, y in zip(first, second)
    ), "epoch 1 produced the same order as epoch 0"


@pytest.mark.skipif(not NATIVE, reason="native build unavailable")
def test_native_sharding_partitions(token_file):
    path, tokens = token_file
    n_samples = 10_000 // 128
    shards = [
        np.concatenate(_collect(native.TokenCorpusLoader(
            path, 128, 2, seed=5, rank=r, world=2, drop_last=False
        )))
        for r in range(2)
    ]
    # equal batch counts on every rank (SPMD lockstep)
    assert shards[0].shape == shards[1].shape
    union = {r.tobytes() for s in shards for r in s}
    want = {tokens[i * 128 : (i + 1) * 128].tobytes() for i in range(n_samples)}
    assert union == want


@pytest.mark.skipif(not NATIVE, reason="native build unavailable")
def test_native_threads_keep_batch_order(token_file):
    path, _ = token_file
    a = _collect(native.TokenCorpusLoader(path, 64, 4, seed=2, threads=1))
    b = _collect(native.TokenCorpusLoader(path, 64, 4, seed=2, threads=4,
                                          prefetch_depth=8))
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


@pytest.mark.skipif(not NATIVE, reason="native build unavailable")
def test_native_uint16_widens(tmp_path):
    tokens = np.arange(4096, dtype=np.uint16)
    path = str(tmp_path / "u16.bin")
    native.write_token_file(path, tokens)
    loader = native.TokenCorpusLoader(path, 64, 2, dtype=np.uint16,
                                      shuffle=False, seed=0)
    first = next(iter(loader))["input_ids"]
    assert first.dtype == np.int32


def test_fallback_same_coverage(token_file):
    """The pure-Python fallback yields the same shapes/counts and covers the
    same sample set (order may differ — different RNG)."""
    path, tokens = token_file
    fb = native.TokenCorpusLoader(path, 128, 4, seed=3, force_fallback=True,
                                  drop_last=False)
    batches = _collect(fb)
    assert len(batches) == len(fb)
    rows = np.concatenate(batches)
    n_samples = 10_000 // 128
    # wraparound may duplicate a few rows in the final batch; the REAL set
    # of distinct windows must be exactly the corpus windows
    seen = {r.tobytes() for r in rows}
    want = {tokens[i * 128 : (i + 1) * 128].tobytes() for i in range(n_samples)}
    assert seen == want


def test_feeds_accelerator_loader(token_file):
    """TokenCorpusLoader is a sized batch iterable: plugs into prepare()."""
    from accelerate_tpu.accelerator import Accelerator

    path, _ = token_file
    acc = Accelerator()
    src = native.TokenCorpusLoader(path, sample_len=64, batch_size=8, seed=1,
                                   force_fallback=not NATIVE)
    loader = acc.prepare(src)
    batch = next(iter(loader))
    import jax

    assert isinstance(batch["input_ids"], jax.Array)
    assert batch["input_ids"].shape == (8, 64)


def test_invalid_shard_raises(token_file):
    path, _ = token_file
    with pytest.raises(ValueError):
        native.TokenCorpusLoader(path, 128, 8, rank=2, world=2)
    with pytest.raises(ValueError):
        native.TokenCorpusLoader(path, 128, 0)


def test_host_sharded_source_not_resharded(token_file):
    """prepare_data_loader must not stride a source that already sharded
    itself per host (is_host_sharded)."""
    from accelerate_tpu.data import prepare_data_loader

    path, _ = token_file
    src = native.TokenCorpusLoader(path, 128, 4, seed=1, rank=0, world=2,
                                   force_fallback=not NATIVE)
    assert src.is_host_sharded
    loader = prepare_data_loader(
        src, num_processes=2, process_index=0, put_on_device=False
    )
    # all of the source's batches come through — not every other one
    assert len(list(loader)) == len(src)


@pytest.mark.skipif(not NATIVE, reason="native build unavailable")
def test_native_and_fallback_identical_order(token_file):
    """SplitMix64 shuffle is reproduced bit-for-bit by the fallback, so a
    mixed native/fallback fleet computes identical permutations (disjoint
    host shards either way)."""
    path, _ = token_file
    for epoch in range(2):
        a = native.TokenCorpusLoader(path, 128, 4, seed=9, rank=1, world=2)
        b = native.TokenCorpusLoader(path, 128, 4, seed=9, rank=1, world=2,
                                     force_fallback=True)
        a.set_epoch(epoch)
        b.set_epoch(epoch)
        for x, y in zip(_collect(a), _collect(b)):
            np.testing.assert_array_equal(x, y)


def test_drop_last_false_reports_remainder(token_file):
    path, _ = token_file
    # 78 samples of 128 tokens; batch 5 -> final batch holds 3 real rows
    src = native.TokenCorpusLoader(path, 128, 5, seed=1, drop_last=False,
                                   force_fallback=not NATIVE)
    assert src.remainder == 78 - 15 * 5
    assert src.tail_layout == (1, 5, 3)
    src2 = native.TokenCorpusLoader(path, 128, 6, seed=1, drop_last=True,
                                    force_fallback=not NATIVE)
    assert src2.remainder == -1
