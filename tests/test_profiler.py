"""Profiler subsystem + debug-mode collective verification."""

import glob
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.profiler import (
    StepTimer,
    annotate,
    causal_lm_train_flops,
    device_memory_stats,
    peak_flops_per_chip,
    profile,
)


def test_profile_writes_trace(tmp_path):
    with profile(str(tmp_path)):
        with annotate("matmul-region"):
            x = jnp.ones((64, 64))
            jax.block_until_ready(x @ x)
    produced = glob.glob(str(tmp_path / "**" / "*"), recursive=True)
    assert any(os.path.isfile(p) for p in produced), produced


def test_step_timer_throughput():
    timer = StepTimer(tokens_per_step=100, warmup_steps=1)
    for _ in range(5):
        timer.tick()
    assert timer.steps_recorded == 3
    assert timer.steps_per_sec > 0
    assert timer.tokens_per_sec == pytest.approx(timer.steps_per_sec * 100)


def test_step_timer_warmup_excluded():
    timer = StepTimer(warmup_steps=10)
    for _ in range(3):
        timer.tick()
    assert timer.steps_recorded == 0
    assert math.isnan(timer.mean_step_time)


def test_step_timer_host_overhead_metrics():
    timer = StepTimer(warmup_steps=1)
    for _ in range(4):
        with timer.input_stall():
            pass
        with timer.dispatch():
            pass
        timer.tick()
    # warmup excluded: first iteration's readings (seen < warmup) dropped
    assert timer._dispatch_hist.count == 3
    assert timer._stall_hist.count == 3
    assert timer.host_dispatch_us >= 0
    assert timer.input_stall_us >= 0
    summary = timer.summary()
    assert "host_dispatch_us_mean" in summary
    assert "input_stall_us_mean" in summary


def test_step_timer_host_overhead_empty_is_nan():
    timer = StepTimer()
    assert math.isnan(timer.host_dispatch_us)
    assert math.isnan(timer.input_stall_us)
    assert "host_dispatch_us_mean" not in timer.summary()


def test_mfu_math():
    timer = StepTimer(flops_per_step=1e12, peak_flops=1e13, num_chips=1,
                      warmup_steps=0)
    timer._step_hist.record(0.5)  # 2e12 FLOPs/s achieved vs 1e13 peak
    assert timer.mfu() == pytest.approx(0.2)


def test_causal_lm_flops():
    base = causal_lm_train_flops(1_000_000, 512, attention=False)
    assert base == pytest.approx(6.0 * 1_000_000 * 512)
    with_attn = causal_lm_train_flops(
        1_000_000, 512, num_layers=4, hidden_size=64, seq_len=128
    )
    assert with_attn > base


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert isinstance(stats, dict)  # CPU backend may legitimately be empty


def test_peak_flops_lookup_unknown_is_zero():
    assert peak_flops_per_chip(jax.devices()[0]) >= 0.0


def test_debug_mode_verifies_collectives(monkeypatch):
    """ACCELERATE_TPU_DEBUG=1 pre-verifies operand skeletons; single-host
    worlds trivially agree, so this asserts the checked path stays silent."""
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import operations as ops

    PartialState._reset_state()
    monkeypatch.setenv("ACCELERATE_TPU_DEBUG", "1")
    state = PartialState()
    assert state.debug
    out = ops.gather(jnp.ones((2,)))
    np.testing.assert_allclose(np.asarray(out), 1.0)
    total = ops.reduce(jnp.asarray(3.0), "sum")
    assert float(np.asarray(total)) == 3.0


def _debug_mismatch_worker():
    import jax.numpy as jnp
    import pytest

    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import operations as ops

    state = PartialState()
    # rank-dependent shape => debug mode must raise on every rank
    bad = jnp.ones((state.process_index + 1,))
    with pytest.raises(ops.DistributedOperationException):
        ops.gather(bad)


@pytest.mark.slow
def test_debug_mode_catches_cross_rank_mismatch():
    from accelerate_tpu.launchers import debug_launcher
    from accelerate_tpu.utils.environment import patch_environment

    with patch_environment(ACCELERATE_TPU_DEBUG="1"):
        debug_launcher(_debug_mismatch_worker, num_processes=2)
