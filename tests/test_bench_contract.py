"""The driver contract bench.py must never break again (round-3 failure:
the TPU tunnel hung at init and the bench produced a stack trace instead of
its one JSON line).

The full end-to-end fallback (subprocess + CPU re-exec) costs minutes of
fresh-interpreter compile, so it is gated behind RUN_SLOW; the cheap
structural pieces run always."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_last_json_line_picks_the_line():
    bench = _load_bench()
    text = "WARNING: noise\n{\"a\": 1}\ntrailer\n{\"metric\": \"x\"}\n"
    assert bench._last_json_line(text) == '{"metric": "x"}'
    assert bench._last_json_line("no json at all") is None


def test_bench_child_env_contract():
    """The parent must spawn children with BENCH_CHILD=1 and never
    initialize JAX itself (jax must not be imported at module scope)."""
    src = open(os.path.join(ROOT, "bench.py")).read()
    assert "BENCH_CHILD" in src
    head = src.split("def run_bench")[0]
    assert "import jax" not in head, "parent-scope jax import would hang on a dead tunnel"


@pytest.mark.slow
def test_bench_emits_one_json_line_when_tpu_hangs():
    """End-to-end: with an effectively-zero TPU budget the bench must still
    print one parseable JSON line carrying an error field, rc=0 — and a
    degraded (CPU-fallback) run must NOT report a headline number in the
    real metric's unit: value/vs_baseline are null, the smoke reading
    lives under extra.cpu_smoke_tokens_per_sec."""
    # pytest's conftest exports JAX_PLATFORMS=cpu, which bench.py treats
    # as a deliberate operator pin (-> "skipped"); clear it so this test
    # exercises the hang->error path the driver would hit. The serving
    # phase rows are exercised by the stubbed tests below — skipping them
    # here keeps this end-to-end run inside its timeout.
    env = {**os.environ, "BENCH_TPU_TIMEOUT": "3", "JAX_PLATFORMS": "",
           "BENCH_SERVING": "0"}
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    payload = json.loads(lines[0])
    assert payload["metric"] == "llama_train_tokens_per_sec_per_chip"
    assert "error" in payload
    assert payload["value"] is None
    assert payload["vs_baseline"] is None
    if "extra" in payload:  # absent only on the hand-built last-resort line
        assert payload["extra"]["cpu_smoke_tokens_per_sec"] > 0


def test_serve_bench_smoke_emits_serving_metrics():
    """Tier-1-safe invocation of the offered-load serving harness: a
    miniature load in-process (no fresh-interpreter compile) must produce
    the serving JSON contract fields with a flat compile count."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(ROOT, "benchmarks", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    engine, cfg = sb.build_tiny_engine(
        "gpt2", num_slots=2, max_len=32, prefill_chunk=8)
    summary = sb.run_offered_load(
        engine, cfg.vocab_size, num_requests=4, rate_hz=500.0,
        prompt_len=(2, 6), max_new_tokens=(2, 4))
    assert summary["requests_finished"] == 4
    assert summary["tokens_per_sec"] > 0
    assert summary["ttft_p50_ms"] > 0
    assert summary["per_token_p50_ms"] > 0
    assert summary["compiles_decode"] == 1
    # the ISSUE 11 acceptance smoke: decode MFU / MXU-idle / goodput
    # non-null on CPU (nominal peaks — labeled, but the pipeline flows),
    # with the compile count still flat (sampling is host-side)
    for key in ("decode_mfu", "decode_mxu_idle_fraction", "goodput",
                "decode_device_time_mean_ms"):
        assert key in summary and summary[key] == summary[key], key
    assert 0.0 < summary["goodput"] <= 1.0


def test_bench_serving_row_shape():
    """bench.py's serving row reports the offered-load fields and can
    never poison the one-line contract (errors fold into the row)."""
    bench = _load_bench()
    row = bench._serving_row()
    assert row["requests_finished"] == 12
    for field in ("tokens_per_sec", "ttft_p50_ms", "ttft_p99_ms",
                  "per_token_p50_ms", "per_token_p99_ms"):
        assert row[field] > 0, row
    # roofline/goodput fields (ISSUE 11) ride the same row
    for field in ("decode_mfu", "decode_mxu_idle_fraction", "goodput"):
        assert field in row and row[field] == row[field], (field, row)


def test_bench_serving_prefix_row_shape():
    """The shared-prefix row (ISSUE 5): hit rate and cached-token
    fraction next to the latency percentiles — a reuse regression shows
    up as prefix_hit_rate 0 in the bench line. Tiny parameters keep this
    tier-1-safe."""
    bench = _load_bench()
    row = bench._serving_prefix_row(num_requests=6, prefix_pool=2,
                                    prefix_len=16, page_size=8)
    assert row["requests_finished"] == 6
    assert row["prefix_hit_rate"] > 0
    assert row["cached_token_fraction"] > 0
    assert row["prefill_chunks"] > 0
    assert row["tokens_per_sec"] > 0


def test_operator_cpu_pin_skips_tpu_attempt(monkeypatch, capsys):
    """ADVICE r4: an operator who exported JAX_PLATFORMS=cpu must not pay
    the TPU hang budget. Behavioral: run main() with subprocess stubbed —
    every spawned child (the train fallback AND the per-phase serving
    children) must be pinned to CPU; the train child is marked skipped
    (not error: a deliberate pin is not an outage)."""
    bench = _load_bench()
    calls = []

    class FakeOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": None, "vs_baseline": None, "skipped": "pin"}) + "\n"

    def fake_run(cmd, env=None, **kw):
        calls.append(env)
        return FakeOut()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("BENCH_CHILD", raising=False)
    bench.main()
    train = [e for e in calls if e.get("BENCH_PHASE") == "train"]
    phases = [e.get("BENCH_PHASE") for e in calls
              if e.get("BENCH_PHASE") != "train"]
    assert len(train) == 1, "TPU child must not be spawned under a cpu pin"
    assert train[0]["BENCH_TPU_SKIPPED"] == "1"
    assert phases == ["serving", "serving_prefix", "server", "pod",
                      "pod_dist", "serving_spec", "serving_host_tier"]
    assert all(e["JAX_PLATFORMS"] == "cpu" for e in calls)
    line = json.loads(capsys.readouterr().out.strip())
    assert "skipped" in line and "error" not in line


def test_hung_phase_is_isolated_to_its_row(monkeypatch, capsys):
    """BENCH_r05 regression: a wedged device during an extra-row phase
    must cost that phase only — its row carries "error", the train
    numbers and the one-line contract survive. Stubbed: the train child
    succeeds, every phase child 'hangs' (TimeoutExpired)."""
    bench = _load_bench()

    class FakeOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 123.0, "vs_baseline": 1.0, "unit": "tokens/s/chip",
            "extra": {"mfu": 0.5}}) + "\n"

    def fake_run(cmd, env=None, timeout=None, **kw):
        if env.get("BENCH_PHASE") != "train":
            raise bench.subprocess.TimeoutExpired(cmd, timeout)
        return FakeOut()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_CHILD", raising=False)
    bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] == 123.0          # the headline survived
    assert "error" not in line             # ... unpoisoned
    assert "hung" in line["extra"]["serving"]["error"]
    assert "hung" in line["extra"]["serving_prefix"]["error"]
    assert "hung" in line["extra"]["server"]["error"]


def test_tunnel_drop_after_train_is_reported_not_cpu_numbers(monkeypatch,
                                                             capsys):
    """A phase child on the TPU-success path that finds no TPU (tunnel
    dropped after the train child) must exit 3 and the parent report it in
    the row's error — never silently attach CPU serving numbers under a
    TPU headline. Stubbed: the train child succeeds, phase children exit
    3."""
    bench = _load_bench()

    class TrainOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 123.0, "vs_baseline": 1.0, "unit": "tokens/s/chip",
            "extra": {"mfu": 0.5}}) + "\n"

    class NoTpuOut:
        returncode = 3
        stderr = ""
        stdout = ""

    def fake_run(cmd, env=None, timeout=None, **kw):
        return TrainOut() if env.get("BENCH_PHASE") == "train" else NoTpuOut()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_CHILD", raising=False)
    bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] == 123.0
    for row in ("serving", "serving_prefix", "server", "pod",
                "pod_dist", "serving_spec", "serving_host_tier"):
        assert "no tpu visible" in line["extra"][row]["error"]


def test_transient_tpu_failure_is_retried_with_backoff(monkeypatch, capsys):
    """ISSUE 7 satellite: a flapping tunnel (down since r03) must not
    cost the TPU row on the first transient drop — failed train attempts
    retry with backoff, and a later success emits the real headline."""
    bench = _load_bench()
    attempts = []
    sleeps = []

    class GoodOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 321.0, "vs_baseline": 1.2, "unit": "tokens/s/chip",
            "extra": {"mfu": 0.5}}) + "\n"

    class FlapOut:
        returncode = 3  # "no tpu visible" — the flap signature
        stderr = ""
        stdout = ""

    def fake_run(cmd, env=None, timeout=None, **kw):
        if env.get("BENCH_PHASE") != "train":
            return GoodOut()  # phase rows: irrelevant here
        attempts.append(1)
        return FlapOut() if len(attempts) < 3 else GoodOut()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
    monkeypatch.setattr(bench, "_TPU_RETRIES", 2)
    monkeypatch.setattr(bench, "_TPU_RETRY_BACKOFF_S", 5.0)
    monkeypatch.setenv("BENCH_SERVING", "0")
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_CHILD", raising=False)
    bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert len(attempts) == 3, "two flaps then success"
    assert sleeps == [5.0, 10.0], "exponential backoff between attempts"
    assert line["value"] == 321.0 and "error" not in line


def test_exhausted_retries_fall_back_to_cpu_with_attempt_count(monkeypatch,
                                                               capsys):
    bench = _load_bench()

    class FlapOut:
        returncode = 3
        stderr = ""
        stdout = ""

    class CpuOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": None, "vs_baseline": None, "unit": "tokens/s/chip",
            "error": "placeholder",
            "extra": {"cpu_smoke_tokens_per_sec": 1.0}}) + "\n"

    def fake_run(cmd, env=None, timeout=None, **kw):
        return CpuOut() if env.get("JAX_PLATFORMS") == "cpu" else FlapOut()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench, "_TPU_RETRIES", 1)
    monkeypatch.setenv("BENCH_SERVING", "0")
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_CHILD", raising=False)
    bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] is None


def test_tunnel_probe_retries_before_declaring_down(monkeypatch, capsys):
    """The probe itself retries a flap instead of failing on the spot,
    and still emits one parseable JSON line when truly down."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tunnel_probe", os.path.join(ROOT, "benchmarks", "tunnel_probe.py"))
    tp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tp)
    calls = []

    def flaky_probe(state_dir=None):
        calls.append(1)
        if len(calls) < 2:
            raise ConnectionError("tunnel flapped")
        return {"metric": "host_device_link", "value": 100.0,
                "unit": "MB/s@256MB", "extra": {}}

    monkeypatch.setattr(tp, "_probe", flaky_probe)
    monkeypatch.setattr(tp.time, "sleep", lambda s: None)
    tp.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] == 100.0 and line["extra"]["attempts"] == 2

    calls.clear()

    def dead_probe(state_dir=None):
        calls.append(1)
        raise ConnectionError("gone")

    monkeypatch.setattr(tp, "_probe", dead_probe)
    monkeypatch.setenv("TUNNEL_PROBE_RETRIES", "2")
    tp.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] is None and "3 attempts" in line["error"]
    assert len(calls) == 3


def test_serve_dry_run_smoke_in_process():
    """ISSUE 7 satellite (the PR 4 __main__-guard lesson): the CLI
    entrypoint `accelerate-tpu serve --dry-run` must build the full
    config in-process, print one JSON line, and exit 0 — so a broken
    entrypoint can never ship silently."""
    from accelerate_tpu.commands.accelerate_cli import main

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["serve", "--dry-run", "--family", "gpt2",
                   "--tenants", "gold:priority=0,weight=4,slo=0.25;"
                   "bronze:priority=1"])
    assert rc == 0
    payload = json.loads(buf.getvalue().strip())
    assert payload["dry_run"] is True
    assert "/v1/completions" in payload["routes"]
    assert "gold" in payload["tenants"]
    # a bad tenant spec must fail loudly, not serve a typo
    assert main(["serve", "--dry-run", "--tenants", "x:weight=0"]) == 2
    assert main(["serve", "--dry-run", "--tenants", "x:bogus=1"]) == 2


def test_bench_server_row_shape():
    """bench.py's extra.server row: the two-tenant HTTP phase reports
    per-tier Prometheus-sourced numbers and the flat compile count."""
    bench = _load_bench()
    row = bench._server_row(num_requests=6)
    assert row["compiles_decode"] == 1.0
    assert row["tenants.gold.sent"] == 3
    assert row["tenants.bronze.sent"] == 3
    assert "tenants.gold.slo_attainment" in row
    assert row["tokens_per_sec"] > 0


def test_schema_v2_row_normalizer():
    """ISSUE 8 satellite: every row carries non-null metric/unit plus
    exactly one non-null of value/error/skipped — including rows that
    arrive with none (the r03-r05 blind spot) or several."""
    bench = _load_bench()
    row = bench._normalize_row({}, "m", "u")
    assert row["metric"] == "m" and row["unit"] == "u"
    assert row["error"]  # nothing produced parses as failure
    row = bench._normalize_row({"metric": None, "unit": None,
                                "value": 1.0}, "m", "u")
    assert row["metric"] == "m" and row["unit"] == "u"
    assert row["value"] == 1.0 and row.get("error") is None
    # error wins over a suspect value
    row = bench._normalize_row({"value": 2.0, "error": "boom"}, "m", "u")
    assert row["error"] == "boom" and row["value"] is None
    # a skipped (operator pin) row stays skipped, not error
    row = bench._normalize_row({"skipped": "pin", "value": None}, "m", "u")
    assert row["skipped"] == "pin" and "error" not in row


def _assert_schema_v2(line: dict):
    assert line["schema_version"] == 2
    rows = [line] + [line["extra"][k]
                     for k in ("serving", "serving_prefix", "server", "pod",
                               "pod_dist", "serving_spec", "serving_host_tier")
                     if k in line.get("extra", {})]
    for row in rows:
        assert row.get("metric"), row
        assert row.get("unit"), row
        populated = [k for k in ("value", "error", "skipped")
                     if row.get(k) is not None]
        assert len(populated) == 1, (populated, row)


def test_emitted_line_meets_schema_v2(monkeypatch, capsys):
    """Both the success and the all-phases-hung shapes satisfy the v2
    row contract end to end (stubbed children, real _emit path)."""
    bench = _load_bench()

    class TrainOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 123.0, "vs_baseline": 1.0, "unit": "tokens/s/chip",
            "extra": {"mfu": 0.5}}) + "\n"

    class PhaseOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({"tokens_per_sec": 9.0}) + "\n"

    def fake_run(cmd, env=None, timeout=None, **kw):
        return TrainOut() if env.get("BENCH_PHASE") == "train" \
            else PhaseOut()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_CHILD", raising=False)
    monkeypatch.setenv("BENCH_SERVING", "1")
    bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    _assert_schema_v2(line)
    assert line["extra"]["serving"]["value"]["tokens_per_sec"] == 9.0
    assert line["extra"]["serving"]["metric"] == "serving_offered_load"

    def hung_run(cmd, env=None, timeout=None, **kw):
        if env.get("BENCH_PHASE") != "train":
            raise bench.subprocess.TimeoutExpired(cmd, timeout)
        return TrainOut()

    monkeypatch.setattr(bench.subprocess, "run", hung_run)
    bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    _assert_schema_v2(line)
    assert "hung" in line["extra"]["server"]["error"]
    assert "hung" in line["extra"]["pod"]["error"]


def test_debug_requests_and_incident_bundle_in_process(tmp_path):
    """ISSUE 8 satellite: in-process smoke through the REAL stack — hit
    /debug/requests on the live HTTP door, then force a watchdog stall
    whose incident bundle (with the engine's dumps) lands in a tmpdir
    and renders through the incident CLI."""
    import asyncio
    import importlib.util

    from accelerate_tpu.commands.accelerate_cli import main as cli_main
    from accelerate_tpu.server.config import ServerConfig
    from accelerate_tpu.server.http import HttpFrontDoor
    from accelerate_tpu.server.service import InferenceService
    from accelerate_tpu.server.tokenizer import get_tokenizer
    from accelerate_tpu.telemetry.watchdog import StallWatchdog

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(ROOT, "benchmarks", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    engine, cfg = sb.build_tiny_engine("gpt2", num_slots=2, max_len=32,
                                       prefill_chunk=8)
    service = InferenceService(
        engine, get_tokenizer("auto", cfg.vocab_size),
        ServerConfig(port=0, debug_endpoints=True))
    door = HttpFrontDoor(service)

    async def scenario():
        await door.start()
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", door.port)
            writer.write(b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
                         b"Content-Length: 47\r\n\r\n"
                         b'{"prompt": [1,2,3], "max_tokens": 2, "n": 1 }  ')
            await writer.drain()
            resp = await reader.read()
            writer.close()
            assert b" 200 " in resp.split(b"\r\n", 1)[0], resp[:200]
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", door.port)
            writer.write(b"GET /debug/requests HTTP/1.1\r\nHost: t\r\n"
                         b"Content-Length: 0\r\n\r\n")
            await writer.drain()
            resp = await reader.read()
            writer.close()
            head, _, body = resp.partition(b"\r\n\r\n")
            assert b" 200 " in head
            dbg = json.loads(body)
            assert dbg["queued"] == [] and dbg["running"] == []
            assert dbg["service"]["healthy"] is True
        finally:
            await door.stop()

    asyncio.run(asyncio.wait_for(scenario(), 120))

    # force a stall: fake clock, bundle into the tmpdir
    now = [0.0]
    wd = StallWatchdog(5.0, clock=lambda: now[0],
                       incident_dir=str(tmp_path),
                       registry=engine.registry,
                       dumps=engine.incident_dumps)
    now[0] = 9.0
    report = wd.check()
    assert report is not None and "bundle_path" in report
    bundle = report["bundle_path"]
    names = set(os.listdir(bundle))
    assert {"manifest.json", "report.json", "stacks.txt", "trace.json",
            "metrics.json", "scheduler.json"} <= names
    assert cli_main(["incident", "show", os.path.basename(bundle),
                     "--dir", str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# pod phase (ISSUE 9)
# ---------------------------------------------------------------------------


def _load_serve_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(ROOT, "benchmarks", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    return sb


def test_serve_bench_pod_roles_parse():
    sb = _load_serve_bench()
    assert sb.parse_pod_roles("prefill=2,decode=3") == (2, 3)
    assert sb.parse_pod_roles("decode=1,prefill=1") == (1, 1)
    with pytest.raises(ValueError, match="BOTH roles"):
        sb.parse_pod_roles("prefill=2")
    with pytest.raises(ValueError, match="bad --pod-roles"):
        sb.parse_pod_roles("prefill=2,decode=x")
    with pytest.raises(ValueError, match="twice"):
        sb.parse_pod_roles("prefill=1,decode=2,decode=8")


def test_serve_bench_pod_mode_smoke():
    """The offered-load harness drives a disaggregated pod through the
    same submit/step surface: miniature in-process load, shipment
    counters populated, per-role compile counts flat."""
    sb = _load_serve_bench()
    engine, cfg = sb.build_tiny_pod_engine(
        "gpt2", pod_roles=(1, 1), num_slots=2, max_len=32, prefill_chunk=8)
    summary = sb.run_offered_load(
        engine, cfg.vocab_size, num_requests=4, rate_hz=500.0,
        prompt_len=(2, 6), max_new_tokens=(2, 4))
    assert summary["requests_finished"] == 4
    assert summary["tokens_per_sec"] > 0
    assert summary["pod_shipments"] > 0
    assert summary["pod_pages_shipped"] > 0
    assert summary["compiles_decode"] == 1
    assert summary["compiles_install"] == 1


def test_bench_pod_row_shape():
    """bench.py's failure-isolated extra.pod phase row: shipment
    counters and per-role compiles next to the latency percentiles."""
    bench = _load_bench()
    row = bench._pod_row(num_requests=5)
    assert row["requests_finished"] == 5
    assert row["pod_shipments"] > 0
    assert row["pod_pages_shipped"] >= row["pod_shipments"]
    assert row["compiles_decode"] == 1
    assert row["compiles_install"] == 1
    assert row["tokens_per_sec"] > 0


def test_bench_serving_row_names_kernel_and_kv_dtype():
    """ISSUE 10: the extra.serving row carries which decode attention op
    and KV dtype produced the numbers (plus the kv-bytes/capacity pair),
    so BENCH_r* lines are comparable across configs."""
    bench = _load_bench()
    row = bench._serving_row()
    assert row["paged_attention"] in ("kernel", "dense")
    assert row["kv_dtype"] in ("int8", "bfloat16", "float32")
    assert row["pages_capacity"] > 0
    assert "kv_bytes_in_use" in row


def test_serve_bench_kv_dtype_and_paged_attention_flags():
    """The --kv-dtype/--no-paged-attention A/B axes reach the engine:
    int8 halves kv_bytes_in_use per page (same page count on the same
    seeded load) and the summary reports the capacity fields."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(ROOT, "benchmarks", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    out = {}
    for kvd in (None, "int8"):
        engine, cfg = sb.build_tiny_engine(
            "gpt2", num_slots=2, max_len=32, prefill_chunk=8,
            kv_dtype=kvd, paged_attention=False)
        assert engine._use_paged_kernel is False
        summary = sb.run_offered_load(
            engine, cfg.vocab_size, num_requests=3, rate_hz=500.0,
            prompt_len=(2, 6), max_new_tokens=(2, 3))
        assert summary["requests_finished"] == 3
        assert summary["pages_capacity"] == engine.cache.num_pages
        out[kvd] = engine.cache.page_nbytes
    # code bytes halve; the per-row scales add the documented 2/D
    ratio = out["int8"] / out[None]
    assert 0.5 < ratio <= 0.6, out


def test_serve_bench_speculative_flag_smoke():
    """The --speculative/--draft-k A/B axis reaches the engine
    (ISSUE 12): the self-draft run reports the speculation summary keys
    — accept rate 1.0 (identical draft), tokens_per_decode_step above
    the acceptance bar (> 1.5 at k=3), five flat compile counts."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(ROOT, "benchmarks", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    engine, cfg = sb.build_tiny_engine(
        "gpt2", num_slots=2, max_len=32, prefill_chunk=8,
        speculative=True, draft_k=3)
    summary = sb.run_offered_load(
        engine, cfg.vocab_size, num_requests=4, rate_hz=500.0,
        prompt_len=(2, 6), max_new_tokens=(4, 6))
    assert summary["requests_finished"] == 4
    assert summary["spec_accept_rate"] == 1.0
    assert summary["tokens_per_decode_step"] > 1.5
    assert summary["spec_drafted_tokens"] == summary["spec_accepted_tokens"]
    for prog in ("admit", "prefill", "draft_prefill", "draft", "verify"):
        assert summary[f"compiles_{prog}"] == 1, prog
    # the decode-role roofline keys read the VERIFY program
    assert "decode_mxu_idle_fraction" in summary


# ---------------------------------------------------------------------------
# device-cost attribution & the bench regression gate (ISSUE 11)
# ---------------------------------------------------------------------------


def _write_row(tmp_path, name: str, row: dict) -> str:
    path = os.path.join(str(tmp_path), name)
    with open(path, "w") as f:
        json.dump(row, f)
    return path


def test_bench_diff_exit_codes(tmp_path):
    """The regression gate's three verdicts, driven on a REAL bench row
    (BENCH_r02.json, the r02 TPU capture): identical rows pass (0), a
    synthetically degraded copy exits 1, a contract-violating row exits
    2."""
    from accelerate_tpu.commands.bench_diff import load_row, main

    real = os.path.join(ROOT, "BENCH_r02.json")
    assert main([real, real]) == 0

    row = load_row(real)
    bad = json.loads(json.dumps(row))
    bad["value"] = row["value"] * 0.8           # tokens/s fell 20%
    bad["extra"]["mfu"] = row["extra"]["mfu"] * 0.7
    degraded = _write_row(tmp_path, "degraded.json", bad)
    assert main([real, degraded]) == 1
    # generous tolerance waves the same drop through
    assert main([real, degraded, "--tolerance", "0.5"]) == 0
    # per-metric override: only the mfu drop is out of tolerance
    assert main([real, degraded, "--tolerance", "0.5",
                 "--metric-tolerance", "mfu=0.1"]) == 1

    # vs_baseline is a compared top-level metric, not a dead table entry
    vb = json.loads(json.dumps(row))
    vb["vs_baseline"] = row["vs_baseline"] * 0.5
    assert main([real, _write_row(tmp_path, "vb.json", vb)]) == 1

    malformed = _write_row(tmp_path, "malformed.json", {"value": 3})
    assert main([real, malformed]) == 2
    assert main([real, os.path.join(str(tmp_path), "missing.json")]) == 2


def test_bench_diff_headline_value_to_error_regresses(tmp_path):
    """Losing the number IS a regression: a baseline with a real value
    against a candidate whose headline carries an error must fail the
    gate (exit 1, 'degraded' in the report) — and a deliberate operator
    skip must NOT."""
    from accelerate_tpu.commands.bench_diff import (
        compare_rows, load_row, main)

    real = os.path.join(ROOT, "BENCH_r02.json")
    err_row = {"schema_version": 2,
               "metric": "llama_train_tokens_per_sec_per_chip",
               "unit": "tokens/s/chip", "value": None,
               "error": "tunnel down", "extra": {}}
    err = _write_row(tmp_path, "err.json", err_row)
    assert main([real, err]) == 1
    report = compare_rows(load_row(real), err_row)
    assert report["degraded"]
    skip_row = dict(err_row, error=None, skipped="operator cpu pin")
    skipped = _write_row(tmp_path, "skip.json", skip_row)
    assert main([real, skipped]) == 0


def test_bench_diff_phase_row_regression(tmp_path):
    """Schema-v2 phase rows compare their value dicts with direction
    awareness: ttft_p99_ms RISING is the regression; tokens_per_sec
    rising is an improvement."""
    from accelerate_tpu.commands.bench_diff import compare_rows

    def line(ttft, tps):
        return {
            "schema_version": 2, "metric": "m", "unit": "u", "value": 1.0,
            "extra": {"serving": {
                "metric": "serving_offered_load", "unit": "summary",
                "value": {"ttft_p99_ms": ttft, "tokens_per_sec": tps,
                          "wall_s": 3.0}}},
        }

    report = compare_rows(line(10.0, 100.0), line(20.0, 150.0))
    keys = {e["key"] for e in report["regressions"]}
    assert keys == {"extra.serving.ttft_p99_ms"}
    assert {e["key"] for e in report["improvements"]} == {
        "extra.serving.tokens_per_sec"}
    # wall_s has no direction: configuration, never compared
    assert not any("wall_s" in e["key"]
                   for e in report["regressions"] + report["improvements"])
    # a phase that went value -> error is a degraded row
    broken = line(10.0, 100.0)
    broken["extra"]["serving"] = {"metric": "serving_offered_load",
                                  "unit": "summary",
                                  "error": "phase hung"}
    report = compare_rows(line(10.0, 100.0), broken)
    assert report["degraded"] == [
        "extra.serving (phase went value -> error)"]


def test_bench_diff_serving_spec_row_compares(tmp_path):
    """ISSUE 12: the extra.serving_spec A/B row runs through bench-diff
    with direction awareness — a drop in the speculative arm's
    tokens_per_decode_step (or accept rate) is a regression; the
    draft_k config scalar and the exactness verdict are never
    compared."""
    from accelerate_tpu.commands.bench_diff import compare_rows

    def line(tps_step, accept):
        return {
            "schema_version": 2, "metric": "m", "unit": "u", "value": 1.0,
            "extra": {"serving_spec": {
                "metric": "serving_speculative_ab", "unit": "summary",
                "value": {"draft_k": 4, "greedy_byte_identical": True,
                          "baseline": {"tokens_per_decode_step": 2.0},
                          "speculative": {"tokens_per_decode_step": tps_step,
                                          "spec_accept_rate": accept}}}},
        }

    report = compare_rows(line(7.5, 1.0), line(1.1, 0.2))
    keys = {e["key"] for e in report["regressions"]}
    assert keys == {
        "extra.serving_spec.speculative.tokens_per_decode_step",
        "extra.serving_spec.speculative.spec_accept_rate"}
    assert not any("draft_k" in e["key"] or "byte_identical" in e["key"]
                   for e in report["regressions"] + report["improvements"])
    assert not compare_rows(line(7.5, 1.0), line(7.5, 1.0))["regressions"]


def test_regression_script_delegates(tmp_path):
    """benchmarks/regression.py is the script form of the same gate:
    same exit codes from a bare checkout."""
    real = os.path.join(ROOT, "BENCH_r02.json")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "regression.py"),
         real, real], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    malformed = _write_row(tmp_path, "bad.json", {"value": 1})
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks", "regression.py"),
         real, malformed], capture_output=True, text=True, timeout=60)
    assert out.returncode == 2, (out.stdout, out.stderr)


def test_debug_profile_gating_and_capture(tmp_path):
    """/debug/profile: 404 for EVERY method when the debug gate is off
    (indistinguishable from unknown paths), a real jax.profiler capture
    when on — the response names the logdir and the trace files exist;
    bad durations answer 400."""
    import asyncio

    from accelerate_tpu.server.config import ServerConfig
    from accelerate_tpu.server.http import HttpFrontDoor
    from accelerate_tpu.server.service import InferenceService
    from accelerate_tpu.server.tokenizer import get_tokenizer

    sb = _load_serve_bench()
    engine, cfg = sb.build_tiny_engine("gpt2", num_slots=2, max_len=32,
                                       prefill_chunk=8)

    async def req(port: int, method: str, target: str) -> tuple[int, bytes]:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(f"{method} {target} HTTP/1.1\r\nHost: t\r\n"
                     "Content-Length: 0\r\n\r\n".encode())
        await writer.drain()
        resp = await reader.read()
        writer.close()
        head, _, body = resp.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), body

    async def gated_off():
        service = InferenceService(
            engine, get_tokenizer("auto", cfg.vocab_size),
            ServerConfig(port=0, debug_endpoints=False))
        door = HttpFrontDoor(service)
        await door.start()
        try:
            for method in ("GET", "POST", "HEAD"):
                status, _ = await req(door.port, method,
                                      "/debug/profile?duration_s=0.01")
                assert status == 404, method
        finally:
            await door.stop()

    asyncio.run(asyncio.wait_for(gated_off(), 60))

    logdir = os.path.join(str(tmp_path), "capture")

    async def gated_on():
        service = InferenceService(
            engine, get_tokenizer("auto", cfg.vocab_size),
            ServerConfig(port=0, debug_endpoints=True))
        door = HttpFrontDoor(service)
        await door.start()
        try:
            status, body = await req(
                door.port, "GET", "/debug/profile?duration_s=bogus")
            assert status == 400
            status, body = await req(
                door.port, "GET", "/debug/profile?duration_s=99")
            assert status == 400
            # HEAD must NOT start a capture (the one side-effecting
            # debug route): 405, never GET-minus-body
            status, _ = await req(door.port, "HEAD",
                                  "/debug/profile?duration_s=30")
            assert status == 405
            status, body = await req(
                door.port, "GET",
                f"/debug/profile?duration_s=0.05&logdir={logdir}")
            assert status == 200, body
            payload = json.loads(body)["profile"]
            assert payload["logdir"] == logdir
        finally:
            await door.stop()

    asyncio.run(asyncio.wait_for(gated_on(), 120))
    produced = [os.path.join(dirpath, f)
                for dirpath, _, files in os.walk(logdir) for f in files]
    assert produced, "profiler capture produced no trace files"


# ---------------------------------------------------------------------------
# resilient training in the bench line (ISSUE 20)
# ---------------------------------------------------------------------------


def test_bench_diff_resilience_directions():
    """The goodput/drain/resume keys the resilient smoke adds to
    extra.goodput must carry direction entries so bench-diff gates them:
    goodput up is better, drain and resume latency down is better."""
    from accelerate_tpu.commands.bench_diff import metric_direction

    assert metric_direction("extra.goodput.goodput") == 1
    assert metric_direction("extra.goodput.resilient") == 1
    assert metric_direction("extra.goodput.checkpoint_drain_p99_s") == -1
    assert metric_direction("extra.goodput.checkpoint_drain_mean_s") == -1
    assert metric_direction("extra.goodput.resume_latency_s") == -1
    # attempt/resume counts are run facts, not compared metrics
    assert metric_direction("extra.goodput.attempts") == 0
    assert metric_direction("extra.goodput.resumes") == 0


def test_bench_diff_flags_goodput_regression():
    from accelerate_tpu.commands.bench_diff import compare_rows

    def line(resilient, drain):
        return {"schema_version": 2, "metric": "m", "unit": "u",
                "value": 1.0,
                "extra": {"goodput": {"resilient": resilient,
                                      "checkpoint_drain_p99_s": drain,
                                      "attempts": 1}}}

    report = compare_rows(line(0.95, 0.05), line(0.60, 0.50))
    keys = {e["key"] for e in report["regressions"]}
    assert "extra.goodput.resilient" in keys
    assert "extra.goodput.checkpoint_drain_p99_s" in keys
    assert not compare_rows(line(0.95, 0.05), line(0.95, 0.05))["regressions"]


def test_bench_resilience_smoke_row(tmp_path, monkeypatch):
    """The in-bench resilient smoke: run_resilient over a toy step must
    produce the extra.goodput keys the trajectory tooling reads, with the
    compile-counter deltas flat."""
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.training import TrainState

    bench = _load_bench()
    monkeypatch.setenv("BENCH_RESUME_DIR", os.path.join(str(tmp_path), "ck"))
    monkeypatch.setenv("BENCH_ATTEMPT", "1")
    acc = Accelerator()
    ts = TrainState.create(apply_fn=None, params={"w": jnp.zeros((8, 8))},
                           tx=optax.sgd(1e-2))

    @jax.jit
    def step(state, batch):
        grads = jax.tree_util.tree_map(jnp.ones_like, state.params)
        return state.apply_gradients(grads), {"loss": jnp.float32(0.0)}

    row = bench._resilience_smoke(acc, step, ts, {"x": 0}, steps=6)
    assert row["attempts"] == 2  # BENCH_ATTEMPT=1 means second try
    assert 0.0 <= row["resilient"] <= 1.0
    assert row["saves"] >= 2 and row["resumes"] == 0
    assert row["train_pin_computations"] == 0
    assert row["train_aot_compiles"] == 0
    assert row["checkpoint_drain_p99_s"] >= 0.0
    assert row["checkpoint_stage_mean_s"] >= 0.0


def test_tpu_retry_attempts_share_resume_dir(monkeypatch, capsys):
    """The parent's flap-retry loop hands every train attempt the SAME
    resume dir plus its attempt index, so a killed attempt's newest
    complete manifest seeds the next one instead of starting over."""
    bench = _load_bench()
    train_envs = []

    class GoodOut:
        returncode = 0
        stderr = ""
        stdout = json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 321.0, "vs_baseline": 1.2, "unit": "tokens/s/chip",
            "extra": {"goodput": {"attempts": 2}}}) + "\n"

    class FlapOut:
        returncode = 3
        stderr = ""
        stdout = ""

    def fake_run(cmd, env=None, timeout=None, **kw):
        if env.get("BENCH_PHASE") == "train":
            train_envs.append(env)
            return FlapOut() if len(train_envs) < 2 else GoodOut()
        return GoodOut()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench, "_TPU_RETRIES", 2)
    monkeypatch.setenv("BENCH_SERVING", "0")
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_CHILD", raising=False)
    bench.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] == 321.0
    assert [e["BENCH_ATTEMPT"] for e in train_envs] == ["0", "1"]
    dirs = {e["BENCH_RESUME_DIR"] for e in train_envs}
    assert len(dirs) == 1 and os.path.isdir(dirs.pop())
    assert line["extra"]["goodput"]["attempts"] == 2


def test_tunnel_probe_resumes_completed_sizes(monkeypatch, capsys,
                                              tmp_path):
    """A probe retry must NOT re-pay transfers that already committed to
    the progress manifest: the second attempt resumes at the first
    unmeasured size and the line reports attempts + resumed_sizes."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tunnel_probe", os.path.join(ROOT, "benchmarks", "tunnel_probe.py"))
    tp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tp)
    manifest = tp._manifest_mod()

    state_dir = str(tmp_path)
    monkeypatch.setenv("TUNNEL_PROBE_STATE_DIR", state_dir)
    monkeypatch.setattr(tp.time, "sleep", lambda s: None)
    measured = []
    flaky = {"armed": True}
    real_probe = tp._probe

    class FakeDev:
        platform = "cpu"

        def __str__(self):
            return "FakeCpuDevice"

    def fake_probe(sd):
        # mimic _probe's manifest protocol without jax: measure each
        # size, committing progress; flap once after two sizes
        committed = manifest.read_manifest(sd) or {}
        rows = dict((committed.get("extra") or {}).get("rows") or {})
        resumed = len(rows)
        for mb in (1, 16, 64, 256):
            key = f"{mb}MB"
            if key in rows:
                continue
            measured.append(key)
            rows[key] = {"seconds": 0.1, "MB_per_s": mb / 0.1}
            manifest.write_manifest(sd, step=len(rows),
                                    extra={"rows": rows})
            if flaky["armed"] and len(rows) == 2:
                flaky["armed"] = False
                raise ConnectionError("tunnel flapped mid-probe")
        return {"metric": "host_device_link",
                "value": rows["256MB"]["MB_per_s"], "unit": "MB/s@256MB",
                "extra": {"sizes": rows, "resumed_sizes": resumed}}

    monkeypatch.setattr(tp, "_probe", fake_probe)
    tp.main()
    line = json.loads(capsys.readouterr().out.strip())
    assert line["value"] == 2560.0
    assert line["extra"]["attempts"] == 2
    assert line["extra"]["resumed_sizes"] == 2  # 1MB+16MB not re-paid
    assert measured == ["1MB", "16MB", "64MB", "256MB"]  # each size once
    assert real_probe is not fake_probe  # the real one still exists
