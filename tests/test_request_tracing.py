"""End-to-end request tracing (ISSUE 8 tentpole a): one trace per
request linking queue-wait -> admit -> prefill chunk(s) -> decode
lifetime under a shared trace id, machine-readable shed reasons on every
terminal path, engine-level live introspection, and the disabled-path
guarantees (compile counts flat, no spans when sampling says no).

Scheduler-side shed-code tests are model-free; the engine section drives
a tiny gpt2 engine on CPU (same shapes as tests/test_server.py so the
in-process jit cache is shared)."""

import numpy as np
import pytest

from accelerate_tpu.serving.scheduler import (
    Request,
    RequestStatus,
    Scheduler,
    TenantSpec,
)
from accelerate_tpu.telemetry import (
    clear_flight_recorder,
    configure_tracing,
    export_chrome_trace,
    flight_recorder,
    trace_events,
)


def _req(n=4, tenant="default", max_new=4, slo=None, **kw):
    return Request(prompt=np.arange(1, n + 1, dtype=np.int32),
                   max_new_tokens=max_new, tenant=tenant,
                   slo_ttft_s=slo, **kw)


@pytest.fixture(autouse=True)
def _tracing_reset():
    configure_tracing(enabled=False, sample_rates={},
                      default_sample_rate=1.0)
    clear_flight_recorder()
    yield
    configure_tracing(enabled=False, sample_rates={},
                      default_sample_rate=1.0)
    clear_flight_recorder()


# ---------------------------------------------------------------------------
# machine-readable shed reasons (model-free)
# ---------------------------------------------------------------------------


class TestShedCodes:
    def test_too_long_and_queue_full(self):
        s = Scheduler(1, 16, max_queue=1)
        long = s.submit(_req(n=20, max_new=20))
        assert long.shed_code == "too_long"
        s.submit(_req())
        bounced = s.submit(_req())
        assert bounced.status is RequestStatus.REJECTED
        assert bounced.shed_code == "queue_full"

    def test_tenant_queue_full(self):
        s = Scheduler(1, 64, max_queue=100,
                      tenants=[TenantSpec("small", max_queue=1)])
        s.submit(_req(tenant="small"))
        r = s.submit(_req(tenant="small"))
        assert r.shed_code == "tenant_queue_full"

    def test_deadline_and_certain_miss(self):
        clock = [0.0]
        s = Scheduler(1, 64, clock=lambda: clock[0],
                      tenants=[TenantSpec("t", ttft_slo_s=0.5)])
        s.note_step_time(0.1)
        dl = s.submit(_req(tenant="t", deadline_s=0.1, slo=100.0))
        miss = s.submit(_req(32, tenant="t"))
        clock[0] = 1.0
        shed = s.shed_expired()
        assert set(shed) == {dl, miss}
        assert dl.shed_code == "deadline"
        assert miss.shed_code == "certain_miss"

    def test_pressure_victim(self):
        clock = [0.0]
        s = Scheduler(1, 64, max_queue=2, clock=lambda: clock[0],
                      tenants=[TenantSpec("t", ttft_slo_s=0.2)])
        s.note_step_time(0.05)
        r1 = s.submit(_req(32, tenant="t", max_new=16))
        r2 = s.submit(_req(32, tenant="t", max_new=16))
        s.submit(_req(2, tenant="t", max_new=2))
        victim = r1 if r1.status is RequestStatus.EXPIRED else r2
        assert victim.shed_code == "pressure_victim"

    def test_displaced_by_tier(self):
        s = Scheduler(1, 64, max_queue=2,
                      tenants=[TenantSpec("gold", priority=0),
                               TenantSpec("bronze", priority=1)])
        s.submit(_req(tenant="bronze"))
        b2 = s.submit(_req(tenant="bronze"))
        s.submit(_req(tenant="gold"))
        assert b2.shed_code == "displaced_by_tier"

    def test_debug_state_shape(self):
        s = Scheduler(2, 64, tenants=[TenantSpec("gold", priority=0,
                                                 weight=4, ttft_slo_s=0.5)])
        s.submit(_req(tenant="gold"))
        s.note_step_time(0.01)
        state = s.debug_state()
        assert state["queue_depth"] == 1
        assert state["step_time_ema_s"] == pytest.approx(0.01)
        gold = state["tenants"]["gold"]
        assert gold["priority"] == 0 and gold["weight"] == 4
        assert gold["queue_depth"] == 1
        assert "drr_deficit" in gold
        assert "gold" in state["tiers"]["0"]
        import json

        json.dumps(state)  # must be JSON-safe as-is


# ---------------------------------------------------------------------------
# engine-level request traces (tiny gpt2, CPU)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gpt2_setup():
    import jax

    from accelerate_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return gpt2, cfg, params


def _make_engine(gpt2_setup, **overrides):
    import jax.numpy as jnp

    from accelerate_tpu.serving import Engine, EngineConfig

    family, cfg, params = gpt2_setup
    defaults = dict(num_slots=2, max_len=64, prefill_chunk=8,
                    cache_dtype=jnp.float32)
    defaults.update(overrides)
    return Engine(family, cfg, params, EngineConfig(**defaults))


class TestEngineRequestTrace:
    def test_full_span_chain_shares_the_trace(self, gpt2_setup):
        """Acceptance: one request yields linked queue-wait -> admit ->
        prefill-chunk(s) -> decode spans under ONE trace id, all
        parented on the request's root span, exported to chrome trace."""
        configure_tracing(enabled=True, annotate=False)
        eng = _make_engine(gpt2_setup)
        r = eng.submit(np.arange(1, 12, dtype=np.int32), max_new_tokens=4)
        assert r.trace_sampled and len(r.trace_id) == 32
        toks = list(eng.stream(r))
        assert len(toks) == 4
        events = trace_events(r.trace_id)
        names = [e["name"] for e in events]
        assert "serving.queue_wait" in names
        assert "serving.admit" in names
        assert names.count("serving.prefill") == 2  # 11 tokens / chunk 8
        assert "serving.decode_lifetime" in names
        assert "serving.request" in names
        root = next(e for e in events if e["name"] == "serving.request")
        assert root["span_id"] == r.span_id
        assert root["attrs"]["status"] == "finished"
        assert root["attrs"]["tokens"] == 4
        children = [e for e in events if e["name"] != "serving.request"]
        assert all(e["trace_id"] == r.trace_id for e in events)
        assert all(e["parent_id"] == r.span_id for e in children)
        doc = export_chrome_trace(trace_id=r.trace_id)
        assert {e["name"] for e in doc["traceEvents"]} == set(names)
        # the shared decode-step spans LINK this request's trace
        decode_steps = [e for e in flight_recorder()
                        if e["name"] == "serving.decode"]
        assert any(r.trace_id in e.get("links", []) for e in decode_steps)

    def test_compile_counts_flat_with_tracing_on(self, gpt2_setup):
        configure_tracing(enabled=True, annotate=False)
        eng = _make_engine(gpt2_setup)
        for n in (3, 11, 7):
            r = eng.submit(np.arange(1, n + 1, dtype=np.int32),
                           max_new_tokens=3, trace_id=None)
            list(eng.stream(r))
        assert eng.compile_stats() == {"admit": 1, "prefill": 1,
                                       "decode": 1}

    def test_cancelled_request_closes_its_span_with_reason(self, gpt2_setup):
        """Satellite: a cancelled request still closes its root span,
        carrying the terminal status."""
        configure_tracing(enabled=True, annotate=False)
        eng = _make_engine(gpt2_setup)
        r = eng.submit(np.arange(1, 10, dtype=np.int32), max_new_tokens=16)
        eng.step()
        assert eng.cancel(r)
        root = next(e for e in trace_events(r.trace_id)
                    if e["name"] == "serving.request")
        assert root["attrs"]["status"] == "cancelled"

    def test_shed_request_closes_its_span_with_shed_code(self, gpt2_setup):
        """Satellite: a deadline-shed queued request's trace closes with
        the machine-readable shed reason."""
        configure_tracing(enabled=True, annotate=False)
        eng = _make_engine(gpt2_setup, num_slots=1)
        blocker = eng.submit(np.arange(1, 10, dtype=np.int32),
                             max_new_tokens=32)
        doomed = eng.submit(np.arange(1, 6, dtype=np.int32),
                            max_new_tokens=4, deadline_s=0.0)
        eng.step()  # shed_expired runs: the queued request's deadline lapsed
        assert doomed.status is RequestStatus.EXPIRED
        root = next(e for e in trace_events(doomed.trace_id)
                    if e["name"] == "serving.request")
        assert root["attrs"]["status"] == "expired"
        assert root["attrs"]["shed_code"] == "deadline"
        assert "reason" in root["attrs"]
        eng.cancel(blocker)

    def test_sampling_zero_records_no_spans_but_keeps_the_id(self,
                                                            gpt2_setup):
        """Satellite: rate 0 -> zero spans, but a supplied trace id (the
        x-request-id the server already returned) is preserved."""
        configure_tracing(enabled=True, annotate=False,
                          default_sample_rate=0.0)
        eng = _make_engine(gpt2_setup)
        r = eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=2,
                       trace_id="ee" * 16)
        list(eng.stream(r))
        assert r.trace_id == "ee" * 16 and not r.trace_sampled
        assert trace_events("ee" * 16) == []

    def test_sampling_zero_still_mints_an_engine_id(self, gpt2_setup):
        """Review regression: the id is minted whenever tracing is ON —
        sampling only gates spans. A rate-0 direct engine caller still
        sees its request id in /debug views and exemplars."""
        configure_tracing(enabled=True, annotate=False,
                          default_sample_rate=0.0)
        eng = _make_engine(gpt2_setup)
        r = eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=2)
        list(eng.stream(r))
        assert r.trace_id is not None and len(r.trace_id) == 32
        assert not r.trace_sampled
        assert trace_events(r.trace_id) == []

    def test_tracing_disabled_requests_carry_no_trace(self, gpt2_setup):
        eng = _make_engine(gpt2_setup)
        r = eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=2)
        list(eng.stream(r))
        assert r.trace_id is None and not r.trace_sampled
        assert flight_recorder() == []

    def test_ttft_exemplar_carries_the_trace_id(self, gpt2_setup):
        configure_tracing(enabled=True, annotate=False)
        eng = _make_engine(gpt2_setup)
        r = eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=2)
        list(eng.stream(r))
        exemplars = eng.metrics.ttft_s.exemplars()
        assert any(label == str(r.trace_id)
                   for _, label, _ in exemplars.values())


class TestEngineIntrospection:
    def test_debug_views_reflect_live_state(self, gpt2_setup):
        eng = _make_engine(gpt2_setup, num_slots=1)
        running = eng.submit(np.arange(1, 10, dtype=np.int32),
                             max_new_tokens=32)
        queued = eng.submit(np.arange(1, 5, dtype=np.int32),
                            max_new_tokens=4)
        eng.step()
        dbg = eng.debug_requests()
        assert [q["request_id"] for q in dbg["queued"]] == [
            queued.request_id]
        assert [q["request_id"] for q in dbg["running"]] == [
            running.request_id]
        assert dbg["running"][0]["tenant"] == "default"
        assert dbg["running"][0]["age_s"] >= 0
        slots = eng.debug_slots()
        assert slots[0]["request_id"] == running.request_id
        assert slots[0]["state"] in ("prefill", "decode")
        assert slots[0]["pages"] > 0
        pages = eng.debug_pages()
        assert pages["pages_in_use"] > 0
        assert pages["page_size"] == eng.engine_config.page_size
        sched = eng.debug_scheduler()
        assert sched["queue_depth"] == 1 and sched["live_slots"] == 1
        import json

        json.dumps({"r": dbg, "s": slots, "p": pages, "c": sched})
        eng.cancel(running)
        eng.cancel(queued)
        eng.run_until_idle()
        dbg = eng.debug_requests()
        assert dbg["queued"] == [] and dbg["running"] == []

    def test_incident_dumps_bundle_everything(self, gpt2_setup):
        eng = _make_engine(gpt2_setup)
        dumps = eng.incident_dumps()
        assert set(dumps) == {"requests", "slots", "pages", "scheduler",
                              "compile_stats", "cost_table"}

    def test_watchdog_stall_writes_engine_bundle(self, gpt2_setup,
                                                 tmp_path):
        """Acceptance: an induced stall on a live engine writes a bundle
        carrying the engine's scheduler/page dumps and metrics."""
        import json
        import os

        from accelerate_tpu.telemetry.watchdog import StallWatchdog

        eng = _make_engine(gpt2_setup)
        r = eng.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=2)
        list(eng.stream(r))
        now = [0.0]
        wd = StallWatchdog(5.0, clock=lambda: now[0],
                           incident_dir=str(tmp_path),
                           registry=eng.registry, dumps=eng.incident_dumps)
        now[0] = 6.0
        report = wd.check()
        path = report["bundle_path"]
        files = set(os.listdir(path))
        assert {"manifest.json", "report.json", "stacks.txt", "trace.json",
                "metrics.json", "metrics.prom", "scheduler.json",
                "pages.json", "requests.json", "slots.json",
                "compile_stats.json"} <= files
        metrics = json.load(open(os.path.join(path, "metrics.json")))
        key = "serving_requests_finished_total"
        assert metrics["counters"][key] == 1.0
        compiles = json.load(
            open(os.path.join(path, "compile_stats.json")))
        assert compiles == {"admit": 1, "prefill": 1, "decode": 1}
