"""Model family tests: shapes, training convergence, sharded execution,
decode-vs-forward consistency."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.models import bert, llama, mixtral
from accelerate_tpu.utils import MeshConfig


def test_llama_forward_shapes():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    ids = jnp.ones((2, 16), jnp.int32)
    logits = llama.forward(cfg, params, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_llama_causal_masking():
    """Changing a future token must not affect earlier logits."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    ids = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    ids2 = ids.at[0, -1].set(99)
    l1 = llama.forward(cfg, params, ids)
    l2 = llama.forward(cfg, params, ids2)
    np.testing.assert_allclose(np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


def test_llama_decode_matches_forward():
    """KV-cache decode must reproduce full-forward logits."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(1))
    ids = jax.random.randint(jax.random.key(2), (2, 10), 0, cfg.vocab_size)
    full = llama.forward(cfg, params, ids)
    caches = llama.init_kv_caches(cfg, 2, 16, dtype=jnp.float32)
    prefix, caches = llama.forward(cfg, params, ids[:, :5], kv_caches=caches)
    np.testing.assert_allclose(np.asarray(prefix), np.asarray(full[:, :5]), atol=2e-2)
    # decode one token at a time — jitted once, positions traced (5 eager
    # op-by-op forwards re-dispatched the whole layer scan per step and
    # were a tier-1 top-30 cost)
    step = jax.jit(lambda tok, pos, c: llama.forward(
        cfg, params, tok, positions=pos, kv_caches=c))
    outs = []
    for t in range(5, 10):
        step_logits, caches = step(ids[:, t : t + 1],
                                   jnp.full((2, 1), t), caches)
        outs.append(step_logits)
    decoded = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full[:, 5:]), atol=2e-2)


def test_llama_generate_greedy_deterministic():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.key(1))
    ids = jnp.ones((1, 4), jnp.int32)
    out1 = llama.generate(cfg, params, ids, max_new_tokens=6)
    out2 = llama.generate(cfg, params, ids, max_new_tokens=6)
    assert out1.shape == (1, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_generate_buckets_share_one_decode_program():
    """ISSUE 16 jit-consolidation: generate() pads its cache length to a
    bucket, so different prompt lengths with the same decode budget reuse
    ONE compiled decode scan (distinct totals used to force a fresh
    lax.scan compile each — a tier-1 top-30 cost across the parity
    suites). Greedy output must be identical to the per-length programs:
    padded cache rows sit at positions the causal mask always hides."""
    # private config value => a decode-program cache this test owns
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(), vocab_size=67)
    params = llama.init_params(cfg, jax.random.key(1))
    rng = np.random.default_rng(3)
    short = jnp.asarray(rng.integers(0, 67, (1, 5)).astype(np.int32))
    long = jnp.asarray(rng.integers(0, 67, (1, 11)).astype(np.int32))
    out_s = llama.generate(cfg, params, short, max_new_tokens=6)
    out_l = llama.generate(cfg, params, long, max_new_tokens=6)
    _, decode_all = llama.generate._programs(cfg, 0.0)
    assert decode_all._cache_size() == 1, decode_all._cache_size()
    # parity with the teacher-forced full forward: generate's greedy path
    # through the bucketed cache argmax-matches the uncached model
    for prompt, out in ((short, out_s), (long, out_l)):
        full = llama.forward(cfg, params, out[:, :-1])
        greedy = jnp.argmax(full[:, prompt.shape[1] - 1 :], axis=-1)
        np.testing.assert_array_equal(
            np.asarray(out[:, prompt.shape[1]:]), np.asarray(greedy))


def test_llama_trains_sharded_tp_fsdp():
    """Flagship path: tiny llama on a 2x4 fsdp x model mesh, loss decreases."""
    cfg = llama.LlamaConfig.tiny()
    acc = Accelerator(mesh_config=MeshConfig(axes={"fsdp": 2, "model": 4}),
                      mixed_precision="bf16")
    params = llama.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params=params, tx=optax.adamw(1e-2)
    ))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)}

    def loss_fn(p, b):
        return llama.causal_lm_loss(cfg, p, b)

    step = acc.train_step(loss_fn, max_grad_norm=1.0)
    losses = []
    for _ in range(10):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5  # memorizing a fixed batch
    # params actually sharded over the mesh
    q = ts.params["layers"]["attn"]["q_proj"]["kernel"]
    assert len(q.sharding.device_set) == 8


def test_llama_remat_matches_no_remat():
    cfg = llama.LlamaConfig.tiny()
    cfg_r = dataclasses.replace(cfg, remat=True)
    params = llama.init_params(cfg, jax.random.key(0))
    ids = jnp.ones((2, 8), jnp.int32)
    g1 = jax.grad(lambda p: llama.causal_lm_loss(cfg, p, {"input_ids": ids}))(params)
    g2 = jax.grad(lambda p: llama.causal_lm_loss(cfg_r, p, {"input_ids": ids}))(params)
    leaves1 = jax.tree_util.tree_leaves(g1)
    leaves2 = jax.tree_util.tree_leaves(g2)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_bert_forward_and_training():
    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32),
        "attention_mask": np.ones((8, 16), np.int32),
        "labels": rng.integers(0, 2, (8,)).astype(np.int32),
    }
    logits = bert.forward(cfg, params, batch["input_ids"], batch["attention_mask"])
    assert logits.shape == (8, 2)
    acc = Accelerator()
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=optax.adam(1e-3)))
    step = acc.train_step(lambda p, b: bert.classification_loss(cfg, p, b))
    ts, m = step(ts, batch)
    first = float(m["loss"])
    for _ in range(15):
        ts, m = step(ts, batch)
    assert float(m["loss"]) < first


def test_bert_padding_mask_ignores_pad_tokens():
    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg, jax.random.key(0))
    ids = np.ones((1, 8), np.int32)
    mask = np.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], np.int32)
    l1 = bert.forward(cfg, params, ids, mask)
    ids2 = ids.copy()
    ids2[0, 5] = 77  # padded position content must not matter
    l2 = bert.forward(cfg, params, ids2, mask)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)


def test_mixtral_forward_and_router():
    cfg = mixtral.MixtralConfig.tiny()
    params = mixtral.init_params(cfg, jax.random.key(0))
    ids = jnp.ones((2, 8), jnp.int32)
    logits, aux = mixtral.forward(cfg, params, ids)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert float(aux) > 0  # load-balance loss is positive


def test_mixtral_trains_expert_parallel():
    cfg = mixtral.MixtralConfig.tiny()
    acc = Accelerator(mesh_config=MeshConfig(axes={"data": 2, "expert": 4}))
    params = mixtral.init_params(cfg, jax.random.key(0))
    ts = acc.prepare(TrainState.create(apply_fn=None, params=params, tx=optax.adam(1e-2)))
    # experts sharded over expert axis (dim 1 of [L, E, in, out])
    g = ts.params["layers"]["moe"]["experts"]["gate_proj"]["kernel"]
    assert g.sharding.spec[1] == "expert"
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)}
    step = acc.train_step(lambda p, b: mixtral.causal_lm_loss(cfg, p, b))
    ts, m = step(ts, batch)
    l0 = float(m["loss"])
    for _ in range(10):
        ts, m = step(ts, batch)
    assert float(m["loss"]) < l0


def test_chunked_causal_lm_loss_matches_full():
    """Chunked projection+xent == full-logits loss, values and gradients."""
    from accelerate_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(max_position_embeddings=64)
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (2, 49)).astype(np.int32)  # S=48
    mask = np.ones((2, 49), np.int32)
    mask[0, 30:] = 0
    batch = {"input_ids": ids, "attention_mask": mask}

    # one jitted value_and_grad per variant: same comparison, but two
    # compiled programs instead of four eager op-by-op walks (~12s -> ~5s)
    def value_and_grad(chunk):
        return jax.jit(jax.value_and_grad(
            lambda p: llama.causal_lm_loss(cfg, p, batch,
                                           loss_chunk_size=chunk)))

    full, g_full = value_and_grad(10_000)(params)
    chunked, g_chunk = value_and_grad(16)(params)
    np.testing.assert_allclose(float(chunked), float(full), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_chunk),
                    jax.tree_util.tree_leaves(g_full)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_sparse_moe_matches_dense_at_full_capacity():
    """With capacity_factor high enough that nothing drops, the sparse
    (GShard capacity) dispatch equals the dense path exactly."""
    from accelerate_tpu.models import mixtral

    base = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=2,
                num_key_value_heads=2, num_local_experts=4,
                num_experts_per_tok=2, max_position_embeddings=32)
    dense_cfg = mixtral.MixtralConfig(**base, moe_impl="dense")
    sparse_cfg = mixtral.MixtralConfig(**base, moe_impl="sparse",
                                       capacity_factor=float(4))  # C = S*k/E*4 >= S
    params = mixtral.init_params(dense_cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (2, 16)).astype(np.int32)
    d_logits, d_aux = mixtral.forward(dense_cfg, params, ids)
    s_logits, s_aux = mixtral.forward(sparse_cfg, params, ids)
    np.testing.assert_allclose(np.asarray(s_logits), np.asarray(d_logits),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(s_aux), float(d_aux), rtol=1e-5)


def test_sparse_moe_drops_over_capacity_gracefully():
    """Tiny capacity: runs, stays finite, and differs from dense (tokens over
    capacity fall through on the residual)."""
    from accelerate_tpu.models import mixtral

    cfg = mixtral.MixtralConfig.tiny(moe_impl="sparse", capacity_factor=0.5)
    params = mixtral.init_params(cfg, jax.random.key(1))
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    logits, aux = mixtral.forward(cfg, params, ids)
    assert np.isfinite(np.asarray(logits)).all()
    assert np.isfinite(float(aux))


def test_sparse_moe_trains():
    from accelerate_tpu.models import mixtral
    import optax

    cfg = mixtral.MixtralConfig.tiny(moe_impl="sparse")
    params = mixtral.init_params(cfg, jax.random.key(2))
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, (4, 17)).astype(np.int32)
    loss_fn = lambda p: mixtral.causal_lm_loss(cfg, p, {"input_ids": ids})
    tx = optax.adam(1e-2)

    # ONE jitted update step (tier-1 runtime: the old op-by-op loop
    # re-traced the sparse-MoE backward five times — the single slowest
    # pre-PR-5 tier-1 test at ~15s; same math, same assertion)
    @jax.jit
    def step(p, o):
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    opt_state = tx.init(params)
    l0 = None
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
        if l0 is None:
            l0 = float(loss)  # loss at the ORIGINAL params (pre-update)
    assert float(loss_fn(params)) < l0


def test_sparse_moe_sort_and_onehot_dispatch_agree(monkeypatch):
    """Both sparse dispatch mechanisms produce identical outputs (same
    assignment priority => same drops), so the size-based auto-selection
    never changes results."""
    from accelerate_tpu.models import mixtral

    cfg = mixtral.MixtralConfig.tiny(moe_impl="sparse", capacity_factor=1.0)
    params = mixtral.init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    out_onehot, aux1 = mixtral.forward(cfg, params, ids)
    monkeypatch.setattr(mixtral, "_ONEHOT_DISPATCH_MAX_ELEMENTS", 0)
    out_sort, aux2 = mixtral.forward(cfg, params, ids)
    np.testing.assert_allclose(
        np.asarray(out_onehot), np.asarray(out_sort), atol=1e-4)
    np.testing.assert_allclose(float(aux1), float(aux2), rtol=1e-6)


def test_sparse_moe_sort_path_matches_dense_at_full_capacity(monkeypatch):
    """Sort dispatch == dense combine when capacity covers all assignments."""
    from accelerate_tpu.models import mixtral

    monkeypatch.setattr(mixtral, "_ONEHOT_DISPATCH_MAX_ELEMENTS", 0)
    dense_cfg = mixtral.MixtralConfig.tiny(moe_impl="dense")
    sparse_cfg = mixtral.MixtralConfig.tiny(
        moe_impl="sparse", capacity_factor=float(mixtral.MixtralConfig.tiny().num_local_experts))
    params = mixtral.init_params(dense_cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, dense_cfg.vocab_size)
    out_d, _ = mixtral.forward(dense_cfg, params, ids)
    out_s, _ = mixtral.forward(sparse_cfg, params, ids)
    np.testing.assert_allclose(
        np.asarray(out_d), np.asarray(out_s), atol=1e-3)


# --- zoo-wide decode (ref benchmarks/big_model_inference.py families) -------


def _zoo_member(name):
    from accelerate_tpu.models import gpt2, gpt_neox, gptj, opt

    mod = {"gpt2": gpt2, "gptj": gptj, "gpt_neox": gpt_neox, "opt": opt}[name]
    cfg_cls = {
        "gpt2": gpt2.GPT2Config, "gptj": gptj.GPTJConfig,
        "gpt_neox": gpt_neox.GPTNeoXConfig, "opt": opt.OPTConfig,
    }[name]
    return mod, cfg_cls.tiny()


@pytest.mark.parametrize("name", ["gpt2", "gptj", "gpt_neox", "opt"])
def test_zoo_decode_matches_forward(name):
    """Every causal family's KV-cache decode must reproduce its own
    full-forward logits (prefill chunk + per-token steps)."""
    mod, cfg = _zoo_member(name)
    params = mod.init_params(cfg, jax.random.key(3))
    ids = jax.random.randint(jax.random.key(4), (2, 10), 0, cfg.vocab_size)
    full = mod.forward(cfg, params, ids)
    caches = mod.init_kv_caches(cfg, 2, 16, dtype=jnp.float32)
    prefix, caches = mod.forward(cfg, params, ids[:, :5], kv_caches=caches)
    np.testing.assert_allclose(np.asarray(prefix), np.asarray(full[:, :5]),
                               atol=2e-2)
    # jitted once, positions traced (5 eager steps per family re-ran the
    # whole layer scan op-by-op — the same tier-1 top-30 cost the
    # past-max-position test below already paid down)
    step = jax.jit(lambda tok, pos, c: mod.forward(
        cfg, params, tok, positions=pos, kv_caches=c))
    outs = []
    for t in range(5, 10):
        step_logits, caches = step(ids[:, t : t + 1],
                                   jnp.full((2, 1), t), caches)
        outs.append(step_logits)
    decoded = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full[:, 5:]),
                               atol=2e-2)


@pytest.mark.parametrize("name", ["gpt2", "gptj", "gpt_neox", "opt"])
def test_zoo_generate_greedy_deterministic(name):
    mod, cfg = _zoo_member(name)
    params = mod.init_params(cfg, jax.random.key(5))
    ids = jnp.ones((1, 4), jnp.int32)
    out1 = mod.generate(cfg, params, ids, max_new_tokens=6)
    out2 = mod.generate(cfg, params, ids, max_new_tokens=6)
    assert out1.shape == (1, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_t5_decode_matches_forward():
    """Incremental enc-dec decode (self cache + precomputed cross K/V) must
    match the teacher-forced full decoder forward."""
    from accelerate_tpu.models import t5

    cfg = t5.T5Config.tiny()
    params = t5.init_params(cfg, jax.random.key(6))
    rng = np.random.default_rng(7)
    enc_ids = rng.integers(0, cfg.vocab_size, (2, 9)).astype(np.int32)
    dec_ids = np.concatenate(
        [np.zeros((2, 1), np.int32),
         rng.integers(0, cfg.vocab_size, (2, 6)).astype(np.int32)], axis=1)
    full = t5.forward(cfg, params, enc_ids, dec_ids)
    state = t5.init_decode_state(cfg, params, enc_ids, max_new_tokens=7)
    # jitted once, positions traced (7 eager op-by-op decoder passes were
    # a tier-1 top-30 cost)
    step = jax.jit(lambda tok, pos, st: t5.decode_step(
        cfg, params, tok, pos, st))
    outs = []
    for t in range(7):
        logits, state = step(dec_ids[:, t : t + 1], jnp.full((2, 1), t),
                             state)
        outs.append(logits)
    decoded = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full),
                               atol=1e-4)


def test_t5_decode_respects_encoder_padding():
    """Cross-attention in decode must honor the encoder padding mask: row 0's
    padded tail, if attended, would change its logits."""
    from accelerate_tpu.models import t5

    cfg = t5.T5Config.tiny()
    params = t5.init_params(cfg, jax.random.key(8))
    rng = np.random.default_rng(9)
    enc_ids = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    mask = np.ones((2, 8), bool)
    mask[0, 5:] = False
    dec_ids = np.zeros((2, 1), np.int32)
    full = t5.forward(cfg, params, enc_ids, dec_ids, attention_mask=mask)
    state = t5.init_decode_state(cfg, params, enc_ids, max_new_tokens=1,
                                 attention_mask=jnp.asarray(mask))
    logits, _ = t5.decode_step(cfg, params, dec_ids, jnp.zeros((2, 1),
                               jnp.int32), state)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               atol=1e-4)


def test_t5_generate_shapes_and_determinism():
    from accelerate_tpu.models import t5

    cfg = t5.T5Config.tiny()
    params = t5.init_params(cfg, jax.random.key(10))
    enc_ids = jnp.ones((2, 5), jnp.int32)
    out1 = t5.generate(cfg, params, enc_ids, max_new_tokens=4)
    out2 = t5.generate(cfg, params, enc_ids, max_new_tokens=4)
    assert out1.shape == (2, 5)  # start token + 4 generated
    assert np.asarray(out1[:, 0]).tolist() == [0, 0]
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


@pytest.mark.parametrize("name", ["gpt2", "gptj", "gpt_neox", "opt"])
def test_zoo_bf16_generate(name):
    """bf16 checkpoints (the big-model benchmark dtype) must flow through
    forward + decode without dtype drift breaking the layer-scan carry
    (regression: GPT-J's interleaved rope upcast bf16 residuals to f32)."""
    mod, cfg = _zoo_member(name)
    params = mod.init_params(cfg, jax.random.key(7), dtype=jnp.bfloat16)
    ids = jnp.ones((1, 8), jnp.int32)
    out = mod.generate(cfg, params, ids, max_new_tokens=3)
    assert out.shape == (1, 11)


@pytest.mark.parametrize("name", ["gptj", "gpt_neox"])
def test_zoo_decode_past_max_position_embeddings(name):
    """Rotary tables must extend to the cache reach: decoding past
    max_position_embeddings would otherwise gather-clamp every overflow
    position to the last table row (silently wrong logits), and diverge
    from streamed_generate which already sized by cache reach."""
    mod, cfg = _zoo_member(name)
    cfg = dataclasses.replace(cfg, max_position_embeddings=16)
    params = mod.init_params(cfg, jax.random.key(9))
    ids = jax.random.randint(jax.random.key(10), (1, 12), 0, cfg.vocab_size)
    # decode to position 19 (> 16): compare one-token steps vs a reference
    # forward whose config admits the longer table
    long_cfg = dataclasses.replace(cfg, max_position_embeddings=24)
    full = mod.forward(long_cfg, params, jnp.concatenate(
        [ids, ids[:, :8]], axis=1))
    caches = mod.init_kv_caches(cfg, 1, 20, dtype=jnp.float32)
    _, caches = mod.forward(cfg, params, ids, kv_caches=caches)
    # jitted once, positions traced (8 eager steps per family re-ran the
    # whole layer scan op-by-op — a tier-1 top-30 cost x3 families)
    step = jax.jit(lambda tok, pos, c: mod.forward(
        cfg, params, tok, positions=pos, kv_caches=c))
    outs = []
    seq = jnp.concatenate([ids, ids[:, :8]], axis=1)
    for t in range(12, 20):
        lg, caches = step(seq[:, t : t + 1], jnp.full((1, 1), t), caches)
        outs.append(lg)
    decoded = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(decoded),
                               np.asarray(full[:, 12:20]), atol=2e-2)


def test_mixtral_a2a_matches_dense_at_full_capacity():
    """Token-sharded all_to_all dispatch through the full model: at generous
    capacity it must reproduce the dense (exact) forward on the expert
    mesh."""
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import MeshConfig

    PartialState._reset_state()
    PartialState(mesh_config=MeshConfig(axes={"expert": 8}))
    try:
        dense_cfg = mixtral.MixtralConfig.tiny(
            num_local_experts=8, moe_impl="dense")
        a2a_cfg = dataclasses.replace(dense_cfg, moe_impl="a2a",
                                      capacity_factor=8.0)
        params = mixtral.init_params(dense_cfg, jax.random.key(80))
        ids = jax.random.randint(jax.random.key(81), (2, 16), 0,
                                 dense_cfg.vocab_size)
        out_d, _ = mixtral.forward(dense_cfg, params, ids)
        out_a, _ = mixtral.forward(a2a_cfg, params, ids)
        np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_a),
                                   atol=2e-3)
    finally:
        PartialState._reset_state()


def test_mixtral_a2a_trains():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils import MeshConfig

    PartialState._reset_state()
    try:
        acc = Accelerator(mesh_config=MeshConfig(axes={"expert": 8}))
        cfg = mixtral.MixtralConfig.tiny(num_local_experts=8, moe_impl="a2a")
        params = mixtral.init_params(cfg, jax.random.key(82))
        import optax

        state = TrainState.create(apply_fn=None, params=params,
                                  tx=optax.adam(1e-3))
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 17)).astype(np.int32)}
        step = acc.train_step(lambda p, b: mixtral.causal_lm_loss(cfg, p, b))
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"] if isinstance(m, dict) else m))
        assert losses[-1] < losses[0] and all(np.isfinite(losses))
    finally:
        PartialState._reset_state()


def test_causal_lm_loss_masks_attention_not_just_loss():
    """The padding mask must reach ATTENTION, not only the loss weights:
    changing a padded tail token must leave the loss bitwise unchanged
    (VERDICT r2 weak #3 — it previously leaked into real tokens' scores)."""
    cfg = llama.LlamaConfig.tiny(attention_backend="einsum")
    params = llama.init_params(cfg, jax.random.key(90))
    rng = np.random.default_rng(90)
    ids = rng.integers(2, cfg.vocab_size, (2, 24)).astype(np.int32)
    mask = np.ones((2, 24), np.int32)
    mask[0, 16:] = 0  # right-padded row
    l1 = llama.causal_lm_loss(cfg, params, {
        "input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)})
    ids2 = ids.copy()
    ids2[0, 20] = 7  # mutate a PAD token
    l2 = llama.causal_lm_loss(cfg, params, {
        "input_ids": jnp.asarray(ids2), "attention_mask": jnp.asarray(mask)})
    np.testing.assert_allclose(float(l1), float(l2), rtol=0, atol=0)


def test_causal_lm_loss_left_padded_runs_and_masks():
    """Left-padded batches run with correctly-masked attention (documented:
    positions stay sequential, so right padding is the recommended layout
    for pretrained checkpoints)."""
    cfg = llama.LlamaConfig.tiny(attention_backend="einsum")
    params = llama.init_params(cfg, jax.random.key(91))
    rng = np.random.default_rng(91)
    ids = rng.integers(2, cfg.vocab_size, (2, 16)).astype(np.int32)
    mask = np.ones((2, 16), np.int32)
    mask[0, :6] = 0  # left padding
    loss = llama.causal_lm_loss(cfg, params, {
        "input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)})
    assert np.isfinite(float(loss))
    # pad mutations still cannot change the loss
    ids2 = ids.copy()
    ids2[0, 2] = 9
    loss2 = llama.causal_lm_loss(cfg, params, {
        "input_ids": jnp.asarray(ids2), "attention_mask": jnp.asarray(mask)})
    np.testing.assert_allclose(float(loss), float(loss2), rtol=0, atol=0)


@pytest.mark.parametrize("family", ["gpt2", "gpt_neox", "opt", "gptj"])
def test_zoo_masked_loss_runs_and_ignores_pads(family):
    """Regression: gpt2/gpt_neox causal_lm_loss raised NameError on any
    masked batch (shifted_padding_masks never imported; round-4 find).
    Padded rows must also not change the loss of the real tokens."""
    import importlib

    mod = importlib.import_module(f"accelerate_tpu.models.{family}")
    cfg_cls = {
        "gpt2": "GPT2Config", "gpt_neox": "GPTNeoXConfig",
        "opt": "OPTConfig", "gptj": "GPTJConfig",
    }[family]
    cfg = getattr(mod, cfg_cls).tiny()
    params = mod.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(1, cfg.vocab_size, (2, 17)).astype(np.int32)
    mask = np.ones_like(ids)
    mask[:, 12:] = 0  # right padding
    ids_padded = ids.copy()
    ids_padded[:, 12:] = 0
    loss_masked = float(mod.causal_lm_loss(
        cfg, params,
        {"input_ids": jnp.asarray(ids_padded),
         "attention_mask": jnp.asarray(mask)},
    ))
    loss_short = float(mod.causal_lm_loss(
        cfg, params, {"input_ids": jnp.asarray(ids[:, :12])},
    ))
    assert np.isfinite(loss_masked)
    np.testing.assert_allclose(loss_masked, loss_short, rtol=2e-3)
