"""Hierarchical KV cache (ISSUE 16): the host-DRAM overflow tier and
the cache-aware scheduler that rides on it.

The contract under test: an evicted refcount-0 prefix swaps OUT to a
byte-budgeted host mirror instead of dying; a radix hit on the
host-resident tail swaps back IN through the jitted transport pair
before admission — token-exactly, in fp32 and int8 pools both, with
compile counts flat across every hit/miss/swap mix (the transport pair
compiles once each). Backpressure stalls the ADMISSION, never decode; a
budget-full tier falls back to the classic destructive eviction. On the
scheduling side, N concurrent identical prompts cost exactly ONE full
prefill (in-flight dedup), and queued prefix-sharers admit back to
back. The pod router routes shipments to the worker already holding
the prefix.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import gpt2
from accelerate_tpu.serving import Engine, EngineConfig, RequestStatus


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (ISSUE 16 hit this the moment
    # an engine module sorted before test_launched_scripts)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return cfg, params


def _engine(cfg, params, **overrides):
    defaults = dict(num_slots=2, max_len=64, prefill_chunk=8, page_size=4,
                    cache_dtype=jnp.float32, sanitize=True,
                    host_tier_bytes=1 << 28)
    defaults.update(overrides)
    return Engine(gpt2, cfg, params, EngineConfig(**defaults))


def _prompt(rng, n, vocab):
    return rng.integers(0, vocab, (n,)).astype(np.int32)


def _churn_out(eng, cfg, rng, n=33, rounds=2):
    """Fill the pool with fresh prefixes until earlier ones evict."""
    for _ in range(rounds):
        r = eng.submit(_prompt(rng, n, cfg.vocab_size), max_new_tokens=4)
        eng.run_until_idle()
        assert r.status is RequestStatus.FINISHED


# ---------------------------------------------------------------------------
# the tentpole: swap-out / swap-in round trip
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv", [None, "int8"])
def test_swap_round_trip_token_exact(gpt2_setup, kv):
    """Cold-decode a prompt, churn its pages out to the host tier,
    decode it again through the swap-in path: byte-identical tokens,
    and the hit is attributed to the HOST tier, not HBM. int8 pools
    swap codes + scales verbatim, so quantized sharing stays
    bit-stable across the round trip."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_pages=18, kv_dtype=kv,
                  cache_dtype=jnp.float32 if kv is None else jnp.bfloat16)
    rng = np.random.default_rng(0)
    prompt = _prompt(rng, 33, cfg.vocab_size)
    cold = eng.submit(prompt, max_new_tokens=6)
    eng.run_until_idle()
    _churn_out(eng, cfg, rng)
    assert eng.allocator.index.host_pages > 0, "churn must swap out"
    warm = eng.submit(prompt, max_new_tokens=6)
    eng.run_until_idle()
    assert list(warm.tokens) == list(cold.tokens)
    assert eng.metrics.swap_in_pages > 0
    assert eng.metrics.prefix_hits_host >= 1
    assert eng.metrics.swap_out_pages >= eng.metrics.swap_in_pages
    eng.close()


def test_compile_counts_flat_across_swap_mixes(gpt2_setup):
    """Cold miss, HBM hit, host-tier hit, partial-host hit: every mix
    runs the same five programs — admit/prefill/decode plus the
    transport extract/install pair — each compiled exactly once."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_pages=18)
    rng = np.random.default_rng(1)
    shared = _prompt(rng, 28, cfg.vocab_size)

    def run(p):
        r = eng.submit(p, max_new_tokens=3)
        eng.run_until_idle()
        return r

    run(shared)                                        # cold
    run(np.concatenate([shared, _prompt(rng, 5, cfg.vocab_size)]))  # HBM hit
    _churn_out(eng, cfg, rng, rounds=3)
    run(shared)                                        # host hit
    _churn_out(eng, cfg, rng, rounds=3)
    run(np.concatenate([shared, _prompt(rng, 5, cfg.vocab_size)]))  # partial
    assert eng.metrics.swap_in_pages > 0
    assert eng.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1,
                                   "extract": 1, "install": 1}
    eng.close()


def test_host_tier_full_falls_back_to_destructive(gpt2_setup):
    """A tier whose byte budget is exhausted (capacity 0 pages here)
    declines every offer: eviction destroys as before, the request
    re-prefills from scratch, and nothing deadlocks or stalls."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_pages=18,
                  host_tier_bytes=1)         # < one page: capacity 0
    assert eng._host_tier.capacity_pages == 0
    rng = np.random.default_rng(2)
    prompt = _prompt(rng, 33, cfg.vocab_size)
    cold = eng.submit(prompt, max_new_tokens=4)
    eng.run_until_idle()
    _churn_out(eng, cfg, rng)
    assert eng._host_tier.rejected_pages > 0
    assert eng.allocator.index.host_pages == 0
    warm = eng.submit(prompt, max_new_tokens=4)
    eng.run_until_idle()
    assert list(warm.tokens) == list(cold.tokens)
    assert eng.metrics.swap_in_pages == 0
    assert eng.metrics.prefix_hits_host == 0
    eng.close()


def test_swap_in_racing_eviction_materializes_synchronously(gpt2_setup):
    """The drain thread is killed so no background device->host copy
    ever runs; a swap-in arriving before its own swap-out drained must
    materialize the bytes synchronously (the per-entry lock path) and
    still decode token-exactly."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_pages=18)
    eng._host_tier._queue.put(None)          # drain thread exits
    eng._host_tier._drain.join(timeout=5.0)
    rng = np.random.default_rng(3)
    prompt = _prompt(rng, 33, cfg.vocab_size)
    cold = eng.submit(prompt, max_new_tokens=4)
    eng.run_until_idle()
    _churn_out(eng, cfg, rng)
    assert eng.allocator.index.host_pages > 0
    for e in list(eng._host_tier._entries.values()):
        assert e.data is None, "nothing may have drained"
    warm = eng.submit(prompt, max_new_tokens=4)
    eng.run_until_idle()
    assert list(warm.tokens) == list(cold.tokens)
    assert eng.metrics.swap_in_pages > 0
    eng.close()


def test_swap_queue_backpressure_stalls_admission_not_decode(gpt2_setup):
    """When the bounded drain queue cannot absorb an eviction's worth
    of offers, allocate() returns None BEFORE evicting anything — the
    request waits in the queue, the tree is untouched, and no victim
    is destroyed while the tier still has budget for it."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_pages=18)
    rng = np.random.default_rng(4)
    eng.submit(_prompt(rng, 33, cfg.vocab_size), max_new_tokens=4)
    eng.run_until_idle()
    alloc = eng.allocator
    cached_before = alloc.index.cached_pages
    assert cached_before > 0
    eng.allocator.swap_stall = lambda need: True     # queue reports full
    from accelerate_tpu.serving.scheduler import Request

    internal = Request(prompt=_prompt(rng, 33, cfg.vocab_size),
                       max_new_tokens=30)
    assert alloc.allocate(internal) is None
    assert alloc.index.cached_pages == cached_before, \
        "a stalled admission must not evict"
    assert eng._host_tier.swapped_out_pages == 0
    eng.close()


def test_rollback_reverts_swap_ins(gpt2_setup):
    """An allocation that re-homed host-resident chunks but then failed
    to admit must put them BACK: residency flips to host, the mirror
    entries survive, the fresh pages return to the pool — and a later
    admission still swaps in token-exactly."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_pages=18)
    rng = np.random.default_rng(5)
    prompt = _prompt(rng, 33, cfg.vocab_size)
    cold = eng.submit(prompt, max_new_tokens=4)
    eng.run_until_idle()
    _churn_out(eng, cfg, rng)
    alloc = eng.allocator
    host_before = alloc.index.host_pages
    free_before = alloc.pages_free
    out_before = eng._host_tier.swapped_out_pages
    assert host_before > 0
    from accelerate_tpu.serving.scheduler import Request

    a = alloc.allocate(Request(prompt=prompt, max_new_tokens=4))
    # the allocation itself may evict MORE pages into the tier — only
    # the swap_ins delta is this allocation's to revert
    new_out = eng._host_tier.swapped_out_pages - out_before
    assert a is not None and a.swap_ins
    assert alloc.index.host_pages == host_before + new_out - len(a.swap_ins)
    alloc.rollback(a)
    assert alloc.index.host_pages == host_before + new_out
    assert alloc.pages_free >= free_before
    assert len(eng._host_tier._entries) == alloc.index.host_pages
    warm = eng.submit(prompt, max_new_tokens=4)
    eng.run_until_idle()
    assert list(warm.tokens) == list(cold.tokens)
    eng.close()


# ---------------------------------------------------------------------------
# cache-aware scheduling: in-flight dedup + prefix grouping
# ---------------------------------------------------------------------------


def test_identical_prompts_cost_one_full_prefill(gpt2_setup):
    """N concurrent identical prompts: the leader prefills the shared
    prefix once; every follower waits for the published pages and pays
    only its own unshareable final partial page — not N full prefills."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=4, num_pages=96)
    rng = np.random.default_rng(6)
    prompt = _prompt(rng, 17, cfg.vocab_size)
    reqs = [eng.submit(prompt.copy(), max_new_tokens=4) for _ in range(4)]
    eng.run_until_idle()
    toks = [list(r.tokens) for r in reqs]
    assert all(t == toks[0] for t in toks)
    # leader: 3 chunks of 8 for 17 tokens; followers: 1 chunk each for
    # the final partial page. Without dedup this would be 12.
    assert eng.metrics.prefill_chunks == 6
    assert eng.metrics.prefix_dedup_hits >= 1
    eng.close()


def test_dedup_leader_cancelled_mid_prefill(gpt2_setup):
    """A follower holding for a leader's published pages must not hang
    when the leader is cancelled mid-prefill: the hold re-evaluates
    each admission attempt and the follower prefills itself."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=2, num_pages=96, max_len=128,
                  prefill_chunk=8)
    rng = np.random.default_rng(7)
    prompt = _prompt(rng, 65, cfg.vocab_size)     # many chunks to cancel in
    leader = eng.submit(prompt, max_new_tokens=4)
    eng.step()                                    # leader admits, chunk 1
    follower = eng.submit(prompt.copy(), max_new_tokens=4)
    eng.step()
    assert follower.status is RequestStatus.QUEUED, \
        "follower must hold while the leader prefills"
    assert eng.cancel(leader)
    eng.run_until_idle()
    assert follower.status is RequestStatus.FINISHED
    assert len(follower.tokens) == 4
    eng.close()


def test_dedup_never_waits_on_lower_priority_leader(gpt2_setup):
    """Bounded wait: a gold request never holds for a bronze leader —
    the tenant-priority guard keeps dedup from inverting QoS."""
    cfg, params = gpt2_setup
    from accelerate_tpu.serving import TenantSpec

    tenants = [TenantSpec("gold", priority=0),
               TenantSpec("bronze", priority=2)]
    eng = _engine(cfg, params, num_slots=2, num_pages=96, max_len=128,
                  prefill_chunk=8, tenants=tenants)
    rng = np.random.default_rng(8)
    prompt = _prompt(rng, 65, cfg.vocab_size)
    eng.submit(prompt, max_new_tokens=4, tenant="bronze")
    eng.step()                                    # bronze leader admits
    gold = eng.submit(prompt.copy(), max_new_tokens=4, tenant="gold")
    eng.step()
    # the gold request must admit (second slot) rather than hold
    assert gold.status is RequestStatus.RUNNING
    eng.run_until_idle()
    assert gold.status is RequestStatus.FINISHED
    eng.close()


def test_admission_groups_queued_prefix_sharers(gpt2_setup):
    """With one slot, a queued request sharing the admitted head's
    prefix is promoted ahead of unrelated traffic, so the shared pages
    are still hot (no eviction window between them)."""
    cfg, params = gpt2_setup
    eng = _engine(cfg, params, num_slots=1, num_pages=96)
    rng = np.random.default_rng(9)
    shared = _prompt(rng, 16, cfg.vocab_size)
    a1 = eng.submit(np.concatenate([shared, _prompt(rng, 3, cfg.vocab_size)]),
                    max_new_tokens=3)
    other = eng.submit(_prompt(rng, 19, cfg.vocab_size), max_new_tokens=3)
    a2 = eng.submit(np.concatenate([shared, _prompt(rng, 4, cfg.vocab_size)]),
                    max_new_tokens=3)
    eng.run_until_idle()
    assert all(r.status is RequestStatus.FINISHED for r in (a1, other, a2))
    assert a2.finished_at < other.finished_at, \
        "the prefix sharer must ride directly behind its head"
    assert eng.metrics.prefix_hits >= 1
    eng.close()


# ---------------------------------------------------------------------------
# pod: prefix-affinity placement
# ---------------------------------------------------------------------------


def test_pod_routes_to_prefix_resident_worker(gpt2_setup):
    """Repeat prompts land on the decode worker already holding their
    prefix (HBM or host tier) instead of round-robining by load — the
    affinity counter proves the placement, the tokens prove it stayed
    exact."""
    from accelerate_tpu.serving.pod import PodConfig, PodEngine

    cfg, params = gpt2_setup
    pod = PodEngine(gpt2, cfg, params,
                    EngineConfig(num_slots=2, max_len=64, prefill_chunk=8,
                                 page_size=4, num_pages=18,
                                 cache_dtype=jnp.float32, sanitize=True,
                                 host_tier_bytes=1 << 28),
                    PodConfig(prefill_workers=1, decode_workers=2))
    rng = np.random.default_rng(10)
    prompt = _prompt(rng, 33, cfg.vocab_size)
    r1 = pod.submit(prompt, max_new_tokens=4)
    pod.run_until_idle()
    for _ in range(2):   # churn the resident worker's pool via the tier
        pod.submit(_prompt(rng, 33, cfg.vocab_size), max_new_tokens=4)
        pod.run_until_idle()
    r2 = pod.submit(prompt, max_new_tokens=4)
    pod.run_until_idle()
    assert list(r2.tokens) == list(r1.tokens)
    s = pod.metrics_summary()
    assert s["pod_affinity_hits"] >= 1
    assert pod.compile_stats() == {"admit": 1, "prefill": 1, "decode": 1,
                                   "extract": 1, "install": 1}
    pod.close()
