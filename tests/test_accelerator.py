"""End-to-end Accelerator tests — the reference's launched-script assertions
(test_utils/scripts/test_script.py, test_sync.py) re-expressed on the virtual
8-device mesh: training parity, accumulation semantics, clipping, metrics
gathering, checkpoint round-trips."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import GradientState, TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.utils import FullyShardedDataParallelPlugin, MeshConfig


def make_regression_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    w = np.asarray([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = x @ w + 0.1
    return x, y


def make_model():
    def apply_fn(params, x):
        h = x @ params["dense"]["kernel"] + params["dense"]["bias"]
        return h

    params = {
        "dense": {
            "kernel": jnp.zeros((4, 1), jnp.float32),
            "bias": jnp.zeros((1,), jnp.float32),
        }
    }
    return apply_fn, params


def loss_fn_for(apply_fn):
    def loss_fn(params, batch):
        pred = apply_fn(params, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    return loss_fn


def batches(x, y, bs):
    return [
        {"x": x[i : i + bs], "y": y[i : i + bs]} for i in range(0, len(x), bs)
    ]


def train(accelerator, num_epochs=10, bs=16, accum=False):
    apply_fn, params = make_model()
    ts = TrainState.create(
        apply_fn=apply_fn,
        params=params,
        tx=optax.adam(0.2),
        use_grad_accum_buffer=accelerator.gradient_accumulation_steps > 1,
    )
    x, y = make_regression_data()
    loader = accelerator.prepare(batches(x, y, bs))
    ts = accelerator.prepare(ts)
    step = accelerator.train_step(loss_fn_for(apply_fn))
    losses = []
    for _ in range(num_epochs):
        for batch in loader:
            ts, metrics = step(ts, batch)
            losses.append(float(metrics["loss"]))
    return ts, losses


def test_fused_train_step_data_parallel_loss_decreases():
    acc = Accelerator()
    ts, losses = train(acc)
    assert losses[-1] < losses[0] * 0.2
    assert int(ts.step) == 40


def test_fsdp_matches_data_parallel():
    """FSDP-sharded training must be numerically equivalent to DP."""
    acc_dp = Accelerator(mesh_config=MeshConfig(axes={"data": 8}))
    ts_dp, losses_dp = train(acc_dp)
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc_fsdp = Accelerator(fsdp_plugin=FullyShardedDataParallelPlugin())
    ts_fsdp, losses_fsdp = train(acc_fsdp)
    np.testing.assert_allclose(losses_dp, losses_fsdp, rtol=2e-4, atol=2e-5)


def test_gradient_accumulation_matches_large_batch():
    """k micro-steps at bs=8 == one step at bs=32 (ref test_sync.py)."""
    acc_big = Accelerator()
    ts_big, losses_big = train(acc_big, num_epochs=1, bs=32)
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc_accum = Accelerator(gradient_accumulation_steps=4)
    ts_small, losses_small = train(acc_accum, num_epochs=1, bs=8)
    # after 1 epoch: big did 2 applies; accum did 8 micro-steps = 2 applies
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(ts_big.params)[0]),
        np.asarray(jax.tree_util.tree_leaves(ts_small.params)[0]),
        rtol=1e-5,
        atol=1e-6,
    )


def test_train_step_bf16_policy_runs():
    acc = Accelerator(mixed_precision="bf16")
    ts, losses = train(acc, num_epochs=2)
    assert losses[-1] < losses[0]
    # master params stay fp32
    assert jax.tree_util.tree_leaves(ts.params)[0].dtype == jnp.float32


def test_train_step_grad_clipping():
    acc = Accelerator(gradient_clipping=1e-6)
    apply_fn, params = make_model()
    ts = acc.prepare(
        TrainState.create(apply_fn=apply_fn, params=params, tx=optax.sgd(1.0))
    )
    x, y = make_regression_data()
    step = acc.train_step(loss_fn_for(apply_fn))
    ts, _ = step(ts, {"x": x, "y": y})
    # grads clipped to global norm 1e-6: with sgd lr=1 params move ~<=1e-6
    assert float(jnp.abs(ts.params["dense"]["kernel"]).max()) < 1e-5


def test_eager_path_backward_step():
    acc = Accelerator()
    apply_fn, params = make_model()
    params = acc.prepare(params)
    opt = acc.prepare_optimizer(optax.adam(0.2), params=params)
    loss_fn = loss_fn_for(apply_fn)
    x, y = make_regression_data()
    loader = acc.prepare(batches(x, y, 16))
    losses = []
    for _ in range(10):
        for batch in loader:
            with acc.accumulate():
                loss, grads = acc.compute_gradients(loss_fn, opt.params, batch)
                acc.backward(grads)
                acc.clip_grad_norm_(max_norm=10.0)
                opt.step()
                opt.zero_grad()
                losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2


def test_eager_accumulation_skips_steps():
    acc = Accelerator(gradient_accumulation_steps=2)
    apply_fn, params = make_model()
    opt = acc.prepare_optimizer(optax.sgd(0.1), params=acc.prepare(params))
    loss_fn = loss_fn_for(apply_fn)
    x, y = make_regression_data(16)
    p0 = np.asarray(opt.params["dense"]["kernel"])
    with acc.accumulate():
        loss, grads = acc.compute_gradients(loss_fn, opt.params, {"x": x, "y": y})
        acc.backward(grads)
        opt.step()  # step 1: accumulating -> skipped
    np.testing.assert_array_equal(np.asarray(opt.params["dense"]["kernel"]), p0)
    assert not acc.sync_gradients
    with acc.accumulate():
        loss, grads = acc.compute_gradients(loss_fn, opt.params, {"x": x, "y": y})
        acc.backward(grads)
        opt.step()  # step 2: sync boundary -> applied
    assert acc.sync_gradients
    assert not np.array_equal(np.asarray(opt.params["dense"]["kernel"]), p0)


def test_backward_rejects_scalar_loss():
    acc = Accelerator()
    with pytest.raises(ValueError, match="backward tape"):
        acc.backward(jnp.float32(1.0))


def test_gather_for_metrics_truncates_tail():
    acc = Accelerator()
    x, y = make_regression_data(20)  # 20 = 2*8 + 4 -> final batch padded
    loader = acc.prepare(batches(x, y, 8))
    seen = 0
    for batch in loader:
        preds = batch["x"]  # stand-in for model outputs
        gathered = acc.gather_for_metrics(preds)
        seen += np.asarray(gathered).shape[0]
    assert seen == 24 - 4  # 3 batches of 8 minus 4 padded dupes


def test_scheduler_steps_with_optimizer():
    acc = Accelerator(gradient_accumulation_steps=2)
    apply_fn, params = make_model()
    opt = acc.prepare_optimizer(optax.sgd(0.1), params=acc.prepare(params))
    schedule = optax.linear_schedule(1.0, 0.0, transition_steps=100)
    sched = acc.prepare(schedule)
    loss_fn = loss_fn_for(apply_fn)
    x, y = make_regression_data(16)
    for i in range(4):
        with acc.accumulate():
            loss, grads = acc.compute_gradients(loss_fn, opt.params, {"x": x, "y": y})
            acc.backward(grads)
            opt.step()
            sched.step()
            opt.zero_grad()
    # 2 optimizer applies, each ticking dp_size=8 -> count 16
    assert sched.count == 16
    assert sched.last_lr == pytest.approx(1.0 - 16 / 100)


def test_trigger_roundtrip():
    acc = Accelerator()
    assert not acc.check_trigger()
    acc.set_trigger()
    assert acc.check_trigger()
    assert not acc.check_trigger()  # reset after firing


def test_save_load_state_roundtrip(tmp_path):
    acc = Accelerator()
    ts, losses = train(acc, num_epochs=2)
    out = acc.save_state(str(tmp_path / "ckpt"), state=ts)
    # clone with zeroed params, then restore
    zeroed = dataclasses.replace(
        ts,
        params=jax.tree_util.tree_map(jnp.zeros_like, ts.params),
        step=jnp.zeros((), jnp.int32),
    )
    acc.load_state(out, state=zeroed)
    np.testing.assert_allclose(
        np.asarray(zeroed.params["dense"]["kernel"]),
        np.asarray(ts.params["dense"]["kernel"]),
    )
    assert int(zeroed.step) == int(ts.step)


def test_save_model_safetensors_roundtrip(tmp_path):
    pytest.importorskip("safetensors")
    from accelerate_tpu.checkpointing import load_model

    acc = Accelerator()
    _, params = make_model()
    params = acc.prepare(jax.tree_util.tree_map(lambda x: x + 1.5, params))
    acc.save_model(params, str(tmp_path / "model"))
    loaded = load_model(str(tmp_path / "model"))
    np.testing.assert_allclose(loaded["dense"]["kernel"], np.ones((4, 1)) * 1.5)


def test_jsonl_tracker(tmp_path):
    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("run1", config={"lr": 0.1})
    acc.log({"loss": 1.25}, step=3)
    acc.end_training()
    import json

    lines = [
        json.loads(l)
        for l in open(tmp_path / "run1" / "metrics.jsonl").read().splitlines()
    ]
    assert lines[0]["event"] == "config" and lines[0]["config"]["lr"] == 0.1
    assert lines[1]["loss"] == 1.25 and lines[1]["step"] == 3


def test_automatic_checkpoint_naming_and_total_limit(tmp_path):
    from accelerate_tpu.utils import ProjectConfiguration

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True, total_limit=2
        )
    )
    d0 = acc.save_state()
    d1 = acc.save_state()
    d2 = acc.save_state()
    assert d2.endswith("checkpoint_2")
    import os

    remaining = sorted(os.listdir(tmp_path / "checkpoints"))
    assert remaining == ["checkpoint_1", "checkpoint_2"]


def test_eager_path_save_load_roundtrip(tmp_path):
    """Eager-path weights (on the optimizer facade) must round-trip too."""
    import optax as _optax

    acc = Accelerator()
    apply_fn, params = make_model()
    opt = acc.prepare_optimizer(_optax.adam(0.2), params=acc.prepare(params))
    loss_fn = loss_fn_for(apply_fn)
    x, y = make_regression_data(16)
    with acc.accumulate():
        loss, grads = acc.compute_gradients(loss_fn, opt.params, {"x": x, "y": y})
        acc.backward(grads)
        opt.step()
    trained = np.asarray(opt.params["dense"]["kernel"]).copy()
    acc.save_state(str(tmp_path / "ckpt"))
    opt.params = jax.tree_util.tree_map(jnp.zeros_like, opt.params)
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(opt.params["dense"]["kernel"]), trained)


def test_async_checkpoint_roundtrip(tmp_path):
    """async_save overlaps writes; wait_for_checkpoints/load drain and the
    restored state matches (SURVEY §5 tensorstore-style async ckpt)."""
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.test_utils.training import (
        regression_loss,
        regression_params,
    )

    acc = Accelerator()
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params=regression_params(1.5, 0.5), tx=optax.adam(0.1)
    ))
    step = acc.train_step(regression_loss)
    batch = {"x": np.arange(8, dtype=np.float32),
             "y": np.arange(8, dtype=np.float32) * 2 + 1}
    ts, _ = step(ts, batch)
    out = acc.save_state(str(tmp_path / "ck"), state=ts, async_save=True)
    drained = acc.wait_for_checkpoints()
    assert drained >= 1
    restored = acc.load_state(out, state=ts)
    ts2 = restored["train_states"][0]
    np.testing.assert_array_equal(
        np.asarray(ts.params["a"]), np.asarray(ts2.params["a"])
    )

    # load without explicit drain must also work (auto-drain on load)
    acc.save_state(str(tmp_path / "ck2"), state=ts, async_save=True)
    restored2 = acc.load_state(str(tmp_path / "ck2"), state=ts)
    np.testing.assert_array_equal(
        np.asarray(restored2["train_states"][0].params["b"]),
        np.asarray(ts.params["b"]),
    )


def test_async_checkpoint_back_to_back_same_dir(tmp_path):
    """Consecutive async saves to the SAME directory serialize on the shared
    checkpointer — the last writer wins, no corruption."""
    import optax

    from accelerate_tpu import TrainState
    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.test_utils.training import (
        regression_loss,
        regression_params,
    )

    acc = Accelerator()
    ts = acc.prepare(TrainState.create(
        apply_fn=None, params=regression_params(1.0, 0.0), tx=optax.sgd(0.1)
    ))
    step = acc.train_step(regression_loss)
    batch = {"x": np.arange(8, dtype=np.float32),
             "y": np.arange(8, dtype=np.float32) * 2 + 1}
    target = str(tmp_path / "same")
    for _ in range(3):
        ts, _ = step(ts, batch)
        acc.save_state(target, state=ts, async_save=True)
    final_a = np.asarray(ts.params["a"])
    restored = acc.load_state(target, state=ts)
    np.testing.assert_array_equal(
        np.asarray(restored["train_states"][0].params["a"]), final_a
    )


def test_join_uneven_inputs_overrides_nested_sampler():
    """The even_batches override must reach the BatchSamplerShard nested
    under a rebuilt torch DataLoader — that flag decides per-host iteration
    counts (code-review r2 finding)."""
    import torch

    from accelerate_tpu.accelerator import Accelerator
    from accelerate_tpu.data import BatchSamplerShard
    from accelerate_tpu.state import PartialState

    from accelerate_tpu.data import prepare_data_loader

    PartialState._reset_state()
    acc = Accelerator()
    ds = torch.utils.data.TensorDataset(torch.arange(10).float())
    loader = torch.utils.data.DataLoader(ds, batch_size=2)
    # the torch rebuild (-> BatchSamplerShard) only engages in multi-process
    # worlds; build that structure explicitly
    prepared = prepare_data_loader(
        loader, num_processes=2, process_index=0, put_on_device=False
    )
    acc._dataloaders.append(prepared)

    def find_sampler(obj, depth=0):
        if obj is None or depth > 4:
            return None
        if isinstance(obj, BatchSamplerShard):
            return obj
        for attr in ("loader", "batch_sampler", "sampler"):
            found = find_sampler(getattr(obj, attr, None), depth + 1)
            if found is not None:
                return found
        return None

    sampler = find_sampler(prepared)
    assert sampler is not None, "expected a nested BatchSamplerShard"
    sampler.even_batches = False
    with acc.join_uneven_inputs([None], even_batches=True):
        assert sampler.even_batches is True
    assert sampler.even_batches is False


def test_clip_grad_norm_semantics():
    """Returned value is the pre-clip global norm; post-clip norm is
    min(norm, max_norm) across ALL prepared optimizers as one group."""
    import optax

    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator()
    apply_fn, params = make_model()
    opt = acc.prepare_optimizer(optax.sgd(0.1), params=acc.prepare(params))
    loss_fn = loss_fn_for(apply_fn)
    x, y = make_regression_data(16)
    with acc.accumulate():
        _, grads = acc.compute_gradients(loss_fn, opt.params, {"x": x, "y": y})
        acc.backward(grads)
        pre_norm = float(optax.global_norm(opt.gradients))
        returned = float(acc.clip_grad_norm_(max_norm=pre_norm / 2))
        post_norm = float(optax.global_norm(opt.gradients))
    assert abs(returned - pre_norm) < 1e-5 * max(1.0, pre_norm)
    assert post_norm <= pre_norm / 2 * 1.001
    # a max_norm above the actual norm must leave gradients untouched
    with acc.accumulate():
        _, grads = acc.compute_gradients(loss_fn, opt.params, {"x": x, "y": y})
        opt.zero_grad()
        acc.backward(grads)
        pre = np.asarray(opt.gradients["dense"]["kernel"])
        acc.clip_grad_norm_(max_norm=1e9)
        np.testing.assert_allclose(
            np.asarray(opt.gradients["dense"]["kernel"]), pre, rtol=1e-6)


# --- fp16 dynamic loss scale (GradScaler parity) -----------------------------


def test_loss_scale_overflow_skips_step_and_backs_off():
    """Non-finite grads: the optimizer apply is skipped and the scale halves
    (torch GradScaler backoff semantics, ref accelerator.py:455-479)."""
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.training import DynamicLossScale

    PartialState._reset_state()
    acc = Accelerator(mixed_precision="fp16")

    def loss_fn(params, batch):
        # huge loss -> scaled loss overflows fp16-ish range -> inf grads
        return jnp.sum(params["w"] * batch["x"]) * 1e38

    ts = acc.prepare(TrainState.create(
        apply_fn=None, params={"w": jnp.ones((4,), jnp.float32)},
        tx=optax.sgd(0.1)))
    assert isinstance(ts.loss_scale, DynamicLossScale)
    s0 = float(ts.loss_scale.scale)
    step = acc.train_step(loss_fn)
    ts, m = step(ts, {"x": jnp.ones((4,), jnp.float32)})
    np.testing.assert_array_equal(np.asarray(ts.params["w"]), np.ones(4))
    assert float(ts.loss_scale.scale) == s0 * 0.5  # backoff


def test_loss_scale_grows_after_interval():
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.training import DynamicLossScale

    PartialState._reset_state()
    scale = DynamicLossScale.create(init_scale=1024.0)
    scale = dataclasses.replace(scale, growth_interval=3)
    for _ in range(2):
        scale = scale.update(jnp.bool_(True))
        assert float(scale.scale) == 1024.0  # not yet
    scale = scale.update(jnp.bool_(True))
    assert float(scale.scale) == 2048.0  # growth at the interval
    assert int(scale.growth_tracker) == 0  # tracker reset
    scale = scale.update(jnp.bool_(False))
    assert float(scale.scale) == 1024.0  # overflow halves again


def test_fp16_fused_step_trains_with_scaling():
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator(mixed_precision="fp16")
    ts, losses = train(acc, num_epochs=5)
    assert losses[-1] < losses[0] * 0.3
    assert ts.loss_scale is not None


def test_fp16_accumulation_zeroes_overflowed_micro_batch():
    """An overflowed micro-batch must not poison the accumulation buffer:
    its contribution is zeroed, the others still apply (GradScaler-style
    per-micro skip)."""
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    acc = Accelerator(mixed_precision="fp16", gradient_accumulation_steps=2)

    def loss_fn(params, batch):
        return jnp.sum(params["w"] * batch["x"]) * batch["boost"]

    ts = acc.prepare(TrainState.create(
        apply_fn=None, params={"w": jnp.ones((4,), jnp.float32)},
        tx=optax.sgd(1.0), use_grad_accum_buffer=True))
    step = acc.train_step(loss_fn)
    # micro 1: overflow (boost blows the scaled grads to inf)
    ts, _ = step(ts, {"x": jnp.ones((4,), jnp.float32),
                      "boost": jnp.float32(1e38)})
    # micro 2: finite; boundary -> apply
    ts, _ = step(ts, {"x": jnp.ones((4,), jnp.float32),
                      "boost": jnp.float32(1.0)})
    w = np.asarray(ts.params["w"])
    # only the finite micro contributed: grad = x * 1.0 / k = 0.5
    np.testing.assert_allclose(w, np.ones(4) - 0.5, rtol=1e-5)


# --- kwargs_handlers (ref accelerator.py:338-376) ----------------------------


def test_kwargs_handlers_timeout_reaches_distributed_init(monkeypatch):
    """InitProcessGroupKwargs.timeout must flow into the
    jax.distributed.initialize path (VERDICT r3 missing #5)."""
    from datetime import timedelta

    import accelerate_tpu.state as state_mod
    from accelerate_tpu.utils import InitProcessGroupKwargs

    seen = {}

    def spy(timeout_s=None):
        seen["timeout_s"] = timeout_s
        return False

    monkeypatch.setattr(state_mod, "_maybe_init_jax_distributed", spy)
    Accelerator(kwargs_handlers=[
        InitProcessGroupKwargs(timeout=timedelta(seconds=123))
    ])
    assert seen["timeout_s"] == 123


def test_kwargs_handlers_autocast_disable_pins_f32():
    from accelerate_tpu.utils import AutocastKwargs

    acc = Accelerator(mixed_precision="bf16",
                      kwargs_handlers=[AutocastKwargs(enabled=False)])
    assert acc.compute_dtype == jnp.float32
    assert acc.mixed_precision == "bf16"  # policy recorded, compute pinned


def test_kwargs_handlers_unknown_and_duplicate_raise():
    from accelerate_tpu.utils import AutocastKwargs
    from accelerate_tpu.utils.dataclasses import KwargsHandler

    with pytest.raises(ValueError, match="Unsupported kwargs handler"):
        Accelerator(kwargs_handlers=[object()])

    class Mystery(KwargsHandler):
        pass

    with pytest.raises(ValueError, match="Unsupported kwargs handler type"):
        Accelerator(kwargs_handlers=[Mystery()])
    with pytest.raises(ValueError, match="only pass one"):
        Accelerator(kwargs_handlers=[AutocastKwargs(), AutocastKwargs()])


def test_kwargs_handlers_fp8_recipe_reaches_model_state():
    from accelerate_tpu.models import llama
    from accelerate_tpu.utils import FP8RecipeKwargs

    acc = Accelerator(kwargs_handlers=[FP8RecipeKwargs(amax_history_len=32)])
    assert acc.fp8_recipe_handler.amax_history_len == 32
    # the recipe reaches every family's init_fp8_state without threading
    st = llama.init_fp8_state(llama.LlamaConfig.tiny())
    hist = st["layers"]["attn"]["q_proj"]["x"].amax_history
    assert hist.shape[-1] == 32
    # explicit arg still wins
    st = llama.init_fp8_state(llama.LlamaConfig.tiny(), history_len=8)
    assert st["layers"]["attn"]["q_proj"]["x"].amax_history.shape[-1] == 8
