"""adamw_8bit: int8 block-quantized Adam moments (the bnb 8-bit-Adam
capability, ref utils/bnb.py:44-467, as a native optax transformation).

Parity contract: trajectories match optax.adamw to quantization noise;
moment dequantization error is bounded by the per-block absmax scale;
the transform runs under the optimizer-sharding planner."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator
from accelerate_tpu.optimizers import _BLOCK, _dequantize, _quantize, adamw_8bit
from accelerate_tpu.utils import MeshConfig


def _mlp_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (8, 32)) * 0.3,
        "w2": jax.random.normal(k2, (32, 1)) * 0.3,
        "b": jnp.zeros((1,)),
    }


def _regression_loss(params, batch):
    h = jnp.tanh(batch["x"] @ params["w1"])
    pred = h @ params["w2"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _train(tx, steps=40, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x[:, :1] * 2.0 - x[:, 1:2] + 0.3).astype(np.float32)
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    params = _mlp_params(jax.random.key(1))
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(_regression_loss)(params, batch)
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state, loss

    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    return losses


def test_quantize_dequantize_error_bound():
    """Round-trip error per element is at most half a quantization step of
    its block (absmax/127), including on non-multiple-of-block sizes."""
    for seed, shape in ((0, (1024,)), (1, (300,)), (2, (7, 130))):
        x = jax.random.normal(jax.random.key(seed), shape) * (seed + 1.0)
        z = _quantize(x)
        back = _dequantize(z, shape)
        flat = x.reshape(-1)
        pad = (-flat.size) % _BLOCK
        blocks = jnp.concatenate([flat, jnp.zeros((pad,))]).reshape(-1, _BLOCK)
        step = jnp.max(jnp.abs(blocks), axis=1) / 127.0
        err = jnp.abs(back.reshape(-1) - flat).reshape(-1)
        bound = jnp.repeat(step, _BLOCK)[: flat.size] * 0.5 + 1e-7
        assert bool(jnp.all(err <= bound)), f"seed={seed} shape={shape}"


def test_adamw_8bit_matches_adamw_trajectory():
    """Loss trajectory tracks f32 adamw within quantization noise and ends
    at a comparably low loss (the 8-bit-Adam convergence result)."""
    ref = _train(optax.adamw(3e-2, weight_decay=1e-3))
    q = _train(adamw_8bit(3e-2, weight_decay=1e-3))
    assert q[-1] < ref[0] * 0.1  # actually converged
    # pointwise trajectory closeness, loose enough for int8 noise
    np.testing.assert_allclose(q, ref, rtol=0.25, atol=5e-3)


def test_adamw_8bit_schedule_and_moments_stay_int8():
    sched = optax.linear_schedule(3e-2, 1e-2, 40)
    losses = _train(adamw_8bit(sched))
    assert losses[-1] < losses[0] * 0.2
    tx = adamw_8bit(1e-2)
    params = _mlp_params(jax.random.key(2))
    state = tx.init(params)
    g = jax.tree_util.tree_map(jnp.ones_like, params)
    _, state = tx.update(g, state, params)
    for z in (state.mu["w1"], state.nu_sqrt["w1"]):
        assert z.q.dtype == jnp.int8
        assert z.scale.dtype == jnp.float32


def test_adamw_8bit_memory_is_sub_f32():
    """The point of the transform: moment bytes per parameter ~2.06, vs 8
    for f32 adam (docs/performance.md)."""
    params = {"w": jnp.zeros((4096, 256))}
    state = adamw_8bit(1e-3).init(params)

    def nbytes(tree):
        return sum(
            np.prod(x.shape) * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(tree)
        )

    n_params = 4096 * 256
    total = nbytes(state.mu) + nbytes(state.nu_sqrt)
    assert total < n_params * 2.2
    assert total >= n_params * 2  # int8 payloads are really there


def test_adamw_8bit_under_optimizer_sharding():
    """plan_optimizer_sharding + device_put + a jitted update must execute
    with the quantized state (VERDICT r3 next-round item 2)."""
    from accelerate_tpu.sharding.planner import (
        plan_optimizer_sharding,
        plan_sharding,
        shard_pytree,
    )

    mesh = MeshConfig(axes={"fsdp": 8}).build()
    params = {"w": jax.random.normal(jax.random.key(3), (16, 8))}
    tx = adamw_8bit(1e-2)
    state = tx.init(params)
    param_plan = plan_sharding(params, mesh)
    plan = plan_optimizer_sharding(tx, state, param_plan, mesh)
    state = shard_pytree(state, plan)

    @jax.jit
    def step(params, state):
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        updates, state = tx.update(g, state, params)
        return optax.apply_updates(params, updates), state

    params2, state2 = step(params, state)
    assert np.isfinite(np.asarray(params2["w"])).all()
    assert state2.mu["w"].q.dtype == jnp.int8


def test_accelerator_prepare_trains_with_adamw_8bit():
    """End-to-end: the fused train_step accepts the quantized optimizer."""
    acc = Accelerator(mesh_config=MeshConfig(axes={"data": 8}))
    params = _mlp_params(jax.random.key(4))
    ts = acc.prepare(
        TrainState.create(apply_fn=None, params=params, tx=adamw_8bit(3e-2))
    )
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x[:, :1] - x[:, 1:2]).astype(np.float32)
    loader = acc.prepare([{"x": x, "y": y}])
    (batch,) = list(loader)
    step = acc.train_step(_regression_loss)
    losses = []
    for _ in range(30):
        ts, m = step(ts, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.2


def test_adamw_8bit_moments_shard_on_fsdp_axis():
    """VERDICT r4 #9: the [blocks, 256] moment payload shards along the
    blocks dim under ZeRO instead of replicating, and the sharded update
    matches the replicated one numerically."""
    from accelerate_tpu.optimizers import _Quantized
    from accelerate_tpu.sharding.planner import (
        plan_optimizer_sharding,
        plan_sharding,
        shard_pytree,
    )

    mesh = MeshConfig(axes={"fsdp": 8}).build()
    # 16*1024 = 16384 params -> 64 blocks, divisible by fsdp=8
    params = {"w": jax.random.normal(jax.random.key(5), (16, 1024))}
    tx = adamw_8bit(1e-2)
    state = tx.init(params)
    param_plan = plan_sharding(params, mesh)
    plan = plan_optimizer_sharding(tx, state, param_plan, mesh)
    assert plan.mu["w"].q.spec == jax.sharding.PartitionSpec("fsdp", None)
    assert plan.nu_sqrt["w"].scale.spec == jax.sharding.PartitionSpec(
        "fsdp", None
    )
    sharded = shard_pytree(state, plan)
    assert len(sharded.mu["w"].q.sharding.device_set) == 8

    g = jax.tree_util.tree_map(jnp.ones_like, params)
    up_sharded, st_sharded = jax.jit(tx.update)(g, sharded, params)
    up_repl, _ = jax.jit(tx.update)(g, state, params)
    np.testing.assert_allclose(
        np.asarray(up_sharded["w"]), np.asarray(up_repl["w"]),
        rtol=1e-6, atol=1e-7,
    )
    assert isinstance(st_sharded.mu["w"], _Quantized)


def test_adamw_8bit_zero_composition_warns_on_indivisible_blocks():
    """Tiny (single-block) moments can't divide the fsdp axis; the user
    hears about it at prepare() time, not from a buried rank-0 log
    (ADVICE r4)."""
    import warnings as _warnings

    from accelerate_tpu.utils.dataclasses import DeepSpeedPlugin

    acc = Accelerator(deepspeed_plugin=DeepSpeedPlugin(zero_stage=2))
    params = {
        "big": jax.random.normal(jax.random.key(6), (64, 256)),  # 64 blocks
        "tiny": jnp.ones((8,)),  # 1 block -> cannot shard
    }
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        ts = acc.prepare(
            TrainState.create(apply_fn=None, params=params, tx=adamw_8bit(1e-3))
        )
    msgs = [str(w.message) for w in caught]
    assert any("adamw_8bit" in m and "REPLICATE" in m for m in msgs), msgs
    # the big moment sharded anyway
    assert any(
        s is not None for s in ts.opt_state.mu["big"].q.sharding.spec
    )


def test_adamw_8bit_sharded_state_checkpoint_roundtrip(tmp_path):
    """The r5 blocks-dim sharding must survive save_state/load_state,
    including restore under a DIFFERENT mesh factorization (the pod-resize
    case cross-mesh restore exists for)."""
    import dataclasses

    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils.dataclasses import DeepSpeedPlugin

    acc = Accelerator(deepspeed_plugin=DeepSpeedPlugin(zero_stage=2))
    params = {"w": jax.random.normal(jax.random.key(9), (64, 256))}
    ts = acc.prepare(
        TrainState.create(apply_fn=None, params=params, tx=adamw_8bit(1e-2))
    )

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"]) ** 2)

    x = np.random.default_rng(0).normal(size=(8, 64)).astype(np.float32)
    loader = acc.prepare([{"x": x}])
    (batch,) = list(loader)
    step = acc.train_step(loss)
    for _ in range(3):
        ts, _ = step(ts, batch)
    assert any(
        s is not None for s in ts.opt_state.mu["w"].q.sharding.spec
    ), "precondition: moments sharded"
    out = acc.save_state(str(tmp_path / "ckpt"), state=ts)
    want_mu = np.asarray(ts.opt_state.mu["w"].q)

    # restore under a different factorization of the same 8 devices
    PartialState._reset_state()
    acc2 = Accelerator(
        deepspeed_plugin=DeepSpeedPlugin(zero_stage=2),
        mesh_config=MeshConfig(axes={"data": 2, "fsdp": 4}),
    )
    # fresh arrays: prepare may alias same-device inputs, and the donated
    # train step then deletes the originals along with the first world's
    # placed copies (docs/performance.md "Pitfalls")
    params2 = {"w": jax.random.normal(jax.random.key(9), (64, 256))}
    ts2 = acc2.prepare(
        TrainState.create(apply_fn=None, params=params2, tx=adamw_8bit(1e-2))
    )
    zeroed = dataclasses.replace(ts2, step=jnp.zeros((), jnp.int32))
    acc2.load_state(out, state=zeroed)
    np.testing.assert_array_equal(
        np.asarray(zeroed.opt_state.mu["w"].q), want_mu
    )
    assert int(zeroed.step) == int(ts.step)
    # and training continues from the restored quantized state
    loader2 = acc2.prepare([{"x": x}])
    (batch2,) = list(loader2)
    step2 = acc2.train_step(loss)
    _, m = step2(zeroed, batch2)
    assert np.isfinite(float(m["loss"]))
