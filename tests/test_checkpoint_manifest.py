"""Commit-protocol semantics of the checkpoint manifest (ISSUE 20).

Model-free on purpose: the manifest layer (accelerate_tpu/utils/manifest.py)
is plain files + atomic rename, so every crash-at-any-byte-offset case is
exercised here without touching jax — a corrupt or missing manifest must
parse as "this checkpoint does not exist", and retention must never delete
the newest complete commit.
"""

from __future__ import annotations

import json
import os

from accelerate_tpu.utils.manifest import (
    MANIFEST_NAME,
    complete_checkpoints,
    is_complete,
    latest_complete,
    prune_complete,
    read_manifest,
    write_manifest,
)


def _commit(base, name, step, files=("a.bin",)):
    d = os.path.join(str(base), name)
    os.makedirs(d, exist_ok=True)
    for f in files:
        with open(os.path.join(d, f), "wb") as fh:
            fh.write(b"x")
    write_manifest(d, step=step, files=files)
    return d


def test_write_read_roundtrip(tmp_path):
    d = _commit(tmp_path, "step_1", 1, files=("a.bin", "b.bin"))
    m = read_manifest(d)
    assert m["step"] == 1
    assert sorted(m["files"]) == ["a.bin", "b.bin"]
    assert is_complete(d)


def test_missing_manifest_is_absent(tmp_path):
    d = os.path.join(str(tmp_path), "torn")
    os.makedirs(d)
    with open(os.path.join(d, "a.bin"), "wb") as fh:
        fh.write(b"x")  # bytes landed, commit never happened
    assert read_manifest(d) is None
    assert not is_complete(d)
    assert latest_complete(str(tmp_path)) is None


def test_corrupt_manifest_is_absent(tmp_path):
    d = _commit(tmp_path, "step_1", 1)
    with open(os.path.join(d, MANIFEST_NAME), "w") as fh:
        fh.write('{"version": 1, "ste')  # torn at an arbitrary byte offset
    assert read_manifest(d) is None
    assert not is_complete(d)


def test_manifest_wrong_shape_is_absent(tmp_path):
    d = os.path.join(str(tmp_path), "odd")
    os.makedirs(d)
    with open(os.path.join(d, MANIFEST_NAME), "w") as fh:
        json.dump(["not", "a", "manifest"], fh)
    assert read_manifest(d) is None


def test_listed_file_missing_means_incomplete(tmp_path):
    d = _commit(tmp_path, "step_1", 1, files=("a.bin", "b.bin"))
    os.remove(os.path.join(d, "b.bin"))
    assert read_manifest(d) is not None  # manifest parses...
    assert not is_complete(d)            # ...but the commit is void


def test_latest_complete_picks_highest_step(tmp_path):
    _commit(tmp_path, "step_2", 2)
    _commit(tmp_path, "step_10", 10)
    _commit(tmp_path, "step_5", 5)
    # a torn later save must not win
    torn = os.path.join(str(tmp_path), "step_11")
    os.makedirs(torn)
    assert latest_complete(str(tmp_path)).endswith("step_10")
    names = [os.path.basename(p) for p in complete_checkpoints(str(tmp_path))]
    assert names == ["step_2", "step_5", "step_10"]


def test_base_dir_itself_can_be_the_checkpoint(tmp_path):
    d = _commit(tmp_path, ".", 7)
    assert latest_complete(d) == os.path.abspath(d)


def test_prune_keeps_newest(tmp_path):
    for s in (1, 2, 3, 4):
        _commit(tmp_path, f"step_{s}", s)
    removed = prune_complete(str(tmp_path), keep_last_n=2)
    assert sorted(os.path.basename(p) for p in removed) == ["step_1", "step_2"]
    assert latest_complete(str(tmp_path)).endswith("step_4")


def test_prune_clamps_to_one_and_never_deletes_newest(tmp_path):
    for s in (1, 2):
        _commit(tmp_path, f"step_{s}", s)
    prune_complete(str(tmp_path), keep_last_n=0)  # clamped to keep >= 1
    assert latest_complete(str(tmp_path)).endswith("step_2")


def test_prune_skips_protected_and_incomplete(tmp_path):
    kept = _commit(tmp_path, "step_1", 1)
    _commit(tmp_path, "step_2", 2)
    _commit(tmp_path, "step_3", 3)
    torn = os.path.join(str(tmp_path), "step_0")
    os.makedirs(torn)  # incomplete: not prune_complete's to delete
    removed = prune_complete(str(tmp_path), keep_last_n=1, protected=(kept,))
    assert [os.path.basename(p) for p in removed] == ["step_2"]
    assert os.path.isdir(kept) and os.path.isdir(torn)


def test_atomic_replace_no_partial_manifest_visible(tmp_path):
    # overwriting a manifest goes through tmp+rename: a reader can only
    # ever see the old or the new version, never a torn one
    d = _commit(tmp_path, "step_1", 1)
    write_manifest(d, step=1, files=("a.bin",), extra={"round": 2})
    m = read_manifest(d)
    assert m["extra"]["round"] == 2
    leftovers = [f for f in os.listdir(d) if f not in ("a.bin", MANIFEST_NAME)]
    assert leftovers == []
