"""The HTTP front door (accelerate_tpu.server) + SLO-aware multi-tenant
scheduling (ISSUE 7).

Layered like the package: protocol/tokenizer/config tests are jax-free
and instant; scheduler policy tests are model-free; the end-to-end
section drives the REAL HTTP server over a tiny gpt2 engine — including
the acceptance contract: a two-tenant overload run where streamed
tokens are byte-identical to `Engine.stream`, the high tier's TTFT p99
beats the low tier's, shed requests get 429 (never a hang), and the
compile count stays exactly 3."""

import asyncio
import json
import threading
import time

import numpy as np
import pytest

from accelerate_tpu.server.config import (
    ServerConfig,
    format_tenants,
    parse_tenants_arg,
)
from accelerate_tpu.server.protocol import (
    ProtocolError,
    parse_chat_request,
    parse_completion_request,
)
from accelerate_tpu.server.tokenizer import (
    ByteTokenizer,
    NumericTokenizer,
    get_tokenizer,
)
from accelerate_tpu.serving.scheduler import (
    Request,
    RequestStatus,
    Scheduler,
    TenantSpec,
)


def _req(n=4, tenant="default", max_new=4, slo=None, **kw):
    return Request(prompt=np.arange(1, n + 1, dtype=np.int32),
                   max_new_tokens=max_new, tenant=tenant,
                   slo_ttft_s=slo, **kw)


# ---------------------------------------------------------------------------
# protocol: validation without a server
# ---------------------------------------------------------------------------


class TestProtocol:
    def test_minimal_completion_parses(self):
        p = parse_completion_request({"prompt": "hi", "max_tokens": 3}, 64)
        assert p.prompt_text == "hi" and p.max_tokens == 3
        assert p.n == 1 and p.best_of == 1 and not p.stream

    def test_prompt_as_token_ids(self):
        p = parse_completion_request({"prompt": [1, 2, 3]}, 64)
        assert p.prompt_ids == [1, 2, 3] and p.prompt_text is None

    @pytest.mark.parametrize("body,frag", [
        ("notadict", "JSON object"),
        ({}, "'prompt' is required"),
        ({"prompt": ""}, "empty"),
        ({"prompt": []}, "empty"),
        ({"prompt": [1, -2]}, "nonnegative"),
        ({"prompt": {"x": 1}}, "string or an array"),
        ({"prompt": "x", "max_tokens": 0}, "max_tokens"),
        ({"prompt": "x", "max_tokens": "4"}, "integer"),
        ({"prompt": "x", "temperature": -1}, "temperature"),
        ({"prompt": "x", "n": 99}, "'n'"),
        ({"prompt": "x", "best_of": 2, "n": 3}, "best_of"),
        ({"prompt": "x", "stream": "yes"}, "stream"),
        ({"prompt": "x", "stop": ["a"] * 5}, "stop"),
        ({"prompt": "x", "seed": 1.5}, "seed"),
    ])
    def test_rejects_malformed(self, body, frag):
        with pytest.raises(ProtocolError) as ei:
            parse_completion_request(body, 64)
        assert ei.value.status == 400 and frag in str(ei.value)

    def test_best_of_cannot_stream(self):
        with pytest.raises(ProtocolError, match="streamed"):
            parse_completion_request(
                {"prompt": "x", "n": 1, "best_of": 3, "stream": True}, 64)

    def test_chat_renders_deterministic_template(self):
        msgs = [{"role": "system", "content": "s"},
                {"role": "user", "content": "u"}]
        a = parse_chat_request({"messages": msgs}, 64)
        b = parse_chat_request({"messages": msgs}, 64)
        assert a.prompt_text == b.prompt_text
        assert a.prompt_text.endswith("<|assistant|>\n")

    def test_chat_rejects_bad_messages(self):
        for bad in ([], [{"role": "alien", "content": "x"}],
                    [{"role": "user"}]):
            with pytest.raises(ProtocolError):
                parse_chat_request({"messages": bad}, 64)


class TestTokenizers:
    def test_byte_roundtrip(self):
        tok = ByteTokenizer(256)
        s = "héllo ⊕ wörld"
        assert tok.decode(tok.encode(s)) == s

    def test_byte_incremental_never_tears_codepoints(self):
        tok = ByteTokenizer(256)
        ids = tok.encode("a⊕b")  # ⊕ is 3 UTF-8 bytes
        inc = tok.incremental()
        pieces = [inc.push([i]) for i in ids]
        # no piece may contain a replacement char; concatenation is exact
        assert "�" not in "".join(pieces)
        assert "".join(pieces) + inc.flush() == "a⊕b"

    def test_byte_requires_vocab(self):
        with pytest.raises(ValueError, match="256"):
            ByteTokenizer(100)

    def test_numeric_roundtrip_and_reject(self):
        tok = NumericTokenizer(50)
        assert tok.encode(tok.decode([3, 14, 1])) == [3, 14, 1]
        with pytest.raises(ValueError, match="token ids"):
            tok.encode("plain text")

    def test_auto_selects_by_vocab(self):
        assert get_tokenizer("auto", 256).name == "byte"
        assert get_tokenizer("auto", 64).name == "numeric"


class TestTenantConfig:
    def test_parse_roundtrip(self):
        arg = "gold:priority=0,weight=4,slo=0.25;bronze:priority=1,weight=1"
        specs = parse_tenants_arg(arg)
        assert [s.name for s in specs] == ["gold", "bronze"]
        assert specs[0].ttft_slo_s == 0.25 and specs[0].weight == 4.0
        assert parse_tenants_arg(format_tenants(specs)) == specs

    def test_parse_extra_keys(self):
        specs, extras = parse_tenants_arg(
            "a:rate=5,priority=0;b:concurrency=3",
            extra_keys={"rate": float, "concurrency": int})
        assert extras["a"] == {"rate": 5.0}
        assert extras["b"] == {"concurrency": 3}
        assert specs[0].priority == 0

    @pytest.mark.parametrize("bad", [
        "x:unknown=1", "x:weight=abc", "a:;a:", ":weight=1"])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_tenants_arg(bad)

    def test_server_config_validates(self):
        with pytest.raises(ValueError, match="unknown_tenants"):
            ServerConfig(unknown_tenants="whatever")


class TestStopSequences:
    """_Choice: stop strings match across chunk boundaries and are never
    half-emitted (the holdback buffer)."""

    def _choice(self, stops):
        from accelerate_tpu.server.http import _Choice

        return _Choice(ByteTokenizer(256), stops)

    def test_stop_across_chunks_truncates(self):
        ch = self._choice(["END"])
        tok = ByteTokenizer(256)
        out = ch.push(tok.encode("abcE"))
        out += ch.push(tok.encode("ND tail"))
        out += ch.finish()
        assert out == "abc" and ch.stopped

    def test_holdback_never_emits_stop_prefix_early(self):
        ch = self._choice(["XY"])
        tok = ByteTokenizer(256)
        first = ch.push(tok.encode("aX"))
        assert "X" not in first, "possible stop prefix must be held back"
        rest = ch.push(tok.encode("Yb"))
        assert ch.stopped and first + rest + ch.finish() == "a"

    def test_no_stop_flushes_everything(self):
        ch = self._choice([])
        tok = ByteTokenizer(256)
        out = ch.push(tok.encode("hello")) + ch.finish()
        assert out == "hello" and not ch.stopped


# ---------------------------------------------------------------------------
# scheduler policy: tiers, DRR, SLO shedding (model-free)
# ---------------------------------------------------------------------------


class TestTenantScheduling:
    def test_priority_tier_admits_first(self):
        s = Scheduler(1, 64, tenants=[TenantSpec("gold", priority=0),
                                      TenantSpec("bronze", priority=1)])
        s.submit(_req(tenant="bronze"))
        g = s.submit(_req(tenant="gold"))
        assert s.admissions()[0][1] is g

    def test_drr_weights_translate_to_service_shares(self):
        s = Scheduler(1, 64, max_queue=1000,
                      tenants=[TenantSpec("a", weight=3),
                               TenantSpec("b", weight=1)])
        for _ in range(150):
            s.submit(_req(8, tenant="a", max_new=8))
            s.submit(_req(8, tenant="b", max_new=8))
        counts = {"a": 0, "b": 0}
        for _ in range(80):
            for slot, r in s.admissions():
                counts[r.tenant] += 1
                slot.free()
        ratio = counts["a"] / counts["b"]
        assert 2.0 < ratio < 4.5, counts

    def test_untenanted_stays_fifo(self):
        s = Scheduler(2, 64)
        rs = [s.submit(_req()) for _ in range(4)]
        assert [r.request_id for _, r in s.admissions()] == [
            rs[0].request_id, rs[1].request_id]

    def test_certain_slo_miss_is_shed_not_served(self):
        clock = [0.0]
        s = Scheduler(1, 64, clock=lambda: clock[0],
                      tenants=[TenantSpec("t", ttft_slo_s=0.5)])
        s.note_step_time(0.1)
        r = s.submit(_req(32, tenant="t"))
        clock[0] = 1.0  # already past the SLO before any admission
        shed = s.shed_expired()
        assert shed == [r] and r.status is RequestStatus.EXPIRED
        assert "SLO" in r.reject_reason and r.retry_after_s is not None
        assert s.expired_slo == 1

    def test_cold_engine_never_sheds_on_slo(self):
        clock = [0.0]
        s = Scheduler(1, 64, clock=lambda: clock[0],
                      tenants=[TenantSpec("t", ttft_slo_s=0.001)])
        s.submit(_req(32, tenant="t"))
        clock[0] = 50.0
        # step_time_ema == 0 (nothing measured): SLO shedding stays off
        assert s.shed_expired() == []

    def test_pressure_sheds_predicted_miss_not_newest(self):
        clock = [0.0]
        s = Scheduler(1, 64, max_queue=2, clock=lambda: clock[0],
                      tenants=[TenantSpec("t", ttft_slo_s=0.2)])
        s.note_step_time(0.05)
        r1 = s.submit(_req(32, tenant="t", max_new=16))
        r2 = s.submit(_req(32, tenant="t", max_new=16))
        r3 = s.submit(_req(2, tenant="t", max_new=2))
        assert r3.status is RequestStatus.QUEUED, "newest survives"
        assert RequestStatus.EXPIRED in (r1.status, r2.status)
        assert s.queue_depth == 2

    def test_full_queue_displaces_lower_tier_for_gold(self):
        s = Scheduler(1, 64, max_queue=2,
                      tenants=[TenantSpec("gold", priority=0),
                               TenantSpec("bronze", priority=1)])
        b1 = s.submit(_req(tenant="bronze"))
        b2 = s.submit(_req(tenant="bronze"))
        g = s.submit(_req(tenant="gold"))
        assert g.status is RequestStatus.QUEUED, "tier 0 must not bounce"
        assert b2.status is RequestStatus.EXPIRED, "newest bronze displaced"
        assert "displaced" in b2.reject_reason
        assert b1.status is RequestStatus.QUEUED
        # a bronze arrival into the still-full queue cannot displace gold
        b3 = s.submit(_req(tenant="bronze"))
        assert b3.status is RequestStatus.REJECTED

    def test_reject_carries_retry_after(self):
        s = Scheduler(1, 64, max_queue=1)
        s.submit(_req())
        r = s.submit(_req())
        assert r.status is RequestStatus.REJECTED
        assert r.retry_after_s and r.retry_after_s > 0

    def test_tenant_queue_cap(self):
        s = Scheduler(1, 64, max_queue=100,
                      tenants=[TenantSpec("small", max_queue=1)])
        s.submit(_req(tenant="small"))
        r = s.submit(_req(tenant="small"))
        assert r.status is RequestStatus.REJECTED
        assert "tenant queue full" in r.reject_reason

    def test_unknown_tenant_gets_default_contract(self):
        s = Scheduler(1, 64)
        r = s.submit(_req(tenant="surprise"))
        assert r.status is RequestStatus.QUEUED
        assert s.tenant_queue_depth("surprise") == 1

    def test_zero_weight_rejected_at_construction(self):
        with pytest.raises(ValueError, match="weight"):
            Scheduler(1, 64, tenants=[TenantSpec("x", weight=0)])

    def test_slo_met_verdicts(self):
        r = _req(slo=1.0)
        assert r.slo_met is None  # in flight, no verdict yet
        r.submitted_at, r.first_token_at = 0.0, 0.5
        assert r.slo_met is True
        r.first_token_at = 2.0
        assert r.slo_met is False
        late = _req(slo=1.0)
        late.status = RequestStatus.EXPIRED
        assert late.slo_met is False


# ---------------------------------------------------------------------------
# end to end over the real HTTP server (tiny gpt2 engine)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module", autouse=True)
def _persistent_compile_cache(tmp_path_factory):
    import os

    from accelerate_tpu.utils.environment import configure_compilation_cache

    prev = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS")
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", "0")
    configure_compilation_cache(
        str(tmp_path_factory.mktemp("xla_cache")), force=True)
    yield
    # scoped: hand the process back with caching OFF — a later module that
    # re-traces an AOT-compiled train step would deserialize a threshold-0
    # entry from this dir and segfault jaxlib (ISSUE 16 hit this the moment
    # an engine module sorted before test_launched_scripts)
    if prev is None:
        os.environ.pop(
            "ACCELERATE_TPU_COMPILATION_CACHE_MIN_COMPILE_SECS", None)
    configure_compilation_cache("off", force=True)


@pytest.fixture(scope="module")
def gpt2_setup():
    import jax

    from accelerate_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.key(0))
    return gpt2, cfg, params


def _make_engine(gpt2_setup, **overrides):
    import jax.numpy as jnp

    from accelerate_tpu.serving import Engine, EngineConfig

    family, cfg, params = gpt2_setup
    defaults = dict(num_slots=2, max_len=64, prefill_chunk=8,
                    cache_dtype=jnp.float32)
    defaults.update(overrides)
    return Engine(family, cfg, params, EngineConfig(**defaults)), cfg


def _stack(gpt2_setup, server_cfg=None, **engine_overrides):
    from accelerate_tpu.server.http import HttpFrontDoor
    from accelerate_tpu.server.service import InferenceService
    from accelerate_tpu.server.tokenizer import get_tokenizer

    engine, cfg = _make_engine(gpt2_setup, **engine_overrides)
    tok = get_tokenizer("auto", cfg.vocab_size)
    service = InferenceService(engine, tok,
                               server_cfg or ServerConfig(port=0))
    return HttpFrontDoor(service), engine, cfg


async def _raw(port, data: bytes) -> bytes:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(data)
    await writer.drain()
    out = await reader.read()
    writer.close()
    return out


async def _call(port, path, body=None, headers=None):
    payload = json.dumps(body).encode() if body is not None else b""
    method = b"POST" if body is not None else b"GET"
    hdr = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    raw = await _raw(port, b"%s %s HTTP/1.1\r\nHost: t\r\n%s"
                     b"Content-Length: %d\r\n\r\n%s"
                     % (method, path.encode(), hdr.encode(), len(payload),
                        payload))
    head, _, body_out = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), head, body_out


def _sse_token_ids(stream_body: bytes) -> list[int]:
    ids = []
    for frame in stream_body.split(b"\n\n"):
        if not frame.startswith(b"data: ") or frame.startswith(b"data: [DONE]"):
            continue
        choice = json.loads(frame[len(b"data: "):])["choices"][0]
        ids.extend(choice.get("token_ids")
                   or choice.get("delta", {}).get("token_ids") or [])
    return ids


def _run(door, coro):
    """Start the stack, run the test coroutine, always stop cleanly."""
    async def wrapper():
        await door.start()
        try:
            return await coro(door.port)
        finally:
            await door.stop()

    return asyncio.run(wrapper())


class TestHttpEndToEnd:
    def test_routes_and_unary_completion(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            st, _, body = await _call(port, "/healthz")
            assert st == 200 and json.loads(body)["status"] == "ok"
            st, _, body = await _call(port, "/v1/models")
            assert st == 200
            assert json.loads(body)["data"][0]["object"] == "model"
            st, _, body = await _call(port, "/404/nope")
            assert st == 404 and b"error" in body
            st, _, _ = await _call(port, "/v1/completions", headers={})
            assert st == 405  # GET on a POST route
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2, 3], "max_tokens": 4, "temperature": 0})
            assert st == 200, body
            out = json.loads(body)
            choice = out["choices"][0]
            assert len(choice["token_ids"]) == 4
            assert out["usage"]["completion_tokens"] == 4
            assert choice["finish_reason"] == "length"
            st, _, body = await _call(
                port, "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}],
                 "max_tokens": 2, "temperature": 0})
            assert st == 200, body
            assert json.loads(body)["choices"][0]["message"]["role"] \
                == "assistant"
            st, _, body = await _call(port, "/metrics")
            assert st == 200 and b"serving_ttft_seconds" in body

        _run(door, scenario)
        assert engine.compile_stats() == {"admit": 1, "prefill": 1,
                                          "decode": 1}

    def test_malformed_and_oversized_never_touch_scheduler(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            raw = await _raw(port, b"POST /v1/completions HTTP/1.1\r\n"
                             b"Host: t\r\nContent-Length: 5\r\n\r\n{bad}")
            assert b" 400 " in raw and b"invalid JSON" in raw
            # oversized prompt: validated at the door, 400 with the
            # OpenAI envelope
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": list(range(1, 60)), "max_tokens": 30})
            assert st == 400
            assert json.loads(body)["error"]["code"] \
                == "context_length_exceeded"
            # oversized BODY: refused before it is even read
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [1], "pad": "x" * (3 * 1024 * 1024)})
            assert st == 413
            # giant token id rejected against the vocab
            st, _, body = await _call(
                port, "/v1/completions", {"prompt": [10 ** 6]})
            assert st == 400 and b"out of range" in body

        _run(door, scenario)
        sch = engine.scheduler
        assert (sch.queue_depth, sch.live_slots) == (0, 0)
        assert sch.rejected_full == sch.rejected_too_long == 0
        assert engine.metrics.finished == 0  # nothing ever submitted

    def test_streamed_tokens_byte_identical_to_engine_stream(
            self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)
        prompt = [5, 9, 2, 11, 4]

        async def scenario(port):
            results = {}
            for seed in (0, 7):
                st, _, body = await _call(
                    port, "/v1/completions",
                    {"prompt": prompt, "max_tokens": 6, "stream": True,
                     "temperature": 0.8, "seed": seed})
                assert st == 200
                assert body.rstrip().endswith(b"data: [DONE]")
                results[seed] = _sse_token_ids(body)
            return results

        via_http = _run(door, scenario)
        # reference: the SAME engine config driven through the Python API
        # with the key derivation the server documents
        ref_engine, _ = _make_engine(gpt2_setup)
        for seed, got in via_http.items():
            req = ref_engine.submit(
                np.asarray(prompt, np.int32), max_new_tokens=6,
                temperature=0.8,
                key=np.array([seed & 0xFFFFFFFF, 0], np.uint32))
            want = list(ref_engine.stream(req))
            assert got == want, (seed, got, want)
        assert via_http[0] != via_http[7], "seeds must differ"

    def test_n_fan_out_returns_distinct_choices(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup, num_slots=3)

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [3, 1, 4], "max_tokens": 5, "n": 2,
                 "temperature": 0.9, "seed": 3})
            assert st == 200
            return json.loads(body)["choices"]

        choices = _run(door, scenario)
        assert [c["index"] for c in choices] == [0, 1]
        assert choices[0]["token_ids"] != choices[1]["token_ids"], \
            "per-candidate keys must decorrelate the samples"

    def test_best_of_returns_n_ranked(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup, num_slots=3)

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [3, 1, 4], "max_tokens": 5, "n": 1, "best_of": 3,
                 "temperature": 0.9, "seed": 1, "eos": None})
            assert st == 200
            return json.loads(body)["choices"]

        choices = _run(door, scenario)
        assert len(choices) == 1

    def test_client_disconnect_mid_stream_frees_the_slot(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            body = json.dumps({"prompt": [1, 2, 3], "max_tokens": 40,
                               "stream": True, "temperature": 0}).encode()
            writer.write(b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
                         + f"Content-Length: {len(body)}\r\n\r\n".encode()
                         + body)
            await writer.drain()
            await reader.readuntil(b"\n\n")   # headers
            await reader.readuntil(b"\n\n")   # first SSE frame: running now
            assert engine.scheduler.live_slots == 1
            writer.close()                     # client walks away
            await writer.wait_closed()
            # the engine must notice at the next flush and retire the slot
            for _ in range(400):
                if engine.scheduler.live_slots == 0:
                    break
                await asyncio.sleep(0.01)
            assert engine.scheduler.live_slots == 0, "slot leaked"

        _run(door, scenario)
        # pages freed too: everything the request reserved went back
        assert engine.allocator.pool.free_count > 0
        assert engine.metrics.cancelled == 1

    def test_healthz_degrades_when_watchdog_fires(self, gpt2_setup):
        from accelerate_tpu.telemetry.watchdog import StallWatchdog

        door, engine, cfg = _stack(gpt2_setup)
        fake_now = [0.0]
        engine.watchdog = StallWatchdog(5.0, clock=lambda: fake_now[0])

        async def scenario(port):
            st, _, _ = await _call(port, "/healthz")
            assert st == 200
            fake_now[0] = 100.0
            engine.watchdog.check()  # fires: silence > timeout
            st, _, body = await _call(port, "/healthz")
            assert st == 503 and b"watchdog" in body
            engine.watchdog.tick()   # progress re-arms
            st, _, _ = await _call(port, "/healthz")
            assert st == 200

        _run(door, scenario)

    def test_draining_rejects_new_work_with_503(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            door.service.draining = True
            st, _, body = await _call(port, "/v1/completions",
                                      {"prompt": [1], "max_tokens": 2})
            assert st == 503
            assert json.loads(body)["error"]["code"] == "draining"
            st, _, _ = await _call(port, "/healthz")
            assert st == 503

        _run(door, scenario)

    def test_unknown_tenant_rejected_in_strict_mode(self, gpt2_setup):
        cfg_srv = ServerConfig(
            port=0, unknown_tenants="reject",
            tenants=parse_tenants_arg("gold:priority=0"))
        door, engine, cfg = _stack(gpt2_setup, server_cfg=cfg_srv)

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions", {"prompt": [1], "max_tokens": 2},
                headers={"X-Tenant": "nosuch"})
            assert st == 401
            assert json.loads(body)["error"]["code"] == "unknown_tenant"
            st, _, _ = await _call(
                port, "/v1/completions",
                {"prompt": [1], "max_tokens": 2, "temperature": 0},
                headers={"X-Tenant": "gold"})
            assert st == 200

        _run(door, scenario)


class TestReviewRegressions:
    """Pins for the review findings on this PR."""

    def test_dead_drive_loop_fails_requests_instead_of_hanging(
            self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)
        engine.step = lambda: (_ for _ in ()).throw(
            RuntimeError("engine exploded"))

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2, 3], "max_tokens": 4, "temperature": 0})
            assert st == 500, body
            assert json.loads(body)["error"]["code"] == "engine_failure"
            st, _, body = await _call(port, "/healthz")
            assert st == 503 and b"drive loop failed" in body

        # bounded: the whole scenario must finish quickly, not hang
        asyncio.run(asyncio.wait_for(_scenario_with(door, scenario), 30))

    def test_pressure_shed_victims_reach_metrics(self):
        clock = [0.0]
        # model-free: drive the Engine bookkeeping path via a Scheduler
        # and a ServingMetrics exactly as Engine.submit does
        from accelerate_tpu.serving.metrics import ServingMetrics

        s = Scheduler(1, 64, max_queue=2, clock=lambda: clock[0],
                      tenants=[TenantSpec("t", ttft_slo_s=0.2)])
        m = ServingMetrics()
        s.note_step_time(0.05)
        s.submit(_req(32, tenant="t", max_new=16))
        s.submit(_req(32, tenant="t", max_new=16))
        s.submit(_req(2, tenant="t", max_new=2))  # sheds a doomed one
        victims = s.drain_shed()
        assert len(victims) == 1
        for v in victims:
            m.observe_request(v)
        assert m.expired == 1
        assert m.registry.counter("serving_slo_total", tenant="t").value == 1
        assert s.drain_shed() == []  # drained exactly once

    def test_tenant_cardinality_is_capped(self):
        s = Scheduler(1, 64, max_tenants=4)
        for i in range(10):
            r = s.submit(_req(tenant=f"rando-{i}"))
        # past the cap, unknown names collapse into "default"
        assert len(s.tenants) == 4
        assert r.tenant == "default"
        assert s.queue_depth == 10

    def test_stream_stop_hit_counts_finished_not_cancelled(
            self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)
        # greedy gpt2-tiny emits token 3 ('\x03') repeatedly for this
        # prompt — use its decoded text as the stop string so the hit is
        # deterministic
        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2, 3], "max_tokens": 30, "stream": True,
                 "temperature": 0, "stop": ["\x03\x03"]})
            assert st == 200
            frames = [f for f in body.split(b"\n\n") if
                      f.startswith(b"data: {")]
            last = json.loads(frames[-1][len(b"data: "):])
            assert last["choices"][0]["finish_reason"] == "stop"

        _run(door, scenario)
        assert engine.metrics.finished == 1
        assert engine.metrics.cancelled == 0
        assert engine.metrics.ttft_s.count == 1  # latency samples kept

    def test_idle_server_with_watchdog_stays_healthy(self, gpt2_setup):
        """An armed stall watchdog must not fail /healthz on a server
        that is merely idle: the drive loop ticks it while waiting for
        work."""
        door, engine, cfg = _stack(gpt2_setup, watchdog_timeout_s=0.4)

        async def scenario(port):
            st, _, _ = await _call(port, "/healthz")
            assert st == 200
            await asyncio.sleep(1.2)  # > watchdog timeout, zero traffic
            st, _, body = await _call(port, "/healthz")
            assert st == 200, body

        _run(door, scenario)

    def test_queued_stream_times_out_with_504_not_a_held_socket(
            self, gpt2_setup):
        cfg_srv = ServerConfig(port=0, request_timeout_s=0.3)
        door, engine, cfg = _stack(gpt2_setup, num_slots=1, max_queue=4,
                                   max_len=4096, server_cfg=cfg_srv)
        # occupy the only slot far beyond the timeout window
        blocker = engine.submit(np.asarray([1, 2, 3], np.int32),
                                max_new_tokens=4000)
        assert blocker.status is RequestStatus.RUNNING

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [4, 5, 6], "max_tokens": 4, "stream": True,
                 "temperature": 0})
            assert st == 504, body
            assert json.loads(body)["error"]["code"] == "timeout"
            engine.cancel(blocker)

        _run(door, scenario)

    def test_pressure_shed_single_pass_matches_per_request_estimate(self):
        """The prefix-sum victim selection must agree with the
        per-request predicted_ttft estimator it replaced."""
        clock = [10.0]
        s = Scheduler(2, 64, max_queue=100, clock=lambda: clock[0],
                      tenants=[TenantSpec("a", priority=0, ttft_slo_s=0.5),
                               TenantSpec("b", priority=1, ttft_slo_s=0.5)])
        s.note_step_time(0.05)
        rs = []
        for i in range(12):
            rs.append(s.submit(_req(16, tenant="a" if i % 3 else "b",
                                    max_new=8)))
        now = clock[0]
        slacks = {r.request_id: 0.5 - s.predicted_ttft(r, now)
                  for r in rs if r.status is RequestStatus.QUEUED}
        expected_victim = min(slacks, key=slacks.get)
        assert s._shed_predicted_miss(rs[0]) == (min(slacks.values()) < 0)
        if min(slacks.values()) < 0:
            shed = [r for r in rs if r.status is RequestStatus.EXPIRED]
            assert [r.request_id for r in shed] == [expected_victim]

    def test_oversized_headers_answer_413(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            big = "X-Pad: " + "a" * (100 * 1024)
            raw = await _raw(port, f"GET /healthz HTTP/1.1\r\nHost: t\r\n"
                             f"{big}\r\n\r\n".encode())
            assert b" 413 " in raw and b"headers too large" in raw

        _run(door, scenario)

    def test_trace_rows_with_unspecced_tenants_get_books(self, gpt2_setup,
                                                         tmp_path):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "sb4", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "benchmarks",
                "serve_bench.py"))
        sb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sb)
        rows = [{"t": 0.0, "tenant": "ghost", "prompt_len": 3,
                 "max_new_tokens": 2},
                {"t": 0.01, "prompt_len": 3, "max_new_tokens": 2}]
        f = tmp_path / "t.jsonl"
        f.write_text("\n".join(json.dumps(r) for r in rows))
        engine, cfg = _make_engine(gpt2_setup)
        specs, loads = sb.parse_tenant_load_arg("gold:priority=0")
        s = sb.run_http_load(engine, cfg.vocab_size, specs, loads,
                             trace=sb.load_trace(str(f)))
        assert s["tenants.ghost.sent"] == 1
        assert s["tenants.default.sent"] == 1


async def _scenario_with(door, coro):
    await door.start()
    try:
        return await coro(door.port)
    finally:
        await door.stop()


class TestOverloadAcceptance:
    """The ISSUE 7 acceptance contract, end to end on CPU."""

    def test_two_tier_overload_slo_and_429(self, gpt2_setup):
        """≥2 tenants at unequal priorities under genuine overload:
        tier-0's measured TTFT p99 beats tier-1's (Prometheus-sourced),
        shed requests answer 429 + Retry-After (stream or not — never a
        hang), and the engine still holds exactly three programs."""
        specs = parse_tenants_arg(
            "gold:priority=0,weight=4,slo=5.0;"
            "bronze:priority=1,weight=1,slo=5.0")
        # tiny capacity + a queue bound: the sustained waves below
        # overload it deterministically
        door, engine, cfg = _stack(gpt2_setup, num_slots=2, max_queue=4,
                                   tenants=specs,
                                   server_cfg=ServerConfig(
                                       port=0, tenants=specs))
        # compile the three programs outside the measured window, so
        # TTFTs measure scheduling, not XLA
        warm = engine.submit(np.asarray([1, 2, 3], np.int32),
                             max_new_tokens=2)
        engine.run_until_idle()
        assert warm.status is RequestStatus.FINISHED
        engine.reset_metrics()

        async def scenario(port):
            async def one(tenant, stream):
                body = {"prompt": list(range(1, 15)), "max_tokens": 24,
                        "temperature": 0, "stream": stream}
                st, head, payload = await _call(
                    port, "/v1/completions", body,
                    headers={"X-Tenant": tenant})
                return tenant, st, head, payload

            # sustained overload: bronze floods ahead of gold in every
            # wave, so gold's advantage can only come from the scheduler
            jobs = []
            for wave in range(8):
                for s in range(5):
                    jobs.append(asyncio.ensure_future(
                        one("bronze", s % 2 == 0)))
                jobs.append(asyncio.ensure_future(one("gold", wave % 2 == 0)))
                await asyncio.sleep(0.02)
            results = await asyncio.gather(*jobs)
            st, _, metrics = await _call(port, "/metrics")
            assert st == 200
            return results, metrics.decode()

        results, prom_text = _run(door, scenario)
        statuses = [st for _, st, _, _ in results]
        assert all(st in (200, 429) for st in statuses), statuses
        sheds = [(st, head) for _, st, head, _ in results if st == 429]
        assert sheds, "overload must shed something"
        for st, head in sheds:
            assert b"retry-after" in head.lower(), head
        gold_ok = [st for t, st, _, _ in results
                   if t == "gold" and st == 200]
        assert len(gold_ok) >= 5, "tier 0 must ride out the overload"
        assert statuses.count(200) >= 8, "capacity-worth must finish"
        # compile-count-flat across the whole overload run
        assert engine.compile_stats() == {"admit": 1, "prefill": 1,
                                          "decode": 1}

        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "sb", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "benchmarks",
                "serve_bench.py"))
        sb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sb)
        prom = sb.parse_prometheus(prom_text)
        gold_p99 = sb._prom_tenant(prom, "serving_ttft_seconds", "gold",
                                   "0.99")
        bronze_p99 = sb._prom_tenant(prom, "serving_ttft_seconds",
                                     "bronze", "0.99")
        assert gold_p99 is not None and bronze_p99 is not None
        assert gold_p99 < bronze_p99, (
            f"tier 0 p99 {gold_p99:.4f}s must beat tier 1 "
            f"{bronze_p99:.4f}s under overload")

    def test_harness_reports_per_tier_attainment_from_prometheus(
            self, gpt2_setup):
        """serve_bench --tenants end to end in-process: per-tier SLO
        attainment keys sourced from the /metrics scrape."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "sb2", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "benchmarks",
                "serve_bench.py"))
        sb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sb)
        specs, loads = sb.parse_tenant_load_arg(
            "gold:priority=0,weight=4,slo=2.0,rate=100;"
            "bronze:priority=1,slo=2.0,rate=100")
        engine, cfg = _make_engine(gpt2_setup, num_slots=2, tenants=specs)
        s = sb.run_http_load(engine, cfg.vocab_size, specs, loads,
                             num_requests=8, prompt_len=(2, 5),
                             max_new_tokens=(2, 4))
        for tenant in ("gold", "bronze"):
            assert s[f"tenants.{tenant}.sent"] == 4
            assert f"tenants.{tenant}.slo_attainment" in s
            assert s[f"tenants.{tenant}.ttft_p99_ms"] > 0
        assert s["compiles_decode"] == 1.0

    def test_burst_arrivals_and_trace_replay(self, gpt2_setup, tmp_path):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "sb3", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "benchmarks",
                "serve_bench.py"))
        sb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sb)
        # arrival schedules: burst preserves count and monotonicity
        rng = np.random.default_rng(0)
        offs = sb._arrival_offsets("burst", 100.0, 20, rng)
        assert len(offs) == 20 and offs == sorted(offs)
        # trace replay drives the HTTP door with recorded tenants
        trace_file = tmp_path / "trace.jsonl"
        rows = [{"t": i * 0.01, "tenant": "default", "prompt_len": 3,
                 "max_new_tokens": 2} for i in range(4)]
        trace_file.write_text("\n".join(json.dumps(r) for r in rows))
        engine, cfg = _make_engine(gpt2_setup)
        s = sb.run_http_load(engine, cfg.vocab_size, (), {},
                             trace=sb.load_trace(str(trace_file)))
        assert s["mode"] == "trace"
        assert s["tenants.default.sent"] == 4
        assert s["tenants.default.ok"] == 4


# ---------------------------------------------------------------------------
# request tracing through the front door + live introspection (ISSUE 8)
# ---------------------------------------------------------------------------


def _header(head: bytes, name: bytes) -> bytes | None:
    for line in head.split(b"\r\n"):
        key, _, value = line.partition(b":")
        if key.strip().lower() == name:
            return value.strip()
    return None


class TestRequestTracingHttp:
    @pytest.fixture(autouse=True)
    def _tracing_reset(self):
        from accelerate_tpu.telemetry import (
            clear_flight_recorder,
            configure_tracing,
        )

        configure_tracing(enabled=False, sample_rates={},
                          default_sample_rate=1.0)
        clear_flight_recorder()
        yield
        configure_tracing(enabled=False, sample_rates={},
                          default_sample_rate=1.0)
        clear_flight_recorder()

    def test_x_request_id_on_success_and_errors(self, gpt2_setup):
        """Every generate response — 200, 4xx — carries x-request-id, and
        error envelopes repeat it in-band."""
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            st, head, _ = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2, 3], "max_tokens": 2, "temperature": 0})
            assert st == 200
            rid = _header(head, b"x-request-id")
            assert rid is not None and len(rid) == 32
            int(rid, 16)  # 32 lowercase hex chars
            st, head, body = await _call(
                port, "/v1/completions",
                {"prompt": [1], "max_tokens": 100000})
            assert st == 400
            rid = _header(head, b"x-request-id")
            assert rid is not None
            env = json.loads(body)["error"]
            assert env["request_id"] == rid.decode()

        _run(door, scenario)

    def test_inbound_traceparent_is_honored(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)
        tid = "ab" * 16

        async def scenario(port):
            st, head, _ = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2], "max_tokens": 2, "temperature": 0},
                headers={"traceparent": f"00-{tid}-{'cd' * 8}-01"})
            assert st == 200
            assert _header(head, b"x-request-id") == tid.encode()

        _run(door, scenario)

    def test_malformed_traceparent_mints_fresh_id(self, gpt2_setup):
        """Satellite: garbage traceparent is ignored — fresh valid id,
        never an error, never propagation of the garbage."""
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            for bad in ("garbage", "00-xyz-abc-01",
                        "00-" + "0" * 32 + "-" + "cd" * 8 + "-01"):
                st, head, _ = await _call(
                    port, "/v1/completions",
                    {"prompt": [1, 2], "max_tokens": 2, "temperature": 0},
                    headers={"traceparent": bad})
                assert st == 200
                rid = _header(head, b"x-request-id")
                assert rid is not None and len(rid) == 32
                assert rid.decode() not in bad
                int(rid, 16)

        _run(door, scenario)

    def test_shed_429_carries_trace_id_and_shed_reason(self, gpt2_setup):
        """Acceptance: a shed request's 429 envelope names its trace AND
        the machine-readable reason, plus the Retry-After header."""
        door, engine, cfg = _stack(gpt2_setup, num_slots=1, max_queue=1,
                                   max_len=4096)
        blocker = engine.submit(np.asarray([1, 2, 3], np.int32),
                                max_new_tokens=4000)
        queued = engine.submit(np.asarray([4, 5], np.int32),
                               max_new_tokens=4)

        async def scenario(port):
            st, head, body = await _call(
                port, "/v1/completions",
                {"prompt": [6, 7], "max_tokens": 2})
            assert st == 429, body
            rid = _header(head, b"x-request-id")
            assert rid is not None
            assert _header(head, b"retry-after") is not None
            env = json.loads(body)["error"]
            assert env["request_id"] == rid.decode()
            assert env["shed_reason"] == "queue_full"
            engine.cancel(blocker)
            engine.cancel(queued)

        _run(door, scenario)

    def test_http_request_yields_linked_trace(self, gpt2_setup):
        """Acceptance: one HTTP request -> one trace whose chrome export
        has queue-wait/admit/prefill/decode spans sharing the
        x-request-id."""
        from accelerate_tpu.telemetry import (
            configure_tracing,
            export_chrome_trace,
            trace_events,
        )

        configure_tracing(enabled=True, annotate=False)
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            st, head, _ = await _call(
                port, "/v1/completions",
                {"prompt": list(range(1, 12)), "max_tokens": 3,
                 "temperature": 0})
            assert st == 200
            return _header(head, b"x-request-id").decode()

        rid = _run(door, scenario)
        names = [e["name"] for e in trace_events(rid)]
        assert "serving.queue_wait" in names
        assert "serving.admit" in names
        assert "serving.prefill" in names
        assert "serving.decode_lifetime" in names
        assert "serving.request" in names
        doc = export_chrome_trace(trace_id=rid)
        assert all(e["args"]["trace_id"] == rid
                   for e in doc["traceEvents"])
        assert engine.compile_stats() == {"admit": 1, "prefill": 1,
                                          "decode": 1}

    def test_sampling_zero_still_returns_x_request_id(self, gpt2_setup):
        """Satellite: rate 0 -> zero spans recorded, but the client still
        gets its request id."""
        from accelerate_tpu.telemetry import (
            configure_tracing,
            trace_events,
        )

        configure_tracing(enabled=True, annotate=False,
                          default_sample_rate=0.0)
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            st, head, _ = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2], "max_tokens": 2, "temperature": 0})
            assert st == 200
            return _header(head, b"x-request-id").decode()

        rid = _run(door, scenario)
        assert len(rid) == 32
        assert trace_events(rid) == []

    def test_metrics_route_negotiates_openmetrics_exemplars(self,
                                                            gpt2_setup):
        from accelerate_tpu.telemetry import configure_tracing

        configure_tracing(enabled=True, annotate=False)
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            st, head, _ = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2, 3], "max_tokens": 2, "temperature": 0})
            assert st == 200
            st, head, body = await _call(port, "/metrics")
            assert st == 200
            assert _header(head, b"content-type").startswith(
                b"text/plain; version=0.0.4")
            assert b"trace_id" not in body
            st, head, body = await _call(
                port, "/metrics",
                headers={"Accept": "application/openmetrics-text"})
            assert st == 200
            assert _header(head, b"content-type").startswith(
                b"application/openmetrics-text")
            assert b'serving_ttft_seconds_bucket' in body
            assert b"trace_id=" in body
            assert body.rstrip().endswith(b"# EOF")
            # HEAD mirrors GET minus the body on the plumbing routes —
            # same probe config must work here and on the standalone
            # exporter (review regression)
            raw = await _raw(port, b"HEAD /metrics HTTP/1.1\r\nHost: t\r\n"
                             b"Content-Length: 0\r\n\r\n")
            head, _, body = raw.partition(b"\r\n\r\n")
            assert b" 200 " in head and body == b""
            assert int(_header(head, b"content-length")) > 0

        _run(door, scenario)


class TestDebugEndpoints:
    def test_gated_off_by_default(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            for section in ("requests", "slots", "pages", "scheduler"):
                st, _, _ = await _call(port, f"/debug/{section}")
                assert st == 404
            # review regression: a non-GET must ALSO 404 when gated off —
            # a 405 would fingerprint the /debug namespace to a prober
            st, _, _ = await _call(port, "/debug/requests", body={})
            assert st == 404

        _run(door, scenario)

    def test_fan_out_samples_once_per_http_request(self, gpt2_setup,
                                                   monkeypatch):
        """Review regression: n/best_of siblings share one trace, so the
        head-sampling decision is made ONCE in the service — a
        per-candidate draw at a fractional rate would record a random
        subset of the request's spans."""
        from accelerate_tpu.server.tokenizer import get_tokenizer
        from accelerate_tpu.server.service import InferenceService
        from accelerate_tpu.server.protocol import parse_completion_request
        from accelerate_tpu.telemetry import trace as trace_mod

        engine, cfg = _make_engine(gpt2_setup, num_slots=4)
        service = InferenceService(
            engine, get_tokenizer("auto", cfg.vocab_size),
            ServerConfig(port=0))
        trace_mod.configure_tracing(enabled=True, annotate=False)
        try:
            draws = []
            flip = [True, False, True, False]

            def fake_sample(tenant="default"):
                draws.append(tenant)
                return flip[len(draws) - 1]

            monkeypatch.setattr(trace_mod, "head_sample", fake_sample)
            params = parse_completion_request(
                {"prompt": [1, 2], "max_tokens": 2, "n": 3,
                 "temperature": 0.5, "seed": 7}, 64)
            reqs = service.submit(params, "default", trace_id="ab" * 16)
            assert len(draws) == 1, "one decision per HTTP request"
            assert [r.trace_sampled for r in reqs] == [True] * 3
            assert all(r.trace_id == "ab" * 16 for r in reqs)
            for r in reqs:
                engine.cancel(r)
        finally:
            trace_mod.configure_tracing(enabled=False)
            engine.close()

    def test_debug_views_over_http(self, gpt2_setup):
        door, engine, cfg = _stack(
            gpt2_setup, num_slots=1, max_len=4096,
            server_cfg=ServerConfig(port=0, debug_endpoints=True))
        running = engine.submit(np.asarray([1, 2, 3], np.int32),
                                max_new_tokens=4000)
        queued = engine.submit(np.asarray([4, 5], np.int32),
                               max_new_tokens=4)

        async def scenario(port):
            st, _, body = await _call(port, "/debug/requests")
            assert st == 200
            dbg = json.loads(body)
            assert [r["request_id"] for r in dbg["running"]] == [
                running.request_id]
            assert [r["request_id"] for r in dbg["queued"]] == [
                queued.request_id]
            assert dbg["service"]["healthy"] is True
            st, _, body = await _call(port, "/debug/slots")
            assert st == 200
            slots = json.loads(body)["slots"]
            assert slots[0]["request_id"] == running.request_id
            st, _, body = await _call(port, "/debug/pages")
            assert st == 200
            assert json.loads(body)["pages_in_use"] > 0
            st, _, body = await _call(port, "/debug/scheduler")
            assert st == 200
            sched = json.loads(body)
            assert sched["queue_depth"] == 1
            assert "default" in sched["tenants"]
            st, _, _ = await _call(port, "/debug/nonsense")
            assert st == 404
            engine.cancel(running)
            engine.cancel(queued)

        _run(door, scenario)


class TestLogprobsAndForking:
    """ISSUE 12: OpenAI `logprobs` on both doors, COW-fork fan-out (one
    prefill for n=8), and best_of ranking by true cumulative logprob."""

    def test_completions_logprobs_block(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2, 3], "max_tokens": 4, "temperature": 0,
                 "logprobs": 1})
            assert st == 200, body
            choice = json.loads(body)["choices"][0]
            lp = choice["logprobs"]
            assert lp["token_ids"] == choice["token_ids"]
            assert len(lp["token_logprobs"]) == 4
            assert all(v <= 0.0 for v in lp["token_logprobs"])
            assert lp["top_logprobs"] is None
            # without the field the block stays null (pre-ISSUE shape)
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2, 3], "max_tokens": 2, "temperature": 0})
            assert json.loads(body)["choices"][0]["logprobs"] is None
            # top-N alternatives are not computed: 400, not truncation
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [1, 2], "max_tokens": 2, "logprobs": 5})
            assert st == 400 and b"must be 0 or 1" in body
            # chat takes the OpenAI boolean
            st, _, body = await _call(
                port, "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}],
                 "max_tokens": 3, "temperature": 0, "logprobs": True})
            assert st == 200, body
            lp = json.loads(body)["choices"][0]["logprobs"]
            assert len(lp["token_logprobs"]) == 3
            st, _, body = await _call(
                port, "/v1/chat/completions",
                {"messages": [{"role": "user", "content": "hi"}],
                 "max_tokens": 2, "logprobs": 1})
            assert st == 400 and b"boolean" in body

        _run(door, scenario)

    def test_streaming_logprobs_frames(self, gpt2_setup):
        door, engine, cfg = _stack(gpt2_setup)

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [2, 4, 6], "max_tokens": 5, "stream": True,
                 "temperature": 0.7, "seed": 2, "logprobs": 0})
            assert st == 200
            return body

        body = _run(door, scenario)
        ids, lps = [], []
        for frame in body.split(b"\n\n"):
            if (not frame.startswith(b"data: ")
                    or frame.startswith(b"data: [DONE]")):
                continue
            choice = json.loads(frame[len(b"data: "):])["choices"][0]
            block = choice.get("logprobs")
            assert block is not None, choice
            # each frame's logprob slice is index-aligned with its ids
            assert block["token_ids"] == choice["token_ids"]
            assert len(block["token_logprobs"]) == len(choice["token_ids"])
            ids.extend(choice["token_ids"])
            lps.extend(block["token_logprobs"])
        assert len(ids) == len(lps) == 5

    def test_n8_fan_out_pays_one_prefill_pinned(self, gpt2_setup):
        """The ISSUE 12 acceptance bar at the HTTP door: an n=8 fan-out
        on an 80-token prompt runs ONE full prompt prefill (5 chunks of
        16) plus one final-partial-page catch-up chunk per fork sibling
        — 12 chunks total, pinned, where independent submissions would
        pay 40."""
        door, engine, cfg = _stack(gpt2_setup, num_slots=4, max_len=128,
                                   prefill_chunk=16, page_size=16)
        prompt = list(np.random.default_rng(5).integers(
            0, cfg.vocab_size, (80,)))

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [int(t) for t in prompt], "max_tokens": 4,
                 "n": 8, "temperature": 0.9, "seed": 11})
            assert st == 200, body
            return json.loads(body)["choices"]

        choices = _run(door, scenario)
        assert len(choices) == 8
        assert engine.metrics.prefill_chunks == 5 + 7, \
            engine.metrics.prefill_chunks
        assert len({tuple(c["token_ids"]) for c in choices}) > 1

    def test_best_of_ranks_by_cumulative_logprob_e2e(self, gpt2_setup):
        """best_of=4, n=2 returns the two candidates with the highest
        true cumulative logprob, in descending order — verified from the
        response's own logprobs blocks."""
        door, engine, cfg = _stack(gpt2_setup, num_slots=4)

        async def scenario(port):
            st, _, body = await _call(
                port, "/v1/completions",
                {"prompt": [3, 1, 4], "max_tokens": 5, "n": 2,
                 "best_of": 4, "temperature": 0.9, "seed": 1,
                 "logprobs": 1})
            assert st == 200, body
            return json.loads(body)["choices"]

        choices = _run(door, scenario)
        assert len(choices) == 2
        sums = [sum(c["logprobs"]["token_logprobs"]) for c in choices]
        assert sums == sorted(sums, reverse=True)


class TestServiceStopOffLoop:
    """ATP303 audit fix (ISSUE 19): `InferenceService.stop()` runs
    `engine.close()` in the default executor — closing joins the
    watchdog / metrics-server / host-tier threads, seconds of blocking
    that must not park every other coroutine on the serving loop."""

    class _StubScheduler:
        queue = ()

        def has_work(self):
            return False

        def running(self):
            return ()

    class _StubEngine:
        watchdog = None

        def __init__(self):
            self.scheduler = TestServiceStopOffLoop._StubScheduler()
            self.closed_on = None
            self.loop_alive_during_close = None

        def cancel(self, req):
            pass

        def close(self):
            self.closed_on = threading.current_thread()
            time.sleep(0.15)  # a watchdog join mid-drain takes this long

    def test_stop_closes_engine_off_the_event_loop(self):
        from accelerate_tpu.server.service import InferenceService
        from accelerate_tpu.server.tokenizer import get_tokenizer

        engine = self._StubEngine()
        service = InferenceService(engine, get_tokenizer("auto", 256),
                                   ServerConfig(port=0, drain_timeout_s=0.1))

        async def scenario():
            await service.start()
            beats = []

            async def heartbeat():
                while True:
                    beats.append(time.monotonic())
                    await asyncio.sleep(0.01)

            hb = asyncio.get_running_loop().create_task(heartbeat())
            before = len(beats)
            await service.stop()
            hb.cancel()
            # the loop kept beating while close() slept in the executor
            engine.loop_alive_during_close = len(beats) - before
            return threading.current_thread()

        loop_thread = asyncio.run(scenario())
        assert engine.closed_on is not None, "stop() never closed the engine"
        assert engine.closed_on is not loop_thread, (
            "engine.close() ran ON the event loop thread — the ATP303 "
            "blocking-call fix regressed")
        assert engine.loop_alive_during_close >= 5, (
            "event loop starved during engine teardown")
