"""Test harness: a virtual 8-device CPU mesh stands in for a TPU slice.

This replaces the reference's `debug_launcher` gloo world
(ref launchers.py:225-257, SURVEY.md §4): distributed semantics run in one
process over 8 XLA host devices, so sharding/collective logic is exercised
without hardware.
"""

import functools
import os

# Must be set before jax initializes its backends.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The hosted-TPU image pins jax_platforms to the tunnel backend at import
# time, which silently overrides JAX_PLATFORMS — force CPU before any backend
# initializes so tests always run on the virtual 8-device mesh.
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, f"expected 8 CPU devices, got {jax.devices()}"

# The persistent compilation cache is DISABLED for the suite. It was
# pointed at a per-checkout .xla_test_cache for the ISSUE 7 headroom work,
# but this jaxlib segfaults executing deserialized entries (ISSUE 7 saw it
# for sub-second programs; ISSUE 16 reproduced it for ordinary jit_step_fn /
# jit_prefill entries too). On an idle machine nothing crosses jax's
# >=1s-compile-time write threshold, so the cache never helped a healthy
# run — but on a loaded machine the suite's own compiles cross 1s, get
# persisted mid-run, and the next identical-HLO trace deserializes the
# fresh entry and segfaults the whole session. Net value negative: off.
# (Export ACCELERATE_TPU_COMPILATION_CACHE=<dir> to opt back in; wipe the
# dir at the first "Fatal Python error" with jit_* entries present.)
from accelerate_tpu.utils.constants import ENV_COMPILATION_CACHE  # noqa: E402
from accelerate_tpu.utils.environment import configure_compilation_cache  # noqa: E402

os.environ.setdefault(ENV_COMPILATION_CACHE, "off")
configure_compilation_cache()

# Serving-state sanitizer (ISSUE 13): every engine the suite builds
# validates its cross-structure invariants (page conservation, refcount
# closure, table discipline, scheduler books) after each step — the
# whole serving/speculative/pod surface runs sanitized in tier-1.
# Host-side only; compile counts are pinned flat with this on.
os.environ.setdefault("ACCELERATE_TPU_SANITIZE", "1")

# Runtime lock-order sanitizer (ISSUE 19): transport / host-tier /
# metrics-registry locks become TrackedLocks recording per-thread
# acquisition order into a process-wide graph — a would-deadlock
# ordering raises LockOrderViolation instead of wedging the suite.
# Same split as the sanitizer above: the ATP3xx static pass proves what
# it can name, lockwatch catches the orderings only runtime sees.
os.environ.setdefault("ACCELERATE_TPU_LOCKWATCH", "1")


def pytest_collection_modifyitems(config, items):
    """Gate @pytest.mark.slow behind RUN_SLOW=1 (ref testing.py slow
    decorator semantics)."""
    if os.environ.get("RUN_SLOW", "0").lower() in ("1", "true", "yes"):
        return
    skip_slow = pytest.mark.skip(reason="slow test; set RUN_SLOW=1 to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)


@pytest.fixture(autouse=True)
def reset_state():
    """Clear the shared-state singletons between tests
    (ref test_utils/testing.py:394-439 AccelerateTestCase)."""
    from accelerate_tpu.state import PartialState

    PartialState._reset_state()
    yield
    PartialState._reset_state()


@pytest.fixture
def devices():
    return jax.devices()


# ---------------------------------------------------------------------------
# forced-host-device subprocess harness (pod-scale serving tests)
# ---------------------------------------------------------------------------

_FORCED_DEVICE_PROBE_CODE = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.exit(0 if jax.device_count() == int(sys.argv[1]) else 7)
"""


@functools.lru_cache()
def _forced_device_unsupported(n: int) -> str | None:
    """None when this jaxlib can stand up an N-forced-host-device CPU
    backend in a fresh process, else a skip reason. Probed ONCE per
    session per N with a minimal import (same spirit as
    test_utils.multiprocess_backend_supported): some jaxlib builds
    ignore the flag or wedge at backend init on exotic CPUs, and a pod
    test must skip with a reason rather than fail collection or hang."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _FORCED_DEVICE_PROBE_CODE, str(n)],
            env=env, capture_output=True, text=True, timeout=120,
            start_new_session=True)
    except subprocess.TimeoutExpired:
        return f"jaxlib wedged initializing a {n}-forced-device CPU backend"
    if proc.returncode == 7:
        return f"jaxlib ignores xla_force_host_platform_device_count={n}"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        return (f"{n}-forced-device probe failed (rc={proc.returncode}): "
                f"{tail[-1][:200] if tail else 'no output'}")
    return None


@pytest.fixture
def forced_device_run():
    """Run a python script in a subprocess pinned to EXACTLY `n_devices`
    forced host CPU devices (`XLA_FLAGS=--xla_force_host_platform_
    device_count=N` + the jax_platforms=cpu config override the hosted
    image needs). Skips with a reason when this jaxlib can't force that
    device count; kills the whole process group on timeout so a wedged
    backend never hangs the suite. Returns the child's stdout."""
    from accelerate_tpu.test_utils import execute_subprocess

    def run(script_path: str, n_devices: int, args=(), timeout: int = 600):
        reason = _forced_device_unsupported(n_devices)
        if reason is not None:
            pytest.skip(reason)
        import sys

        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={n_devices}",
        }
        return execute_subprocess(
            [sys.executable, script_path, *map(str, args)], env=env,
            timeout=timeout)

    return run
