"""Interactive-menu and cloud-launcher tests: menu key handling is driven
through injected streams (no pty), cloud command assembly is verified offline
(ref tests/test_sagemaker.py pattern — conversion logic only, no cloud)."""

import io

import pytest

from accelerate_tpu.commands.cloud import (
    TPUCloudConfig,
    build_create_cmd,
    build_delete_cmd,
    build_remote_launch_cmd,
    cloud_command,
)
from accelerate_tpu.commands.menu import BulletMenu, read_key


# --- key decoding -----------------------------------------------------------


@pytest.mark.parametrize(
    "raw,expected",
    [
        ("\x1b[A", "up"),
        ("\x1b[B", "down"),
        ("\x1bOA", "up"),
        ("\r", "enter"),
        ("\n", "enter"),
        (" ", "enter"),
        ("k", "up"),
        ("j", "down"),
        ("q", "abort"),
        ("\x03", "abort"),
        ("3", "3"),
    ],
)
def test_read_key_decodes(raw, expected):
    assert read_key(io.StringIO(raw)) == expected


def test_read_key_empty_stream_aborts():
    assert read_key(io.StringIO("")) == "abort"


# --- menu -------------------------------------------------------------------


def _run_menu(keys: str, choices=("a", "b", "c"), default=0):
    menu = BulletMenu(
        "pick", choices, default=default,
        in_stream=io.StringIO(keys), out_stream=io.StringIO(),
    )
    return menu._run_interactive()


def test_menu_down_enter():
    assert _run_menu("j\r") == 1


def test_menu_wraps_upward():
    assert _run_menu("k\r") == 2


def test_menu_digit_jump():
    assert _run_menu("2\r") == 2


def test_menu_abort_returns_default():
    assert _run_menu("q", default=1) == 1


def test_menu_arrow_sequences():
    assert _run_menu("\x1b[B\x1b[B\r") == 2


def test_menu_plain_fallback():
    menu = BulletMenu(
        "pick", ["x", "y"], default=0,
        in_stream=io.StringIO("1\n"), out_stream=io.StringIO(),
    )
    assert menu._run_plain() == 1


def test_menu_plain_fallback_bad_input_uses_default():
    menu = BulletMenu(
        "pick", ["x", "y"], default=0,
        in_stream=io.StringIO("zzz\n"), out_stream=io.StringIO(),
    )
    assert menu._run_plain() == 0


def test_menu_rejects_empty_choices():
    with pytest.raises(ValueError):
        BulletMenu("pick", [])


# --- cloud command assembly -------------------------------------------------


def test_cloud_rejects_stray_positional_for_non_launch_verbs():
    from accelerate_tpu.commands.accelerate_cli import build_parser

    args = build_parser().parse_args(["cloud", "create", "my-tpu", "--dry_run"])
    with pytest.raises(SystemExit, match="my-tpu"):
        cloud_command(args)


def test_build_create_cmd():
    cfg = TPUCloudConfig(
        tpu_name="trainer", accelerator_type="v5p-16", zone="us-east5-a",
        project="proj", spot=True, tags=["ml", "tpu"],
    )
    cmd = build_create_cmd(cfg)
    assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create", "trainer"]
    assert "--accelerator-type" in cmd and cmd[cmd.index("--accelerator-type") + 1] == "v5p-16"
    assert "--spot" in cmd and "--project" in cmd
    assert cmd[cmd.index("--tags") + 1] == "ml,tpu"


def test_build_delete_cmd_quiet():
    cmd = build_delete_cmd(TPUCloudConfig(tpu_name="t"))
    assert cmd[4:6] == ["delete", "t"] and "--quiet" in cmd


def test_build_remote_launch_cmd_all_workers():
    cfg = TPUCloudConfig(tpu_name="pod")
    cmd = build_remote_launch_cmd(cfg, "train.py", ["--lr", "1e-3"])
    assert cmd[cmd.index("--worker") + 1] == "all"
    inner = cmd[cmd.index("--command") + 1]
    assert "accelerate-tpu launch train.py --lr 1e-3" == inner


def test_cloud_subcommand_registered():
    from accelerate_tpu.commands.accelerate_cli import build_parser

    parser = build_parser()
    args = parser.parse_args(["cloud", "describe", "--name", "x", "--dry_run"])
    assert args.tpu_name == "x" and args.verb == "describe" and args.dry_run
    args = parser.parse_args(
        ["cloud", "launch", "train.py", "--name", "pod", "--", "--lr", "1e-3"]
    )
    assert args.script == "train.py" and args.script_args == ["--lr", "1e-3"]


def test_cloud_uses_saved_config_topology(tmp_path, monkeypatch, capsys):
    """The questionnaire's pod-topology answers (tpu_accelerator_type,
    zone, name) reach `accelerate-tpu cloud create` as defaults; explicit
    CLI flags still win (VERDICT r4 #6 wiring)."""
    import argparse

    from accelerate_tpu.commands.cloud import cloud_command, register_subcommand
    from accelerate_tpu.commands.config.config_args import LaunchConfig

    monkeypatch.setenv("ACCELERATE_TPU_CONFIG_HOME", str(tmp_path))
    import accelerate_tpu.commands.config.config_args as ca
    monkeypatch.setattr(ca, "CACHE_DIR", tmp_path)
    LaunchConfig(
        tpu_name="my-pod", tpu_zone="us-central2-b",
        tpu_accelerator_type="v5p-64",
    ).save(tmp_path / "default_config.yaml")

    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers()
    register_subcommand(sub)
    args = parser.parse_args(["cloud", "create", "--dry_run"])
    assert args.func(args) == 0
    out = capsys.readouterr().out
    assert "my-pod" in out
    assert "v5p-64" in out
    assert "us-central2-b" in out

    # CLI wins over yaml
    args = parser.parse_args(
        ["cloud", "create", "--dry_run", "--accelerator_type", "v5litepod-4"]
    )
    assert args.func(args) == 0
    out = capsys.readouterr().out
    assert "v5litepod-4" in out and "v5p-64" not in out
