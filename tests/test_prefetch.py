"""Zero-overhead steady-state fast path: device-side input prefetch, cached
train-step dispatch (treedef-keyed pins, AOT warmup), and the persistent
compilation cache. All CPU-runnable under the virtual 8-device mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from accelerate_tpu import TrainState
from accelerate_tpu.accelerator import Accelerator, _CompiledTrainStep
from accelerate_tpu.data import DataLoaderShard, DevicePrefetchIterator
from accelerate_tpu.models import llama
from accelerate_tpu.utils.dataclasses import DataLoaderConfiguration, MeshConfig


def _mesh():
    return MeshConfig.data_parallel().build(jax.devices())


# ---------------------------------------------------------------------------
# DevicePrefetchIterator
# ---------------------------------------------------------------------------


class TestDevicePrefetchIterator:
    def test_ordering_preserved(self):
        out = list(DevicePrefetchIterator(range(10), lambda x: x * 10, depth=3))
        assert out == [i * 10 for i in range(10)]

    def test_transfers_stay_within_depth_ahead(self):
        """`place` (the async device_put stand-in) runs ahead of the
        consumer, but never more than depth+1 batches ahead (the +1 is the
        batch handed out)."""
        placed = []
        it = DevicePrefetchIterator(range(10), lambda x: placed.append(x) or x,
                                    depth=2)
        consumed = 0
        for _ in it:
            consumed += 1
            assert len(placed) <= consumed + 2
        assert consumed == 10 and len(placed) == 10

    def test_prefetch_is_eager_after_first_next(self):
        placed = []
        it = DevicePrefetchIterator(range(10), lambda x: placed.append(x) or x,
                                    depth=3)
        assert next(it) == 0
        # depth filled before hand-out, topped back up after
        assert len(placed) == 4

    def test_empty_and_exhaustion(self):
        it = DevicePrefetchIterator([], lambda x: x, depth=2)
        with pytest.raises(StopIteration):
            next(it)
        it = DevicePrefetchIterator([1], lambda x: x, depth=4)
        assert next(it) == 1
        with pytest.raises(StopIteration):
            next(it)

    def test_depth_floor_is_one(self):
        assert list(DevicePrefetchIterator(range(3), lambda x: x, depth=0)) == [0, 1, 2]


# ---------------------------------------------------------------------------
# DataLoaderShard with the device buffer
# ---------------------------------------------------------------------------


def _dict_batches(n_batches, rows=8, start=0):
    return [
        {"x": np.arange(start + i * rows, start + (i + 1) * rows,
                        dtype=np.float32).reshape(rows, 1)}
        for i in range(n_batches)
    ]


class TestLoaderDevicePrefetch:
    def test_ordering_and_placement(self):
        loader = DataLoaderShard(_dict_batches(5), mesh=_mesh(),
                                 device_prefetch_depth=3)
        seen = [np.asarray(b["x"])[:, 0] for b in loader]
        flat = np.concatenate(seen)
        assert flat.tolist() == list(np.arange(40, dtype=np.float32))
        out = list(iter(loader))
        assert all(isinstance(b["x"], jax.Array) for b in out)
        assert all(
            isinstance(b["x"].sharding, jax.sharding.NamedSharding) for b in out
        )

    def test_epoch_boundary_bumps_epoch_and_reiterates(self):
        loader = DataLoaderShard(_dict_batches(4), mesh=_mesh(),
                                 device_prefetch_depth=2)
        first = [np.asarray(b["x"]) for b in loader]
        assert loader.epoch == 1  # full pass advances the epoch
        second = [np.asarray(b["x"]) for b in loader]
        assert loader.epoch == 2
        assert len(first) == len(second) == 4
        np.testing.assert_array_equal(first[0], second[0])

    def test_uneven_tail_remainder_survives_prefetch(self):
        """end_of_dataloader's one-batch-ahead detection and the remainder
        bookkeeping must still fire with the device buffer in between."""
        batches = _dict_batches(3) + [
            {"x": np.arange(24, 27, dtype=np.float32).reshape(3, 1)}
        ]
        loader = DataLoaderShard(batches, mesh=_mesh(),
                                 device_prefetch_depth=2)
        sizes = []
        for b in loader:
            sizes.append(int(b["x"].shape[0]))
            if sizes[-1] == 8 and len(sizes) < 4:
                assert not loader.end_of_dataloader
        assert loader.end_of_dataloader
        # 3 real rows, padded up to the per-host device multiple (8)
        assert sizes[-1] == 8
        assert loader.remainder == 3

    def test_drop_last_style_source_not_padded(self):
        """A source that already dropped its tail (equal-size batches only)
        must flow through the prefetch pipeline without padding or
        remainder tracking."""
        loader = DataLoaderShard(_dict_batches(3), mesh=_mesh(),
                                 device_prefetch_depth=2)
        sizes = [int(b["x"].shape[0]) for b in loader]
        assert sizes == [8, 8, 8]
        assert loader.remainder == -1

    def test_depth_zero_disables_device_buffer(self):
        loader = DataLoaderShard(_dict_batches(3), mesh=_mesh(),
                                 device_prefetch_depth=0)
        out = [np.asarray(b["x"])[:, 0] for b in loader]
        assert np.concatenate(out).tolist() == list(np.arange(24, dtype=np.float32))

    def test_config_threads_depth_through_prepare(self):
        acc = Accelerator(
            dataloader_config=DataLoaderConfiguration(device_prefetch_depth=5,
                                                      prefetch_size=3)
        )
        loader = acc.prepare(_dict_batches(2))
        assert isinstance(loader, DataLoaderShard)
        assert loader.device_prefetch_depth == 5
        assert loader.prefetch_size == 3

    def test_explicit_kwarg_beats_config(self):
        from accelerate_tpu.data import prepare_data_loader

        loader = prepare_data_loader(
            _dict_batches(2), mesh=_mesh(),
            config=DataLoaderConfiguration(),  # defaults: depth 2, size 2
            device_prefetch_depth=0, prefetch_size=7,
        )
        assert loader.device_prefetch_depth == 0
        assert loader.prefetch_size == 7


# ---------------------------------------------------------------------------
# cached dispatch (_CompiledTrainStep)
# ---------------------------------------------------------------------------


def _make_toy_step():
    # a FRESH function object per test: jax.jit shares its dispatch cache
    # across wrappers of the same function, so a module-level step_fn would
    # leak `_cache_size()` entries between tests
    def _toy_step(state, *batch):
        new = jax.tree_util.tree_map(lambda x: x + 1.0, state)
        metrics = {"loss": jnp.float32(0.0)}
        return new, metrics

    return _toy_step


def _placed_state(tree):
    mesh = _mesh()
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), tree)


class TestCachedDispatch:
    def test_treedef_collision_gets_separate_jits(self):
        """Regression: two states with DIFFERENT treedefs but identical
        flattened sharding tuples must not share a jit — the out_shardings
        pytree is built from the first structure and would reject (or
        mispin) the second."""
        step = _CompiledTrainStep(_make_toy_step(), donate=False)
        a = _placed_state({"a": jnp.ones((8,)), "b": jnp.ones((8,))})
        b = _placed_state({"c": {"d": jnp.ones((8,)), "e": jnp.ones((8,))}})
        out_a, _ = step(a)
        out_b, _ = step(b)
        assert set(out_a) == {"a", "b"}
        assert set(out_b) == {"c"} and set(out_b["c"]) == {"d", "e"}
        assert len(step._by_layout) == 2
        assert step._pin_computations == 2

    def test_pin_tree_computed_once_across_steps(self):
        """Acceptance: steady-state dispatch is a cache hit — exactly ONE
        pin-tree computation for a fixed state structure over N steps."""
        step = _CompiledTrainStep(_make_toy_step(), donate=False)
        state = _placed_state({"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))})
        for i in range(10):
            state, _ = step(state)
        assert step._pin_computations == 1
        assert float(np.asarray(state["b"][0])) == 10.0

    def test_identity_fast_path_reset_on_new_layout(self):
        step = _CompiledTrainStep(_make_toy_step(), donate=False)
        state = _placed_state({"w": jnp.zeros((8, 4))})
        state, _ = step(state)
        # a re-prepared state with a DIFFERENT layout must get fresh pins
        mesh = _mesh()
        sharded = NamedSharding(mesh, PartitionSpec("data"))
        other = {"w": jax.device_put(np.zeros((8, 4), np.float32), sharded)}
        out, _ = step(other)
        assert step._pin_computations == 2
        assert out["w"].sharding == sharded

    def test_accelerator_train_step_pin_count(self):
        """End-to-end: the real fused train step over a prepared TrainState
        computes its pin tree once no matter how many steps run."""
        acc = Accelerator(mesh_config=MeshConfig(axes={"fsdp": 8}))
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.key(0))
        ts = acc.prepare(
            TrainState.create(apply_fn=None, params=params,
                              tx=optax.adamw(1e-3))
        )
        step = acc.train_step(lambda p, b: llama.causal_lm_loss(cfg, p, b))
        rng = np.random.default_rng(0)
        for _ in range(4):
            ids = rng.integers(0, cfg.vocab_size, (8, 65)).astype(np.int32)
            loader = acc.prepare([{"input_ids": ids}])
            (batch,) = list(loader)
            ts, metrics = step(ts, batch)
        assert step._pin_computations == 1
        assert step._cache_size() == 1


class TestWarmup:
    def test_warmup_compiles_without_executing(self):
        step = _CompiledTrainStep(_make_toy_step(), donate=True)
        state = _placed_state({"w": jnp.zeros((8, 4))})
        batch = jnp.ones((8, 2))
        compiled = step.warmup(state, batch)
        assert compiled is not None
        # nothing executed, nothing donated: the state is still usable
        assert float(np.asarray(state["w"][0, 0])) == 0.0
        # idempotent for the same signature
        assert step.warmup(state, batch) is compiled

    def test_warmed_up_steps_never_touch_the_jit_cache(self):
        step = _CompiledTrainStep(_make_toy_step(), donate=False)
        state = _placed_state({"w": jnp.zeros((8, 4))})
        batch = jnp.ones((8, 2))
        step.warmup(state, batch)
        for _ in range(5):
            state, _ = step(state, batch)
        # every call dispatched to the AOT executable — the jit cache is
        # still cold, and the first loop step paid dispatch only
        assert step._cache_size() == 0
        assert float(np.asarray(state["w"][0, 0])) == 5.0

    def test_midloop_warmup_resets_identity_fast_path(self):
        """warmup() for an upcoming batch shape must be consulted by the
        next call even when the loop's identity fast path is active."""
        step = _CompiledTrainStep(_make_toy_step(), donate=False)
        state = _placed_state({"w": jnp.zeros((8, 4))})
        batch_a, batch_b = jnp.ones((8, 2)), jnp.ones((16, 2))
        step.warmup(state, batch_a)
        state, _ = step(state, batch_a)
        step.warmup(state, batch_b)          # precompile the next shape
        state, _ = step(state, batch_b)      # must hit the fresh executable
        assert step._cache_size() == 0
        assert float(np.asarray(state["w"][0, 0])) == 2.0

    def test_batch_shape_drift_falls_back_to_jit(self):
        step = _CompiledTrainStep(_make_toy_step(), donate=False)
        state = _placed_state({"w": jnp.zeros((8, 4))})
        step.warmup(state, jnp.ones((8, 2)))
        state, _ = step(state, jnp.ones((8, 2)))     # AOT path
        state, _ = step(state, jnp.ones((16, 2)))    # drifted: jit path
        assert float(np.asarray(state["w"][0, 0])) == 2.0
        assert step._cache_size() == 1

    def test_alternating_shape_warmups_compile_once_each(self):
        """Regression (PR 1 review item): the AOT cache kept ONE executable
        per layout key, so alternating warmups across two batch shapes
        evicted each other and recompiled every time. Keyed by
        (layout, batch signature) they must each compile exactly once."""
        step = _CompiledTrainStep(_make_toy_step(), donate=False)
        state = _placed_state({"w": jnp.zeros((8, 4))})
        batch_a, batch_b = jnp.ones((8, 2)), jnp.ones((16, 2))
        first_a = step.warmup(state, batch_a)
        first_b = step.warmup(state, batch_b)
        for _ in range(3):
            assert step.warmup(state, batch_a) is first_a
            assert step.warmup(state, batch_b) is first_b
        assert step._aot_compiles == 2
        assert len(step._aot) == 2
        # both warmed shapes dispatch AOT — the jit cache stays cold
        state, _ = step(state, batch_a)
        state, _ = step(state, batch_b)
        state, _ = step(state, batch_a)
        assert step._cache_size() == 0
        assert float(np.asarray(state["w"][0, 0])) == 3.0


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------


class TestCompilationCache:
    def test_smoke_writes_and_reuses_entries(self, tmp_path, monkeypatch):
        from accelerate_tpu.utils import environment as env_mod
        from accelerate_tpu.utils.constants import (
            ENV_COMPILATION_CACHE_MIN_COMPILE_SECS,
            ENV_COMPILATION_CACHE_MIN_ENTRY_BYTES,
        )
        from jax.experimental.compilation_cache import compilation_cache as cc

        cache_dir = str(tmp_path / "xla-cache")
        monkeypatch.setenv(ENV_COMPILATION_CACHE_MIN_COMPILE_SECS, "0")
        monkeypatch.setenv(ENV_COMPILATION_CACHE_MIN_ENTRY_BYTES, "-1")
        prev_dir = jax.config.jax_compilation_cache_dir
        try:
            applied = env_mod.configure_compilation_cache(cache_dir, force=True)
            assert applied == cache_dir
            # a fresh computation compiles and persists
            x = jnp.arange(17.0)
            jax.jit(lambda v: jnp.cos(v) * 17.0 + v)(x).block_until_ready()
            entries = os.listdir(cache_dir)
            assert entries, "no persistent cache entries written"
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            cc.reset_cache()
            env_mod._compilation_cache_dir_applied = None

    def test_env_disable(self, monkeypatch):
        from accelerate_tpu.utils import environment as env_mod
        from accelerate_tpu.utils.constants import ENV_COMPILATION_CACHE

        monkeypatch.setenv(ENV_COMPILATION_CACHE, "off")
        assert env_mod.configure_compilation_cache() is None

    def test_partial_state_records_dir(self, tmp_path, monkeypatch):
        from accelerate_tpu.state import PartialState
        from accelerate_tpu.utils import environment as env_mod
        from accelerate_tpu.utils.constants import ENV_COMPILATION_CACHE
        from jax.experimental.compilation_cache import compilation_cache as cc

        cache_dir = str(tmp_path / "state-cache")
        monkeypatch.setenv(ENV_COMPILATION_CACHE, cache_dir)
        prev_dir = jax.config.jax_compilation_cache_dir
        try:
            state = PartialState()
            assert state.compilation_cache_dir == cache_dir
            assert os.path.isdir(cache_dir)
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            cc.reset_cache()
            env_mod._compilation_cache_dir_applied = None


# ---------------------------------------------------------------------------
# tier-1 collection guard
# ---------------------------------------------------------------------------


def test_prefetch_tests_are_tier1_collected():
    """The ROADMAP tier-1 command runs `pytest tests/ -m 'not slow'`; the
    fast-path tests in this file must be collected by it (i.e. none are
    gated behind a slow marker or a collection error).

    This guard executing at all proves the file imports and collects under
    the tier-1 flags, so the only property left to check is that no test
    here hides behind a slow marker — read off the AST instead of running
    a nested ``pytest.main`` collection, which cost ~12s of whole-session
    overhead (plugin/rewrite setup against a multi-GB heap) inside the
    full tier-1 run.
    """
    roadmap = os.path.join(os.path.dirname(__file__), os.pardir, "ROADMAP.md")
    with open(roadmap) as f:
        text = f.read()
    assert "-m 'not slow'" in text and "pytest tests/" in text, (
        "tier-1 command changed; update this guard"
    )

    import ast

    with open(os.path.abspath(__file__)) as f:
        tree = ast.parse(f.read())
    names: list = []
    slow_marked: list = []

    def scan(body, prefix=""):
        for node in body:
            if isinstance(node, ast.ClassDef) and node.name.startswith("Test"):
                scan(node.body, prefix=f"{node.name}::")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name.startswith("test_"):
                names.append(prefix + node.name)
                if any("slow" in ast.dump(dec)
                       for dec in node.decorator_list):
                    slow_marked.append(prefix + node.name)

    scan(tree.body)
    assert len(names) >= 15, names
    assert slow_marked == [], slow_marked
