"""Runnable model families (flagship workloads for benchmarks/examples),
plus HF-checkpoint import (`hf_import`) validated logit-exact against
transformers."""

from . import bert, common, hf_import, llama, mixtral
from .bert import BertConfig
from .hf_import import config_from_hf, load_hf_checkpoint, params_from_hf
from .llama import LlamaConfig
from .mixtral import MixtralConfig
