"""Runnable model families (flagship workloads for benchmarks/examples)."""

from . import bert, common, llama, mixtral
from .bert import BertConfig
from .llama import LlamaConfig
from .mixtral import MixtralConfig
