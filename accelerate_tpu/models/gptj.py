"""GPT-J causal LM (the GPT-J-6B rows of the reference's big-model-inference
benchmark, ref benchmarks/README.md:29-30).

Same TPU-first scan-over-stacked-layers layout as the other families.
GPT-J specifics: a SINGLE LayerNorm per layer feeding both attention and
MLP (parallel residual), partial rotary embeddings in the interleaved
"rotate every two" convention (unlike llama/NeoX's rotate-half), no
attention biases, and an untied LM head WITH bias.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .common import (
    cross_entropy_loss,
    shifted_padding_masks,
    dense,
    dense_maybe_fp8,
    dot_product_attention,
    layer_norm,
    normal_init,
)
from .decode import (
    build_generate,
    build_streamed_generate,
    decode_attention,
    make_kv_caches,
    rope_table_len,
)


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    hidden_size: int = 4096          # n_embd
    num_hidden_layers: int = 28      # n_layer
    num_attention_heads: int = 16    # n_head
    max_position_embeddings: int = 2048  # n_positions
    rotary_dim: int = 64
    layer_norm_epsilon: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls, **overrides) -> "GPTJConfig":
        defaults = dict(
            vocab_size=256, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=128, rotary_dim=8,
        )
        defaults.update(overrides)
        return cls(**defaults)


def init_params(config: GPTJConfig, key: jax.Array, dtype=jnp.float32) -> dict:
    keys = jax.random.split(key, 8)
    h, L = config.hidden_size, config.num_hidden_layers

    def lin(k, d_in, d_out, bias=True):
        out = {"kernel": normal_init(k, (L, d_in, d_out), 0.02, dtype)}
        if bias:
            out["bias"] = jnp.zeros((L, d_out), dtype)
        return out

    return {
        "wte": {"embedding": normal_init(keys[0], (config.vocab_size, h), 0.02, dtype)},
        "layers": {
            "ln_1": {"scale": jnp.ones((L, h), dtype), "bias": jnp.zeros((L, h), dtype)},
            "attn": {
                "q_proj": lin(keys[1], h, h, bias=False),
                "k_proj": lin(keys[2], h, h, bias=False),
                "v_proj": lin(keys[3], h, h, bias=False),
                "out_proj": lin(keys[4], h, h, bias=False),
            },
            "mlp": {
                "fc_in": lin(keys[5], h, 4 * h),
                "fc_out": lin(keys[6], 4 * h, h),
            },
        },
        "ln_f": {"scale": jnp.ones((h,), dtype), "bias": jnp.zeros((h,), dtype)},
        "lm_head": {
            "kernel": normal_init(keys[7], (h, config.vocab_size), 0.02, dtype),
            "bias": jnp.zeros((config.vocab_size,), dtype),
        },
    }


def _interleaved_rope_tables(rotary_dim: int, max_len: int, dtype=jnp.float32):
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, rotary_dim, 2) / rotary_dim))
    t = np.arange(max_len)
    freqs = np.einsum("i,j->ij", t, inv_freq)          # [T, rot/2]
    return jnp.asarray(np.sin(freqs), dtype), jnp.asarray(np.cos(freqs), dtype)


def _rotate_every_two(x):
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    return jnp.stack((-x2, x1), axis=-1).reshape(x.shape)


def _apply_interleaved_rope(x, sin, cos, positions):
    """GPT-J rotary: pairs are interleaved (dims 0&1, 2&3, ...) rather than
    split-half; sin/cos repeat per pair. Rotation math runs f32 but the
    output keeps x's dtype (bf16 checkpoints must not upcast the residual
    stream — the layer scan carry dtype is fixed)."""
    sin_p = jnp.repeat(sin[positions], 2, axis=-1)[:, :, None, :]
    cos_p = jnp.repeat(cos[positions], 2, axis=-1)[:, :, None, :]
    xf = x.astype(jnp.float32)
    return (xf * cos_p + _rotate_every_two(xf) * sin_p).astype(x.dtype)


def _layer_body(config: GPTJConfig, x, layer, sin, cos, positions, mask,
                kv_cache=None, fp8=None):
    b, s, h = x.shape
    nh, hd, rot = config.num_attention_heads, config.head_dim, config.rotary_dim
    eps = config.layer_norm_epsilon
    fa = fp8["attn"] if fp8 is not None else {}
    fm = fp8["mlp"] if fp8 is not None else {}

    y = layer_norm(x, layer["ln_1"]["scale"], layer["ln_1"]["bias"], eps)
    q, m_q = dense_maybe_fp8(y, layer["attn"]["q_proj"]["kernel"], fa.get("q_proj"))
    k, m_k = dense_maybe_fp8(y, layer["attn"]["k_proj"]["kernel"], fa.get("k_proj"))
    v, m_v = dense_maybe_fp8(y, layer["attn"]["v_proj"]["kernel"], fa.get("v_proj"))
    q, k, v = (t.reshape(b, s, nh, hd) for t in (q, k, v))
    q = jnp.concatenate([
        _apply_interleaved_rope(q[..., :rot], sin, cos, positions),
        q[..., rot:],
    ], axis=-1)
    k = jnp.concatenate([
        _apply_interleaved_rope(k[..., :rot], sin, cos, positions),
        k[..., rot:],
    ], axis=-1)
    new_cache = None
    if kv_cache is not None:
        # shared cache-attend step (models/decode.py): dense stacked
        # caches keep the classic extend/mask/einsum path; the serving
        # engine's paged pool streams live pages through the Pallas
        # paged-attention kernel instead of gathering
        attn, new_cache = decode_attention(q, k, v, kv_cache, positions,
                                           mask=mask)
    else:
        attn = dot_product_attention(q, k, v, mask=mask, causal=True)
    attn_out, m_o = dense_maybe_fp8(
        attn.reshape(b, s, h), layer["attn"]["out_proj"]["kernel"],
        fa.get("out_proj"))

    # parallel residual off the SAME ln_1 output
    m, m_fi = dense_maybe_fp8(y, layer["mlp"]["fc_in"]["kernel"],
                              fm.get("fc_in"), layer["mlp"]["fc_in"]["bias"])
    m = jax.nn.gelu(m.astype(jnp.float32), approximate=True).astype(x.dtype)
    mlp_out, m_fo = dense_maybe_fp8(m, layer["mlp"]["fc_out"]["kernel"],
                                    fm.get("fc_out"),
                                    layer["mlp"]["fc_out"]["bias"])
    new_fp8 = (
        {"attn": {"q_proj": m_q, "k_proj": m_k, "v_proj": m_v,
                  "out_proj": m_o},
         "mlp": {"fc_in": m_fi, "fc_out": m_fo}}
        if fp8 is not None else None
    )
    return x + attn_out + mlp_out, new_cache, new_fp8


def _project_out(config: GPTJConfig, params: dict, x):
    x = layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"],
                   config.layer_norm_epsilon)
    return jnp.einsum(
        "bsh,hv->bsv", x, params["lm_head"]["kernel"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    ) + params["lm_head"]["bias"].astype(jnp.float32)


def forward(
    config: GPTJConfig,
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array | None = None,
    positions: jax.Array | None = None,
    kv_caches=None,
    fp8_state=None,
) -> jax.Array | tuple:
    """Logits [B, S, V]; with `kv_caches` (see `init_kv_caches`), returns
    (logits, new_caches) — the incremental-decode path behind `generate`.
    With `fp8_state` (see `init_fp8_state`), layer projections run fp8 and
    the result is (logits, new_fp8_state)."""
    if fp8_state is not None and kv_caches is not None:
        raise ValueError("fp8 is a training-path feature; decode "
                         "(kv_caches) runs bf16")
    x = params["wte"]["embedding"][input_ids]
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(input_ids.shape[1]), input_ids.shape
        )
    sin, cos = _interleaved_rope_tables(
        config.rotary_dim,
        rope_table_len(config.max_position_embeddings, kv_caches))

    if kv_caches is not None:
        ck, cv, cache_len = kv_caches

        def decode_body(carry, xs):
            layer, ck_l, cv_l = xs
            y, cache, _ = _layer_body(config, carry, layer, sin, cos,
                                      positions, attention_mask,
                                      (ck_l, cv_l, cache_len))
            nk, nv, _ = cache
            return y, (nk, nv)

        x, (nk, nv) = jax.lax.scan(decode_body, x, (params["layers"], ck, cv))
        return (_project_out(config, params, x),
                (nk, nv, cache_len + input_ids.shape[1]))

    if fp8_state is not None:
        def scan_body(carry, xs):
            layer, f = xs
            y, _, nf = _layer_body(config, carry, layer, sin, cos, positions,
                                   attention_mask, fp8=f)
            return y, nf

        x, new_fp8 = jax.lax.scan(
            scan_body, x, (params["layers"], fp8_state["layers"])
        )
        return _project_out(config, params, x), {"layers": new_fp8}

    def scan_body(carry, layer):
        return _layer_body(config, carry, layer, sin, cos, positions,
                           attention_mask)[0], None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    return _project_out(config, params, x)


def init_kv_caches(config: GPTJConfig, batch: int, max_len: int,
                   dtype=jnp.bfloat16):
    return make_kv_caches(config.num_hidden_layers, batch, max_len,
                          config.num_attention_heads, config.head_dim, dtype)


generate = build_generate(forward, init_kv_caches)


def causal_lm_loss(config: GPTJConfig, params: dict, batch: dict,
                   fp8_state=None) -> jax.Array | tuple:
    """Next-token loss; with `fp8_state` (mixed_precision="fp8") returns
    (loss, new_fp8_state)."""
    input_ids = batch["input_ids"]
    labels = input_ids[:, 1:]
    attn_mask, mask = shifted_padding_masks(batch.get("attention_mask"))
    out = forward(config, params, input_ids[:, :-1],
                  attention_mask=attn_mask, fp8_state=fp8_state)
    if fp8_state is not None:
        logits, new_fp8 = out
        return cross_entropy_loss(logits, labels, mask), new_fp8
    return cross_entropy_loss(out, labels, mask)


def init_fp8_state(config: GPTJConfig, history_len: int | None = None) -> dict:
    """Per-layer delayed-scaling metas for the six layer projections
    (shared builder: ops/fp8.py stacked_fp8_metas; honors the Accelerator's
    FP8RecipeKwargs)."""
    from ..ops.fp8 import stacked_fp8_metas

    return stacked_fp8_metas(config.num_hidden_layers, {
        "attn": ("q_proj", "k_proj", "v_proj", "out_proj"),
        "mlp": ("fc_in", "fc_out"),
    }, history_len)


@functools.lru_cache(maxsize=8)
def make_decode_layer_step(config: GPTJConfig):
    """jit'd single-layer decode body for `streamed_generate` (offloaded
    weights — the reference's GPT-J-6B cpu-offload benchmark rows)."""

    @jax.jit
    def step(layer, x, positions, kv_cache):
        max_len = max(config.max_position_embeddings, kv_cache[0].shape[1])
        sin, cos = _interleaved_rope_tables(config.rotary_dim, max_len)
        y, cache, _ = _layer_body(config, x, layer, sin, cos, positions,
                                  None, kv_cache)
        return y, cache

    return step


# _project_out includes the final layer norm, so it is directly the
# streamed path's projection
streamed_generate = build_streamed_generate(
    make_decode_layer_step,
    embed_fn=lambda config, res, ids, pos: res["wte"]["embedding"][ids],
    project_fn=lambda config, res, x: _project_out(config, res, x),
    cache_dims=lambda c: (c.num_attention_heads, c.head_dim),
)
